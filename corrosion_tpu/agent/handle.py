"""The Agent handle: shared state every subsystem hangs off.

Counterpart of the `Agent` god-handle in `klukai-types/src/agent.rs:64-273`
(actor id, pools, HLC clock, channels, members, booked versions, write
semaphore, schema, subs/updates managers, sync-concurrency limits).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, List, Optional

from corrosion_tpu.agent.members import Members
from corrosion_tpu.agent.membership import Membership
from corrosion_tpu.net.transport import Listener, Transport
from corrosion_tpu.runtime.channels import Receiver, Sender
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.runtime.writegate import PriorityWriteGate
from corrosion_tpu.runtime.locks import LockRegistry
from corrosion_tpu.runtime.tripwire import TaskTracker, Tripwire
from corrosion_tpu.store.bookkeeping import Bookie
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import Actor, ActorId, ClusterId
from corrosion_tpu.types.base import HLClock
from corrosion_tpu.types.change import Change, ChangeV1


class ChangeSource(Enum):
    BROADCAST = "broadcast"
    SYNC = "sync"


@dataclass
class BroadcastInput:
    """AddBroadcast (our own fresh change) or Rebroadcast (relayed)."""

    change: ChangeV1
    is_local: bool  # True = AddBroadcast, False = Rebroadcast


# subs/updates hook: called with every batch of impactful committed
# changes plus the batch's latency stamp (runtime/latency.py BatchStamp;
# origin may be None when no stamp traveled with the changes)
ChangeHook = Callable[..., None]


@dataclass
class Agent:
    actor: Actor
    config: Config
    store: CrdtStore
    bookie: Bookie
    clock: HLClock
    members: Members
    membership: Membership
    transport: Transport
    listener: Listener
    tripwire: Tripwire
    tracker: TaskTracker

    tx_bcast: Sender
    rx_bcast: Receiver
    tx_changes: Sender
    rx_changes: Receiver
    tx_apply: Sender
    rx_apply: Receiver

    # SplitPool write-permit analog: one writer at a time, waiters queued
    # 3-lane priority gate in front of the single write path
    # (SplitPool's priority/normal/low write queues, agent.rs:478-519)
    write_gate: PriorityWriteGate = field(default_factory=PriorityWriteGate)
    # ≤3 concurrent inbound sync serves (agent.rs:144-146)
    sync_serve_sem: asyncio.Semaphore = field(default_factory=lambda: asyncio.Semaphore(3))
    change_hooks: List[ChangeHook] = field(default_factory=list)
    # live-query + raw-update managers (agent.rs:64-273 subs/updates)
    subs: Optional[object] = None  # SubsManager
    updates: Optional[object] = None  # UpdatesManager
    # r11 SLO plane: per-agent latency-objective monitor
    # (runtime/latency.py SloMonitor), checked by /v1/slo + the canary
    slo: Optional[object] = None
    # r12 cluster observatory (agent/observatory.py): digest
    # anti-entropy store + view-divergence detector, serves /v1/cluster
    observatory: Optional[object] = None
    # r20 alerting plane (runtime/alerts.py): declarative rules over
    # the metrics TSDB with a pending→firing→resolved lifecycle;
    # serves /v1/alerts, summaries ride the observatory digests
    alerts: Optional[object] = None
    # r22 remediation plane (agent/remediation.py): the supervisor
    # that turns alert firings into typed, cooldown-gated actuator
    # runs; serves GET /v1/remediation
    remediation: Optional[object] = None
    # r22 refuse-bulk deadline (monotonic): while in the future this
    # node refuses to SERVE bulk snapshot transfers (catchup.py rejects
    # BUSY) and to START one as a bootstrap client — armed by the
    # store-faults actuator, cleared by its revert hook (or expiry)
    bulk_refuse_until: float = 0.0
    # r14 write-path group commit (agent/run.py GroupCommitter):
    # concurrent local writers coalesce into shared sqlite transactions
    commit_group: Optional[object] = None
    # r17 catch-up plane (agent/catchup.py): serve-side cached snapshot
    # (store/snapshot.py SnapshotCache) + its async build lock/permits,
    # per-peer sync circuit state, and the bootstrap census /v1/status
    # serves
    snapshots: Optional[object] = None  # SnapshotCache
    snapshot_build_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # sized from [sync] max_concurrent_snapshot_serves at agent build
    # time (agent/run.py); the default only covers hand-built test agents
    snapshot_serve_sem: asyncio.Semaphore = field(
        default_factory=lambda: asyncio.Semaphore(2)
    )
    # ActorId -> PeerCircuit (agent/syncer.py): consecutive-failure
    # breaker consulted by peer choice and the resumable sync waves
    sync_circuits: dict = field(default_factory=dict)
    # bootstrap census: {"state": idle|fetching|installed|failed, ...}
    catchup_census: dict = field(default_factory=dict)
    # r18 (found by the traffic_sim zombie-node scenario): the
    # announcer picks its sleep while healthy — the 300 s steady
    # period — and used to sleep straight through an isolation that
    # began mid-sleep, leaving an evicted node silent for up to 5
    # minutes after the fault cleared.  This event is set when the
    # SWIM view collapses to self (run.py on_notification) and the
    # announcer waits on it alongside the tripwire, so isolation
    # restarts the jittered announce ramp IMMEDIATELY
    announce_wake: asyncio.Event = field(default_factory=asyncio.Event)
    # bumped by a snapshot install: the ingest seen-cache must drop
    # everything it remembers, because "seen" changes applied BEFORE
    # the database swap were discarded by it — a stale entry would
    # shadow the re-served version forever (agent/ingest.py)
    ingest_epoch: int = 0
    # instrumented-lock registry (agent.rs:707-1066), admin `locks` command
    lock_registry: LockRegistry = field(default_factory=LockRegistry)

    @property
    def actor_id(self) -> ActorId:
        return self.actor.id

    @property
    def cluster_id(self) -> ClusterId:
        return self.actor.cluster_id

    def notify_change_hooks(
        self,
        changes: List[Change],
        origin_wall: Optional[float] = None,
        traceparent: Optional[str] = None,
        trace_meta: Optional[int] = None,
    ) -> None:
        """Feed one committed batch to the subs/updates hooks.  Runs on
        whatever thread committed (write path / ingest worker): the
        histogram makes the per-batch hook cost visible so a routing
        regression back to O(subs × changes) shows up as a rising
        write-path tax, not a mystery throughput loss.

        r11: the batch's latency stamp travels with it — `applied` is
        NOW (the commit that produced these changes just happened on
        this thread), `origin` is the origin node's commit wall clock
        when it rode the envelope here (None otherwise).  The matcher
        measures apply→event against it and the stream write measures
        the end-to-end total.  r19: the origin's W3C trace context +
        tail-sampling meta ride the same stamp so the match/deliver
        stage spans stitch to the write's trace."""
        import time as _time

        from corrosion_tpu.runtime.latency import BatchStamp
        from corrosion_tpu.runtime.metrics import METRICS

        stamp = BatchStamp(
            origin=origin_wall, applied=_time.time(),
            traceparent=traceparent, trace_meta=trace_meta,
        )
        start = _time.monotonic()
        for hook in list(self.change_hooks):
            hook(changes, stamp)
        METRICS.histogram("corro.agent.changes.hooks.seconds").observe(
            _time.monotonic() - start
        )

    def notify_change_hooks_group(
        self,
        batches: List[tuple],
        origin_wall: Optional[float] = None,
    ) -> None:
        """Group-commit form of `notify_change_hooks` (r21): feed every
        committed tx of one group batch through the hooks with ONE
        applied stamp, one hooks-list snapshot and one histogram
        observe, instead of a full per-tx flush for each follower.
        Each tx keeps its OWN BatchStamp (its traceparent/trace_meta
        differ), so subscribers still see per-tx batch boundaries —
        only the bookkeeping around the hook calls amortizes.
        ``batches`` yields ``(changes, traceparent, trace_meta)``."""
        import time as _time

        from corrosion_tpu.runtime.latency import BatchStamp
        from corrosion_tpu.runtime.metrics import METRICS

        applied = _time.time()
        hooks = list(self.change_hooks)
        start = _time.monotonic()
        for changes, traceparent, trace_meta in batches:
            stamp = BatchStamp(
                origin=origin_wall, applied=applied,
                traceparent=traceparent, trace_meta=trace_meta,
            )
            for hook in hooks:
                hook(changes, stamp)
        METRICS.histogram("corro.agent.changes.hooks.seconds").observe(
            _time.monotonic() - start
        )

"""Member-state persistence + startup resurrection + bootstrap fallback.

Counterparts:
  - `diff_member_states` (`klukai-agent/src/broadcast/mod.rs:814-949`):
    every 60 s, diff live SWIM membership against the last persisted
    snapshot and upsert JSON member states + min RTT into
    `__corro_members`, deleting rows for actors that vanished.
  - `initialise_foca`/`load_member_states` + scheduled rejoin
    (`klukai-agent/src/agent/util.rs:74-179`): on startup, re-apply the
    persisted states so a restarted node remembers the cluster, then do a
    full re-announce 25 s (+ jitter) later to refresh what changed while
    we were down.
  - stored-member bootstrap fallback (`klukai-agent/src/agent/
    bootstrap.rs:29-50`): when the configured bootstrap list is empty,
    announce to up to 5 random persisted members.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.net.gossip_codec import MemberState
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.actor import Actor, ActorId, ClusterId
from corrosion_tpu.types.base import Timestamp

log = logging.getLogger(__name__)

DIFF_PERIOD_S = 60.0  # broadcast/mod.rs:190 member-state diff tick
REJOIN_DELAY_S = 25.0  # util.rs:114-133 scheduled full rejoin
REJOIN_JITTER_S = 10.0
BOOTSTRAP_FALLBACK_COUNT = 5  # bootstrap.rs:29-50


def _state_json(actor: Actor, incarnation: int, state: MemberState) -> str:
    return json.dumps(
        {
            "id": str(actor.id),
            "addr": actor.addr,
            "ts": actor.ts.ntp64,
            "cluster_id": actor.cluster_id.value,
            "bump": actor.bump,
            "incarnation": incarnation,
            "state": state.name,
        },
        sort_keys=True,
    )


def _state_from_json(text: str) -> Optional[Tuple[Actor, int, MemberState]]:
    try:
        d = json.loads(text)
        actor = Actor(
            id=ActorId.from_uuid_str(d["id"]),
            addr=d["addr"],
            ts=Timestamp(d["ts"]),
            cluster_id=ClusterId(d["cluster_id"]),
            bump=d["bump"],
        )
        return actor, d["incarnation"], MemberState[d["state"]]
    except (ValueError, KeyError, TypeError):
        return None


def snapshot_membership(agent) -> Dict[ActorId, str]:
    """Serialize the live SWIM view (non-down members, like the reference
    which persists foca's active member set)."""
    out: Dict[ActorId, str] = {}
    # runs on a worker thread (member_states_loop's to_thread) while the
    # event loop mutates membership: dict(d) is a single GIL-held copy,
    # iterating the live dict raised "changed size during iteration"
    # under absorption load
    for aid, m in dict(agent.membership.members).items():
        if m.state == MemberState.DOWN:
            continue
        out[aid] = _state_json(m.actor, m.incarnation, m.state)
    return out


def _min_rtt_ms(agent, addr: str) -> Optional[float]:
    # worker thread (diff_member_states' to_thread) vs event-loop
    # appends: copy the deque in one GIL-held C call before iterating,
    # same idiom as snapshot_membership's dict(...) above
    window = agent.members.rtts.get(addr)
    if not window:
        return None
    return min(window.copy())


def diff_member_states(
    agent, last: Dict[ActorId, str]
) -> Dict[ActorId, str]:
    """One diff pass: upsert changed states, delete gone actors; returns
    the new snapshot (broadcast/mod.rs:814-949)."""
    current = snapshot_membership(agent)
    now = int(time.time())
    upserts = []
    for aid, state_json in current.items():
        if last.get(aid) == state_json:
            continue
        d = json.loads(state_json)
        upserts.append(
            (
                aid.bytes16,
                d["addr"],
                state_json,
                _min_rtt_ms(agent, d["addr"]),
                now,
            )
        )
    gone = [aid.bytes16 for aid in last.keys() - current.keys()]
    if upserts or gone:
        agent.store.update_member_rows(upserts, gone)
        METRICS.counter("corro.members.persisted").inc(len(upserts))
        METRICS.counter("corro.members.deleted").inc(len(gone))
    return current


async def member_states_loop(agent) -> None:
    """60 s cadence diff loop; a final diff runs on shutdown so the next
    start sees the freshest view."""
    last: Dict[ActorId, str] = {}
    while not agent.tripwire.tripped:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(agent.tripwire.wait(), DIFF_PERIOD_S)
        try:
            last = await asyncio.to_thread(diff_member_states, agent, last)
        except Exception:
            log.exception("member-state diff failed")


def load_member_states(store) -> List[Tuple[Actor, int, MemberState]]:
    """Persisted member states for resurrection (util.rs:74-111)."""
    out = []
    for text in store.member_state_rows():
        parsed = _state_from_json(text)
        if parsed is not None:
            out.append(parsed)
    return out


def stored_bootstrap_addrs(store, count=BOOTSTRAP_FALLBACK_COUNT) -> List[str]:
    """Random persisted member addresses, the bootstrap fallback when no
    bootstrap list is configured (bootstrap.rs:29-50)."""
    return store.random_member_addresses(count)


async def resurrect_and_schedule_rejoin(agent) -> None:
    """Apply persisted states, then a full re-announce after 25 s + jitter
    (util.rs:114-133: the cluster may have moved on while we were down)."""
    states = await asyncio.to_thread(load_member_states, agent.store)
    if states:
        states = [
            s
            for s in states
            if s[0].id != agent.actor_id
            and s[0].cluster_id == agent.cluster_id
        ]
        agent.membership.apply_many(states)
        log.info("resurrected %d persisted members", len(states))
        METRICS.counter("corro.members.resurrected").inc(len(states))

    delay = REJOIN_DELAY_S + random.random() * REJOIN_JITTER_S
    with contextlib.suppress(asyncio.TimeoutError):
        await asyncio.wait_for(agent.tripwire.wait(), delay)
    if agent.tripwire.tripped:
        return
    for actor in agent.membership.active_members():
        with contextlib.suppress(Exception):
            await agent.membership.announce(actor.addr)

"""Cluster-member view with RTT rings.

Counterpart of `klukai-types/src/members.rs:38-178`: the agent-side
registry of known peers (distinct from SWIM's internal state), keyed by
ActorId, each with a gossip address and an RTT ring assignment. Ring 0
(median RTT < 6 ms) gets priority broadcast delivery
(`broadcast/mod.rs:591-651`); higher rings are reached through random
fanout. RTT observations stream in from the transport.
"""

from __future__ import annotations

import statistics
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterable, List, Optional, Set

from corrosion_tpu.types.actor import Actor, ActorId

# ring upper bounds in milliseconds; index = ring number
RING_BOUNDS_MS = [6.0, 15.0, 50.0, 100.0, 200.0]
RTT_WINDOW = 20  # observations kept per address


def ring_for_rtt(rtt_ms: float) -> int:
    for ring, bound in enumerate(RING_BOUNDS_MS):
        if rtt_ms < bound:
            return ring
    return len(RING_BOUNDS_MS)


@dataclass
class MemberInfo:
    actor: Actor
    ring: Optional[int] = None
    last_sync_ts: Optional[int] = None  # HLC value of last successful sync


@dataclass
class Members:
    states: Dict[ActorId, MemberInfo] = field(default_factory=dict)
    by_addr: Dict[str, ActorId] = field(default_factory=dict)
    rtts: Dict[str, Deque[float]] = field(default_factory=dict)

    def add_member(self, actor: Actor) -> bool:
        """Insert/refresh a member; True if it is new (members.rs:52-92)."""
        existing = self.states.get(actor.id)
        is_new = existing is None
        info = existing or MemberInfo(actor=actor)
        info.actor = actor
        self.states[actor.id] = info
        self.by_addr[actor.addr] = actor.id
        self._recompute_ring(actor.addr)
        return is_new

    def remove_member(self, actor: Actor) -> bool:
        """Drop a member; True if it was present and removed."""
        existing = self.states.get(actor.id)
        if existing is None:
            return False
        # a renewed identity (newer ts/bump) must not be clobbered by a
        # stale Down about the old identity
        if (existing.actor.ts, existing.actor.bump) > (actor.ts, actor.bump):
            return False
        del self.states[actor.id]
        if self.by_addr.get(actor.addr) == actor.id:
            del self.by_addr[actor.addr]
        return True

    def observe_rtt(self, addr: str, rtt_seconds: float) -> None:
        window = self.rtts.setdefault(addr, deque(maxlen=RTT_WINDOW))
        window.append(rtt_seconds * 1000.0)
        self._recompute_ring(addr)

    def _recompute_ring(self, addr: str) -> None:
        actor_id = self.by_addr.get(addr)
        if actor_id is None:
            return
        window = self.rtts.get(addr)
        if not window:
            return
        self.states[actor_id].ring = ring_for_rtt(statistics.median(window))

    # -- selection helpers used by broadcast + sync ------------------------

    def ring0(self, exclude: Set[ActorId] = frozenset()) -> List[Actor]:
        return [
            info.actor
            for aid, info in self.states.items()
            if info.ring == 0 and aid not in exclude
        ]

    def not_ring0(self, exclude: Set[ActorId] = frozenset()) -> List[Actor]:
        return [
            info.actor
            for aid, info in self.states.items()
            if info.ring != 0 and aid not in exclude
        ]

    def all_actors(self) -> List[Actor]:
        return [info.actor for info in self.states.values()]

    def get(self, actor_id: ActorId) -> Optional[MemberInfo]:
        return self.states.get(actor_id)

    def __len__(self) -> int:
        return len(self.states)

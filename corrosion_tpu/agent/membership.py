"""Per-node SWIM failure detector and membership gossip (foca equivalent).

Behavioral counterpart of the foca-driven runtime loop in
`klukai-agent/src/broadcast/mod.rs:121-386` plus foca's own protocol:
round-robin probing with direct + indirect pings, suspicion with
incarnation-numbered refutation, piggybacked membership updates with
infection-style retransmission decay, announce/feed join, graceful leave,
and identity `renew()` auto-rejoin when declared down
(`klukai-types/src/actor.rs:199-206`).

This is the event-driven path for *real* agents (a handful of nodes per
process over real sockets). The 10⁴–10⁶-member batched path — the same
state machine vectorized over the member axis — is
`corrosion_tpu.ops.swim`; parity between the two (convergence windows,
failure-detection latency, no false positives under loss) is asserted in
`tests/test_swim_parity.py`, which also pins the sharded↔unsharded
equivalence of the kernel.

Config scaling mirrors `foca::Config::new_wan` as applied at
`broadcast/mod.rs:951-960`: probe cadence and suspicion windows grow with
log(cluster size), packets stay ≤1178 B.
"""

from __future__ import annotations

import asyncio
import contextlib
import math
import random
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple

from corrosion_tpu.net.gossip_codec import (
    MAX_PACKET,
    MemberState,
    MemberUpdate,
    MsgKind,
    SwimMessage,
    actor_wire_size,
    decode_swim,
    encode_swim,
    fill_updates,
    update_wire_size,
)
from corrosion_tpu.net.transport import Transport, TransportError
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.tripwire import Tripwire
from corrosion_tpu.types.actor import Actor, ActorId


@dataclass
class SwimConfig:
    probe_period: float = 1.0
    probe_rtt: float = 0.4  # wait for a direct ack
    num_indirect_probes: int = 3
    suspicion_mult: float = 4.0  # suspect window = mult * log2(n+2) * period
    max_transmissions_base: int = 10  # scaled down for big clusters
    # carrier-budget multiplier for DOWN updates: a DOWN that goes
    # extinct before full coverage costs a straggler its entire
    # self-discovery round (own probe-ring pass + suspicion window —
    # measured 13-20 periods vs ~8 cluster-wide at n=8 with mult 1;
    # tail gone over 20 trials at mult 3). The batched kernel closes
    # the same hole with its anti-entropy tail pushes
    # (ops/swim.py `antientropy`); the full agent additionally repairs
    # via the steady announce/feed loop (run.py)
    down_transmissions_mult: int = 3
    remove_down_after: float = 48 * 3600.0  # broadcast/mod.rs:953
    announce_backoff_start: float = 5.0
    announce_backoff_max: float = 120.0
    announce_steady_period: float = 300.0
    # ---- Lifeguard (r9, arXiv:1707.00788) --------------------------------
    # Off by default: the vanilla timings above are what every existing
    # timing-sensitive test and the batched kernels' default mode pin.
    lifeguard: bool = False
    lhm_max: int = 8  # Local Health Multiplier score ceiling: probe
    # period, ack waits and suspicion windows scale by (1 + score)
    susp_ceiling: float = 3.0  # a suspect's window OPENS at
    # susp_ceiling * suspect_timeout(n) and shrinks toward the floor as
    # independent peers confirm the suspicion
    susp_k: int = 3  # confirmers needed to shrink to the floor

    def suspect_timeout(self, n: int) -> float:
        return self.suspicion_mult * math.log2(n + 2) * self.probe_period

    def suspect_timeout_confirmed(self, n: int, confirmers: int) -> float:
        """Lifeguard LHA-Suspicion window for a suspect with
        `confirmers` INDEPENDENT suspectors (the suspecting peers we
        received the assertion from, ourselves included): starts at the
        ceiling, decays logarithmically to the plain `suspect_timeout`
        floor at susp_k confirmers — a lone (possibly sick) accuser
        leaves the target the whole ceiling to refute, a cluster-wide
        suspicion fires at the floor."""
        lo = self.suspect_timeout(n)
        if not self.lifeguard:
            return lo
        hi = lo * self.susp_ceiling
        k = max(1, self.susp_k)
        c = min(max(confirmers - 1, 0), k)
        return max(lo, hi - (hi - lo) * math.log2(c + 1) / math.log2(k + 1))

    def max_transmissions(self, n: int) -> int:
        # infection-style: O(log n) sends suffice; foca's new_wan keeps ~10
        return max(3, min(self.max_transmissions_base, int(math.log2(n + 2)) + 3))


class Notification(Enum):
    MEMBER_UP = "up"
    MEMBER_DOWN = "down"
    ACTIVE = "active"  # we joined / rejoined a cluster
    DEFUNCT = "defunct"  # our identity was declared down (pre-renew)


# precedence within one incarnation: Down > Suspect > Alive
_PREC = {MemberState.ALIVE: 0, MemberState.SUSPECT: 1, MemberState.DOWN: 2}


def _supersedes(
    new_state: MemberState, new_inc: int, old_state: MemberState, old_inc: int
) -> bool:
    """Standard SWIM update-precedence rule."""
    if new_inc != old_inc:
        return new_inc > old_inc
    return _PREC[new_state] > _PREC[old_state]


@dataclass
class _Member:
    actor: Actor
    incarnation: int = 0
    state: MemberState = MemberState.ALIVE
    state_since: float = field(default_factory=time.monotonic)
    # Lifeguard LHA-Suspicion: the distinct peers we received the
    # current suspicion from (ourselves included when we raised it) —
    # each independent confirmer shrinks the suspect→down window
    # (`SwimConfig.suspect_timeout_confirmed`). Reset on every state
    # transition.
    suspectors: set = field(default_factory=set)


@dataclass
class _Dissemination:
    update: MemberUpdate
    sends_left: int


@dataclass
class _Probe:
    target: Actor
    started: float
    indirect_sent: bool = False


class Membership:
    """One node's SWIM instance driving the three-way datagram dance."""

    def __init__(
        self,
        identity: Actor,
        transport: Transport,
        config: Optional[SwimConfig] = None,
        rng: Optional[random.Random] = None,
        on_notification: Optional[Callable[[Notification, Actor], None]] = None,
    ):
        self.identity = identity
        self.transport = transport
        self.config = config or SwimConfig()
        self.rng = rng or random.Random()
        self.on_notification = on_notification or (lambda n, a: None)
        self.members: Dict[ActorId, _Member] = {}
        self.downed: Dict[ActorId, float] = {}  # id -> when declared down
        # Lifeguard LHA-Probe: saturating local-health score in
        # [0, lhm_max]; +1 per missed ack / failed probe / hearing
        # ourselves suspected, -1 per acked probe. Timer multiplier is
        # (1 + score) — a node that is itself sick probes slower and
        # waits longer instead of falsely accusing healthy peers.
        self._lhm = 0
        # dissemination queue keyed by subject: one live assertion per
        # actor (a newer assertion replaces the queued one in O(1));
        # insertion order doubles as freshness order for _piggyback
        self._queue: Dict[ActorId, _Dissemination] = {}
        self._incarnation = 0
        self._probe_no = 0
        self._pending: Dict[int, _Probe] = {}
        self._probe_ring: List[ActorId] = []
        self._ring_set: set = set()  # O(1) membership for the hot add path
        self._probe_pos = 0
        self._tasks: List[asyncio.Task] = []
        # r12 cluster observatory hooks (agent/observatory.py): every
        # outgoing datagram offers its spare packet budget to
        # `digest_source(budget) -> encoded digest | None`, and every
        # received digest ext is handed to `on_digest(src, bytes)`.
        # Both default None — standalone Membership instances (tests,
        # sims) gossip exactly the pre-r12 bytes.
        self.digest_source: Optional[Callable[[int], Optional[bytes]]] = None
        self.on_digest: Optional[Callable[[str, bytes], None]] = None

    # -- public surface ----------------------------------------------------

    @property
    def lhm(self) -> int:
        """Current Local Health Multiplier score (0 = healthy)."""
        return self._lhm

    @property
    def lhm_multiplier(self) -> float:
        """Effective timer multiplier: 1 + score (1.0 with lifeguard
        off — every wait below multiplies by this unconditionally)."""
        if not self.config.lifeguard:
            return 1.0
        return 1.0 + min(self._lhm, self.config.lhm_max)

    def _lhm_bump(self, why: str) -> None:
        if not self.config.lifeguard:
            return
        if self._lhm < self.config.lhm_max:
            self._lhm += 1
        METRICS.gauge("corro.gossip.lhm").set(self._lhm)
        METRICS.counter("corro.gossip.lhm.bumped", why=why).inc()

    def _lhm_relax(self) -> None:
        if not self.config.lifeguard or self._lhm == 0:
            return
        self._lhm -= 1
        METRICS.gauge("corro.gossip.lhm").set(self._lhm)

    @property
    def cluster_size(self) -> int:
        # members never retains DOWN entries (every DOWN transition
        # deletes, _apply_update:278/308), so the active count is just
        # the dict size — this is on the per-update hot path during mass
        # absorption and an O(N) sum here made absorption quadratic
        return 1 + len(self.members)

    def active_members(self) -> List[Actor]:
        return [
            m.actor
            for m in self.members.values()
            if m.state != MemberState.DOWN
        ]

    def start(self, tripwire: Tripwire) -> None:
        self._tasks = [
            asyncio.ensure_future(self._probe_loop(tripwire)),
            asyncio.ensure_future(self._suspicion_loop(tripwire)),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await t

    async def announce(self, addr: str) -> None:
        """Join via a bootstrap address (handlers.rs:197-248)."""
        await self._send(addr, SwimMessage(MsgKind.ANNOUNCE, 0, self.identity))

    async def leave(self) -> None:
        """Graceful departure: tell peers we're down at our own incarnation
        (broadcast/mod.rs:327-366 leave_cluster)."""
        update = MemberUpdate(
            self.identity, self._incarnation, MemberState.DOWN
        )
        targets = self.active_members()
        self.rng.shuffle(targets)
        for actor in targets[: max(3, self.config.num_indirect_probes)]:
            msg = SwimMessage(
                MsgKind.LEAVE, 0, self.identity, updates=[update]
            )
            await self._send(actor.addr, msg)

    def apply_many(self, states: List[Tuple[Actor, int, MemberState]]) -> None:
        """Resurrect persisted member states on startup (util.rs:74-111)."""
        for actor, incarnation, state in states:
            self._apply_update(MemberUpdate(actor, incarnation, state))

    async def rejoin(self) -> Actor:
        """Operator-triggered full rejoin (admin Cluster Rejoin →
        FocaCmd::Rejoin, `klukai/src/admin.rs`): renew identity and
        re-announce to every active member."""
        self.identity = self.identity.renew()
        self._incarnation = 0
        self._disseminate(MemberUpdate(self.identity, 0, MemberState.ALIVE))
        for actor in self.active_members():
            await self.announce(actor.addr)
        return self.identity

    async def change_cluster_id(self, cluster_id) -> Actor:
        """Admin Cluster SetId → ChangeIdentity: same node id, new cluster.
        Peers in the old cluster will drop our datagrams from now on."""
        from dataclasses import replace

        self.identity = replace(
            self.identity.renew(), cluster_id=cluster_id
        )
        self._incarnation = 0
        self._disseminate(MemberUpdate(self.identity, 0, MemberState.ALIVE))
        return self.identity

    # -- sending -----------------------------------------------------------

    async def _send(self, addr: str, msg: SwimMessage) -> None:
        self._piggyback(msg)
        data = encode_swim(msg)
        if self.digest_source is not None:
            # offer the packet's remaining budget to the observatory;
            # the trailing ext keeps digest-free bytes byte-identical
            ext = self.digest_source(MAX_PACKET - len(data))
            if ext is not None:
                msg.digest = ext
                data = encode_swim(msg)
        try:
            await self.transport.send_datagram(addr, data)
            METRICS.counter("corro.gossip.message.sent", kind=msg.kind.name).inc()
        except TransportError:
            METRICS.counter("corro.gossip.send.failed").inc()

    def _piggyback(self, msg: SwimMessage) -> None:
        """Fill the remaining packet budget with queued updates, newest
        assertions first (infection-style dissemination: fresh updates
        have the most sends left — iterating insertion order backwards
        gives the same priority as the old sort without the O(Q log Q)
        per packet, and the fill stops at the packet budget instead of
        scanning the whole queue)."""
        budget = MAX_PACKET - 64 - actor_wire_size(msg.sender)
        if msg.target:
            budget -= actor_wire_size(msg.target)
        if msg.origin:
            budget -= actor_wire_size(msg.origin)
        if not self._queue:
            return
        spent: List[ActorId] = []
        for aid in reversed(list(self._queue)):
            d = self._queue[aid]
            size = update_wire_size(d.update)
            if budget - size < 0 or len(msg.updates) >= 64:
                break
            msg.updates.append(d.update)
            budget -= size
            d.sends_left -= 1
            if d.sends_left <= 0:
                spent.append(aid)
        for aid in spent:
            self._queue.pop(aid, None)

    def _disseminate(self, update: MemberUpdate) -> None:
        n = self.cluster_size
        sends = self.config.max_transmissions(n)
        if update.state == MemberState.DOWN:
            # deaths are rare and extinction of a DOWN is expensive;
            # see down_transmissions_mult in SwimConfig
            sends *= self.config.down_transmissions_mult
        # replace any queued assertion about the same actor (O(1): the
        # queue is keyed by subject), re-entering at the fresh end
        self._queue.pop(update.actor.id, None)
        self._queue[update.actor.id] = _Dissemination(update, sends)

    # -- update application -------------------------------------------------

    def _apply_update(
        self, u: MemberUpdate, via: Optional[ActorId] = None
    ) -> bool:
        """Merge one membership assertion; True if it changed our view.
        `via` names the peer the assertion arrived from — Lifeguard's
        independent-confirmer signal (SWIM updates carry no origin, so
        the forwarding peer is the independence proxy)."""
        if u.actor.id == self.identity.id:
            return self._apply_self_update(u)
        cur = self.members.get(u.actor.id)
        # LHA-Suspicion confirmation: a suspect assertion about an
        # already-suspect member does NOT supersede (equal precedence)
        # but a new distinct peer asserting it shrinks the window
        if (
            self.config.lifeguard
            and via is not None
            and u.state == MemberState.SUSPECT
            and cur is not None
            and cur.state == MemberState.SUSPECT
            and u.incarnation >= cur.incarnation
            and via not in cur.suspectors
        ):
            cur.suspectors.add(via)
            METRICS.counter("corro.gossip.suspicion.confirmed").inc()
        replaced_old: Optional[_Member] = None
        if cur is not None:
            cur_identity = (cur.actor.ts, cur.actor.bump)
            new_identity = (u.actor.ts, u.actor.bump)
            if new_identity < cur_identity:
                return False  # stale assertion about a renewed identity
            if new_identity > cur_identity:
                # renewed identity: brand-new member lifecycle
                replaced_old = cur
                cur = None
        if cur is None:
            if u.state == MemberState.DOWN:
                self.downed.setdefault(u.actor.id, time.monotonic())
                if replaced_old is not None:
                    # the renewed identity died: retire the stale record
                    del self.members[u.actor.id]
                    self._disseminate(u)
                    if replaced_old.state != MemberState.DOWN:
                        self.on_notification(
                            Notification.MEMBER_DOWN, u.actor
                        )
                        METRICS.counter("corro.gossip.member.removed").inc()
                    return True
                return False
            self.members[u.actor.id] = _Member(
                actor=u.actor, incarnation=u.incarnation, state=u.state
            )
            self.downed.pop(u.actor.id, None)
            if u.actor.id not in self._ring_set:
                self._probe_ring.append(u.actor.id)
                self._ring_set.add(u.actor.id)
            self._disseminate(u)
            # fires for renewed identities too: Members.add_member must
            # refresh to the new ts/bump
            self.on_notification(Notification.MEMBER_UP, u.actor)
            METRICS.counter("corro.gossip.member.added").inc()
            return True
        if not _supersedes(u.state, u.incarnation, cur.state, cur.incarnation):
            return False
        was_active = cur.state != MemberState.DOWN
        cur.actor = u.actor
        cur.incarnation = u.incarnation
        cur.state = u.state
        cur.state_since = time.monotonic()
        # fresh state transition: the confirmer set restarts (a NEW
        # suspicion epoch begins with just the asserting peer)
        cur.suspectors = (
            {via} if (u.state == MemberState.SUSPECT and via is not None)
            else set()
        )
        self._disseminate(u)
        if u.state == MemberState.DOWN:
            del self.members[u.actor.id]
            self.downed[u.actor.id] = time.monotonic()
            if was_active:
                self.on_notification(Notification.MEMBER_DOWN, u.actor)
                METRICS.counter("corro.gossip.member.removed").inc()
        return True

    def _apply_self_update(self, u: MemberUpdate) -> bool:
        """Refute suspicion; renew identity if declared down (actor.rs:199)."""
        if (u.actor.ts, u.actor.bump) < (self.identity.ts, self.identity.bump):
            return False  # about an identity we already renewed past
        if u.state == MemberState.SUSPECT and u.incarnation >= self._incarnation:
            # hearing ourselves suspected is direct evidence our own
            # timers/replies are running late (Lifeguard LHA-Probe)
            self._lhm_bump("self_suspected")
            self._incarnation = u.incarnation + 1
            self._disseminate(
                MemberUpdate(
                    self.identity, self._incarnation, MemberState.ALIVE
                )
            )
            METRICS.counter("corro.gossip.self.refuted").inc()
            return True
        if u.state == MemberState.DOWN and u.incarnation >= self._incarnation:
            self.on_notification(Notification.DEFUNCT, self.identity)
            self.identity = self.identity.renew()
            self._incarnation = 0
            self._disseminate(
                MemberUpdate(self.identity, 0, MemberState.ALIVE)
            )
            self.on_notification(Notification.ACTIVE, self.identity)
            METRICS.counter("corro.gossip.self.renewed").inc()
            return True
        return False

    # -- inbound -----------------------------------------------------------

    async def handle_datagram(self, src: str, data: bytes) -> None:
        try:
            msg = decode_swim(data)
        except (ValueError, IndexError):
            METRICS.counter("corro.gossip.decode.failed").inc()
            return
        if msg.sender.cluster_id != self.identity.cluster_id:
            return
        if msg.sender.id != self.identity.id:
            self._apply_update(
                MemberUpdate(msg.sender, 0, MemberState.ALIVE)
            )
        for u in msg.updates:
            self._apply_update(u, via=msg.sender.id)
        if msg.digest is not None and self.on_digest is not None:
            self.on_digest(src, msg.digest)

        k, me = msg.kind, self.identity
        if k == MsgKind.PING:
            await self._send(
                msg.sender.addr, SwimMessage(MsgKind.ACK, msg.probe_no, me)
            )
        elif k == MsgKind.ACK:
            self._on_ack(msg.probe_no, msg.sender)
        elif k == MsgKind.PING_REQ and msg.target is not None:
            await self._send(
                msg.target.addr,
                SwimMessage(
                    MsgKind.INDIRECT_PING,
                    msg.probe_no,
                    me,
                    target=msg.target,
                    origin=msg.sender,
                ),
            )
        elif k == MsgKind.INDIRECT_PING and msg.origin is not None:
            await self._send(
                msg.sender.addr,
                SwimMessage(
                    MsgKind.INDIRECT_ACK,
                    msg.probe_no,
                    me,
                    origin=msg.origin,
                ),
            )
        elif k == MsgKind.INDIRECT_ACK and msg.origin is not None:
            await self._send(
                msg.origin.addr,
                SwimMessage(
                    MsgKind.FORWARDED_ACK,
                    msg.probe_no,
                    me,
                    target=msg.sender,
                ),
            )
        elif k == MsgKind.FORWARDED_ACK:
            acked = msg.target or msg.sender
            self._on_ack(msg.probe_no, acked)
        elif k == MsgKind.ANNOUNCE:
            await self._on_announce(msg.sender)
        elif k == MsgKind.FEED:
            pass  # updates already applied above
        elif k == MsgKind.LEAVE:
            pass  # the DOWN update rode in msg.updates

    async def _on_announce(self, joiner: Actor) -> None:
        """Reply with a membership snapshot that fits one packet."""
        self._disseminate(MemberUpdate(joiner, 0, MemberState.ALIVE))
        feed = SwimMessage(MsgKind.FEED, 0, self.identity)
        sample = [
            MemberUpdate(m.actor, m.incarnation, m.state)
            for m in self.members.values()
            if m.actor.id != joiner.id
        ]
        self.rng.shuffle(sample)
        fill_updates(feed, sample)
        await self.transport.send_datagram(joiner.addr, encode_swim(feed))

    def _on_ack(self, probe_no: int, from_actor: Actor) -> None:
        probe = self._pending.get(probe_no)
        if probe is None or probe.target.id != from_actor.id:
            return
        del self._pending[probe_no]
        rtt = time.monotonic() - probe.started
        self.transport.observe_rtt(probe.target.addr, rtt)
        self._lhm_relax()  # a completed probe round: health evidence
        m = self.members.get(from_actor.id)
        if m is not None and m.state == MemberState.SUSPECT:
            # direct evidence of life clears our own suspicion
            self._apply_update(
                MemberUpdate(m.actor, m.incarnation + 1, MemberState.ALIVE)
            )

    # -- probe cycle ---------------------------------------------------------

    def _next_probe_target(self) -> Optional[Actor]:
        # departed members are skipped inline and compacted out once per
        # ring cycle — rebuilding the whole ring per probe was O(N) on
        # the probe cadence
        while self._probe_ring:
            if self._probe_pos >= len(self._probe_ring):
                self._probe_ring = [
                    aid for aid in self._probe_ring if aid in self.members
                ]
                self._ring_set = set(self._probe_ring)
                self._probe_pos = 0
                if not self._probe_ring:
                    return None
                self.rng.shuffle(self._probe_ring)
                continue
            actor_id = self._probe_ring[self._probe_pos]
            self._probe_pos += 1
            m = self.members.get(actor_id)
            if m is not None and m.state != MemberState.DOWN:
                return m.actor
        return None

    async def _probe_loop(self, tripwire: Tripwire) -> None:
        cfg = self.config
        while not tripwire.tripped:
            # LHA-Probe: a sick node (high LHM) probes SLOWER — its own
            # lateness would otherwise read as everyone else's failure
            # (multiplier is 1.0 with lifeguard off)
            await asyncio.sleep(cfg.probe_period * self.lhm_multiplier)
            target = self._next_probe_target()
            if target is None:
                continue
            self._probe_no += 1
            probe_no = self._probe_no
            self._pending[probe_no] = _Probe(target, time.monotonic())
            msg = SwimMessage(MsgKind.PING, probe_no, self.identity)
            if cfg.lifeguard:
                # LHA-Refute buddy system: if we hold the target as
                # SUSPECT, tell it IN the ping — it refutes immediately
                # instead of waiting for the rumor to gossip its way
                # around (the ping already flows; zero extra packets)
                m = self.members.get(target.id)
                if m is not None and m.state == MemberState.SUSPECT:
                    msg.updates.append(
                        MemberUpdate(
                            m.actor, m.incarnation, MemberState.SUSPECT
                        )
                    )
                    METRICS.counter("corro.gossip.buddy.notified").inc()
            await self._send(target.addr, msg)
            asyncio.ensure_future(self._probe_escalation(probe_no))

    async def _probe_escalation(self, probe_no: int) -> None:
        cfg = self.config
        # ack windows stretch with our OWN health score: if we are the
        # slow one, the ack is probably sitting in our queue already
        await asyncio.sleep(cfg.probe_rtt * self.lhm_multiplier)
        probe = self._pending.get(probe_no)
        if probe is None:
            return  # acked
        probe.indirect_sent = True
        self._lhm_bump("direct_miss")
        target = probe.target
        helpers = [
            m.actor
            for m in self.members.values()
            if m.state == MemberState.ALIVE and m.actor.id != target.id
        ]
        self.rng.shuffle(helpers)
        for helper in helpers[: cfg.num_indirect_probes]:
            await self._send(
                helper.addr,
                SwimMessage(
                    MsgKind.PING_REQ,
                    probe_no,
                    self.identity,
                    target=target,
                ),
            )
        await asyncio.sleep(2 * cfg.probe_rtt * self.lhm_multiplier)
        probe = self._pending.pop(probe_no, None)
        if probe is None:
            return  # indirectly acked
        self._lhm_bump("probe_failed")
        m = self.members.get(target.id)
        if m is not None and m.state == MemberState.ALIVE:
            self._apply_update(
                MemberUpdate(m.actor, m.incarnation, MemberState.SUSPECT),
                via=self.identity.id,
            )
            METRICS.counter("corro.gossip.member.suspected").inc()

    async def _suspicion_loop(self, tripwire: Tripwire) -> None:
        """Expire suspects to Down; forget long-Down members."""
        cfg = self.config
        while not tripwire.tripped:
            await asyncio.sleep(cfg.probe_period)
            now = time.monotonic()
            n = self.cluster_size
            # per-suspect Lifeguard window: ceiling shrunk by that
            # suspect's independent confirmer count, stretched by our
            # OWN health multiplier (with lifeguard off both collapse
            # to the vanilla fixed suspect_timeout)
            mult = self.lhm_multiplier
            expired = [
                m
                for m in self.members.values()
                if m.state == MemberState.SUSPECT
                and now - m.state_since
                > cfg.suspect_timeout_confirmed(
                    n, max(1, len(m.suspectors))
                ) * mult
            ]
            for m in expired:
                self._apply_update(
                    MemberUpdate(m.actor, m.incarnation, MemberState.DOWN)
                )
            cutoff = now - cfg.remove_down_after
            self.downed = {
                aid: t for aid, t in self.downed.items() if t > cutoff
            }

"""Agent assembly and lifecycle: setup → run → shutdown.

Counterparts:
  - `setup()` (`klukai-agent/src/agent/setup.rs:74-289`): open the store,
    derive the actor identity from the site id, apply schema files, bind
    the gossip endpoint, create the channel graph from PerfConfig, warm
    the bookie from durable state.
  - `start_with_config`/`run` (`agent/run_root.rs:32-234`): wire the SWIM
    loop, broadcast loop, ingestion loop, apply loop, sync loop, gossip
    server handlers and announcers, then hand back the Agent handle.
  - local write path `make_broadcastable_changes`
    (`api/public/mod.rs:57-258`) + `broadcast_changes`
    (`klukai-types/src/broadcast.rs:605-675`).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import random
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from corrosion_tpu.agent.broadcast import broadcast_loop
from corrosion_tpu.agent.handle import Agent, BroadcastInput, ChangeSource
from corrosion_tpu.agent.ingest import (
    apply_fully_buffered_loop,
    handle_changes,
)
from corrosion_tpu.agent.members import Members
from corrosion_tpu.agent.membership import (
    Membership,
    Notification,
    SwimConfig,
)
from corrosion_tpu.agent.syncer import serve_sync, sync_loop
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.net.tcp import TcpListener, TcpTransport, split_addr
from corrosion_tpu.net.transport import BiStream
from corrosion_tpu.runtime.channels import bounded
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.tripwire import TaskTracker, Tripwire

log = logging.getLogger(__name__)
from corrosion_tpu.store.bookkeeping import Bookie
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import Actor, ClusterId
from corrosion_tpu.types.base import HLClock, Timestamp
from corrosion_tpu.types.change import ChangeV1, ChangesetFull, chunk_changes
from corrosion_tpu.types.codec import decode_uni_payload
from corrosion_tpu.types.rangeset import RangeSet


async def setup(
    config: Config,
    network: Optional[MemNetwork] = None,
    tripwire: Optional[Tripwire] = None,
) -> Agent:
    tripwire = tripwire or Tripwire()
    store = CrdtStore(config.db.path)
    for schema_path in config.db.schema_paths:
        sql = Path(schema_path).read_text()
        store.apply_schema_sql(sql)
    clock = HLClock()

    if network is not None:
        addr = config.gossip.bind_addr
        listener = network.listener(addr)
        transport = network.transport(addr)
    elif config.gossip.transport == "quic":
        # plaintext QUIC, the reference's native gossip plane
        # (quinn_plaintext.rs:23-35): datagram/uni/bi lanes on one UDP
        # socket. TLS-QUIC would need a TLS 1.3 handshake stack; this
        # build pairs QUIC with the plaintext session only, so the
        # secured path stays on the TCP/TLS lanes.
        if not config.gossip.plaintext:
            raise ValueError(
                "gossip.transport = 'quic' supports plaintext mode only "
                "(set gossip.plaintext = true, or use the tcp transport "
                "with [gossip.tls])"
            )
        from corrosion_tpu.net.quic import MAX_UDP, QuicEndpoint, QuicTransport

        host, port = split_addr(config.gossip.bind_addr)
        # parse BEFORE binding anything: a malformed client_addr must
        # not leave the gossip socket bound behind a config error
        c_host, c_port = split_addr(config.gossip.client_addr or ":0")
        mtu = min(config.gossip.max_mtu or MAX_UDP, MAX_UDP)
        listener = await QuicEndpoint.bind(
            host or "127.0.0.1", port,
            # gossip.max_mtu (api/peer/mod.rs:121-150 fixed-MTU knob)
            mtu=mtu,
        )
        # outbound spread (transport.rs:57-71): 8 hashed dial-only
        # sockets when client_addr's port is 0 (the default), 1 when an
        # operator pinned a port
        n_client = 8 if c_port == 0 else 1
        client_eps = []
        try:
            for _ in range(n_client):
                client_eps.append(await QuicEndpoint.bind(
                    c_host or host or "127.0.0.1", c_port,
                    mtu=mtu, accept_inbound=False,
                ))
        except OSError:
            # e.g. a pinned client_addr port already in use: release
            # everything bound so far or a setup() retry hits EADDRINUSE
            # on our own gossip port
            for ep in client_eps:
                await ep.close()
            await listener.close()
            raise
        transport = QuicTransport(
            listener, idle_timeout=float(config.gossip.idle_timeout_secs),
            client_endpoints=client_eps,
        )
    elif config.gossip.transport != "tcp":
        raise ValueError(
            f"unknown gossip.transport {config.gossip.transport!r} "
            "(expected 'tcp' or 'quic')"
        )
    else:
        host, port = split_addr(config.gossip.bind_addr)
        server_ctx = client_ctx = None
        if not config.gossip.plaintext:
            # secured gossip plane (peer/mod.rs:152-373): plaintext stays
            # the explicit opt-in via gossip.plaintext = true. An operator
            # who turned plaintext OFF with a broken/missing [gossip.tls]
            # gets an error here — NEVER a silent plaintext fallback
            from corrosion_tpu.tls import build_ssl_contexts

            server_ctx, client_ctx = build_ssl_contexts(config.gossip.tls)
        listener = await TcpListener.bind(
            host or "127.0.0.1", int(port), ssl_context=server_ctx
        )
        transport = TcpTransport(
            listener, ssl_context=client_ctx,
            idle_timeout=float(config.gossip.idle_timeout_secs),
        )

    gossip_addr = config.gossip.external_addr or listener.addr
    actor = Actor(
        id=store.site_id,
        addr=gossip_addr,
        ts=clock.new_timestamp(),
        cluster_id=ClusterId(config.gossip.cluster_id),
    )

    perf = config.perf
    tx_bcast, rx_bcast = bounded(perf.bcast_channel_len, "broadcast")
    tx_changes, rx_changes = bounded(perf.changes_channel_len, "changes")
    tx_apply, rx_apply = bounded(perf.apply_channel_len, "apply")

    members = Members()
    membership = Membership(
        actor,
        transport,
        SwimConfig(),
        rng=random.Random(actor.id.bytes16[:8].hex()),
    )
    transport.set_rtt_sink(members.observe_rtt)

    # instrumented-lock registry: bookie guards register here so the admin
    # `locks` command shows live holds (agent.rs:707-1066)
    from corrosion_tpu.runtime.locks import LockRegistry

    lock_registry = LockRegistry()
    bookie = Bookie(registry=lock_registry)
    for aid in store.booked_actor_ids():
        bookie.insert(aid, store.load_booked_versions(aid))

    agent = Agent(
        lock_registry=lock_registry,
        actor=actor,
        config=config,
        store=store,
        bookie=bookie,
        clock=clock,
        members=members,
        membership=membership,
        transport=transport,
        listener=listener,
        tripwire=tripwire,
        tracker=TaskTracker(),
        tx_bcast=tx_bcast,
        rx_bcast=rx_bcast,
        tx_changes=tx_changes,
        rx_changes=rx_changes,
        tx_apply=tx_apply,
        rx_apply=rx_apply,
    )

    # live-query + raw-update engines fed from every committed batch
    from corrosion_tpu.pubsub import SubsManager, UpdatesManager

    agent.subs = SubsManager(store, config.db.subscriptions_path)
    agent.updates = UpdatesManager(store)
    agent.change_hooks.append(agent.subs.match_changes)
    agent.change_hooks.append(agent.updates.match_changes)

    # SWIM notifications keep the member view current (handlers.rs:283-373)
    def on_notification(note: Notification, peer: Actor) -> None:
        if note == Notification.MEMBER_UP:
            agent.members.add_member(peer)
        elif note == Notification.MEMBER_DOWN:
            agent.members.remove_member(peer)
        elif note == Notification.ACTIVE and peer.id == agent.actor.id:
            agent.actor = peer  # renewed identity after being declared down

    membership.on_notification = on_notification
    return agent


async def run(agent: Agent) -> None:
    """Start every loop; returns immediately (tasks run until tripwire)."""

    async def on_datagram(src: str, data: bytes) -> None:
        await agent.membership.handle_datagram(src, data)

    async def on_uni(src: str, frame: bytes) -> None:
        try:
            cv, cluster_id = decode_uni_payload(frame)
        except (ValueError, IndexError):
            METRICS.counter("corro.agent.uni.decode.failed").inc()
            return
        if cluster_id != agent.cluster_id:
            return
        if cv.actor_id == agent.actor_id:
            return  # our own broadcast reflected back
        agent.tx_changes.try_send((cv, ChangeSource.BROADCAST))

    async def on_bi(stream: BiStream) -> None:
        await serve_sync(agent, stream)

    agent.listener.serve(on_datagram, on_uni, on_bi)
    agent.membership.start(agent.tripwire)
    if agent.subs is not None:
        await agent.subs.restore()  # setup.rs:296-349
    t = agent.tracker
    t.spawn(handle_changes(agent))
    t.spawn(apply_fully_buffered_loop(agent))
    t.spawn(broadcast_loop(agent))
    t.spawn(sync_loop(agent))
    t.spawn(_watchdog(agent))
    # member-state persistence + restart resurrection
    # (broadcast/mod.rs:814-949, util.rs:74-179)
    from corrosion_tpu.agent.member_store import (
        member_states_loop,
        resurrect_and_schedule_rejoin,
    )

    t.spawn(member_states_loop(agent))
    t.spawn(resurrect_and_schedule_rejoin(agent))
    t.spawn(_announcer(agent))
    # db maintenance: WAL truncate ladder + incremental vacuum
    # (handlers.rs:379-547) — this is what makes perf.wal_threshold_gb live
    from corrosion_tpu.store.maintenance import vacuum_loop, wal_maintenance_loop

    t.spawn(wal_maintenance_loop(agent))
    t.spawn(vacuum_loop(agent))
    # periodic per-table/gap/membership gauges (metrics.rs:18-108)
    from corrosion_tpu.agent.agent_metrics import metrics_loop

    t.spawn(metrics_loop(agent))
    # event-loop lag/task gauges — tokio-metrics analog (agent.rs:29-63)
    from corrosion_tpu.runtime import loopmon

    loopmon.start(t, agent.tripwire)
    # schedule fully-buffered applies for partials already complete on disk
    for actor_id, booked in agent.bookie.items().items():
        with booked.read() as bv:
            done = [v for v, p in bv.partials.items() if p.is_complete()]
        for version in done:
            agent.tx_apply.try_send((actor_id, version))


async def _watchdog(agent: Agent) -> None:
    """Lock-registry watchdog (setup.rs:188-246); ends on tripwire."""
    task = asyncio.ensure_future(agent.lock_registry.watchdog())
    await agent.tripwire.wait()
    task.cancel()
    with contextlib.suppress(asyncio.CancelledError):
        await task


async def _announcer(agent: Agent) -> None:
    """Announce to resolved bootstrap addresses with FULL-JITTER backoff
    5 s → 120 s, then a steady 300 s re-announce (handlers.rs:197-248).
    Full jitter (runtime/backoff.py r9) instead of the old deterministic
    doubling: after a partition heal every isolated node's announce
    timer used to fire in the same beat — a synchronized rejoin storm at
    exactly the moment the survivors are busiest.  Bootstrap entries
    support `host:port[@dns_server]` (bootstrap.rs:60-156); an empty
    bootstrap list falls back to up to 5 random persisted members
    (bootstrap.rs:29-50)."""
    from corrosion_tpu.agent.member_store import stored_bootstrap_addrs
    from corrosion_tpu.net.dns import resolve_bootstrap
    from corrosion_tpu.runtime.backoff import Backoff

    cfg = agent.membership.config

    def fresh_backoff():
        return iter(Backoff(
            min_interval=cfg.announce_backoff_start,
            max_interval=cfg.announce_backoff_max,
            factor=2.0, mode="full", retries=None,
        ))

    boff = fresh_backoff()
    while not agent.tripwire.tripped:
        if agent.config.gossip.bootstrap:
            addrs = await resolve_bootstrap(agent.config.gossip.bootstrap)
            if not addrs:
                log.warning(
                    "bootstrap list %r resolved to no addresses",
                    agent.config.gossip.bootstrap,
                )
        else:
            # no list configured: fall back to persisted members
            addrs = await asyncio.to_thread(
                stored_bootstrap_addrs, agent.store
            )
        for addr in addrs:
            if addr != agent.actor.addr:
                await agent.membership.announce(addr)
        if len(agent.members) > 0:
            delay = cfg.announce_steady_period
            # membership regained: the NEXT isolation restarts the
            # jittered ramp from the bottom instead of resuming capped
            boff = fresh_backoff()
        else:
            # floor keeps full jitter from hot-looping announces when
            # the draw lands near zero
            delay = max(0.05, next(boff))
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(agent.tripwire.wait(), delay)


async def shutdown(agent: Agent) -> None:
    """Graceful: leave the cluster, trip, drain counted tasks ≤60 s."""
    with contextlib.suppress(Exception):
        await agent.membership.leave()
    agent.tripwire.trip()
    if agent.subs is not None:
        await agent.subs.stop_all()
    if agent.updates is not None:
        await agent.updates.stop_all()
    agent.tx_changes.close()
    agent.tx_bcast.close()
    agent.tx_apply.close()
    await agent.membership.stop()
    await agent.tracker.wait_all(timeout=60.0)
    await agent.transport.close()
    await agent.listener.close()
    agent.store.close()


# -- local write path ------------------------------------------------------


@dataclass
class ExecResult:
    rows_affected: int
    results: List[object]
    version: int  # db_version assigned (0 = no changes)


async def make_broadcastable_changes(
    agent: Agent, fn: Callable[["object"], List[object]]
) -> ExecResult:
    """Run local statements in one write tx, then broadcast the committed
    changes (the `/v1/transactions` path, api/public/mod.rs:57-258).

    `fn(tx)` executes statements against the WriteTx and returns
    per-statement results.
    """
    # local client writes take the PRIORITY lane (agent.rs:586)
    async with agent.write_gate.priority():
        ts = agent.clock.new_timestamp()
        booked = agent.bookie.ensure(agent.actor_id)

        def txn() -> Tuple[List[object], list, int, int]:
            with booked.write("make_broadcastable_changes"):
                with agent.store.write_tx(ts) as tx:
                    results = fn(tx)
                    changes, db_version, last_seq = tx.commit()
                if db_version:
                    agent.store.record_last_seq(
                        agent.actor_id, db_version, last_seq
                    )
                with booked.write("commit bookkeeping") as bv:
                    if db_version:
                        snap = bv.snapshot()
                        snap.insert_db(
                            agent.store.gap_store(),
                            RangeSet([(db_version, db_version)]),
                        )
                        bv.commit_snapshot(snap)
                return results, changes, db_version, last_seq

        results, changes, db_version, last_seq = await asyncio.to_thread(txn)

    if changes:
        agent.notify_change_hooks(changes)
        for chunk, seqs in chunk_changes(changes, last_seq):
            cv = ChangeV1(
                actor_id=agent.actor_id,
                changeset=ChangesetFull(
                    version=db_version,
                    changes=tuple(chunk),
                    seqs=seqs,
                    last_seq=last_seq,
                    ts=ts,
                ),
            )
            await agent.tx_bcast.send(BroadcastInput(change=cv, is_local=True))
    rows = sum(r for r in _int_results(results))
    return ExecResult(rows_affected=rows, results=results, version=db_version)


def _int_results(results: List[object]):
    for r in results:
        if isinstance(r, int):
            yield r

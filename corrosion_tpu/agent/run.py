"""Agent assembly and lifecycle: setup → run → shutdown.

Counterparts:
  - `setup()` (`klukai-agent/src/agent/setup.rs:74-289`): open the store,
    derive the actor identity from the site id, apply schema files, bind
    the gossip endpoint, create the channel graph from PerfConfig, warm
    the bookie from durable state.
  - `start_with_config`/`run` (`agent/run_root.rs:32-234`): wire the SWIM
    loop, broadcast loop, ingestion loop, apply loop, sync loop, gossip
    server handlers and announcers, then hand back the Agent handle.
  - local write path `make_broadcastable_changes`
    (`api/public/mod.rs:57-258`) + `broadcast_changes`
    (`klukai-types/src/broadcast.rs:605-675`).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import random
import threading
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from corrosion_tpu.agent.broadcast import broadcast_loop
from corrosion_tpu.agent.handle import Agent, BroadcastInput, ChangeSource
from corrosion_tpu.agent.ingest import (
    apply_fully_buffered_loop,
    handle_changes,
)
from corrosion_tpu.agent.members import Members
from corrosion_tpu.agent.membership import (
    Membership,
    Notification,
    SwimConfig,
)
from corrosion_tpu.agent.syncer import serve_sync, sync_loop
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.net.tcp import TcpListener, TcpTransport, split_addr
from corrosion_tpu.net.transport import BiStream
from corrosion_tpu.runtime import profiler as _rt_profiler
from corrosion_tpu.runtime.channels import bounded
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.tripwire import TaskTracker, Tripwire

log = logging.getLogger(__name__)
from corrosion_tpu.store.bookkeeping import Bookie
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import Actor, ClusterId
from corrosion_tpu.types.base import HLClock, Timestamp
from corrosion_tpu.types.codec import chunked_change_v1, decode_uni_payload_ext
from corrosion_tpu.types.rangeset import RangeSet


async def setup(
    config: Config,
    network: Optional[MemNetwork] = None,
    tripwire: Optional[Tripwire] = None,
) -> Agent:
    tripwire = tripwire or Tripwire()

    def _boot_store() -> CrdtStore:
        # sqlite open + schema file reads + declarative re-apply are
        # all blocking I/O; a caller embedding setup() next to live
        # traffic (devcluster scale-up, tests with a running loop) must
        # not stall its event loop for the duration of a schema apply
        store = CrdtStore(config.db.path)
        # r15: [perf] direct_capture gates the in-memory write capture
        # (CORRO_CAPTURE=trigger overrides per process)
        store.direct_capture = config.perf.direct_capture
        # the canary table is system-owned (created at runtime by the
        # SLO canary probe, r11) and never appears in the user's schema
        # files: carry a persisted one through the declarative
        # re-apply, or a restart would be refused as a destructive
        # table drop
        canary_t = store.schema.tables.get(config.slo.canary_table)
        canary_ddl = (
            canary_t.raw_sql.rstrip(";") + ";" if canary_t else None
        )
        for schema_path in config.db.schema_paths:
            sql = Path(schema_path).read_text()
            if canary_ddl:
                sql = sql + "\n" + canary_ddl
            store.apply_schema_sql(sql)
        return store

    store = await asyncio.to_thread(_boot_store)
    clock = HLClock()

    if network is not None:
        addr = config.gossip.bind_addr
        listener = network.listener(addr)
        transport = network.transport(addr)
    elif config.gossip.transport == "quic":
        # plaintext QUIC, the reference's native gossip plane
        # (quinn_plaintext.rs:23-35): datagram/uni/bi lanes on one UDP
        # socket. TLS-QUIC would need a TLS 1.3 handshake stack; this
        # build pairs QUIC with the plaintext session only, so the
        # secured path stays on the TCP/TLS lanes.
        if not config.gossip.plaintext:
            raise ValueError(
                "gossip.transport = 'quic' supports plaintext mode only "
                "(set gossip.plaintext = true, or use the tcp transport "
                "with [gossip.tls])"
            )
        from corrosion_tpu.net.quic import MAX_UDP, QuicEndpoint, QuicTransport

        host, port = split_addr(config.gossip.bind_addr)
        # parse BEFORE binding anything: a malformed client_addr must
        # not leave the gossip socket bound behind a config error
        c_host, c_port = split_addr(config.gossip.client_addr or ":0")
        mtu = min(config.gossip.max_mtu or MAX_UDP, MAX_UDP)
        listener = await QuicEndpoint.bind(
            host or "127.0.0.1", port,
            # gossip.max_mtu (api/peer/mod.rs:121-150 fixed-MTU knob)
            mtu=mtu,
        )
        # outbound spread (transport.rs:57-71): 8 hashed dial-only
        # sockets when client_addr's port is 0 (the default), 1 when an
        # operator pinned a port
        n_client = 8 if c_port == 0 else 1
        client_eps = []
        try:
            for _ in range(n_client):
                client_eps.append(await QuicEndpoint.bind(
                    c_host or host or "127.0.0.1", c_port,
                    mtu=mtu, accept_inbound=False,
                ))
        except OSError:
            # e.g. a pinned client_addr port already in use: release
            # everything bound so far or a setup() retry hits EADDRINUSE
            # on our own gossip port
            for ep in client_eps:
                await ep.close()
            await listener.close()
            raise
        transport = QuicTransport(
            listener, idle_timeout=float(config.gossip.idle_timeout_secs),
            client_endpoints=client_eps,
        )
    elif config.gossip.transport != "tcp":
        raise ValueError(
            f"unknown gossip.transport {config.gossip.transport!r} "
            "(expected 'tcp' or 'quic')"
        )
    else:
        host, port = split_addr(config.gossip.bind_addr)
        server_ctx = client_ctx = None
        if not config.gossip.plaintext:
            # secured gossip plane (peer/mod.rs:152-373): plaintext stays
            # the explicit opt-in via gossip.plaintext = true. An operator
            # who turned plaintext OFF with a broken/missing [gossip.tls]
            # gets an error here — NEVER a silent plaintext fallback
            from corrosion_tpu.tls import build_ssl_contexts

            server_ctx, client_ctx = build_ssl_contexts(config.gossip.tls)
        listener = await TcpListener.bind(
            host or "127.0.0.1", int(port), ssl_context=server_ctx
        )
        transport = TcpTransport(
            listener, ssl_context=client_ctx,
            idle_timeout=float(config.gossip.idle_timeout_secs),
        )

    gossip_addr = config.gossip.external_addr or listener.addr
    actor = Actor(
        id=store.site_id,
        addr=gossip_addr,
        ts=clock.new_timestamp(),
        cluster_id=ClusterId(config.gossip.cluster_id),
    )

    perf = config.perf
    tx_bcast, rx_bcast = bounded(perf.bcast_channel_len, "broadcast")
    tx_changes, rx_changes = bounded(perf.changes_channel_len, "changes")
    tx_apply, rx_apply = bounded(perf.apply_channel_len, "apply")

    members = Members()
    membership = Membership(
        actor,
        transport,
        SwimConfig(),
        rng=random.Random(actor.id.bytes16[:8].hex()),
    )
    transport.set_rtt_sink(members.observe_rtt)

    # instrumented-lock registry: bookie guards register here so the admin
    # `locks` command shows live holds (agent.rs:707-1066)
    from corrosion_tpu.runtime.locks import LockRegistry

    lock_registry = LockRegistry()
    bookie = Bookie(registry=lock_registry)
    for aid in store.booked_actor_ids():
        bookie.insert(aid, store.load_booked_versions(aid))

    agent = Agent(
        lock_registry=lock_registry,
        actor=actor,
        config=config,
        store=store,
        bookie=bookie,
        clock=clock,
        members=members,
        membership=membership,
        transport=transport,
        listener=listener,
        tripwire=tripwire,
        tracker=TaskTracker(),
        tx_bcast=tx_bcast,
        rx_bcast=rx_bcast,
        tx_changes=tx_changes,
        rx_changes=rx_changes,
        tx_apply=tx_apply,
        rx_apply=rx_apply,
        # [sync] max_concurrent_snapshot_serves: the serve-side permit
        # pool is sized here, not in the dataclass default
        snapshot_serve_sem=asyncio.Semaphore(
            max(1, config.sync.max_concurrent_snapshot_serves)
        ),
    )

    # live-query + raw-update engines fed from every committed batch
    from corrosion_tpu.pubsub import SubsManager, UpdatesManager

    agent.subs = SubsManager(
        store,
        config.db.subscriptions_path,
        batch_wait=config.pubsub.candidate_batch_wait,
        cfg=config.subs,
    )
    agent.updates = UpdatesManager(store)

    # r11 SLO plane: per-stage latency objectives + error-budget burn
    from corrosion_tpu.runtime.latency import SloMonitor

    agent.slo = SloMonitor(
        targets=config.slo.targets,
        objective=config.slo.objective,
        window_secs=config.slo.window_secs,
        breach_checks=config.slo.breach_checks,
    )

    # r19 tail-based trace capture: stage spans buffer per-trace and
    # are kept only on error / SLO breach / lottery (tracestore.py).
    # Process-global like the metrics registry — the first agent's
    # config wins when several share a process (tests call configure()
    # directly for other knobs)
    if config.trace.enabled:
        from corrosion_tpu.runtime import tracestore

        tracestore.ensure(
            targets=config.slo.targets,
            lottery_n=config.trace.lottery_n,
            max_traces=config.trace.max_traces,
            max_spans_per_trace=config.trace.max_spans_per_trace,
            keep_max=config.trace.keep_max,
            idle_close_secs=config.trace.idle_close_secs,
        )

    # r20 alerting plane: the TSDB sampler thread is process-global
    # (first agent's [tsdb] knobs win, the tracestore rule); the rule
    # engine is per-agent so its health score reads THIS node's
    # membership LHM and its summaries ride THIS node's digests
    if config.tsdb.enabled and config.alerts.enabled:
        from corrosion_tpu.runtime import tsdb as _tsdb
        from corrosion_tpu.runtime.alerts import AlertEngine

        db = _tsdb.ensure(
            sample_interval_secs=config.tsdb.sample_interval_secs,
            slots=config.tsdb.slots,
            max_series=config.tsdb.max_series,
        )
        agent.alerts = AlertEngine(
            tsdb=db, cfg=config.alerts, agent=agent
        )
        # r22 remediation plane: always built beside the alert engine —
        # [remediation] enabled=false (the default kill-switch) keeps
        # it observe-only (typed "would_act" events, no actions), so
        # GET /v1/remediation audits the plane before anyone arms it
        from corrosion_tpu.agent.remediation import RemediationSupervisor

        agent.remediation = RemediationSupervisor(
            agent, cfg=config.remediation
        )

    # r23 continuous profiling plane: the wall-clock stack sampler is
    # process-global like the TSDB (first agent's [profile] knobs win);
    # the loop thread registers itself in run() so samples carry
    # subsystem;task prefixes
    if config.profile.enabled:
        _rt_profiler.ensure(
            hz=config.profile.hz,
            shed_hz=config.profile.shed_hz,
            max_overhead_pct=config.profile.max_overhead_pct,
            window_secs=config.profile.window_secs,
            slots=config.profile.slots,
            max_stacks=config.profile.max_stacks,
        )

    # r12 cluster observatory: telemetry digests piggyback the gossip
    # datagrams (hooks below) + broadcast envelopes (broadcast_loop);
    # received digests feed the anti-entropy store behind /v1/cluster
    if config.cluster.digests:
        from corrosion_tpu.agent.observatory import Observatory

        agent.observatory = Observatory(agent)
        membership.digest_source = agent.observatory.pick_ext
        membership.on_digest = (
            lambda _src, data: agent.observatory.receive(data)
        )
    agent.change_hooks.append(agent.subs.match_changes)
    agent.change_hooks.append(agent.updates.match_changes)

    # r14: local-commit group coalescer (concurrent writers share one
    # sqlite transaction; see GroupCommitter)
    agent.commit_group = GroupCommitter(agent)

    # SWIM notifications keep the member view current (handlers.rs:283-373)
    def on_notification(note: Notification, peer: Actor) -> None:
        if note == Notification.MEMBER_UP:
            agent.members.add_member(peer)
        elif note == Notification.MEMBER_DOWN:
            agent.members.remove_member(peer)
            if agent.membership.cluster_size <= 1:
                # SWIM view collapsed to self: wake the announcer NOW —
                # it may be mid-way through a 300 s steady-period sleep
                # chosen while the cluster was healthy (the r18 zombie
                # orphaning)
                agent.announce_wake.set()
        elif note == Notification.ACTIVE and peer.id == agent.actor.id:
            agent.actor = peer  # renewed identity after being declared down

    membership.on_notification = on_notification
    return agent


async def run(agent: Agent) -> None:
    """Start every loop; returns immediately (tasks run until tripwire)."""

    async def on_datagram(src: str, data: bytes) -> None:
        await agent.membership.handle_datagram(src, data)

    async def on_uni(src: str, frame: bytes) -> None:
        try:
            cv, cluster_id, dig = decode_uni_payload_ext(frame)
        except (ValueError, IndexError):
            METRICS.counter("corro.agent.uni.decode.failed").inc()
            return
        if cluster_id != agent.cluster_id:
            return
        if dig is not None and agent.observatory is not None:
            # r12: a telemetry digest rode the broadcast envelope ext —
            # adopt it even when the CHANGE is our own reflected back
            # (the relaying peer picked the digest, not the origin)
            agent.observatory.receive(dig)
        if cv.actor_id == agent.actor_id:
            return  # our own broadcast reflected back
        if cv.traceparent:
            # stitch the origin's span on the EAGER dissemination path
            # too (sync already adopts the SyncStart traceparent); the
            # traceparent stays ON the cv so a re-broadcast relays it.
            # stage="recv" buffers the hop marker with the trace in the
            # r19 tail sampler (which node saw the frame, at which hop)
            from corrosion_tpu.runtime.trace import continue_from, meta_hop

            with continue_from(
                cv.traceparent, "broadcast.recv", peer=src,
                stage="recv", actor=str(agent.actor_id),
                hop=meta_hop(cv.trace_meta),
            ):
                agent.tx_changes.try_send((cv, ChangeSource.BROADCAST))
        else:
            agent.tx_changes.try_send((cv, ChangeSource.BROADCAST))

    async def on_bi(stream: BiStream) -> None:
        await serve_sync(agent, stream)

    agent.listener.serve(on_datagram, on_uni, on_bi)
    agent.membership.start(agent.tripwire)
    # r23: register THIS loop thread with the continuous profiler so
    # its samples resolve the running asyncio task name (runs here, on
    # the loop thread, because the mapping is tid→loop)
    _prof = _rt_profiler.get()
    if _prof is not None:
        _prof.register_loop_coldpath()
    if agent.subs is not None:
        await agent.subs.restore()  # setup.rs:296-349
    t = agent.tracker
    t.spawn(handle_changes(agent))
    t.spawn(apply_fully_buffered_loop(agent))
    t.spawn(broadcast_loop(agent))
    t.spawn(sync_loop(agent))
    t.spawn(_watchdog(agent))
    # member-state persistence + restart resurrection
    # (broadcast/mod.rs:814-949, util.rs:74-179)
    from corrosion_tpu.agent.member_store import (
        member_states_loop,
        resurrect_and_schedule_rejoin,
    )

    t.spawn(member_states_loop(agent))
    t.spawn(resurrect_and_schedule_rejoin(agent))
    t.spawn(_announcer(agent))
    if agent.config.slo.canary:
        # opt-in end-to-end canary probe (r11): synthetic writes under a
        # self-subscription, continuously measuring true write→event
        # latency on the live cluster
        t.spawn(canary_loop(agent))
    if agent.observatory is not None:
        # r12: periodic digest build/dissemination + divergence checks
        from corrosion_tpu.agent.observatory import observatory_loop

        t.spawn(observatory_loop(agent))
    if agent.alerts is not None:
        # r20: rule evaluation over the TSDB (pending→firing→resolved)
        from corrosion_tpu.runtime.alerts import alerts_loop

        t.spawn(alerts_loop(agent))
    if agent.remediation is not None:
        # r22: the acting half — consume firings, drive actuators
        from corrosion_tpu.agent.remediation import remediation_loop

        t.spawn(remediation_loop(agent))
    # db maintenance: WAL truncate ladder + incremental vacuum
    # (handlers.rs:379-547) — this is what makes perf.wal_threshold_gb live
    from corrosion_tpu.store.maintenance import vacuum_loop, wal_maintenance_loop

    t.spawn(wal_maintenance_loop(agent))
    t.spawn(vacuum_loop(agent))
    # periodic per-table/gap/membership gauges (metrics.rs:18-108)
    from corrosion_tpu.agent.agent_metrics import metrics_loop

    t.spawn(metrics_loop(agent))
    # event-loop lag/task gauges — tokio-metrics analog (agent.rs:29-63)
    from corrosion_tpu.runtime import loopmon

    loopmon.start(t, agent.tripwire)
    # schedule fully-buffered applies for partials already complete on disk
    for actor_id, booked in agent.bookie.items().items():
        with booked.read() as bv:
            done = [v for v, p in bv.partials.items() if p.is_complete()]
        for version in done:
            agent.tx_apply.try_send((actor_id, version))


async def _watchdog(agent: Agent) -> None:
    """Lock-registry watchdog (setup.rs:188-246); ends on tripwire."""
    task = asyncio.ensure_future(agent.lock_registry.watchdog())
    await agent.tripwire.wait()
    task.cancel()
    with contextlib.suppress(asyncio.CancelledError):
        await task


async def _announcer(agent: Agent) -> None:
    """Announce to resolved bootstrap addresses with FULL-JITTER backoff
    5 s → 120 s, then a steady 300 s re-announce (handlers.rs:197-248).
    Full jitter (runtime/backoff.py r9) instead of the old deterministic
    doubling: after a partition heal every isolated node's announce
    timer used to fire in the same beat — a synchronized rejoin storm at
    exactly the moment the survivors are busiest.  Bootstrap entries
    support `host:port[@dns_server]` (bootstrap.rs:60-156); an empty
    bootstrap list falls back to up to 5 random persisted members
    (bootstrap.rs:29-50)."""
    from corrosion_tpu.agent.member_store import stored_bootstrap_addrs
    from corrosion_tpu.net.dns import resolve_bootstrap
    from corrosion_tpu.runtime.backoff import Backoff

    cfg = agent.membership.config

    def fresh_backoff():
        return iter(Backoff(
            min_interval=cfg.announce_backoff_start,
            max_interval=cfg.announce_backoff_max,
            factor=2.0, mode="full", retries=None,
        ))

    boff = fresh_backoff()
    while not agent.tripwire.tripped:
        if agent.config.gossip.bootstrap:
            addrs = await resolve_bootstrap(agent.config.gossip.bootstrap)
            if not addrs:
                log.warning(
                    "bootstrap list %r resolved to no addresses",
                    agent.config.gossip.bootstrap,
                )
        else:
            # no list configured: fall back to persisted members
            addrs = await asyncio.to_thread(
                stored_bootstrap_addrs, agent.store
            )
        for addr in addrs:
            if addr != agent.actor.addr:
                await agent.membership.announce(addr)
        if len(agent.members) > 0:
            delay = cfg.announce_steady_period
            # membership regained: the NEXT isolation restarts the
            # jittered ramp from the bottom instead of resuming capped
            boff = fresh_backoff()
        else:
            # floor keeps full jitter from hot-looping announces when
            # the draw lands near zero
            delay = max(0.05, next(boff))
        # sleep until delay, tripwire, OR the SWIM view collapsing to
        # self (announce_wake, set by on_notification): a steady-period
        # sleep chosen while healthy must not outlive the health it was
        # chosen under — the r18 zombie-node scenario caught an evicted
        # node sleeping silently through the rest of its 300 s period.
        # No wake is lost to the clear(): both the members check above
        # and this clear run without an intervening await, and
        # notifications only fire at await points.
        agent.announce_wake.clear()
        trip = asyncio.ensure_future(agent.tripwire.wait())
        wake = asyncio.ensure_future(agent.announce_wake.wait())
        try:
            await asyncio.wait(
                {trip, wake}, timeout=delay,
                return_when=asyncio.FIRST_COMPLETED,
            )
        finally:
            for t in (trip, wake):
                t.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await t


async def canary_loop(agent: Agent) -> None:
    """The SLO canary (r11): write one tiny synthetic row per interval
    to the canary table through the REAL public write path, watch it
    come back through a REAL self-subscription, and record the observed
    write→event latency — the ground-truth end-to-end measurement the
    per-stage `corro.e2e.*` histograms decompose.

    Every node keys its own row by actor id, so on a cluster each
    node's subscription also receives the OTHER nodes' canary updates:
    those measure true cross-node write→event latency from the origin
    wall stamp embedded in the row (scope="remote", skew-clamped).
    Each cycle also runs the agent's SloMonitor check, which is what
    arms the sustained-breach incident dump on a live cluster."""
    import time as _time

    from corrosion_tpu.pubsub.matcher import SubDead
    from corrosion_tpu.runtime.latency import e2e_observe
    from corrosion_tpu.runtime.records import FLIGHT

    cfg = agent.config.slo
    table = cfg.canary_table

    def ensure_table() -> None:
        # additive re-apply: the schema engine diffs declaratively, so
        # the canary table must be appended to the FULL current schema
        # (not applied alone — that would unregister the user's tables)
        if table in agent.store.schema.tables:
            return
        parts = []
        for t in agent.store.schema.tables.values():
            parts.append(t.raw_sql.rstrip(";") + ";")
            for idx in t.indexes.values():
                parts.append(idx.raw_sql.rstrip(";") + ";")
        parts.append(
            f'CREATE TABLE "{table}" (src TEXT NOT NULL PRIMARY KEY,'
            " n INTEGER, wall REAL);"
        )
        agent.store.apply_schema_sql("\n".join(parts))

    try:
        await asyncio.to_thread(ensure_table)
        handle, _created = await agent.subs.get_or_insert(
            f'SELECT src, n, wall FROM "{table}"'
        )
    except Exception:
        log.exception("canary disabled: table/subscription setup failed")
        return
    q = handle.attach()
    src = str(agent.actor_id)
    n = 0
    loop = asyncio.get_running_loop()
    try:
        while not agent.tripwire.tripped:
            n += 1
            wall = _time.time()
            try:
                await make_broadcastable_changes(
                    agent,
                    lambda tx: [
                        tx.execute(
                            f'INSERT OR REPLACE INTO "{table}"'
                            " (src, n, wall) VALUES (?, ?, ?)",
                            [src, n, wall],
                        )
                    ],
                )
            except Exception:
                METRICS.counter("corro.slo.canary.missed.total").inc()
                await asyncio.sleep(cfg.canary_interval_secs)
                continue
            METRICS.counter("corro.slo.canary.writes.total").inc()
            # drain subscription events until our own row's event lands
            # (or the wait budget elapses → a miss); remote canary rows
            # observed along the way measure cross-node latency
            deadline = loop.time() + max(2.0, cfg.canary_interval_secs)
            got = False
            while not got:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    item = await asyncio.wait_for(q.get(), timeout)
                except asyncio.TimeoutError:
                    break
                if item is None or isinstance(item, SubDead):
                    return  # subscription torn down: canary ends
                for ev in item:
                    vals = ev.values
                    if len(vals) < 3:
                        continue
                    if vals[0] == src:
                        if vals[1] == n:
                            lat = _time.time() - wall
                            e2e_observe("canary", lat, scope="local")
                            METRICS.gauge(
                                "corro.slo.canary.last.seconds"
                            ).set(lat)
                            FLIGHT.record_host_frame(
                                "canary",
                                {"lat_us": int(lat * 1e6), "remote": 0},
                            )
                            got = True
                    elif vals[2]:
                        lat = e2e_observe(
                            "canary",
                            _time.time() - float(vals[2]),
                            scope="remote",
                        )
                        FLIGHT.record_host_frame(
                            "canary",
                            {"lat_us": int(lat * 1e6), "remote": 1},
                        )
            if not got:
                METRICS.counter("corro.slo.canary.missed.total").inc()
            if agent.slo is not None:
                agent.slo.check()
            remain = (wall + cfg.canary_interval_secs) - _time.time()
            if remain > 0:
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(agent.tripwire.wait(), remain)
    finally:
        handle.detach(q)


async def shutdown(agent: Agent) -> None:
    """Graceful: leave the cluster, trip, drain counted tasks ≤60 s."""
    with contextlib.suppress(Exception):
        await agent.membership.leave()
    agent.tripwire.trip()
    if agent.subs is not None:
        await agent.subs.stop_all()
    if agent.updates is not None:
        await agent.updates.stop_all()
    agent.tx_changes.close()
    agent.tx_bcast.close()
    agent.tx_apply.close()
    await agent.membership.stop()
    await agent.tracker.wait_all(timeout=60.0)
    await agent.transport.close()
    await agent.listener.close()
    if agent.commit_group is not None:
        # r24: join the dedicated committer thread BEFORE the store
        # closes under it (an in-flight commit finishes; new submits
        # get a typed refusal instead of racing the close)
        agent.commit_group.close()
    agent.store.close()


# -- local write path ------------------------------------------------------


@dataclass
class ExecResult:
    rows_affected: int
    results: List[object]
    version: int  # db_version assigned (0 = no changes)


def _cancelled_error() -> BaseException:
    return asyncio.CancelledError("group leader cancelled before commit")


def _count_write_error(e: BaseException) -> None:
    """Typed store-fault accounting on the local write path: every
    sqlite-level writer failure (sick disk: SQLITE_BUSY, I/O errors;
    real or chaos-injected — both raise the same typed error) lands in
    `corro.store.write.errors.total{kind=}`, the series the
    `store-faults` alert rule (runtime/alerts.py) watches."""
    import sqlite3 as _sqlite3

    if not isinstance(e, _sqlite3.Error):
        return
    msg = str(e).lower()
    if "locked" in msg or "busy" in msg:
        kind = "busy"
    elif "i/o" in msg or "disk" in msg:
        kind = "io"
    else:
        kind = "other"
    METRICS.counter("corro.store.write.errors.total", kind=kind).inc()


def _pending_row_bytes(r) -> int:
    """Rough wire-size of one captured-cell row — (tbl, pk, cid, val)
    tuples since r15's in-memory direct capture (the group byte budget:
    Change.estimated_byte_size before the Change exists)."""
    val = r[3]
    return 48 + len(r[1]) + (
        len(val) if isinstance(val, (str, bytes)) else 8
    )


def _group_fanout_enabled(perf) -> bool:
    """r21 per-group fanout gate: `[perf] group_fanout` config, with the
    CORRO_GROUP_FANOUT env var overriding for bench A/B axes (mirrors
    CORRO_CAPTURE / CORRO_FINALIZE — the pre rung runs the per-tx
    post-commit path in the same process tree)."""
    env = os.environ.get("CORRO_GROUP_FANOUT")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off")
    return getattr(perf, "group_fanout", True)


def _committer_thread_enabled(perf) -> bool:
    """r24 dedicated-committer gate: `[perf] committer_thread` config,
    with the CORRO_COMMITTER env var overriding for bench A/B axes
    (mirrors CORRO_GROUP_FANOUT — `to_thread`/`0` restores the
    per-batch `asyncio.to_thread` hop as the r24 pre mode)."""
    env = os.environ.get("CORRO_COMMITTER")
    if env is not None:
        return env.strip().lower() not in (
            "0", "false", "no", "off", "to_thread"
        )
    return getattr(perf, "committer_thread", True)


class _CommitterThread:
    """One long-lived commit thread per store (r24, write-path round 4).

    The r14–r23 path paid one `asyncio.to_thread` per batch: an
    executor submit, a work-queue wakeup, a wrapper future and a
    context copy — measured as the `to_thread_hop`+`asyncio_dispatch`
    share (~37%) of the solo-writer wall in WRITE_PROFILE.json.  Here
    the leader hands the batch over lock-free — a plain `deque.append`
    (GIL-atomic) plus one `threading.Event` set — and parks on an
    asyncio future; the committer drains whole entries in one dequeue
    pass and resolves the parked future with a single
    `loop.call_soon_threadsafe` wakeup.

    Backpressure is unchanged by design: the leader still holds the
    priority write gate for the whole commit, so a wedged committer
    surfaces exactly like a wedged `to_thread` commit did — writers
    queue behind the gate and the existing admission machinery turns
    overload into typed refusals, never a new unbounded hang.  The
    thread is named `corro-committer` so the continuous profiler's
    `_NAME_TAGS` table classifies its samples under the `committer`
    subsystem."""

    def __init__(self, run: Callable):
        self._run = run  # _commit_batch: called on the thread, may raise
        self._q: deque = deque()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    def submit(self, loop, batch) -> asyncio.Future:
        """Enqueue one batch (event-loop thread only); returns the
        future the leader parks on.  Lazily starts the thread so agents
        on the `to_thread` path never own an idle thread."""
        import time as _time

        fut = loop.create_future()
        t = self._thread
        if t is None or not t.is_alive():
            if self._stop:  # closed at shutdown: refuse, don't strand
                fut.set_exception(
                    RuntimeError("committer thread is shut down")
                )
                return fut
            self._thread = threading.Thread(
                target=self._main, name="corro-committer", daemon=True
            )
            self._thread.start()
        self._q.append((loop, fut, batch, _time.monotonic()))
        METRICS.gauge("corro.write.committer.queue.depth").set(
            len(self._q)
        )
        self._wake.set()
        return fut

    def close(self, timeout: float = 5.0) -> None:
        self._stop = True
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _main(self) -> None:
        import time as _time

        q = self._q
        while True:
            self._wake.wait()
            self._wake.clear()
            while q:
                loop, fut, batch, enq = q.popleft()
                METRICS.histogram(
                    "corro.write.committer.handoff.seconds"
                ).observe(_time.monotonic() - enq)
                try:
                    self._run(batch)
                except BaseException as e:
                    err: Optional[BaseException] = e
                else:
                    err = None
                self._resolve(loop, fut, err)
            if self._stop:
                return

    @staticmethod
    def _resolve(loop, fut, err: Optional[BaseException]) -> None:
        def _settle() -> None:
            if fut.done():
                return  # leader's loop died mid-commit; commit stands
            if err is None:
                fut.set_result(None)
            else:
                fut.set_exception(err)

        try:
            loop.call_soon_threadsafe(_settle)
        except RuntimeError:
            # the loop closed under us (hard shutdown): the sqlite
            # commit itself stands — there is nobody left to tell
            pass


@dataclass
class _GroupItem:
    """One writer's slot in a commit group."""

    fn: Callable
    ts: Timestamp  # HLC timestamp of this writer's WriteTx
    fut: asyncio.Future
    enq: float  # monotonic submit time (group wait metric)
    results: Optional[List[object]] = None
    changes: Optional[list] = None
    db_version: int = 0
    last_seq: int = 0
    error: Optional[BaseException] = None
    # r21 per-group fanout: the writer's trace context rides the item
    # so the LEADER can run the whole batch's post-commit block
    traceparent: Optional[str] = None
    write_span: Optional[object] = None
    # True once the leader's single fanout pass covered this tx's
    # hooks+chunk+broadcast (the caller must then skip its own block)
    fanned: bool = False
    # r23 write-profile stamps (monotonic): the leader/commit thread
    # fill these so submit() can attribute the full submit→resolve wall
    # across {asyncio dispatch, write gate, handoff, finalize,
    # sqlite flush} (corro.write.profile.seconds → WRITE_PROFILE.json)
    gate_start: float = 0.0
    gate_acq: float = 0.0
    dispatch: float = 0.0
    thread_start: float = 0.0
    thread_done: float = 0.0
    finalize_secs: float = 0.0
    flush_secs: float = 0.0


class GroupCommitter:
    """Coalesces concurrent local write transactions into shared sqlite
    commits (r14 write-path round).

    Before: every `/v1/transactions` caller ran its own
    BEGIN IMMEDIATE..COMMIT behind the priority write gate — N
    concurrent writers paid N sequential fsyncs, N store-lock holds and
    N bookkeeping rounds.  Now the first caller becomes the LEADER:
    while its batch commits on a worker thread, later callers enqueue;
    the next batch takes them ALL into one transaction (consecutive
    db_versions inside one BEGIN/COMMIT, one gap-store/bookie round for
    the whole group).  Each writer runs in its own SAVEPOINT
    (`WriteTx(nested=True)`), so a failed statement aborts only its own
    sub-tx and surfaces only to its own caller.  A solo writer's batch
    is size 1 and commits immediately — p50 latency of an uncontended
    write is unchanged (`perf.group_commit_wait` > 0 opts into an extra
    coalescing window).  `perf.group_commit_max_writers` /
    `group_commit_max_bytes` bound one shared transaction.
    """

    def __init__(self, agent: Agent):
        self.agent = agent
        self._pending: "deque[_GroupItem]" = deque()
        self._leader = False
        # the in-flight r21 fanout task: at most ONE batch's post-commit
        # fanout runs detached (overlapping the NEXT batch's commit
        # thread); the leader awaits it before scheduling another, so a
        # slow broadcast plane backpressures commits instead of piling
        # unfinished fanouts
        self._fanout_job: Optional[asyncio.Future] = None
        # r24: the dedicated committer thread (lazily started on the
        # first thread-mode batch; close() joins it at shutdown)
        self._committer = _CommitterThread(self._commit_batch)

    def close(self) -> None:
        """Stop the committer thread (agent shutdown)."""
        self._committer.close()

    async def submit(
        self,
        fn: Callable,
        traceparent: Optional[str] = None,
        write_span=None,
    ) -> _GroupItem:
        """Enqueue one writer; returns its completed item (or raises its
        own sub-tx failure).  Runs on the agent's event loop.

        The first free caller leads INLINE (no task hop: a solo writer
        pays zero extra scheduling round-trips over the old per-writer
        path); followers enqueue and await.  If the inline leader is
        cancelled mid-drain, leadership detaches to a task so followers
        can never strand."""
        import time as _time

        loop = asyncio.get_running_loop()
        item = _GroupItem(
            fn=fn,
            ts=self.agent.clock.new_timestamp(),
            fut=loop.create_future(),
            enq=_time.monotonic(),
            traceparent=traceparent,
            write_span=write_span,
        )
        self._pending.append(item)
        if not self._leader:
            self._leader = True
            try:
                await self._lead()
            finally:
                self._release_leadership()
        res = await item.fut
        # r23: bank the five-bucket wall attribution when the continuous
        # profiler is installed (one global None-check otherwise)
        if _rt_profiler.installed():
            _rt_profiler.record_write_buckets(
                enq=item.enq,
                gate_start=item.gate_start,
                gate_acq=item.gate_acq,
                dispatch=item.dispatch,
                thread_start=item.thread_start,
                thread_done=item.thread_done,
                resolved=_time.monotonic(),
                finalize_secs=item.finalize_secs,
            )
        return res

    def _release_leadership(self) -> None:
        self._leader = False
        if self._pending:
            # arrivals raced the drain check (or the leader died with
            # waiters queued): hand leadership to a detached task
            self._leader = True
            asyncio.ensure_future(self._lead_detached())

    async def _lead_detached(self) -> None:
        try:
            await self._lead()
        finally:
            self._release_leadership()

    async def _lead(self) -> None:
        import time as _time

        agent = self.agent
        perf = agent.config.perf
        amortized = _group_fanout_enabled(perf)
        while self._pending:
            if amortized:
                # one loop pass before gathering (r21, gated with the
                # rest of the per-group amortization): writers settled
                # by the previous batch have their wakeups queued
                # BEHIND this coroutine (the new leader is simply
                # whichever of them ran first), and without the yield
                # the leader commits a batch of one while its
                # just-woken peers re-enqueue a batch too late — steady
                # state alternates full and size-1 batches.  An
                # actually-solo writer pays one ready-queue pass (~µs),
                # not a timed wait.
                await asyncio.sleep(0)
            batch: List[_GroupItem] = []
            commit_job = None
            t_gate = _time.monotonic()  # r23 write-profile stamp
            try:
                async with agent.write_gate.priority():
                    t_acq = _time.monotonic()
                    if (
                        perf.group_commit_wait > 0
                        and len(self._pending) == 1
                    ):
                        # opt-in window for bursty single writers
                        await asyncio.sleep(perf.group_commit_wait)
                    while (
                        self._pending
                        and len(batch) < perf.group_commit_max_writers
                    ):
                        batch.append(self._pending.popleft())
                    t_dispatch = _time.monotonic()
                    for it in batch:
                        it.gate_start = t_gate
                        it.gate_acq = t_acq
                        it.dispatch = t_dispatch
                    if _committer_thread_enabled(perf):
                        # r24: lock-free handoff to the long-lived
                        # committer thread — no executor submit, no
                        # wrapper task, one loop wakeup on completion
                        commit_job = self._committer.submit(
                            asyncio.get_running_loop(), batch
                        )
                    else:
                        commit_job = asyncio.ensure_future(
                            asyncio.to_thread(self._commit_batch, batch)
                        )
                    # shielded: a cancelled leader must not abandon a
                    # commit thread mid-flight (the store lock, not this
                    # gate, is the true sqlite guard)
                    await asyncio.shield(commit_job)
            except asyncio.CancelledError:
                if commit_job is not None:
                    # the thread finishes on its own; finish the batch
                    # from its outcome so no follower ever strands
                    commit_job.add_done_callback(
                        lambda job, b=batch: asyncio.ensure_future(
                            self._finish_batch(b, job.exception())
                        )
                    )
                else:
                    self._settle(batch, _cancelled_error())
                raise
            except BaseException as e:
                if not batch and self._pending:
                    # the gate itself failed: fail one waiter, not none,
                    # so the loop cannot spin without progress
                    batch = [self._pending.popleft()]
                self._settle(batch, e)
                continue
            await self._finish_batch(batch, None)

    async def _finish_batch(
        self, batch: List[_GroupItem], error: Optional[BaseException]
    ) -> None:
        """Post-commit half on the event loop: settle every writer's
        future FIRST (marking committed items fanned, so their callers
        return without any per-tx post-commit block), then run the
        group's single fanout pass as a one-deep pipelined task — it
        executes on the loop while the NEXT batch's commit occupies the
        worker thread, preserving the thread/loop overlap the per-tx
        path had (settling after an inline fanout serialized the two
        and LOST throughput at w16)."""
        committed: List[_GroupItem] = []
        if error is None and _group_fanout_enabled(self.agent.config.perf):
            committed = [
                it for it in batch if it.error is None and it.changes
            ]
            for it in committed:
                it.fanned = True
        self._settle(batch, error)
        if committed:
            prev, self._fanout_job = self._fanout_job, None
            if prev is not None:
                await prev
            self._fanout_job = asyncio.ensure_future(
                self._fanout(committed)
            )

    async def _fanout(self, committed: List[_GroupItem]) -> None:
        """ONE post-commit loop re-entry for the whole group (r21): a
        single origin stamp, one amortized hooks flush, one chunk pass
        over the wire cells finalize already stamped, and one channel
        round — instead of every follower paying its own hooks + chunk
        + per-chunk `tx_bcast.send` block after its future resolves
        (~0.4 ms/tx of loop bookkeeping at w16 in the r15 profile).
        Runs detached after the writers' futures settled: a failure
        here (realistically ChannelClosed at shutdown) cannot reach the
        callers — their commits stand — so it is logged, not raised."""
        import time as _time

        agent = self.agent
        try:
            from corrosion_tpu.runtime import tracestore
            from corrosion_tpu.runtime.trace import make_meta

            st = tracestore.store()
            origin_wall = _time.time()
            hook_batches: List[tuple] = []
            inputs: List[BroadcastInput] = []
            for it in committed:
                trace_meta = None
                if it.write_span is not None and st is not None:
                    it.write_span.attrs["table"] = it.changes[0].table
                    trace_meta = make_meta(
                        forced=st.head_forced(it.write_span.ctx.trace_id)
                    )
                hook_batches.append(
                    (it.changes, it.traceparent, trace_meta)
                )
                inputs.extend(
                    BroadcastInput(change=cv, is_local=True)
                    for cv in chunked_change_v1(
                        agent.actor_id, it.db_version, it.changes,
                        it.last_seq, it.ts, origin_ts=origin_wall,
                        traceparent=it.traceparent, trace_meta=trace_meta,
                    )
                )
            agent.notify_change_hooks_group(hook_batches, origin_wall)
            await agent.tx_bcast.send_many(inputs)
            METRICS.counter(
                "corro.write.group.amortized.flush.total"
            ).inc()
            METRICS.counter("corro.write.group.amortized.txs.total").inc(
                len(committed)
            )
        except asyncio.CancelledError:
            raise
        except BaseException:
            log.warning(
                "group fanout failed for %d committed tx(s); commits "
                "stand, broadcast/hooks for the batch were lost",
                len(committed), exc_info=True,
            )

    def _settle(
        self, batch: List[_GroupItem], error: Optional[BaseException]
    ) -> None:
        """Resolve a batch's futures: committed items succeed, items
        whose own sub-tx failed get their error, uncommitted items
        inherit the batch-level failure."""
        for it in batch:
            if it.error is None and it.changes is None and error is not None:
                it.error = error
            if it.fut.done():
                continue  # caller cancelled; the commit stands
            if it.error is not None:
                it.fut.set_exception(it.error)
            else:
                it.fut.set_result(it)

    def _commit_batch(self, batch: List[_GroupItem]) -> None:
        """Worker-thread half: run every writer's statements + finalize
        inside shared transactions, then ONE bookkeeping round for all
        committed versions."""
        import time as _time

        agent = self.agent
        store = agent.store
        max_bytes = agent.config.perf.group_commit_max_bytes
        booked = agent.bookie.ensure(agent.actor_id)
        committed: List[_GroupItem] = []
        t_thread = _time.monotonic()  # r23: the commit-thread handoff landed
        # a SOLO batch skips the per-writer savepoint (r15): with one
        # writer there are no batchmates to isolate, and its failure
        # aborts the whole group tx below — the uncontended fast path
        # saves the SAVEPOINT/RELEASE round-trip on every solo commit
        use_sp = len(batch) > 1
        with booked.write("group_commit") as bv:
            i = 0
            while i < len(batch):
                group: List[tuple] = []  # (item, captured pending rows)
                used = 0
                try:
                    with store.group_tx():
                        while i < len(batch):
                            item = batch[i]
                            i += 1
                            try:
                                with store.write_tx(
                                    item.ts, nested=True, savepoint=use_sp
                                ) as tx:
                                    item.results = item.fn(tx)
                                    pending = tx.commit_deferred()
                            except BaseException as e:
                                item.error = e
                                _count_write_error(e)
                                if not use_sp:
                                    # savepoint-free sub-tx: the shared
                                    # transaction is poisoned — abort it
                                    raise
                                continue
                            group.append((item, pending))
                            used += sum(
                                _pending_row_bytes(r) for r in pending
                            )
                            if used >= max_bytes:
                                break
                        # ONE vectorized finalize + flush for the whole
                        # group (consecutive db_versions assigned inside)
                        t0 = _time.monotonic()
                        finalized = store.finalize_group(
                            [(p, it.ts) for it, p in group]
                        )
                        fin_dur = _time.monotonic() - t0
                        METRICS.histogram(
                            "corro.write.finalize.seconds"
                        ).observe(fin_dur)
                        fin_share = fin_dur / max(1, len(group))
                        for (it, _p), (changes, dv, last_seq) in zip(
                            group, finalized
                        ):
                            it.changes = changes
                            it.db_version = dv
                            it.last_seq = last_seq
                            it.finalize_secs = fin_share
                except BaseException as e:
                    # the shared finalize/COMMIT died: every sub-tx in
                    # this group rolled back with it (a failed
                    # savepoint-free solo writer keeps its OWN error)
                    if not any(it.error is e for it in batch):
                        _count_write_error(e)  # group-level fault
                    for it, _p in group:
                        if it.error is None:
                            it.error = e
                        it.changes = None
                        it.db_version = 0
                    continue
                committed.extend(it for it, _p in group)
                # r23: per-item share of the group's COMMIT flush wall
                # (crdt.group_tx stamps last_flush_secs on exit)
                flush_share = store.last_flush_secs / max(1, len(group))
                for it, _p in group:
                    it.flush_secs = flush_share
                METRICS.histogram("corro.write.group.size").observe(
                    len(group)
                )
            versions = RangeSet()
            if any(it.db_version for it in committed):
                for it in committed:
                    if it.db_version:
                        versions.insert(it.db_version, it.db_version)
                snap = bv.snapshot()
                snap.insert_db(store.gap_store(), versions)
                bv.commit_snapshot(snap)
        now = _time.monotonic()
        for it in committed:
            it.thread_start = t_thread
            it.thread_done = now
            METRICS.histogram("corro.write.group.wait.seconds").observe(
                now - it.enq
            )


async def make_broadcastable_changes(
    agent: Agent, fn: Callable[["object"], List[object]]
) -> ExecResult:
    """Run local statements in one write tx, then broadcast the committed
    changes (the `/v1/transactions` path, api/public/mod.rs:57-258).

    `fn(tx)` executes statements against the WriteTx and returns
    per-statement results.
    """
    from corrosion_tpu.runtime.trace import span

    # one ROOT span per local write: its W3C context rides the broadcast
    # envelope so remote applies stitch to this trace (r11 — the eager
    # path's counterpart of the SyncStart traceparent); stage="write"
    # routes it into the r19 tail sampler when one is configured
    with span(
        "write.local", stage="write", actor=str(agent.actor_id)
    ) as write_span:
        return await _make_broadcastable_changes_inner(
            agent, fn, write_span.ctx.traceparent(), write_span
        )


async def _make_broadcastable_changes_inner(
    agent: Agent, fn: Callable[["object"], List[object]], traceparent: str,
    write_span=None,
) -> ExecResult:
    import time as _time

    gc = agent.commit_group
    if gc is not None and agent.config.perf.group_commit:
        item = await gc.submit(fn, traceparent=traceparent,
                               write_span=write_span)
        results, changes = item.results, item.changes
        db_version, last_seq, ts = item.db_version, item.last_seq, item.ts
        if item.fanned:
            # r21: the group leader's single fanout pass already ran
            # this tx's hooks + chunk + broadcast block — return
            # straight to the caller with zero per-tx loop work
            rows = sum(r for r in _int_results(results))
            return ExecResult(
                rows_affected=rows, results=results, version=db_version
            )
    else:
        # solo path (group commit disabled): per-writer gate + commit —
        # local client writes take the PRIORITY lane (agent.rs:586)
        async with agent.write_gate.priority():
            ts = agent.clock.new_timestamp()
            booked = agent.bookie.ensure(agent.actor_id)

            def txn() -> Tuple[List[object], list, int, int]:
                with booked.write("make_broadcastable_changes"):
                    with agent.store.write_tx(ts) as tx:
                        results = fn(tx)
                        changes, db_version, last_seq = tx.commit()
                    if db_version:
                        agent.store.record_last_seq(
                            agent.actor_id, db_version, last_seq
                        )
                    with booked.write("commit bookkeeping") as bv:
                        if db_version:
                            snap = bv.snapshot()
                            snap.insert_db(
                                agent.store.gap_store(),
                                RangeSet([(db_version, db_version)]),
                            )
                            bv.commit_snapshot(snap)
                    return results, changes, db_version, last_seq

            try:
                results, changes, db_version, last_seq = (
                    await asyncio.to_thread(txn)
                )
            except BaseException as e:
                _count_write_error(e)
                raise

    if changes:
        # the ORIGIN stamp: wall clock at local commit — every
        # corro.e2e.* stage downstream measures against this instant
        origin_wall = _time.time()
        # r19 trace meta: the origin's cached head decision (lottery on
        # the trace id) rides the envelope so every node on the path
        # keeps the same trace without coordination; hop starts at 0
        trace_meta = None
        if write_span is not None:
            from corrosion_tpu.runtime import tracestore
            from corrosion_tpu.runtime.trace import make_meta

            st = tracestore.store()
            if st is not None:
                write_span.attrs["table"] = changes[0].table
                trace_meta = make_meta(
                    forced=st.head_forced(write_span.ctx.trace_id)
                )
        agent.notify_change_hooks(
            changes, origin_wall, traceparent=traceparent,
            trace_meta=trace_meta,
        )
        # encode-once, spliced (r16): each chunk's body is assembled
        # from the wire_cell bytes finalize_group already stamped — one
        # header/tail pack + a join per chunk, no per-value re-walk
        # (byte-identity with the r14 with_wire_body path pinned in
        # test_codec.py); broadcast and every re-transmission/relay
        # wrap the shared bytes
        for cv in chunked_change_v1(
            agent.actor_id, db_version, changes, last_seq, ts,
            origin_ts=origin_wall, traceparent=traceparent,
            trace_meta=trace_meta,
        ):
            await agent.tx_bcast.send(BroadcastInput(change=cv, is_local=True))
    rows = sum(r for r in _int_results(results))
    return ExecResult(rows_affected=rows, results=results, version=db_version)


def _int_results(results: List[object]):
    for r in results:
        if isinstance(r, int):
            yield r

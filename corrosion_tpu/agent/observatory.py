"""Cluster observatory: digest anti-entropy + the view-divergence
(split-brain) detector (r12).

Every observability plane before this one was node-local: answering
"how is the CLUSTER doing" meant scraping N agents.  The observatory
closes that gap with the machinery the cluster already runs — each node
periodically builds a compact `NodeDigest` (runtime/digest.py) and
piggybacks it on the gossip datagrams (`Membership` ext hook) and the
broadcast envelopes (`agent/broadcast.py`); received digests are kept
freshest-per-node and RELAYED with the same infection-style
transmission budget membership updates get, so every node converges on
every node's digest without any new connection, poll loop, or central
scraper.  On top of the aggregated store:

- `GET /v1/cluster` (api/http.py) serves cluster-merged write→event
  stage percentiles (exact: the digests carry mergeable histograms),
  a per-node health roll-up, and digest coverage/staleness — from ANY
  single node.
- the divergence detector compares the canonical membership-view
  hashes the digests carry: nodes that disagree about who is in the
  cluster (or that went digest-silent while still held ACTIVE) are a
  partition/split-brain observable (`corro.cluster.divergence.*`), and
  a divergence sustained for `divergence_checks` consecutive checks
  trips ONE flight-recorder incident dump per episode — the standing
  pview split-brain failure class, made a first-class page.

Load tolerance: a 1-core host that deschedules this whole process
would, on resume, see every peer's digest as "old" at once.  The loop
therefore tracks its own wakeup lag and suppresses the SILENCE signal
for rounds where it was itself late (the Lifeguard discipline of r9:
never turn your own sickness into accusations of peers); the view-hash
comparison is timing-free and stays armed.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from corrosion_tpu.runtime import latency as lat
from corrosion_tpu.runtime.digest import (
    NodeDigest,
    decode_digest,
    encode_digest,
    merge_stage_hists,
    view_hash,
)
from corrosion_tpu.runtime.metrics import METRICS, kernel_event_totals
from corrosion_tpu.runtime.records import FLIGHT
from corrosion_tpu.types.actor import ActorId

log = logging.getLogger(__name__)

# gossip-ext byte overhead on top of the encoded digest (version byte +
# u32 length prefix, net/gossip_codec.py / types/codec.py ext v2)
_EXT_OVERHEAD = 8


@dataclass
class _Held:
    digest: NodeDigest
    encoded: bytes
    sends_left: int
    received_mono: float  # LOCAL receipt/build clock — staleness basis


class Observatory:
    """One agent's digest store + divergence tracker.

    Thread contract: `build_and_store` runs on a WORKER thread
    (observatory_loop's `asyncio.to_thread` — the bookie read locks and
    histogram encodes must not stall the loop) while `receive` /
    `pick_ext` mutate the same digest store from transport callbacks on
    the event loop, and `cluster_report` iterates it from the API
    handler.  Every `_store`/`_seq` touch therefore holds `_lock` (the
    r7 metrics-lock discipline); the divergence episode counters are
    loop-only and stay lock-free."""

    def __init__(self, agent):
        self.agent = agent
        self.cfg = agent.config.cluster
        self._lock = threading.Lock()
        self._store: Dict[bytes, _Held] = {}
        self._seq = 0
        self._pick_rr = 0
        self._div_streak = 0
        self._clean_streak = 0
        self._episode_open = False
        self._episodes = 0
        self._last_wake: Optional[float] = None
        self._self_lagged = False
        self._armed = True

    def disarm(self) -> None:
        """Stop opening/closing divergence episodes (checks still
        report).  Called before a planned teardown — peers winding down
        one by one otherwise read as a silence divergence in the gap
        between their last digest and their LEAVE propagating."""
        self._armed = False

    # -- knobs -------------------------------------------------------------

    @property
    def silent_after(self) -> float:
        if self.cfg.silent_after_secs > 0:
            return self.cfg.silent_after_secs
        return self.cfg.silent_after_mult * self.cfg.digest_interval_secs

    # -- building ----------------------------------------------------------

    def snapshot_local(self) -> NodeDigest:
        """Build this node's digest from the planes it already runs.
        Registry reads are non-mutating snapshots; the bookie read locks
        are brief (same pattern as the sync scheduler)."""
        mship = self.agent.membership
        from corrosion_tpu.agent.membership import MemberState

        members = list(mship.members.values())
        active_ids = [self.agent.actor_id.bytes16] + [
            m.actor.id.bytes16
            for m in members
            if m.state != MemberState.DOWN
        ]
        alive = 1 + sum(1 for m in members if m.state == MemberState.ALIVE)
        suspect = sum(1 for m in members if m.state == MemberState.SUSPECT)

        backlog: Dict[bytes, int] = {}
        heads_total = 0  # r17: versions held, the catch-up freshness ad
        for aid, booked in self.agent.bookie.items().items():
            with booked.read() as bv:
                need = sum(e - s + 1 for s, e in bv.needed)
                need += sum(
                    1 for p in bv.partials.values() if not p.is_complete()
                )
                heads_total += (bv.last() or 0) - need
            if need:
                backlog[aid.bytes16] = need

        loop_lag = 0.0
        for kind, name, _labels, value in METRICS.snapshot():
            if kind == "gauge" and name == "corro.runtime.loop.lag.max.seconds":
                loop_lag = max(loop_lag, value)

        events: Dict[str, int] = {}
        for _kernel, by_event in kernel_event_totals(METRICS).items():
            for ev, v in by_event.items():
                events[ev] = events.get(ev, 0) + int(v)

        # r20: the node's active alerts ride the digest so any node can
        # serve the cluster alert view (bounded, firing-first)
        eng = getattr(self.agent, "alerts", None)
        alerts = eng.active_summaries() if eng is not None else []

        # r23: the node's top self-time profile frames ride too — the
        # cluster-scope hotspot table any node serves.  First tier shed
        # under the wire-budget ladder (build_and_store): color, not
        # core.
        from corrosion_tpu.runtime import profiler as _profiler

        prof = _profiler.get()
        hotspots = prof.hotspots() if prof is not None else []

        with self._lock:
            self._seq += 1
            seq = self._seq
        return NodeDigest(
            actor_id=self.agent.actor_id.bytes16,
            seq=seq,
            wall=time.time(),
            view_hash=view_hash(active_ids),
            view_size=len(active_ids),
            alive=alive,
            suspect=suspect,
            downed=len(mship.downed),
            lhm=mship.lhm,
            loop_lag=loop_lag,
            sync_backlog=backlog,
            heads_total=max(0, heads_total),
            alerts=alerts,
            hotspots=hotspots,
            events=events,
            stages=lat.stage_hists(window_secs=None),
        )

    def advertised_heads(self) -> Dict[bytes, int]:
        """actor id -> that node's digest-advertised `heads_total` —
        the r17 catch-up plane's freshness map (peer-choice bias +
        snapshot-bootstrap gap estimate).  Lock: vs the worker-thread
        builder."""
        with self._lock:
            return {
                aid: held.digest.heads_total
                for aid, held in self._store.items()
            }

    def build_and_store(self) -> NodeDigest:
        """Refresh the local digest and queue it for dissemination with
        a full infection-style transmission budget.

        The digest must FIT the gossip plane or it never ships: pick_ext
        skips anything over the frame's leftover budget, and since the
        stage histograms are cumulative the overflow is permanent once
        crossed — with an open divergence episode inflating the alert
        block, oversize is self-sustaining (no digests → silence →
        episode stays open → alert block stays on).  Degrade tiers keep
        the view/census core shipping: drop the profile hotspots first
        (r23 — flamegraph color, never load-bearing), then the
        non-total stage histograms, then all stages/events and the
        alert tail."""
        d = self.snapshot_local()
        enc = encode_digest(d)
        if len(enc) > self.cfg.max_wire_bytes and d.hotspots:
            d.hotspots = []
            enc = encode_digest(d)
            METRICS.counter(
                "corro.digest.degraded.total", level="profile"
            ).inc()
        if len(enc) > self.cfg.max_wire_bytes:
            d.stages = {k: v for k, v in d.stages.items() if k == "total"}
            enc = encode_digest(d)
            METRICS.counter(
                "corro.digest.degraded.total", level="stages"
            ).inc()
            if len(enc) > self.cfg.max_wire_bytes:
                d.stages = {}
                d.events = {}
                d.alerts = d.alerts[:3]
                enc = encode_digest(d)
                METRICS.counter(
                    "corro.digest.degraded.total", level="census"
                ).inc()
        with self._lock:
            self._store[d.actor_id] = _Held(
                digest=d,
                encoded=enc,
                sends_left=self._transmissions(),
                received_mono=time.monotonic(),
            )
            nodes = len(self._store)
        METRICS.counter("corro.digest.built.total").inc()
        METRICS.gauge("corro.digest.size.bytes").set(len(enc))
        METRICS.gauge("corro.digest.nodes").set(nodes)
        return d

    def _transmissions(self) -> int:
        return self.agent.membership.config.max_transmissions(
            self.agent.membership.cluster_size
        )

    # -- dissemination -----------------------------------------------------

    def pick_ext(self, budget: int, plane: str = "gossip") -> Optional[bytes]:
        """One digest that still has sends left and fits `budget`
        encoded bytes, round-robin across nodes (own digest and relays
        compete equally — the same epidemic fairness the membership
        piggyback uses).  Returns the encoded bytes or None."""
        if not self.cfg.digests or not self._store:
            return None
        skipped_oversize = False
        picked = None
        with self._lock:  # vs build_and_store on the worker thread
            keys = sorted(self._store)
            n = len(keys)
            for i in range(n):
                held = self._store[keys[(self._pick_rr + i) % n]]
                if held.sends_left <= 0:
                    continue
                if len(held.encoded) + _EXT_OVERHEAD > budget:
                    skipped_oversize = True
                    continue
                self._pick_rr = (self._pick_rr + i + 1) % n
                held.sends_left -= 1
                picked = held.encoded
                break
        if picked is not None:
            METRICS.counter("corro.digest.sent.total", plane=plane).inc()
            return picked
        if skipped_oversize:
            METRICS.counter("corro.digest.oversize.skipped.total").inc()
        return None

    def receive(self, data: bytes) -> Optional[NodeDigest]:
        """Adopt a gossiped digest if it is the freshest we have seen
        from its origin node; fresh adoptions re-enter the relay queue
        (anti-entropy: digests reach nodes the origin never talks to)."""
        try:
            d = decode_digest(data)
        except (ValueError, IndexError):
            METRICS.counter("corro.digest.decode.failed").inc()
            return None
        if d.actor_id == self.agent.actor_id.bytes16:
            return None  # our own digest relayed back — ours is fresher
        with self._lock:  # vs build_and_store on the worker thread
            known = self._store.get(d.actor_id)
            if not d.fresher_than(known.digest if known else None):
                stale = True
            else:
                stale = False
                self._store[d.actor_id] = _Held(
                    digest=d,
                    encoded=bytes(data),
                    sends_left=self._transmissions(),
                    received_mono=time.monotonic(),
                )
            nodes = len(self._store)
        if stale:
            METRICS.counter("corro.digest.stale.total").inc()
            return None
        METRICS.counter("corro.digest.received.total").inc()
        METRICS.gauge("corro.digest.nodes").set(nodes)
        return d

    # -- divergence detection ----------------------------------------------

    def _active_member_ids(self) -> List[bytes]:
        from corrosion_tpu.agent.membership import MemberState

        return [
            m.actor.id.bytes16
            for m in self.agent.membership.members.values()
            if m.state != MemberState.DOWN
        ]

    def check_divergence(self) -> dict:
        """One detector pass: compare the view hashes of every ACTIVE
        member's remembered digest (within `divergence_memory_secs`)
        against our own, and flag active members whose digests went
        silent.  A divergence sustained `divergence_checks` consecutive
        passes opens an episode: ONE incident dump + episode counter;
        a clean pass closes it and re-arms."""
        now_mono = time.monotonic()
        my_ids = self._active_member_ids()
        my_hash = view_hash(my_ids + [self.agent.actor_id.bytes16])
        views: Dict[int, List[str]] = {
            my_hash: [str(self.agent.actor_id)]
        }
        silent: List[str] = []
        with self._lock:  # snapshot vs the worker-thread builder
            store = dict(self._store)
        for mid in my_ids:
            held = store.get(mid)
            if held is None:
                continue  # never reported — no evidence either way
            age = now_mono - held.received_mono
            if age > self.cfg.divergence_memory_secs:
                continue
            name = str(ActorId(mid))
            views.setdefault(held.digest.view_hash, []).append(name)
            if age > self.silent_after and not self._self_lagged:
                silent.append(name)
        groups = len(views)
        divergent = groups > 1 or bool(silent)

        # one kernel="cluster" host frame per check: the black box then
        # holds the divergence timeline that preceded an incident dump
        # (and guarantees the dump is never skipped-as-empty on agents
        # that host no kernel sim)
        FLIGHT.record_host_frame(
            "cluster",
            {
                "groups": groups,
                "silent": len(silent),
                "streak": self._div_streak,
                "episode_open": int(self._episode_open),
                "digest_nodes": len(store),
                "view_size": len(my_ids) + 1,
            },
        )
        METRICS.counter("corro.cluster.divergence.checks.total").inc()
        METRICS.gauge("corro.cluster.divergence.groups").set(groups)
        METRICS.gauge("corro.cluster.divergence.silent").set(len(silent))
        if not self._armed:
            pass  # episode state frozen (planned teardown)
        elif divergent:
            self._div_streak += 1
            self._clean_streak = 0
            if (
                self._div_streak >= self.cfg.divergence_checks
                and not self._episode_open
            ):
                self._episode_open = True
                self._episodes += 1
                METRICS.counter(
                    "corro.cluster.divergence.episodes.total"
                ).inc()
                FLIGHT.snapshot_incident("cluster_divergence")
                log.warning(
                    "cluster view divergence: %d view group(s), "
                    "%d silent active node(s)", groups, len(silent),
                )
        elif self._self_lagged and self._episode_open:
            # a lagged round suppressed the silence signal, so "clean"
            # is not evidence: hold the open episode instead of closing
            # it and double-counting the same fault on the next round
            pass
        else:
            self._div_streak = 0
            self._clean_streak += 1
            # symmetric hysteresis: an episode closes only after the
            # SAME number of consecutive clean checks that opened it —
            # a single bounced check can neither open nor split one
            if self._clean_streak >= self.cfg.divergence_checks:
                self._episode_open = False
        METRICS.gauge("corro.cluster.divergence.active").set(
            1.0 if self._episode_open else 0.0
        )
        return {
            "divergent": divergent,
            "episode_open": self._episode_open,
            "episodes": self._episodes,
            "streak": self._div_streak,
            "groups": groups,
            "silent": silent,
            "view_hash": format(my_hash, "016x"),
            "views": {
                format(h, "016x"): sorted(nodes)
                for h, nodes in views.items()
            },
        }

    # -- the any-node cluster plane ----------------------------------------

    def cluster_alerts(self) -> dict:
        """What `GET /v1/alerts?scope=cluster` serves: every node's
        digest-carried active alerts plus a per-rule rollup — from ANY
        single node, over the same anti-entropy store /v1/cluster
        reads.  The serving node's own digest is rebuilt at read time
        (same discipline as cluster_report)."""
        self.build_and_store()
        now_mono = time.monotonic()
        stale_after = self.cfg.stale_after_secs
        nodes: Dict[str, dict] = {}
        rollup: Dict[str, dict] = {}
        with self._lock:  # snapshot vs the worker-thread builder
            held_all = list(self._store.values())
        for held in held_all:
            d = held.digest
            age = now_mono - held.received_mono
            name = str(ActorId(d.actor_id))
            nodes[name] = {
                "age_secs": round(age, 3),
                "fresh": age <= stale_after,
                "alerts": list(d.alerts),
            }
            if age > stale_after:
                continue  # stale digests list but never roll up
            for a in d.alerts:
                row = rollup.setdefault(a["rule"], {
                    "severity": a["severity"],
                    "firing": [], "pending": [], "drill": False,
                })
                row[a["state"]].append(name)
                row["drill"] = row["drill"] or bool(a.get("drill"))
        for row in rollup.values():
            row["firing"].sort()
            row["pending"].sort()
        return {
            "actor_id": str(self.agent.actor_id),
            "scope": "cluster",
            "coverage": {
                "known": len(nodes),
                "fresh": sum(1 for n in nodes.values() if n["fresh"]),
                "stale_after_secs": stale_after,
            },
            "rollup": rollup,
            "nodes": nodes,
        }

    def cluster_hotspots(self) -> dict:
        """What `GET /v1/profile?scope=cluster` serves: every node's
        digest-carried top self-time frames plus a cluster-merged
        hotspot table — from ANY single node, over the anti-entropy
        store.  Same rebuild-at-read + fresh-only-rollup discipline as
        cluster_alerts; a node whose digest shed its hotspot block
        under the wire-budget ladder simply contributes none."""
        self.build_and_store()
        now_mono = time.monotonic()
        stale_after = self.cfg.stale_after_secs
        nodes: Dict[str, dict] = {}
        merged: Dict[str, int] = {}
        with self._lock:  # snapshot vs the worker-thread builder
            held_all = list(self._store.values())
        for held in held_all:
            d = held.digest
            age = now_mono - held.received_mono
            name = str(ActorId(d.actor_id))
            nodes[name] = {
                "age_secs": round(age, 3),
                "fresh": age <= stale_after,
                "hotspots": list(d.hotspots),
            }
            if age > stale_after:
                continue  # stale digests list but never roll up
            for h in d.hotspots:
                merged[h["frame"]] = (
                    merged.get(h["frame"], 0) + int(h["samples"])
                )
        rollup = [
            {"frame": fr, "samples": n}
            for fr, n in sorted(
                merged.items(), key=lambda kv: (-kv[1], kv[0])
            )
        ]
        return {
            "actor_id": str(self.agent.actor_id),
            "scope": "cluster",
            "coverage": {
                "known": len(nodes),
                "fresh": sum(1 for n in nodes.values() if n["fresh"]),
                "stale_after_secs": stale_after,
            },
            "rollup": rollup,
            "nodes": nodes,
        }

    def cluster_report(self) -> dict:
        """What `GET /v1/cluster` serves: digest coverage, per-node
        health roll-up, EXACT cluster-merged stage percentiles, and the
        divergence verdict.  The serving node's own digest is rebuilt
        at read time so 'any node' includes the one you asked."""
        self.build_and_store()
        now_mono = time.monotonic()
        stale_after = self.cfg.stale_after_secs
        nodes: Dict[str, dict] = {}
        fresh: List[NodeDigest] = []
        with self._lock:  # snapshot vs the worker-thread builder
            held_all = list(self._store.values())
        for held in held_all:
            d = held.digest
            age = now_mono - held.received_mono
            is_fresh = age <= stale_after
            if is_fresh:
                fresh.append(d)
            nodes[str(ActorId(d.actor_id))] = {
                "age_secs": round(age, 3),
                "fresh": is_fresh,
                "seq": d.seq,
                "view_hash": format(d.view_hash, "016x"),
                "view_size": d.view_size,
                "alive": d.alive,
                "suspect": d.suspect,
                "downed": d.downed,
                "lhm": d.lhm,
                "loop_lag_seconds": d.loop_lag,
                "sync_backlog_versions": sum(d.sync_backlog.values()),
                "sync_backlog_peers": len(d.sync_backlog),
                "events": dict(d.events),
                "stage_counts": {
                    s: h.count for s, h in d.stages.items() if h.count
                },
            }
        merged = merge_stage_hists(fresh)
        stages = {}
        for stage, h in merged.items():
            row = {lat._qname(q): h.quantile(q) for q in lat.QUANTILES}
            row["count"] = h.count
            row["mean"] = (h.total / h.count) if h.count else None
            stages[stage] = row
        expected = 1 + len(self._active_member_ids())
        return {
            "actor_id": str(self.agent.actor_id),
            "coverage": {
                "expected": expected,
                "known": len(nodes),
                "fresh": len(fresh),
                "stale_after_secs": stale_after,
                "digest_interval_secs": self.cfg.digest_interval_secs,
            },
            "nodes": nodes,
            "stages": stages,
            "divergence": self.check_divergence(),
        }


async def observatory_loop(agent) -> None:
    """Build + disseminate the local digest and run the divergence
    detector every `digest_interval_secs` until tripwire.  Wakeup lag
    beyond `2 × interval` marks the NEXT check self-lagged (silence
    suppression — see module docstring)."""
    obs = agent.observatory
    if obs is None:
        return
    interval = obs.cfg.digest_interval_secs
    while not agent.tripwire.tripped:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(agent.tripwire.wait(), interval)
        if agent.tripwire.tripped:
            return
        now = time.monotonic()
        lagged = (
            obs._last_wake is not None
            and now - obs._last_wake > 2.0 * interval
        )
        obs._last_wake = now
        obs._self_lagged = lagged
        if lagged:
            METRICS.counter("corro.cluster.self.lagged.total").inc()
        try:
            await asyncio.to_thread(obs.build_and_store)
            obs.check_divergence()
        except Exception:
            log.exception("observatory tick failed")

"""Change ingestion: the backpressure heart + the CRDT apply path.

Counterparts:
  - `handle_changes` queue (`klukai-agent/src/agent/handlers.rs:555-789`):
    dedupe against a seen-cache and the bookie, drop oldest beyond
    `processing_queue_len`, batch to `apply_queue_len` cost or a 10 ms
    tick, ≤`max_concurrent_applies` concurrent apply jobs, re-broadcast
    novel broadcast-sourced changes, pull HLC forward from change
    timestamps (`handlers.rs:696-708`).
  - `process_multiple_changes` (`agent/util.rs:703-1054`): one write
    transaction per batch — complete changesets merge into the store,
    incomplete ones buffer with seq-range bookkeeping, empties only move
    the gap set; closing a version's last seq gap schedules a
    fully-buffered apply (`util.rs:1000-1023`); committed impactful rows
    feed the subs/updates hooks (`util.rs:1042-1047`).  The hooks run
    HERE on the apply worker thread: since r10 the subs hook is the
    manager's inverted routing index (O(changes + hits), sub count out
    of the loop) and the per-batch hook cost is recorded as
    `corro.agent.changes.hooks.seconds` — a regression back to
    O(subs × changes) shows up as a rising ingest tax.
  - `process_fully_buffered_changes` (`util.rs:552-700`).
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import List, Optional, Set, Tuple

from corrosion_tpu.agent.handle import Agent, BroadcastInput, ChangeSource
from corrosion_tpu.runtime.channels import ChannelClosed
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.store.bookkeeping import PartialVersion
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import (
    ChangeV1,
    ChangesetEmpty,
    ChangesetEmptySet,
    ChangesetFull,
)
from corrosion_tpu.types.rangeset import RangeSet

# seen-cache key: (actor, version range, seq range or None)
_SeenKey = Tuple[ActorId, Tuple[int, int], Optional[Tuple[int, int]]]
_SEEN_CACHE_MAX = 4096


def _seen_key(cv: ChangeV1) -> List[_SeenKey]:
    cs = cv.changeset
    if isinstance(cs, ChangesetFull):
        return [(cv.actor_id, (cs.version, cs.version), cs.seqs)]
    if isinstance(cs, ChangesetEmpty):
        return [(cv.actor_id, cs.versions, None)]
    if isinstance(cs, ChangesetEmptySet):
        return [(cv.actor_id, vr, None) for vr in cs.versions]
    return []


def _bookie_has(agent: Agent, cv: ChangeV1) -> bool:
    booked = agent.bookie.get(cv.actor_id)
    if booked is None:
        return False
    cs = cv.changeset
    with booked.read() as bv:
        if isinstance(cs, ChangesetFull):
            return bv.contains(cs.version, cs.seqs)
        if isinstance(cs, ChangesetEmpty):
            return bv.contains_all(cs.versions)
        if isinstance(cs, ChangesetEmptySet):
            return all(bv.contains_all(vr) for vr in cs.versions)
    return False


async def handle_changes(agent: Agent) -> None:
    """The hot ingestion loop; owns rx_changes."""
    perf = agent.config.perf
    seen: "OrderedDict[_SeenKey, None]" = OrderedDict()
    buf: List[Tuple[ChangeV1, ChangeSource, List[_SeenKey], float]] = []
    apply_sem = asyncio.Semaphore(perf.max_concurrent_applies)
    jobs: Set[asyncio.Task] = set()

    def unsee(keys: List[_SeenKey]) -> None:
        # seen-cache repair: a dropped/failed change must be re-deliverable
        # (handlers.rs:732-751)
        for k in keys:
            seen.pop(k, None)

    async def flush() -> None:
        if not buf:
            return
        batch, buf[:] = buf[:], []
        now = time.monotonic()
        for _, _, _, t_enq in batch:
            METRICS.histogram("corro.agent.changes.queued.seconds").observe(
                now - t_enq
            )
        METRICS.histogram("corro.agent.changes.batch.size").observe(len(batch))
        METRICS.counter("corro.agent.changes.batch.spawned").inc()
        METRICS.counter("corro.agent.changes.processing.started").inc(
            len(batch)
        )
        await apply_sem.acquire()
        METRICS.gauge("corro.agent.changes.processing.jobs").set(len(jobs) + 1)

        async def job():
            try:
                # remote applies queue on the NORMAL write lane so local
                # client writes (PRIORITY) overtake a sync burst
                # (agent.rs:503-519)
                async with agent.write_gate.normal():
                    await asyncio.to_thread(
                        process_multiple_changes,
                        agent,
                        [(cv, src) for cv, src, _, _ in batch],
                    )
            except Exception:
                METRICS.counter("corro.agent.changes.processing.failed").inc()
                for _, _, keys, _ in batch:
                    unsee(keys)
                raise
            finally:
                apply_sem.release()
                METRICS.gauge("corro.agent.changes.processing.jobs").set(
                    max(0, len(jobs) - 1)
                )

        t = asyncio.ensure_future(job())
        jobs.add(t)
        t.add_done_callback(jobs.discard)

    deadline: Optional[float] = None
    epoch = agent.ingest_epoch
    while not agent.tripwire.tripped:
        timeout = None
        if deadline is not None:
            timeout = max(0.0, deadline - time.monotonic())
        try:
            item = await asyncio.wait_for(
                agent.rx_changes.recv(),
                timeout if timeout is not None else perf.sync_interval_max_secs,
            )
        except asyncio.TimeoutError:
            item = None
        except ChannelClosed:
            break

        if agent.ingest_epoch != epoch:
            # r17 snapshot install swapped the database: every "seen"
            # verdict predates the swap and may describe data the swap
            # dropped — a stale entry would make this loop skip the
            # re-served version forever (the catch-up plane's re-pull
            # would grind against it each round).  Checked AFTER the
            # recv so the verdict for THIS item is never the stale one.
            epoch = agent.ingest_epoch
            seen.clear()
        if item is not None:
            cv, source = item
            METRICS.counter("corro.agent.changes.recv").inc()
            keys = _seen_key(cv)
            if all(k in seen for k in keys) or _bookie_has(agent, cv):
                METRICS.counter("corro.agent.changes.skipped").inc()
            else:
                for k in keys:
                    seen[k] = None
                while len(seen) > _SEEN_CACHE_MAX:
                    seen.popitem(last=False)
                # pull our HLC forward from the change's timestamp
                cs = cv.changeset
                ts = getattr(cs, "ts", None)
                if ts and not ts.is_zero():
                    agent.clock.update_with_timestamp(ts)
                # novel broadcast-sourced changes get re-broadcast;
                # a traced change relays with its hop count bumped so
                # downstream apply spans name their distance from the
                # origin (r19 — the forced-keep bit travels untouched)
                if source == ChangeSource.BROADCAST and not _is_empty(cv):
                    if cv.trace_meta is not None:
                        from dataclasses import replace as _replace

                        from corrosion_tpu.runtime.trace import bump_hop

                        relay = _replace(
                            cv, trace_meta=bump_hop(cv.trace_meta)
                        )
                    else:
                        relay = cv
                    agent.tx_bcast.try_send(
                        BroadcastInput(change=relay, is_local=False)
                    )
                buf.append((cv, source, keys, time.monotonic()))
                if len(buf) > perf.processing_queue_len:
                    _, _, old_keys, _ = buf.pop(0)  # drop oldest
                    unsee(old_keys)
                    METRICS.counter("corro.agent.changes.dropped").inc()
                if deadline is None:
                    deadline = (
                        time.monotonic() + perf.apply_queue_timeout_ms / 1000.0
                    )

        METRICS.gauge("corro.agent.changes.in_queue").set(len(buf))
        cost = sum(_cost(cv) for cv, _, _, _ in buf)
        expired = deadline is not None and time.monotonic() >= deadline
        if cost >= perf.apply_queue_len or (expired and buf):
            await flush()
            METRICS.gauge("corro.agent.changes.in_queue").set(0)
            deadline = None
        elif expired:
            deadline = None

    await flush()
    for t in list(jobs):
        try:
            await t
        except Exception:
            pass


def _is_empty(cv: ChangeV1) -> bool:
    cs = cv.changeset
    return isinstance(cs, (ChangesetEmpty, ChangesetEmptySet)) or (
        isinstance(cs, ChangesetFull) and not cs.changes
    )


def _cost(cv: ChangeV1) -> int:
    cs = cv.changeset
    return max(1, len(cs.changes)) if isinstance(cs, ChangesetFull) else 1


def process_multiple_changes(
    agent: Agent, batch: List[Tuple[ChangeV1, ChangeSource]]
) -> None:
    """Apply a batch synchronously (runs on a worker thread).

    Per-actor bookie write locks are taken one actor at a time, sorted,
    like the blocking-write lock dance in util.rs:703-790.
    """
    start = time.monotonic()
    by_actor: "OrderedDict[ActorId, List[ChangeV1]]" = OrderedDict()
    for cv, _source in batch:
        by_actor.setdefault(cv.actor_id, []).append(cv)

    all_impactful = []
    for actor_id in sorted(by_actor, key=lambda a: a.bytes16):
        booked = agent.bookie.ensure(actor_id)
        # interruptible: a wedged apply is interrupted at 60 s (the
        # reference's InterruptibleTransaction timeout on write txs,
        # sqlite_pool/mod.rs) — the OperationalError propagates, the
        # caller repairs the seen cache, and the changes re-deliver
        with agent.store.interrupt_after(60.0), booked.write(
            "process_multiple_changes"
        ) as bv:
            snap = bv.snapshot()
            observed = RangeSet()
            to_apply_later: List[int] = []
            for cv in by_actor[actor_id]:
                impactful = _process_one(
                    agent, actor_id, cv, bv, snap, observed, to_apply_later
                )
                all_impactful.extend(impactful)
            snap.insert_db(agent.store.gap_store(), observed)
            bv.commit_snapshot(snap)
        for version in to_apply_later:
            changes = process_fully_buffered(agent, actor_id, version)
            all_impactful.extend(changes)

    # r11 latency plane: commit→apply per stamped change (the origin
    # wall stamp rode the broadcast/sync envelope here).  Cross-node
    # wall-clock delta: e2e_observe clamps skew-negative values.  The
    # OLDEST origin travels on to the hooks so apply→event and the
    # end-to-end total attribute against the batch's worst element.
    # r19: each traced change also records an `ingest.apply` stage span
    # (origin commit → local apply committed) continuing the origin's
    # trace, and the oldest element's trace context rides the stamp to
    # the match/deliver stages.
    from corrosion_tpu.runtime.latency import e2e_observe
    from corrosion_tpu.runtime.trace import meta_forced, meta_hop, stage_span

    origin_min: Optional[float] = None
    oldest_tp: Optional[str] = None
    oldest_meta: Optional[int] = None
    now_wall = time.time()
    actor_str = str(agent.actor_id)
    for cv, source in batch:
        if cv.origin_ts is None:
            continue
        delta = e2e_observe(
            "apply", now_wall - cv.origin_ts, source=source.value
        )
        if cv.traceparent is not None:
            cs = cv.changeset
            stage_span(
                cv.traceparent, "ingest.apply", "apply", delta,
                forced=meta_forced(cv.trace_meta),
                actor=actor_str, source=source.value,
                hop=meta_hop(cv.trace_meta),
                table=(
                    cs.changes[0].table
                    if isinstance(cs, ChangesetFull) and cs.changes
                    else ""
                ),
            )
        if origin_min is None or cv.origin_ts < origin_min:
            origin_min = cv.origin_ts
            oldest_tp = cv.traceparent
            oldest_meta = cv.trace_meta

    if all_impactful:
        agent.notify_change_hooks(
            all_impactful, origin_min,
            traceparent=oldest_tp, trace_meta=oldest_meta,
        )
    METRICS.histogram("corro.agent.changes.processing.time.seconds").observe(
        time.monotonic() - start
    )


def _process_one(agent, actor_id, cv, bv, snap, observed, to_apply_later) -> list:
    cs = cv.changeset
    store = agent.store

    if isinstance(cs, ChangesetEmptySet):
        for s, e in cs.versions:
            observed.insert(s, e)
        METRICS.counter("corro.agent.changes.empty.applied").inc()
        return []
    if isinstance(cs, ChangesetEmpty):
        observed.insert(*cs.versions)
        return []

    assert isinstance(cs, ChangesetFull)
    if bv.contains(cs.version, cs.seqs):
        return []

    if cs.is_complete():
        applied = store.apply_changes(cs.changes)
        store.record_last_seq(actor_id, cs.version, cs.last_seq)
        observed.insert(cs.version, cs.version)
        METRICS.counter("corro.agent.changes.complete.applied").inc()
        return applied.impactful

    # incomplete: buffer + seq bookkeeping (util.rs:1070-1203)
    store.buffer_partial_changes(
        actor_id, cs.version, cs.changes, cs.seqs, cs.last_seq, cs.ts
    )
    partial = bv.insert_partial(
        cs.version,
        PartialVersion(
            seqs=RangeSet([cs.seqs]), last_seq=cs.last_seq, ts=cs.ts
        ),
    )
    # the batch snapshot predates this insert and commit_snapshot
    # REPLACES bv.partials with the snapshot's dict, so a partial first
    # seen in this batch must be mirrored into the snapshot or it is
    # silently wiped at commit — after which later chunks dedupe as
    # "already present" and generate_sync reports nothing to repair:
    # the version is lost until a full re-sync (r5 chaos-soak find)
    snap.partials[cs.version] = partial
    # partial versions are observed (KnownDbVersion::Partial) — the gap
    # algebra must not re-mark them needed when later versions land
    observed.insert(cs.version, cs.version)
    METRICS.counter("corro.agent.changes.incomplete.buffered").inc()
    if partial.is_complete():
        to_apply_later.append(cs.version)
    return []


def process_fully_buffered(agent: Agent, actor_id: ActorId, version: int):
    """Drain a completed buffered version into the store (util.rs:552-700)."""
    from corrosion_tpu.runtime import invariants

    store = agent.store
    changes = store.take_buffered_version(actor_id, version)
    if changes:
        if invariants.enabled():
            # seqs of a fully-buffered version must be gap-free before
            # the drain (ref assert_always "contiguous seq ranges",
            # util.rs:1170) — the sort is the expensive part, so only
            # the CHECK sits behind the mode gate
            seqs = sorted(c.seq for c in changes)
            invariants.assert_always(
                all(b - a <= 1 for a, b in zip(seqs, seqs[1:])),
                "buffered.seqs_contiguous",
                {"actor": str(actor_id), "version": version},
            )
        # the coverage marker is cheap and must record in every mode —
        # the soak's sometimes-contract depends on it
        invariants.assert_sometimes("buffered version drained")
    impactful = []
    if changes:
        applied = store.apply_changes(changes)
        impactful = applied.impactful
        store.record_last_seq(actor_id, version, changes[-1].seq)
    store.clear_buffered_version(actor_id, version)
    booked = agent.bookie.ensure(actor_id)
    with booked.write("process_fully_buffered") as bv:
        bv.partials.pop(version, None)
        snap = bv.snapshot()
        snap.partials.pop(version, None)
        snap.insert_db(agent.store.gap_store(), RangeSet([(version, version)]))
        bv.commit_snapshot(snap)
    METRICS.counter("corro.agent.changes.buffered.applied").inc()
    return impactful


async def apply_fully_buffered_loop(agent: Agent) -> None:
    """Consume tx_apply requests (actor, version) — scheduled when seq
    gaps close or at startup warm-up (run_root.rs:136-197)."""
    while not agent.tripwire.tripped:
        try:
            item = await agent.rx_apply.recv()
        except ChannelClosed:
            break
        actor_id, version = item
        async with agent.write_gate.normal():
            changes = await asyncio.to_thread(
                process_fully_buffered, agent, actor_id, version
            )
        if changes:
            agent.notify_change_hooks(changes)

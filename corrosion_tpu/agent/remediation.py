"""The supervised remediation plane (r22): close the observe→act loop.

r19–r20 made every anomaly a typed, lifecycle-tracked object (traces,
TSDB, alert rules) — and stopped at "page a human".  This module is the
acting half: a supervisor tick consumes the local `AlertEngine`'s
FIRING rules (`runtime/alerts.py::DEFAULT_ACTIONS` binds rule →
actuator) and drives a registry of typed actuators built from levers
the repo already has:

- `view-divergence` → **targeted-sync**: one immediate anti-entropy
  round (`agent/syncer.py::targeted_sync`) outside the sync_loop's
  backoff — the loop backs off exactly when nothing arrives, i.e.
  exactly when divergence opens.
- `store-faults` → **drain-refuse-bulk**: drain this node's matcher
  homes (`SubsManager.drain` — every stream ends with the clean typed
  terminal the r16 resume path handles) and mark the node refuse-bulk:
  new stream admission 503s (`SubsManager.refuse_until`) and bulk
  snapshot serves/bootstraps reject BUSY (`Agent.bulk_refuse_until`,
  checked in `agent/catchup.py`) until the revert clears the flags.
- sustained `slo-burn` → **shed-laggards**: shed the clogged sink tier
  (`FanoutWriter.shed_clogged`) with the typed `SubLagging` frame
  before clients time out.

Every decision is a typed, drill-aware, flight-recorded event: acts
emit `FLIGHT.record_host_frame("remediation", ...)` frames (so they
ride every incident dump) and append cooldown-stamped history rows
served by `GET /v1/remediation` (api/http.py).

Gates, in order, per firing rule:

1. **sustain** — the rule must have been firing `sustain_secs`
   (slo-burn only by default: a transient burn blip must not shed).
2. **cooldown** — per-actuator; an act stamps it, a would-act does not.
3. **precondition** — a typed refusal ("no laggard sinks to shed")
   instead of a no-op act that burns the cooldown.
4. **Lifeguard self-distrust** (arXiv:1707.00788) — when the local
   `health_score()` is at/above `[remediation] defer_health`, this
   node's impulse DEFERS to the digest-merged cluster rollup
   (`observatory.cluster_alerts()`): it acts only when another node's
   digest confirms the same rule firing.  A sick node acting on its
   own sick telemetry is how remediation storms start.
5. **kill-switch** — `[remediation] enabled=false` (the default) is
   observe-only: every gate above still runs and a typed `would_act`
   event is recorded, so operators audit the plane before arming it.

Prime CCL bar (arXiv:2505.14065): every actuator SHRINKS capacity
(sheds, drains, refuses) with a typed signal — none may convert a
request into a stall.  The chaos matrix is the proof harness
(`scripts/traffic_sim.py --remediation`): remediation ON must strictly
improve recovery walls with timeouts==0 and the availability floors
intact.

Thread contract: the supervisor runs entirely on the event loop
(`remediation_loop` tick → async acts → HTTP reads) — no cross-thread
mutation, no lock.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from collections import deque
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

from corrosion_tpu.chaos.faults import CENSUS
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.records import FLIGHT

log = logging.getLogger(__name__)

MODES = ("acted", "would_act", "deferred", "refused", "failed", "reverted")


@dataclass(frozen=True)
class Actuator:
    """One typed remediation action.  `act` does the work and returns a
    JSON-ready detail dict; `revert` (optional) undoes the standing
    side effects when the bound rule resolves; `precondition` returns
    None to allow or a typed refusal reason.  Discipline (pinned by the
    `actuator-discipline` static rule, analysis/actuators.py): every
    actuator carries a positive cooldown, and every `act` body checks
    the chaos CENSUS drill marker and emits a flight-recorder frame."""

    name: str
    rule: str  # the alert rule that drives it (alerts.DEFAULT_ACTIONS)
    summary: str
    cooldown_secs: float
    act: Callable[..., Awaitable[dict]]
    revert: Optional[Callable[..., Awaitable[dict]]] = None
    precondition: Optional[Callable[..., Optional[str]]] = None
    sustain_secs: float = 0.0  # min firing age before acting


# -- the default actuators (the levers the repo already has) ---------------


def _pre_targeted_sync(agent) -> Optional[str]:
    if not any(
        aid != agent.actor_id for aid in agent.members.states
    ):
        return "no peers known to sync against"
    return None


async def _act_targeted_sync(agent) -> dict:
    from corrosion_tpu.agent.syncer import targeted_sync

    drill = CENSUS.snapshot()
    received = await targeted_sync(
        agent, timeout=agent.config.remediation.act_timeout_secs
    )
    FLIGHT.record_host_frame(
        "remediation",
        {"targeted_sync": 1, "changes_received": received},
    )
    return {
        "changes_received": received,
        "drill": drill.get("scenario"),
    }


async def _act_drain_refuse_bulk(agent) -> dict:
    drill = CENSUS.snapshot()
    refuse = agent.config.remediation.refuse_bulk_secs
    deadline = time.monotonic() + refuse
    drained = 0
    if agent.subs is not None:
        drained = await agent.subs.drain()
        agent.subs.refuse_until = deadline
    agent.bulk_refuse_until = deadline
    FLIGHT.record_host_frame(
        "remediation", {"drain": 1, "homes_drained": drained}
    )
    return {
        "homes_drained": drained,
        "refuse_bulk_secs": refuse,
        "drill": drill.get("scenario"),
    }


async def _revert_drain_refuse_bulk(agent) -> dict:
    """Store healthy again: stop refusing early (the deadline would
    expire on its own — the revert just gets there sooner).  Drained
    matcher homes are NOT rebuilt here; re-subscribing clients rebuild
    them on demand through the normal dedupe path."""
    agent.bulk_refuse_until = 0.0
    if agent.subs is not None:
        agent.subs.refuse_until = 0.0
    return {"refuse_bulk": "cleared"}


def _pre_shed_laggards(agent) -> Optional[str]:
    if agent.subs is None:
        return "no subscription manager on this node"
    if agent.subs.fanout.clogged_count() == 0:
        return "no laggard sinks to shed"
    return None


async def _act_shed_laggards(agent) -> dict:
    drill = CENSUS.snapshot()
    shed = agent.subs.fanout.shed_clogged()
    FLIGHT.record_host_frame(
        "remediation", {"shed": 1, "laggards_shed": shed}
    )
    return {"laggards_shed": shed, "drill": drill.get("scenario")}


def default_actuators(cfg) -> Dict[str, Actuator]:
    """The built-in registry, cooldowns from the `[remediation]`
    config.  Adding one: write the act (CENSUS drill check + FLIGHT
    frame, see the discipline note on `Actuator`), bind its rule in
    `alerts.DEFAULT_ACTIONS`, and document it in COMPONENTS.md."""
    return {
        a.name: a
        for a in (
            Actuator(
                name="targeted-sync",
                rule="view-divergence",
                summary="immediate anti-entropy round, bypassing the "
                        "sync_loop backoff",
                cooldown_secs=cfg.sync_cooldown_secs,
                act=_act_targeted_sync,
                precondition=_pre_targeted_sync,
            ),
            Actuator(
                name="drain-refuse-bulk",
                rule="store-faults",
                summary="drain matcher homes; refuse new streams and "
                        "bulk snapshot transfers while the store is "
                        "sick",
                cooldown_secs=cfg.drain_cooldown_secs,
                act=_act_drain_refuse_bulk,
                revert=_revert_drain_refuse_bulk,
            ),
            Actuator(
                name="shed-laggards",
                rule="slo-burn",
                summary="shed the clogged sink tier with the typed "
                        "lagging terminal before clients time out",
                cooldown_secs=cfg.shed_cooldown_secs,
                act=_act_shed_laggards,
                precondition=_pre_shed_laggards,
                sustain_secs=cfg.slo_sustain_secs,
            ),
        )
    }


# -- the supervisor ---------------------------------------------------------


class RemediationSupervisor:
    """One node's observe→act loop.  `tick()` is the whole protocol:
    revert actuators whose rule resolved, then gate + drive actuators
    for the rules firing now.  All state lives on the event loop."""

    def __init__(
        self,
        agent,
        cfg=None,
        actuators: Optional[Dict[str, Actuator]] = None,
        bindings: Optional[Dict[str, str]] = None,
        clock=time.monotonic,
        wall=time.time,
    ):
        from corrosion_tpu.runtime.alerts import DEFAULT_ACTIONS
        from corrosion_tpu.runtime.config import RemediationConfig

        self.agent = agent
        self.cfg = cfg if cfg is not None else RemediationConfig()
        self.actuators = (
            actuators if actuators is not None
            else default_actuators(self.cfg)
        )
        self.bindings = dict(
            bindings if bindings is not None else DEFAULT_ACTIONS
        )
        self._clock = clock
        self._wall = wall
        self._last_act: Dict[str, float] = {}  # actuator -> mono stamp
        self._acted_rules: Dict[str, str] = {}  # rule -> actuator name
        # (rule, mode) pairs already recorded this episode: deferred/
        # refused/would_act states persist across ticks — one history
        # row per episode, not one per tick
        self._noted: Set[Tuple[str, str]] = set()
        self._history: deque = deque(maxlen=int(self.cfg.history_max))
        self._counts: Dict[str, int] = {m: 0 for m in MODES}

    # -- consensus (Lifeguard deferral) ------------------------------------

    def _cluster_confirms(self, rule: str) -> bool:
        """Does the digest-merged cluster rollup show `rule` firing on
        some OTHER node?  That is the consensus a self-distrusting node
        requires before acting on its own telemetry."""
        obs = self.agent.observatory
        if obs is None:
            return False
        try:
            rollup = obs.cluster_alerts().get("rollup", {})
        except Exception:
            return False
        row = rollup.get(rule)
        if not row:
            return False
        me = str(self.agent.actor_id)
        return any(n != me for n in row.get("firing", []))

    # -- event plumbing ----------------------------------------------------

    def _record(
        self,
        actuator: Actuator,
        rule: str,
        mode: str,
        detail: dict,
        drill: Optional[str],
    ) -> None:
        self._history.append(
            {
                "action": actuator.name,
                "rule": rule,
                "mode": mode,
                "wall": self._wall(),
                "drill": drill,
                "cooldown_secs": actuator.cooldown_secs,
                "detail": detail,
            }
        )
        self._counts[mode] = self._counts.get(mode, 0) + 1
        METRICS.counter(
            "corro.remediation.actions.total",
            actuator=actuator.name, mode=mode,
        ).inc()
        if mode != "acted":
            # acts emit their own richer frame from inside the
            # actuator body (the lintable discipline); every other
            # outcome is stamped here so incident dumps carry the
            # full decision trail
            FLIGHT.record_host_frame(
                "remediation", {mode: 1}
            )

    def _note_once(
        self,
        actuator: Actuator,
        rule: str,
        mode: str,
        detail: dict,
        drill: Optional[str],
    ) -> None:
        key = (rule, mode)
        if key in self._noted:
            return
        self._noted.add(key)
        self._record(actuator, rule, mode, detail, drill)

    def _drill(self) -> Optional[str]:
        chaos = CENSUS.snapshot()
        return (
            (chaos.get("scenario") or "injection")
            if chaos.get("active") else None
        )

    # -- the tick ----------------------------------------------------------

    async def tick(self) -> None:
        eng = self.agent.alerts
        if eng is None:
            return
        firing = {f["rule"]: f for f in eng.firing_snapshot()}
        await self._handle_resolved(firing)
        for rule, f in firing.items():
            name = self.bindings.get(rule)
            act = self.actuators.get(name) if name else None
            if act is None:
                continue
            await self._consider(act, rule, f)

    async def _handle_resolved(self, firing: Dict[str, dict]) -> None:
        for rule in [r for r in self._acted_rules if r not in firing]:
            name = self._acted_rules.pop(rule)
            act = self.actuators.get(name)
            if act is None or act.revert is None:
                continue
            try:
                detail = await asyncio.wait_for(
                    act.revert(self.agent), self.cfg.act_timeout_secs
                )
            except Exception as e:
                detail = {"error": str(e)}
                log.exception("remediation revert %s failed", name)
            METRICS.counter(
                "corro.remediation.reverts.total", actuator=name
            ).inc()
            self._record(act, rule, "reverted", detail, self._drill())
            log.info("remediation reverted: %s (%s resolved)", name, rule)
        # episode bookkeeping: a rule leaving the firing set re-arms
        # its once-per-episode notes
        self._noted = {
            (r, m) for r, m in self._noted if r in firing
        }

    async def _consider(
        self, act: Actuator, rule: str, f: dict
    ) -> None:
        now = self._clock()
        drill = self._drill()
        if f.get("firing_secs", 0.0) < act.sustain_secs:
            METRICS.counter(
                "corro.remediation.skips.total", reason="sustain"
            ).inc()
            return
        last = self._last_act.get(act.name)
        if last is not None and now - last < act.cooldown_secs:
            METRICS.counter(
                "corro.remediation.skips.total", reason="cooldown"
            ).inc()
            return
        if act.precondition is not None:
            reason = act.precondition(self.agent)
            if reason is not None:
                self._note_once(
                    act, rule, "refused", {"reason": reason}, drill
                )
                return
        health = self.agent.alerts.health_score()
        if health >= self.cfg.defer_health and not self._cluster_confirms(
            rule
        ):
            # Lifeguard: this node's own telemetry is suspect — hold
            # until another node's digest confirms the same rule
            self._note_once(
                act, rule, "deferred",
                {"health_score": round(health, 4),
                 "defer_health": self.cfg.defer_health},
                drill,
            )
            return
        if not self.cfg.enabled:
            self._note_once(
                act, rule, "would_act",
                {"kill_switch": "[remediation] enabled=false"},
                drill,
            )
            return
        self._last_act[act.name] = now
        try:
            detail = await asyncio.wait_for(
                act.act(self.agent), self.cfg.act_timeout_secs
            )
        except Exception as e:
            self._record(act, rule, "failed", {"error": str(e)}, drill)
            log.exception("remediation act %s failed", act.name)
            return
        self._acted_rules[rule] = act.name
        self._record(act, rule, "acted", detail, drill)
        log.warning(
            "REMEDIATION acted: %s (rule %s)%s %s", act.name, rule,
            f" [drill: {drill}]" if drill else "", detail,
        )

    # -- read side (event loop; copies only) -------------------------------

    def census(self) -> dict:
        """The /v1/status block."""
        return {
            "enabled": True,
            "armed": bool(self.cfg.enabled),
            "actuators": len(self.actuators),
            "counts": {
                m: n for m, n in self._counts.items() if n
            },
        }

    def report(self, history: bool = True) -> dict:
        """GET /v1/remediation: the actuator census + action history."""
        now = self._clock()
        rows = []
        for name, act in sorted(self.actuators.items()):
            last = self._last_act.get(name)
            rows.append(
                {
                    "name": name,
                    "rule": act.rule,
                    "summary": act.summary,
                    "cooldown_secs": act.cooldown_secs,
                    "sustain_secs": act.sustain_secs,
                    "has_revert": act.revert is not None,
                    "cooldown_remaining_secs": (
                        round(max(0.0, act.cooldown_secs - (now - last)), 3)
                        if last is not None else 0.0
                    ),
                }
            )
        out = {
            "enabled": True,
            "armed": bool(self.cfg.enabled),
            "defer_health": self.cfg.defer_health,
            "actuators": rows,
            "counts": dict(self._counts),
        }
        if history:
            out["history"] = list(self._history)
        return out


async def remediation_loop(agent) -> None:
    """Tick the supervisor every `[remediation] tick_secs` until
    tripwire — the acting sibling of `alerts_loop`.  Ticks run ON the
    event loop: every gate is a cheap in-memory read and every act is
    itself async (network sync, matcher drain) with its own bound."""
    sup = agent.remediation
    if sup is None:
        return
    interval = agent.config.remediation.tick_secs
    METRICS.gauge("corro.remediation.armed").set(
        1 if agent.config.remediation.enabled else 0
    )
    while not agent.tripwire.tripped:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(agent.tripwire.wait(), interval)
        if agent.tripwire.tripped:
            return
        try:
            await sup.tick()
        except Exception:
            log.exception("remediation tick failed")

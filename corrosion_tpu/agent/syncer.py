"""Anti-entropy sync sessions over bi-streams.

Counterparts:
  - client `parallel_sync` (`klukai-agent/src/api/peer/mod.rs:1082-1482`):
    open a bi-stream to N chosen peers concurrently, exchange
    SyncStart + Clock for State + Clock, derive requests with
    `compute_available_needs`, dedupe ranges across peers, stream
    received changesets into the ingestion pipeline.
  - server `serve_sync` (`peer/mod.rs:1485-1728`): reject foreign
    clusters and >3 concurrent sessions, send own State + Clock, then
    serve each request batch from the store (`handle_need`,
    `peer/mod.rs:450-984`) — live versions stream as ≤8 KiB Full chunks,
    overwritten versions collapse into `ChangesetEmptySet`, partially
    buffered versions serve their buffered seq ranges.
  - scheduler (`agent/handlers.rs:796-897`): every 1–15 s pick
    `clamp(members/100, min, max)` peers by (need, last-sync, RTT ring).

The wire protocol frames `SyncMessage`s with the u32-BE length prefix; a
side that has nothing more to say half-closes, and a session ends when
both sides have seen EOF — the same stop condition as the reference's
peer-stopped-stream bookkeeping.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.agent.handle import Agent, ChangeSource
from corrosion_tpu.net.transport import BiStream, TransportError
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.trace import continue_from, span
from corrosion_tpu.sync import (
    chunk_range,
    compute_available_needs,
    generate_sync,
    state_need_len,
)
from corrosion_tpu.types.actor import Actor, ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import (
    ChangeV1,
    ChangesetEmptySet,
    ChangesetFull,
    chunk_changes,
)
from corrosion_tpu.types.codec import (
    NeedEmpty,
    NeedFull,
    NeedPartial,
    SyncRejection,
    SyncState,
    SyncTraceContext,
    decode_sync_msg,
    encode_bi_payload_sync_start,
    encode_sync_msg,
)
from corrosion_tpu.types.rangeset import RangeSet

MAX_NEEDS_PER_TURN = 10  # peer/mod.rs: round-robin ≤10 needs/peer/turn
VERSIONS_PER_CHUNK = 10  # chunk Full ranges to ≤10 versions
RECV_TIMEOUT = 10.0

# r11 latency plane: sync-served changesets carry an origin wall stamp
# (envelope ext) only when the change is FRESH — live catch-up during
# write traffic, the case the e2e `apply{source="sync"}` histogram is
# meant to measure.  Cold bulk catch-up of hours-old versions is gated
# out so it cannot masquerade as write→event latency.
E2E_SYNC_FRESH_S = 60.0


def _sync_origin(ts) -> "float | None":
    if ts is None or ts.is_zero():
        return None
    wall = ts.to_unix()
    return wall if 0 <= time.time() - wall < E2E_SYNC_FRESH_S else None

# adaptive chunk sizing (peer/mod.rs:444-447, 808-869)
CHUNK_TARGET_MAX = 8 * 1024  # grow back up to the 8 KiB target
CHUNK_TARGET_FLOOR = 1024  # never below 1 KiB
ADAPT_SLOW_SEND_S = 0.5  # halve when one send takes > 500 ms
ADAPT_GROW = 1.5
SEND_TIMEOUT = 30.0  # stalled-peer cutoff: frees snapshot conn + permit
# r18 timeout discipline: EVERY network await in this module carries a
# deadline (the zombie-node scenario's bug class — a peer whose kernel
# accepts bytes while its event loop never answers must cost a counted
# timeout, never a stalled round; enforced repo-wide by the
# timeout-discipline corro-analyze rule)
OPEN_TIMEOUT = 10.0  # dial cutoff for open_bi


class AdaptiveChunkSize:
    """Per-session chunk-size controller: a send that takes longer than
    500 ms halves the byte target (slow peer / congested path), a fast
    send grows it ×1.5 back toward 8 KiB, floored at 1 KiB — the
    reference's policy at `peer/mod.rs:808-869`."""

    def __init__(self):
        self.target = CHUNK_TARGET_MAX

    def observe(self, send_seconds: float) -> None:
        if send_seconds > ADAPT_SLOW_SEND_S:
            self.target = max(CHUNK_TARGET_FLOOR, self.target // 2)
        else:
            self.target = min(
                CHUNK_TARGET_MAX, int(self.target * ADAPT_GROW)
            )
        METRICS.gauge("corro.sync.server.chunk_target_bytes").set(self.target)

    async def timed_send(self, stream, frame: bytes) -> None:
        """Send with the session's send timeout: a peer that stops
        reading must not pin the server's snapshot read connection (an
        open reader blocks WAL truncation) nor hold a serve permit
        forever — the timeout tears the session down instead."""
        t0 = time.monotonic()
        await asyncio.wait_for(stream.send(frame), SEND_TIMEOUT)
        self.observe(time.monotonic() - t0)


# -- server ----------------------------------------------------------------


async def serve_sync(agent: Agent, stream: BiStream) -> None:
    """Handle one inbound bi-stream: a SyncStart session, or (r17) a
    SnapshotReq from a cold node bootstrapping (agent/catchup.py).  The
    dispatch is the version gate: a pre-r17 build raises ValueError on
    the snapshot variant and lands in the counted-failure close below,
    which the requester reads as EOF → pure-delta fallback."""
    from corrosion_tpu.types.codec import decode_bi_payload_any

    try:
        first = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
        if first is None:
            return
        kind, payload = decode_bi_payload_any(first)
        if kind == "snapshot":
            from corrosion_tpu.agent.catchup import serve_snapshot

            await serve_snapshot(agent, stream, payload)
            return
        peer_actor_id, trace, cluster_id = payload
        if cluster_id != agent.cluster_id:
            await asyncio.wait_for(
                stream.send(encode_sync_msg(SyncRejection(reason=1))),
                SEND_TIMEOUT,
            )
            await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
            return
        if agent.sync_serve_sem.locked():
            await asyncio.wait_for(
                stream.send(encode_sync_msg(SyncRejection(reason=2))),
                SEND_TIMEOUT,
            )
            await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
            return
        async with agent.sync_serve_sem:
            # adopt the client's W3C trace context from the wire
            # (peer/mod.rs:1494-1496)
            with continue_from(
                trace.traceparent, "sync.server", peer=str(peer_actor_id)
            ):
                await _serve_sync_inner(agent, stream, peer_actor_id)
    except (asyncio.TimeoutError, TransportError, ValueError):
        METRICS.counter("corro.sync.server.failed").inc()
    finally:
        stream.close()


async def _serve_sync_inner(
    agent: Agent, stream: BiStream, peer_actor_id: ActorId
) -> None:
    METRICS.counter("corro.sync.server.started").inc()
    state = generate_sync(agent.bookie, agent.actor_id)
    await asyncio.wait_for(
        stream.send(encode_sync_msg(agent.clock.new_timestamp())),
        SEND_TIMEOUT,
    )
    await asyncio.wait_for(stream.send(encode_sync_msg(state)), SEND_TIMEOUT)

    sent = 0
    chunker = AdaptiveChunkSize()  # per-session adaptation state
    while True:
        frame = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
        if frame is None:
            break
        msg = decode_sync_msg(frame)
        if isinstance(msg, Timestamp):
            agent.clock.update_with_timestamp(msg)
            continue
        if not isinstance(msg, list):
            continue  # unexpected; ignore like unknown requests
        for actor_id, needs in msg:
            for need in needs:
                sent += await _handle_need(
                    agent, stream, actor_id, need, chunker
                )
    await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
    METRICS.counter("corro.sync.server.changes.sent").inc(sent)


async def _handle_need(
    agent: Agent, stream: BiStream, actor_id: ActorId, need,
    chunker: "AdaptiveChunkSize" = None,
) -> int:
    """Serve one need from the store; returns changes sent
    (peer/mod.rs:450-806)."""
    store = agent.store
    sent = 0
    chunker = chunker or AdaptiveChunkSize()
    if isinstance(need, NeedFull):
        start, end = need.versions
        served = RangeSet()
        loop = asyncio.get_running_loop()

        # Stream ONE version at a time off the executor instead of
        # materializing the whole range: a large sync holds a single
        # version's changes in memory (changes_for_versions itself reads
        # per-version, db_version DESC — peer/mod.rs:620-700)
        def open_conn():
            # snapshot-isolated pooled read conn: never observe a writer
            # thread's in-flight BEGIN IMMEDIATE on the write connection
            return store.acquire_read()

        conn = await loop.run_in_executor(None, open_conn)
        ok = False
        try:
            gen = store.changes_for_versions(actor_id, start, end, conn=conn)

            def next_version():
                try:
                    version, changes = next(gen)
                except StopIteration:
                    return None
                return (
                    version,
                    changes,
                    store.last_seq_for_version(actor_id, version, conn=conn),
                )

            while True:
                item = await loop.run_in_executor(None, next_version)
                if item is None:
                    break
                version, changes, last_seq = item
                served.insert(version, version)
                if last_seq is None:
                    last_seq = changes[-1].seq if changes else 0
                for chunk, seqs in chunk_changes(
                    changes, last_seq, max_bytes_fn=lambda: chunker.target
                ):
                    ts = chunk[-1].ts if chunk else Timestamp(0)
                    cv = ChangeV1(
                        actor_id=actor_id,
                        changeset=ChangesetFull(
                            version=version,
                            changes=tuple(chunk),
                            seqs=seqs,
                            last_seq=last_seq,
                            ts=ts,
                        ),
                        origin_ts=_sync_origin(ts),
                    )
                    await chunker.timed_send(stream, encode_sync_msg(cv))
                    sent += len(chunk)
            ok = True
        finally:
            # a send error abandons the half-consumed generator: its open
            # cursor pins the conn's read snapshot, so discard, not pool
            await loop.run_in_executor(None, store.release_read, conn, not ok)
        # versions we know (≤ our head for this actor) but have no live
        # rows for were overwritten/cleared → EmptySet (peer/mod.rs:532-566)
        empties = _empty_versions(agent, actor_id, start, end, served)
        if empties:
            cv = ChangeV1(
                actor_id=actor_id,
                changeset=ChangesetEmptySet(
                    versions=tuple(empties), ts=agent.clock.new_timestamp()
                ),
            )
            await chunker.timed_send(stream, encode_sync_msg(cv))
    elif isinstance(need, NeedPartial):
        version = need.version

        def read_partial():
            with store.pooled_read() as conn:
                buffered = store.take_buffered_version(
                    actor_id, version, conn=conn
                )
                true_last = store.buffered_last_seq(
                    actor_id, version, conn=conn
                )
                covered = store.buffered_seq_ranges(
                    actor_id, version, conn=conn
                )
                live = []
                if not buffered:
                    # maybe fully applied since the peer's summary —
                    # serve from live rows
                    for v2, changes in store.changes_for_versions(
                        actor_id, version, version, conn=conn
                    ):
                        live.append(
                            (
                                v2,
                                changes,
                                store.last_seq_for_version(
                                    actor_id, v2, conn=conn
                                ),
                            )
                        )
                return buffered, true_last, covered, live

        (
            buffered,
            true_last,
            covered,
            live,
        ) = await asyncio.get_running_loop().run_in_executor(None, read_partial)
        # only claim seq ranges we actually hold (wanted ∩ covered)
        requested = RangeSet(list(need.seqs))
        wanted = RangeSet()
        for s, e in requested:
            for cs_, ce in covered.overlapping(s, e):
                wanted.insert(max(s, cs_), min(e, ce))
        chosen = [c for c in buffered if wanted.contains(c.seq)]
        if chosen:
            # the version's REAL final seq — never the buffered max, or a
            # half version would be applied as complete by the peer
            last_seq = (
                true_last
                if true_last is not None
                else max(c.seq for c in buffered)
            )
            for chunk, chunk_seqs in _partial_chunks(
                chosen, wanted, max_bytes_fn=lambda: chunker.target
            ):
                ts = chunk[-1].ts if chunk else Timestamp(0)
                cv = ChangeV1(
                    actor_id=actor_id,
                    changeset=ChangesetFull(
                        version=version,
                        changes=tuple(chunk),
                        seqs=chunk_seqs,
                        last_seq=last_seq,
                        ts=ts,
                    ),
                    origin_ts=_sync_origin(ts),
                )
                await chunker.timed_send(stream, encode_sync_msg(cv))
                sent += len(chunk)
        else:
            for version2, changes, last_seq in live:
                if last_seq is None:
                    last_seq = changes[-1].seq if changes else 0
                for chunk, seqs in chunk_changes(
                    changes, last_seq, max_bytes_fn=lambda: chunker.target
                ):
                    ts = chunk[-1].ts if chunk else Timestamp(0)
                    cv = ChangeV1(
                        actor_id=actor_id,
                        changeset=ChangesetFull(
                            version=version2,
                            changes=tuple(chunk),
                            seqs=seqs,
                            last_seq=last_seq,
                            ts=ts,
                        ),
                        origin_ts=_sync_origin(ts),
                    )
                    await chunker.timed_send(stream, encode_sync_msg(cv))
                    sent += len(chunk)
    elif isinstance(need, NeedEmpty):
        pass  # informational
    return sent


def _partial_chunks(changes, wanted: RangeSet, max_bytes_fn=None):
    """Chunk partial-need serves per requested seq range (≤8 KiB each,
    adaptive when `max_bytes_fn` is given) so each emitted `seqs` range
    covers exactly a served sub-range (peer/mod.rs:568-614)."""
    from corrosion_tpu.types.change import MAX_CHANGES_BYTE_SIZE

    if max_bytes_fn is None:
        max_bytes_fn = lambda: MAX_CHANGES_BYTE_SIZE  # noqa: E731

    for rs, re_ in wanted:
        in_range = [c for c in changes if rs <= c.seq <= re_]
        if not in_range:
            continue
        buf, size, start = [], 0, rs
        for c in in_range:
            buf.append(c)
            size += c.estimated_byte_size()
            if size >= max_bytes_fn():
                yield buf, (start, c.seq)
                start = c.seq + 1
                buf, size = [], 0
        if buf:
            yield buf, (start, re_)


def _empty_versions(
    agent: Agent, actor_id: ActorId, start: int, end: int, served: RangeSet
) -> List[Tuple[int, int]]:
    booked = agent.bookie.get(actor_id)
    if booked is None:
        return []
    with booked.read() as bv:
        head = bv.last() or 0
        empties = RangeSet()
        hi = min(end, head)
        if start <= hi:
            empties.insert(start, hi)
        for s, e in served:
            empties.remove(s, e)
        # don't claim versions we ourselves still need or only have partially
        for s, e in bv.needed:
            empties.remove(s, e)
        for v in bv.partials:
            empties.remove(v, v)
        return list(empties)


# -- client ----------------------------------------------------------------


@dataclass
class PeerCircuit:
    """Per-peer circuit state (r17): `circuit_failures` consecutive
    failed sessions open the breaker for `circuit_reset_secs` — the
    peer is skipped by peer choice and the resume waves until it
    half-opens, instead of burning a slot of every round on a node
    that is down or wedged."""

    failures: int = 0
    open_until: float = 0.0

    def allows(self, now: float) -> bool:
        return now >= self.open_until


def _circuit_allows(agent: Agent, actor_id: ActorId, now: float) -> bool:
    c = agent.sync_circuits.get(actor_id)
    return c is None or c.allows(now)


def _record_failure(agent: Agent, actor_id: ActorId) -> None:
    cfg = agent.config.sync
    c = agent.sync_circuits.setdefault(actor_id, PeerCircuit())
    c.failures += 1
    if c.failures >= cfg.circuit_failures:
        c.failures = 0
        # auto mode tracks the sync cadence: the breaker horizon is a
        # handful of rounds whatever the deployment's interval is
        reset = cfg.circuit_reset_secs or (
            4.0 * agent.config.perf.sync_interval_max_secs
        )
        c.open_until = time.monotonic() + reset
        METRICS.counter("corro.sync.circuit.opened.total").inc()


def _record_success(agent: Agent, actor_id: ActorId) -> None:
    c = agent.sync_circuits.get(actor_id)
    if c is not None:
        c.failures = 0
        c.open_until = 0.0


class _Outstanding:
    """One session's claimed-but-not-yet-received ranges.  Shrinks as
    changesets arrive (stream order guarantees a version whose final
    chunk landed arrived whole); whatever remains at session death is
    handed back to the ledger for a sibling to re-claim."""

    __slots__ = ("full", "partials")

    def __init__(self):
        self.full: Dict[ActorId, RangeSet] = {}
        self.partials: Dict[Tuple[ActorId, int], RangeSet] = {}

    def observe(self, cv: ChangeV1) -> None:
        cs = cv.changeset
        if isinstance(cs, ChangesetFull):
            if cs.seqs[1] >= cs.last_seq:  # final chunk of the version
                rs = self.full.get(cv.actor_id)
                if rs is not None:
                    rs.remove(cs.version, cs.version)
                self.partials.pop((cv.actor_id, cs.version), None)
            else:
                prs = self.partials.get((cv.actor_id, cs.version))
                if prs is not None:
                    prs.remove(cs.seqs[0], cs.seqs[1])
        elif isinstance(cs, ChangesetEmptySet):
            rs = self.full.get(cv.actor_id)
            if rs is not None:
                for s, e in cs.versions:
                    rs.remove(s, e)


class _ClaimLedger:
    """Cross-peer dedupe of requested ranges (peer/mod.rs:1274-1351)
    plus the r17 resume half: a failed session RELEASES its unserved
    claims so a surviving peer's next wave re-claims them — a dropout
    mid-stream costs the un-received tail, never a restart."""

    def __init__(self):
        self.full: Dict[ActorId, RangeSet] = {}
        self.partials: Dict[Tuple[ActorId, int], RangeSet] = {}
        self.lock = asyncio.Lock()

    async def claim(
        self, needs: Dict[ActorId, List[object]], out: _Outstanding
    ) -> List[Tuple[ActorId, List[object]]]:
        request: List[Tuple[ActorId, List[object]]] = []
        async with self.lock:
            for actor_id, need_list in needs.items():
                claimed: List[object] = []
                for need in need_list:
                    if isinstance(need, NeedFull):
                        got = self.full.setdefault(actor_id, RangeSet())
                        s, e = need.versions
                        fresh = RangeSet([(s, e)])
                        for gs, ge in got.overlapping(s, e):
                            fresh.remove(gs, ge)
                        for fs, fe in list(fresh):
                            got.insert(fs, fe)
                            out.full.setdefault(
                                actor_id, RangeSet()
                            ).insert(fs, fe)
                            for cs_, ce in chunk_range(
                                fs, fe, VERSIONS_PER_CHUNK
                            ):
                                claimed.append(NeedFull((cs_, ce)))
                    elif isinstance(need, NeedPartial):
                        key = (actor_id, need.version)
                        got = self.partials.setdefault(key, RangeSet())
                        fresh_seqs = []
                        for s, e in need.seqs:
                            seg = RangeSet([(s, e)])
                            for gs, ge in got.overlapping(s, e):
                                seg.remove(gs, ge)
                            for fs, fe in seg:
                                got.insert(fs, fe)
                                out.partials.setdefault(
                                    key, RangeSet()
                                ).insert(fs, fe)
                                fresh_seqs.append((fs, fe))
                        if fresh_seqs:
                            claimed.append(
                                NeedPartial(need.version, tuple(fresh_seqs))
                            )
                if claimed:
                    request.append((actor_id, claimed))
        return request

    async def release(self, out: _Outstanding) -> int:
        """Un-claim a dead session's outstanding ranges; returns the
        version count handed back."""
        released = 0
        async with self.lock:
            for actor_id, rs in out.full.items():
                got = self.full.get(actor_id)
                for s, e in rs:
                    released += e - s + 1
                    if got is not None:
                        got.remove(s, e)
            for key, rs in out.partials.items():
                got = self.partials.get(key)
                if got is None:
                    continue
                for s, e in rs:
                    got.remove(s, e)
                released += 1 if len(list(rs)) else 0
        out.full.clear()
        out.partials.clear()
        return released


async def parallel_sync(
    agent: Agent, peers: List[Actor], ours: Optional[SyncState] = None
) -> int:
    """Sync with several peers concurrently; returns changes received.

    r17 resumable: runs up to `[sync] max_waves` waves inside ONE call —
    a wave's failed sessions release their unserved ranges and the next
    wave (fresh `generate_sync` against the surviving peers, paced by
    `runtime/backoff.py`) re-claims them, so a peer dropping mid-stream
    degrades the transfer instead of restarting it."""
    cfg = agent.config.sync
    if not peers:
        return 0
    ledger = _ClaimLedger()
    from corrosion_tpu.runtime.backoff import Backoff

    pacing = Backoff(
        min_interval=cfg.resume_backoff_min_secs,
        max_interval=cfg.resume_backoff_max_secs,
    ).iter()
    total = 0
    wave = 0
    while peers:
        wave += 1
        if ours is None:
            ours = generate_sync(agent.bookie, agent.actor_id)
        results = await asyncio.gather(
            *(_sync_one_peer(agent, peer, ours, ledger) for peer in peers),
            return_exceptions=True,
        )
        survivors: List[Actor] = []
        released = 0
        for peer, res in zip(peers, results):
            if isinstance(res, BaseException):
                # unexpected (session code failed before its own error
                # envelope): counted, no resume info to salvage
                METRICS.counter("corro.sync.client.failed").inc()
                _record_failure(agent, peer.id)
                continue
            received, ok, freed = res
            total += received
            if not ok:
                METRICS.counter("corro.sync.client.failed").inc()
                _record_failure(agent, peer.id)
                released += freed
                continue
            _record_success(agent, peer.id)
            survivors.append(peer)
            info = agent.members.get(peer.id)
            if info is not None:
                info.last_sync_ts = agent.clock.new_timestamp().ntp64
        if released == 0 or wave >= cfg.max_waves or not survivors:
            break
        METRICS.counter("corro.sync.resume.waves.total").inc()
        METRICS.counter("corro.sync.resume.versions.total").inc(released)
        await asyncio.sleep(next(pacing))
        peers = survivors
        ours = None  # regenerate: the bookie advanced under wave N
    return total


async def fetch_peer_state(
    agent: Agent, peer: Actor, timeout: Optional[float] = None
) -> Optional[SyncState]:
    """One state-only handshake: SyncStart + clock, read the peer's
    summary, half-close without requesting anything.  The cold-boot gap
    probe (`agent/catchup.py`) — cheap enough to run before the first
    digest arrives.

    The deadline resolves at CALL time (r18): a `timeout=RECV_TIMEOUT`
    default froze the module constant at import, so tuned deadlines
    (the chaos replica's tight tiny-shape timeouts) silently did not
    apply here — the zombie-node scenario caught the cold-boot probe
    blocking a sync round for the stale 10 s."""
    import contextlib

    if timeout is None:
        timeout = RECV_TIMEOUT

    try:
        stream = await asyncio.wait_for(
            agent.transport.open_bi(peer.addr), OPEN_TIMEOUT
        )
    except (TransportError, OSError, asyncio.TimeoutError):
        return None
    try:
        await asyncio.wait_for(
            stream.send(
                encode_bi_payload_sync_start(
                    agent.actor_id, cluster_id=agent.cluster_id
                )
            ),
            SEND_TIMEOUT,
        )
        await asyncio.wait_for(
            stream.send(encode_sync_msg(agent.clock.new_timestamp())),
            SEND_TIMEOUT,
        )
        while True:
            frame = await asyncio.wait_for(stream.recv(), timeout)
            if frame is None:
                return None
            msg = decode_sync_msg(frame)
            if isinstance(msg, Timestamp):
                agent.clock.update_with_timestamp(msg)
            elif isinstance(msg, SyncRejection):
                return None
            elif isinstance(msg, SyncState):
                return msg
    except (asyncio.TimeoutError, TransportError, ValueError):
        return None
    finally:
        with contextlib.suppress(Exception):
            await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
        stream.close()


async def _sync_one_peer(
    agent: Agent,
    peer: Actor,
    ours: SyncState,
    ledger: _ClaimLedger,
) -> Tuple[int, bool, int]:
    """One client session.  Returns (changes received, clean, versions
    released back to the ledger on failure) — expected transport/decode
    faults are turned into a resume record here, never raised."""
    out = _Outstanding()
    received = 0
    try:
        stream = await asyncio.wait_for(
            agent.transport.open_bi(peer.addr), OPEN_TIMEOUT
        )
    except (TransportError, OSError, asyncio.TimeoutError):
        return 0, False, 0
    # the whole client session is one span; its W3C context rides the
    # SyncStart frame (peer/mod.rs:1098-1101 inject)
    sp = span("sync.client", peer=peer.addr)
    sp.__enter__()
    try:
        await asyncio.wait_for(
            stream.send(
                encode_bi_payload_sync_start(
                    agent.actor_id,
                    trace=SyncTraceContext(traceparent=sp.ctx.traceparent()),
                    cluster_id=agent.cluster_id,
                )
            ),
            SEND_TIMEOUT,
        )
        await asyncio.wait_for(
            stream.send(encode_sync_msg(agent.clock.new_timestamp())),
            SEND_TIMEOUT,
        )

        theirs: Optional[SyncState] = None
        while theirs is None:
            frame = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
            if frame is None:
                return 0, True, 0
            msg = decode_sync_msg(frame)
            if isinstance(msg, Timestamp):
                agent.clock.update_with_timestamp(msg)
            elif isinstance(msg, SyncRejection):
                METRICS.counter("corro.sync.client.rejected").inc()
                return 0, True, 0
            elif isinstance(msg, SyncState):
                theirs = msg

        needs = compute_available_needs(ours, theirs)
        # claim ranges not already requested from another peer
        request = await ledger.claim(needs, out)

        # round-robin the claimed needs in ≤MAX_NEEDS_PER_TURN batches
        flat: List[Tuple[ActorId, object]] = [
            (aid, n) for aid, ns in request for n in ns
        ]
        for i in range(0, len(flat), MAX_NEEDS_PER_TURN):
            turn = flat[i : i + MAX_NEEDS_PER_TURN]
            grouped: Dict[ActorId, List[object]] = {}
            for aid, n in turn:
                grouped.setdefault(aid, []).append(n)
            await asyncio.wait_for(
                stream.send(encode_sync_msg(list(grouped.items()))),
                SEND_TIMEOUT,
            )
        await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)

        while True:
            frame = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
            if frame is None:
                break
            msg = decode_sync_msg(frame)
            if isinstance(msg, Timestamp):
                agent.clock.update_with_timestamp(msg)
            elif isinstance(msg, ChangeV1):
                # EmptySets from third parties are rejected
                # (peer/mod.rs:1429-1432)
                if (
                    isinstance(msg.changeset, ChangesetEmptySet)
                    and msg.actor_id != peer.id
                ):
                    continue
                await agent.tx_changes.send((msg, ChangeSource.SYNC))
                out.observe(msg)
                cs = msg.changeset
                received += len(getattr(cs, "changes", ()))
        METRICS.counter("corro.sync.client.changes.received").inc(received)
        return received, True, 0
    except (asyncio.TimeoutError, TransportError, ValueError, OSError):
        released = await ledger.release(out)
        METRICS.counter("corro.sync.client.changes.received").inc(received)
        return received, False, released
    finally:
        sp.__exit__(None, None, None)
        stream.close()


# -- scheduler -------------------------------------------------------------


def choose_sync_peers(agent: Agent, rng: random.Random) -> List[Actor]:
    """clamp(members/100, min, max) peers, sampled 2×, sorted by
    (freshest-advertised-heads, oldest-last-sync, lowest RTT ring)
    (handlers.rs:811-866).

    r17: the uniform-random pick was why a repair could take ~n rounds
    in a mostly-can't-serve population (the r12 test_bridge note —
    virtual kernel peers close bi-streams): peers whose observatory
    digest advertises the most held versions (`heads_total`) sort
    first, so the node most likely to HAVE what we need is asked first.
    With no digests known the sort degrades to the old random-sample
    ordering.  Circuit-open peers are DEPRIORITIZED, never excluded:
    in a small cluster every candidate still gets picked (anti-entropy
    must keep probing through a flap — the repair race against the
    broadcast plane is tight), while at scale an open breaker stops
    burning one of the few want-slots on a dead node."""
    perf = agent.config.perf
    now = time.monotonic()
    candidates = [
        info
        for aid, info in agent.members.states.items()
        if aid != agent.actor_id
    ]
    if not candidates:
        return []
    want = max(
        perf.sync_peers_min,
        min(perf.sync_peers_max, len(candidates) // 100),
    )
    sample = rng.sample(candidates, min(len(candidates), want * 2))
    heads: Dict[bytes, int] = {}
    if agent.observatory is not None:
        heads = agent.observatory.advertised_heads()
    if heads:
        sample.sort(
            key=lambda info: (
                0 if _circuit_allows(agent, info.actor.id, now) else 1,
                -heads.get(info.actor.id.bytes16, -1),
                info.last_sync_ts or 0,
                info.ring if info.ring is not None else 99,
            )
        )
    else:
        sample.sort(
            key=lambda info: (
                0 if _circuit_allows(agent, info.actor.id, now) else 1,
                info.last_sync_ts or 0,
                info.ring if info.ring is not None else 99,
            )
        )
    chosen = sample[:want]
    for info in sample[want:]:
        if not _circuit_allows(agent, info.actor.id, now):
            METRICS.counter("corro.sync.circuit.skipped.total").inc()
    return [info.actor for info in chosen]


async def targeted_sync(
    agent: Agent, timeout: float = 30.0,
    rng: Optional[random.Random] = None,
) -> int:
    """One immediate anti-entropy round OUTSIDE the sync_loop cadence —
    the r22 view-divergence actuator (agent/remediation.py).  The
    steady loop backs off toward `sync_interval_max_secs` exactly when
    nothing has been arriving — i.e. exactly when a divergence episode
    opens — so a firing alert would otherwise wait out the whole
    backoff before the next repair attempt.  Same peer choice (digest-
    freshest first, circuits deprioritized) and the same resumable
    `parallel_sync`; bounded by `timeout` so a wedged round degrades to
    a counted zero instead of pinning the supervisor.  Returns changes
    received."""
    peers = choose_sync_peers(agent, rng or random.Random())
    if not peers:
        return 0
    try:
        received = await asyncio.wait_for(
            parallel_sync(agent, peers), timeout
        )
    except asyncio.TimeoutError:
        received = 0
    METRICS.counter("corro.sync.targeted.rounds.total").inc()
    return received


async def sync_loop(agent: Agent, rng: Optional[random.Random] = None) -> None:
    """Periodic anti-entropy with exponential backoff 1–15 s
    (agent/util.rs:359-405)."""
    perf = agent.config.perf
    rng = rng or random.Random()
    interval = perf.sync_interval_min_secs
    while not agent.tripwire.tripped:
        await asyncio.sleep(interval)
        if agent.tripwire.tripped:
            break
        peers = choose_sync_peers(agent, rng)
        if not peers:
            interval = min(interval * 2, perf.sync_interval_max_secs)
            continue
        # r17 cold-gap check: a node far enough behind bootstraps from a
        # peer snapshot FIRST, then the same round's delta sync tops up
        # from the watermark (agent/catchup.py; never raises)
        from corrosion_tpu.agent.catchup import maybe_snapshot_bootstrap

        installed = await maybe_snapshot_bootstrap(agent, peers)
        start = time.monotonic()
        try:
            if installed and agent.catchup_census.get("traceparent"):
                # r19: the watermark top-up continues the bootstrap's
                # root trace — snapshot fetch + serve + install + delta
                # top-up read as ONE trace on the collector
                with continue_from(
                    agent.catchup_census["traceparent"], "catchup.topup"
                ):
                    received = await asyncio.wait_for(
                        parallel_sync(agent, peers), 300
                    )
            else:
                received = await asyncio.wait_for(
                    parallel_sync(agent, peers), 300
                )
        except asyncio.TimeoutError:
            received = 0
        elapsed = max(time.monotonic() - start, 1e-9)
        if received:
            from corrosion_tpu.runtime.invariants import assert_sometimes

            # ref assert_sometimes "Corrosion syncs with other nodes"
            # (handlers.rs:840)
            assert_sometimes("syncs with other nodes")
        METRICS.counter("corro.sync.client.rounds").inc()
        METRICS.histogram("corro.sync.client.round.seconds").observe(elapsed)
        METRICS.histogram("corro.sync.client.changes_per_sec").observe(
            received / elapsed
        )
        if received:
            interval = perf.sync_interval_min_secs
        else:
            interval = min(interval * 2, perf.sync_interval_max_secs)

"""Epidemic broadcast dissemination loop.

Counterpart of `klukai-agent/src/broadcast/mod.rs:410-812`: batches
`AddBroadcast` (fresh local changes) and `Rebroadcast` inputs on a 500 ms
/ 64 KiB cadence; ring-0 members (median RTT < 6 ms) receive local
changes first, everyone else is reached by random infection-style fanout
`max(num_indirect_probes, (members - ring0)/(max_transmissions*10))`;
items re-queue with a linearly growing delay until `max_transmissions`;
a global 10 MiB/s token bucket rate-limits egress and halves the fanout
while saturated; the most-sent items are dropped once the pending queue
exceeds `processing_queue_len`.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from dataclasses import dataclass, field
from typing import List, Optional, Set, Tuple

from corrosion_tpu.agent.handle import Agent, BroadcastInput
from corrosion_tpu.net.transport import TransportError
from corrosion_tpu.runtime.channels import ChannelClosed
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.actor import Actor
from corrosion_tpu.types.change import ChangesetFull
from corrosion_tpu.types.codec import (
    chunked_change_v1,
    encode_uni_from_prefix,
    encode_uni_prefix,
)

# r18 timeout discipline: bound on one uni-stream dispatch (dial + write
# of a ≤64 KiB payload) — generous for a healthy peer, finite for a
# zombie whose kernel accepts while its event loop never drains
SEND_TIMEOUT = 30.0


class TokenBucket:
    """10 MiB/s egress limiter (governor at broadcast/mod.rs:460-463)."""

    def __init__(self, rate_bytes_per_s: float, burst: Optional[float] = None):
        self.rate = rate_bytes_per_s
        self.capacity = burst or rate_bytes_per_s
        self.tokens = self.capacity
        self.last = time.monotonic()

    def try_take(self, n: int) -> bool:
        now = time.monotonic()
        self.tokens = min(self.capacity, self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


@dataclass(order=True)
class _Pending:
    due: float
    seq: int  # tiebreaker
    # encode-once (r14): `payload` is the digest-free bytes, shared by
    # every re-transmission; `prefix` (header + body + cluster id) is
    # what a per-transmission digest ext gets appended to — the
    # changeset body itself is never re-encoded after commit/decode
    payload: bytes = field(compare=False)
    prefix: bytes = field(compare=False)
    origin: bytes = field(compare=False)  # actor id bytes to exclude
    send_count: int = field(compare=False, default=0)
    # origin commit wall clock (r11 latency plane): stamps the
    # commit→wire hop when the FIRST transmission happens
    origin_wall: Optional[float] = field(compare=False, default=None)
    # envelope-ext stamps re-written per transmission with the digest
    ext_origin_ts: Optional[float] = field(compare=False, default=None)
    ext_traceparent: Optional[str] = field(compare=False, default=None)
    ext_trace_meta: Optional[int] = field(compare=False, default=None)


async def broadcast_loop(agent: Agent) -> None:
    perf = agent.config.perf
    bucket = TokenBucket(perf.broadcast_rate_limit_bytes)
    pending: List[_Pending] = []  # heap by due time
    seq = 0
    interval = perf.broadcast_interval_ms / 1000.0

    while not agent.tripwire.tripped:
        # gather inputs for up to one interval or until the byte cutoff
        batch: List[BroadcastInput] = []
        batch_bytes = 0
        deadline = time.monotonic() + interval
        while batch_bytes < perf.broadcast_cutoff_bytes:
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                break
            try:
                item = await asyncio.wait_for(agent.rx_bcast.recv(), timeout)
            except asyncio.TimeoutError:
                break
            except ChannelClosed:
                return
            batch.append(item)
            # r21: a local commit (or decoded relay) arrives with its
            # chunk body already stamped — the cutoff accounting reads
            # ONE cached length instead of re-walking every change's
            # field sizes; only a body-less changeset (hand-built in
            # tests) still pays the per-change estimate
            wb = item.change.wire_body
            if wb is not None:
                batch_bytes += len(wb)
            else:
                cs = item.change.changeset
                batch_bytes += sum(
                    c.estimated_byte_size()
                    for c in getattr(cs, "changes", ())
                )

        now = time.monotonic()
        for item in batch:
            # r16 broadcast chunking: an oversize changeset (one that
            # could never pass the token bucket and was previously
            # DROPPED whole) is split into bucket-sized partials whose
            # bodies splice the cached wire_cell bytes — receivers
            # buffer the seq sub-ranges and apply when they close; the
            # common small payload takes the unchanged single-frame path
            for cv in _fit_to_bucket(item.change, bucket.capacity):
                # encode-once: the body bytes were stamped at commit
                # (local), captured at decode (relay), or spliced by the
                # chunker — this wraps, never re-walks
                prefix = encode_uni_prefix(cv, agent.cluster_id)
                seq += 1
                heapq.heappush(
                    pending,
                    _Pending(
                        due=now,
                        seq=seq,
                        payload=encode_uni_from_prefix(
                            prefix, cv.origin_ts, cv.traceparent,
                            trace_meta=cv.trace_meta,
                        ),
                        prefix=prefix,
                        origin=cv.actor_id.bytes16,
                        send_count=0,
                        # only the ORIGIN node's own fresh changes stamp
                        # the commit→wire hop; relayed changes already
                        # counted theirs at their origin
                        origin_wall=(
                            cv.origin_ts if item.is_local else None
                        ),
                        ext_origin_ts=cv.origin_ts,
                        ext_traceparent=cv.traceparent,
                        ext_trace_meta=cv.trace_meta,
                    ),
                )

        # transmit everything due
        max_tx = agent.membership.config.max_transmissions(
            max(1, len(agent.members) + 1)
        )
        requeue: List[_Pending] = []
        while pending and pending[0].due <= now:
            p = heapq.heappop(pending)
            limited = await _transmit(agent, bucket, p)
            p.send_count += 1
            if p.send_count < max_tx:
                # decaying resend: 100–500 ms × count (mod.rs:759-775)
                delay = min(0.5, 0.1 * p.send_count) * p.send_count
                p.due = now + max(0.1, delay)
                requeue.append(p)
            if limited:
                METRICS.counter("corro.broadcast.rate_limited").inc()
        for p in requeue:
            heapq.heappush(pending, p)
        METRICS.gauge("corro.broadcast.pending.count").set(len(pending))
        METRICS.gauge("corro.broadcast.limiter.remaining_burst").set(
            bucket.tokens
        )

        # overflow: drop the most-sent items first (mod.rs:793-812)
        if len(pending) > perf.max_inflight_broadcasts:
            pending.sort(key=lambda p: p.send_count)
            dropped = len(pending) - perf.max_inflight_broadcasts
            del pending[perf.max_inflight_broadcasts :]
            heapq.heapify(pending)
            METRICS.counter("corro.broadcast.dropped").inc(dropped)


def _fit_to_bucket(cv, capacity: float):
    """Split a ChangeV1 whose body can never pass the egress token
    bucket into partial-changeset chunks (spliced from cached cell
    bytes, types/codec.py `chunked_change_v1`).  Anything that fits —
    or is irreducible (not a multi-change full set) — passes through
    unchanged and keeps the byte-identical r14 path."""
    cs = cv.changeset
    body_len = (
        len(cv.wire_body)
        if cv.wire_body is not None
        else sum(
            c.estimated_byte_size() for c in getattr(cs, "changes", ())
        )
    )
    # ~14 bytes of uni header/cluster-id plus the envelope ext ride on
    # top of the body; half-capacity chunks leave slack for both and
    # for the estimator's undershoot
    if body_len + 64 <= capacity or not isinstance(cs, ChangesetFull):
        return (cv,)
    if len(cs.changes) < 2:
        return (cv,)  # irreducible: the oversized-drop counter handles it
    chunks = chunked_change_v1(
        cv.actor_id, cs.version, cs.changes, cs.last_seq, cs.ts,
        origin_ts=cv.origin_ts, traceparent=cv.traceparent,
        max_bytes=max(1, int(capacity) // 2), seq_range=cs.seqs,
        trace_meta=cv.trace_meta,
    )
    METRICS.counter("corro.broadcast.chunked.total").inc(len(chunks))
    return chunks


async def _transmit(agent: Agent, bucket: TokenBucket, p: _Pending) -> bool:
    """Send one payload to its chosen targets; True if rate-limited."""
    exclude = {agent.actor_id}
    members = agent.members
    cfg = agent.membership.config
    limited = False
    # r12/r14: offer the envelope ext to the observatory PER
    # TRANSMISSION — a digest (own or relayed) piggybacks the broadcast
    # plane the same way it rides gossip datagrams, appended to the
    # shared prefix so the changeset body is never re-encoded; uni
    # frames have no packet budget, so any digest size fits
    digest = (
        agent.observatory.pick_ext(1 << 20, plane="broadcast")
        if agent.observatory is not None
        else None
    )
    payload = (
        p.payload
        if digest is None
        else encode_uni_from_prefix(
            p.prefix, p.ext_origin_ts, p.ext_traceparent, digest,
            p.ext_trace_meta,
        )
    )
    if len(payload) > bucket.capacity:
        # can never pass the bucket: drop instead of spinning forever
        METRICS.counter("corro.broadcast.oversized.dropped").inc()
        return False

    targets: List[Actor] = []
    if p.send_count == 0 and p.origin_wall is not None:
        # commit→wire: broadcast batching + queue delay at the origin
        from corrosion_tpu.runtime.latency import e2e_observe

        delta = e2e_observe("broadcast", time.time() - p.origin_wall)
        if p.ext_traceparent is not None:
            # r19: the same hop as a stage span on the write's trace
            from corrosion_tpu.runtime.trace import meta_forced, stage_span

            stage_span(
                p.ext_traceparent, "broadcast.send", "broadcast", delta,
                forced=meta_forced(p.ext_trace_meta),
                actor=str(agent.actor_id),
            )
    if p.send_count == 0:
        # ring0 gets first-transmission priority (mod.rs:591-651)
        targets.extend(
            a for a in members.ring0(exclude) if a.id.bytes16 != p.origin
        )
    others = [
        a
        for a in (members.not_ring0(exclude) if p.send_count == 0 else members.all_actors())
        if a.id.bytes16 != p.origin and a.id not in exclude
    ]
    n_members = len(members)
    fanout = max(
        cfg.num_indirect_probes,
        (n_members - len(targets)) // (cfg.max_transmissions(n_members + 1) * 10),
    )
    agent.membership.rng.shuffle(others)
    targets.extend(others[:fanout])

    i = 0
    while i < len(targets):
        if not bucket.try_take(len(payload)):
            # halve remaining fanout under rate pressure (mod.rs:668-671)
            limited = True
            remaining = targets[i:]
            targets = targets[:i] + remaining[: max(1, len(remaining) // 2)]
            await asyncio.sleep(0.01)  # let the bucket refill a little
            continue
        await _send_one(agent, targets[i], payload)
        i += 1
    return limited


async def _send_one(agent: Agent, actor: Actor, payload: bytes) -> None:
    try:
        # r18 timeout discipline: a peer whose kernel accepts the dial
        # but whose loop is stalled (zombie) must cost a counted failed
        # send, never wedge the broadcast loop behind one uni stream
        await asyncio.wait_for(
            agent.transport.send_uni(actor.addr, payload), SEND_TIMEOUT
        )
        METRICS.counter("corro.broadcast.sent").inc()
        from corrosion_tpu.runtime.invariants import assert_sometimes

        # ref assert_sometimes "changes broadcast" (broadcast.rs:642)
        assert_sometimes("changes broadcast")
    except (TransportError, asyncio.TimeoutError):
        METRICS.counter("corro.broadcast.send.failed").inc()

"""Admin socket: JSON-framed command protocol over a unix domain socket.

Counterpart of the reference's admin UDS server (`klukai/src/admin.rs:217-780`):
a LengthDelimited+JSON protocol whose Command enum covers cluster
introspection and repair. Frames here are 4-byte big-endian length + JSON.

Commands (JSON objects):
  {"cmd": "ping"}
  {"cmd": "sync", "sub": "generate"}            — debug dump of generate_sync
  {"cmd": "sync", "sub": "reconcile-gaps"}      — rebuild gap bookkeeping
  {"cmd": "locks", "top": N}                    — longest-held live locks
  {"cmd": "cluster", "sub": "members"}
  {"cmd": "cluster", "sub": "membership-states"}
  {"cmd": "cluster", "sub": "rejoin"}
  {"cmd": "cluster", "sub": "set-id", "cluster_id": N}
  {"cmd": "actor", "sub": "version", "actor_id": hex, "version": N}
  {"cmd": "subs", "sub": "list"}
  {"cmd": "subs", "sub": "info", "id"|"hash": ...}
  {"cmd": "log", "sub": "set", "filter": "name=LEVEL,..."}
  {"cmd": "log", "sub": "reset"}

Responses stream until a terminal one:
  {"kind": "log", "msg": ...}    (zero or more)
  {"kind": "json", "value": ...} (zero or more)
  {"kind": "success"} | {"kind": "error", "msg": ...}
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import struct
from typing import Any, AsyncIterator, Dict, List, Optional

from corrosion_tpu.sync import generate_sync
from corrosion_tpu.types.actor import ActorId, ClusterId

log = logging.getLogger(__name__)

_MAX_FRAME = 16 * 1024 * 1024


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = struct.unpack(">I", hdr)
    if n > _MAX_FRAME:
        raise ValueError(f"admin frame too large: {n}")
    body = await reader.readexactly(n)
    return json.loads(body)


def write_frame(writer: asyncio.StreamWriter, obj: dict) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode()
    writer.write(struct.pack(">I", len(body)) + body)


class AdminServer:
    """Serves admin commands for a running Agent on a unix socket."""

    def __init__(self, agent, path: str):
        self.agent = agent
        self.path = path
        self._server: Optional[asyncio.AbstractServer] = None
        # remembered root level for `log reset`
        self._log_baseline = logging.getLogger().level

    async def start(self) -> None:
        self._server = await asyncio.start_unix_server(
            self._handle_conn, path=self.path
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                cmd = await read_frame(reader)
                if cmd is None:
                    break
                try:
                    for resp in await self._dispatch(cmd):
                        write_frame(writer, resp)
                except Exception as e:  # any handler error → Error response
                    log.exception("admin command failed: %r", cmd)
                    write_frame(writer, {"kind": "error", "msg": str(e)})
                await writer.drain()
        finally:
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    async def _dispatch(self, cmd: dict) -> List[dict]:
        name = cmd.get("cmd")
        sub = cmd.get("sub")
        if name == "ping":
            return [{"kind": "json", "value": "pong"}, {"kind": "success"}]
        if name == "sync" and sub == "generate":
            # Store scans take the store lock and can be slow on a large
            # db; keep them off the agent's event loop so gossip timers
            # and HTTP streams don't stall for the duration.
            return await asyncio.to_thread(self._sync_generate)
        if name == "sync" and sub == "reconcile-gaps":
            return await asyncio.to_thread(self._reconcile_gaps)
        if name == "locks":
            return self._locks(cmd.get("top"))
        if name == "cluster" and sub == "members":
            return self._cluster_members()
        if name == "cluster" and sub == "membership-states":
            return self._membership_states()
        if name == "cluster" and sub == "rejoin":
            actor = await self.agent.membership.rejoin()
            return [
                {"kind": "log", "msg": f"rejoined as {actor.id}"},
                {"kind": "success"},
            ]
        if name == "cluster" and sub == "set-id":
            cid = ClusterId(int(cmd["cluster_id"]))
            actor = await self.agent.membership.change_cluster_id(cid)
            self.agent.actor = actor
            return [
                {"kind": "log", "msg": f"cluster id set to {cid.value}"},
                {"kind": "success"},
            ]
        if name == "actor" and sub == "version":
            return self._actor_version(
                cmd["actor_id"], int(cmd["version"])
            )
        if name == "subs" and sub == "list":
            return self._subs_list()
        if name == "subs" and sub == "info":
            return self._subs_info(cmd.get("id"), cmd.get("hash"))
        if name == "log" and sub == "set":
            return self._log_set(cmd["filter"])
        if name == "log" and sub == "reset":
            return self._log_reset()
        return [{"kind": "error", "msg": f"unknown command: {cmd}"}]

    # -- handlers ----------------------------------------------------------

    def _sync_generate(self) -> List[dict]:
        state = generate_sync(self.agent.bookie, self.agent.actor_id)
        value = {
            "actor_id": str(state.actor_id),
            "heads": {str(a): h for a, h in state.heads.items()},
            "need": {
                str(a): [list(r) for r in rs] for a, rs in state.need.items()
            },
            "partial_need": {
                str(a): {
                    str(v): [list(r) for r in rs] for v, rs in vs.items()
                }
                for a, vs in state.partial_need.items()
            },
        }
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _reconcile_gaps(self) -> List[dict]:
        """Drop gap claims disproved by the clock tables — versions the gap
        bookkeeping says are missing but whose changes are actually present
        (admin.rs Command::ReconcileGaps — the repair tool). Conservative:
        never *adds* gaps, since overwritten ("cleared") versions
        legitimately leave no clock rows."""
        out: List[dict] = []
        fixed = 0
        for aid in self.agent.store.booked_actor_ids():
            present = self.agent.store.present_versions(aid)
            booked = self.agent.bookie.ensure(aid)
            with booked.write("reconcile") as bv:
                before = list(bv.needed)
                for s, e in present:
                    bv.needed.remove(s, e)
                after = list(bv.needed)
                if before != after:
                    fixed += 1
                    self.agent.store.rewrite_gaps(aid, bv.needed)
                    out.append(
                        {
                            "kind": "log",
                            "msg": f"actor {aid}: gaps {before} -> {after}",
                        }
                    )
        out.append({"kind": "json", "value": {"actors_fixed": fixed}})
        out.append({"kind": "success"})
        return out

    def _locks(self, top: Optional[int]) -> List[dict]:
        registry = getattr(self.agent, "lock_registry", None)
        snap = registry.snapshot(top) if registry is not None else []
        value = [
            {
                "id": m.id,
                "label": m.label,
                "kind": m.kind,
                "state": m.state,
                "held_s": round(m.held_for(), 3),
            }
            for m in snap
        ]
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _cluster_members(self) -> List[dict]:
        value = []
        for actor in self.agent.members.all_actors():
            info = self.agent.members.get(actor.id)
            rtts = self.agent.members.rtts.get(actor.addr)
            value.append(
                {
                    "id": str(actor.id),
                    "addr": actor.addr,
                    "cluster_id": actor.cluster_id.value,
                    "ring": getattr(info, "ring", None),
                    "rtt_min_ms": round(min(rtts) * 1000, 3) if rtts else None,
                }
            )
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _membership_states(self) -> List[dict]:
        ms = self.agent.membership
        value = [
            {
                "id": str(m.actor.id),
                "addr": m.actor.addr,
                "state": m.state.name,
                "incarnation": m.incarnation,
            }
            for m in ms.members.values()
        ]
        value.append(
            {
                "id": str(ms.identity.id),
                "addr": ms.identity.addr,
                "state": "ALIVE",
                "incarnation": ms._incarnation,
                "self": True,
            }
        )
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _actor_version(self, actor_hex: str, version: int) -> List[dict]:
        aid = ActorId.from_uuid_str(actor_hex)
        booked = self.agent.bookie.get(aid)
        if booked is None:
            return [{"kind": "error", "msg": f"unknown actor {actor_hex}"}]
        with booked.read() as bv:
            if bv.contains_version(version):
                partial = bv.get_partial(version)
                if partial is not None and not partial.is_complete():
                    value: Any = {
                        "state": "partial",
                        "seqs": [list(r) for r in partial.gaps()],
                    }
                else:
                    value = {"state": "current"}
            else:
                value = {"state": "unknown"}
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _subs_list(self) -> List[dict]:
        subs = self.agent.subs
        value = []
        if subs is not None:
            for handle in subs.handles():
                value.append(
                    {
                        "id": handle.id,
                        "hash": handle.hash,
                        "sql": handle.sql,
                        "subscribers": handle.subscriber_count,
                        "last_change_id": handle.last_change_id,
                    }
                )
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _subs_info(
        self, sub_id: Optional[str], sql_hash: Optional[str]
    ) -> List[dict]:
        subs = self.agent.subs
        handle = None
        if subs is not None:
            if sub_id is not None:
                handle = subs.get(sub_id)
            elif sql_hash is not None:
                for h in subs.handles():
                    if h.hash == sql_hash:
                        handle = h
                        break
        if handle is None:
            return [{"kind": "error", "msg": "unknown subscription"}]
        value = {
            "id": handle.id,
            "hash": handle.hash,
            "sql": handle.sql,
            "columns": handle.columns,
            "subscribers": handle.subscriber_count,
            "last_change_id": handle.last_change_id,
            "processed": handle.processed,
            "created_at": handle.created_at,
            "error": handle.error,
        }
        return [{"kind": "json", "value": value}, {"kind": "success"}]

    def _log_set(self, filter_spec: str) -> List[dict]:
        """Dynamic log-filter reload (admin.rs:215 TracingHandle). Spec:
        "LEVEL" for root or "logger=LEVEL,logger2=LEVEL2"."""
        for part in filter_spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                name, _, level = part.partition("=")
                logging.getLogger(name.strip()).setLevel(
                    level.strip().upper()
                )
            else:
                logging.getLogger().setLevel(part.upper())
        return [
            {"kind": "log", "msg": f"log filter set: {filter_spec}"},
            {"kind": "success"},
        ]

    def _log_reset(self) -> List[dict]:
        root = logging.getLogger()
        root.setLevel(self._log_baseline)
        # drop per-module overrides
        for name in list(logging.Logger.manager.loggerDict):
            if name.startswith("corrosion_tpu"):
                logging.getLogger(name).setLevel(logging.NOTSET)
        return [{"kind": "log", "msg": "log filter reset"}, {"kind": "success"}]


class AdminClient:
    """Client side of the admin protocol (used by the CLI)."""

    def __init__(self, path: str):
        self.path = path
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def __aenter__(self) -> "AdminClient":
        self._reader, self._writer = await asyncio.open_unix_connection(
            self.path
        )
        return self

    async def __aexit__(self, *exc) -> None:
        if self._writer is not None:
            self._writer.close()
            with contextlib.suppress(Exception):
                await self._writer.wait_closed()

    async def send(self, cmd: dict) -> AsyncIterator[dict]:
        assert self._reader is not None and self._writer is not None
        write_frame(self._writer, cmd)
        await self._writer.drain()
        while True:
            resp = await read_frame(self._reader)
            if resp is None:
                raise ConnectionError("admin connection closed mid-response")
            yield resp
            if resp.get("kind") in ("success", "error"):
                break

    async def call(self, cmd: dict) -> Dict[str, Any]:
        """Collect a full response: {'ok': bool, 'json': [...], 'logs': [...]}"""
        logs: List[str] = []
        values: List[Any] = []
        ok = False
        err: Optional[str] = None
        async for resp in self.send(cmd):
            kind = resp.get("kind")
            if kind == "log":
                logs.append(resp["msg"])
            elif kind == "json":
                values.append(resp["value"])
            elif kind == "success":
                ok = True
            elif kind == "error":
                err = resp.get("msg")
        return {"ok": ok, "error": err, "json": values, "logs": logs}

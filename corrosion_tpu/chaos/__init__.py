"""Chaos engine (r18): production traffic simulator + multi-layer fault
injection.

The package is the adversary the observability planes (r11 /v1/slo,
r12 /v1/cluster) were built to grade: `faults` holds the store-layer
injector and the process-global chaos census, `scenarios` composes the
network/store/process knobs into named scenarios driven by a
`ChaosEngine`, and `workload` runs the mixed read/write/subscribe/render
traffic the scenario matrix measures under (`scripts/traffic_sim.py`
banks the matrix as TRAFFIC_SIM.json).
"""

from corrosion_tpu.chaos.faults import CENSUS, ChaosCensus, StoreFaults
from corrosion_tpu.chaos.scenarios import ChaosEngine, Injection, Scenario

__all__ = [
    "CENSUS",
    "ChaosCensus",
    "ChaosEngine",
    "Injection",
    "Scenario",
    "StoreFaults",
]

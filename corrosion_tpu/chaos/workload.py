"""Mixed-workload driver: the production traffic the chaos matrix
measures under.

Four concurrent stages against an N-agent devcluster, all through the
REAL serving surfaces (HTTP API + live subscription streams — never
store-handle shortcuts):

- ``write``  — small INSERT OR REPLACE transactions round-robin over
  the nodes (`/v1/transactions`)
- ``query``  — point SELECTs against random nodes (`/v1/queries`)
- ``subscribe`` — one live subscription per node, counting delivered
  change events (`/v1/subscriptions`; sheds resume via the client's
  changes-log replay)
- ``render`` — template renders (`tpl.py` engine) whose `sql()` calls
  ride `/v1/queries`

Every op runs under a DEADLINE (`op_timeout_secs`): the accounting
distinguishes the four ways a production request can end —

  ok        the cluster served it
  refusal   a TYPED fast no (4xx/503 admission, shed frame): the
            serving plane answered; Prime CCL-style degradation
  error     a fast transport failure (connection refused/reset): a
            node is down, the caller knows immediately
  timeout   the op hit its deadline — the HANG WITNESS.  The scenario
            matrix's standing bar is timeouts == 0: faults may shrink
            `ok`, they must never convert requests into stalls.

``availability`` = (ok + refusals) / attempts — the fraction of
requests the serving plane ANSWERED (a typed shed is an answer; a
hang or dead socket is not).

Client-side op latencies land in `runtime/latency.py` histograms
(p50/p99 per stage); the cluster's own verdict is scraped from the
`/v1/slo` and `/v1/cluster` planes at collection time — the point of
the r11/r12 observatories is that the cluster grades its own scorecard.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import aiohttp

from corrosion_tpu.client import (
    ClientError,
    CorrosionApiClient,
    SubShedError,
)
from corrosion_tpu.net.h2 import StreamReset
from corrosion_tpu.runtime.latency import LatencyHistogram

# transport-level failure set every stage shares: fast, typed-ish,
# retry-able — a downed node's refused connection lands here
_TRANSPORT_ERRORS = (
    aiohttp.ClientError,
    StreamReset,
    ConnectionError,
    OSError,
)

RENDER_TEMPLATE = (
    '<% for row in sql("SELECT id, text FROM tests '
    'ORDER BY id DESC LIMIT 5") %><%= row[0] %>=<%= row[1] %>\n<% end %>'
)


@dataclass
class StageStats:
    attempts: int = 0
    ok: int = 0
    refusals: int = 0
    errors: int = 0
    timeouts: int = 0
    hist: LatencyHistogram = field(default_factory=LatencyHistogram)

    def record(self, outcome: str, secs: Optional[float] = None) -> None:
        self.attempts += 1
        setattr(self, outcome, getattr(self, outcome) + 1)
        if secs is not None and outcome == "ok":
            self.hist.observe(secs)

    @property
    def availability(self) -> float:
        if self.attempts == 0:
            return 1.0
        return (self.ok + self.refusals) / self.attempts

    def report(self) -> dict:
        return {
            "attempts": self.attempts,
            "ok": self.ok,
            "refusals": self.refusals,
            "errors": self.errors,
            "timeouts": self.timeouts,
            "availability": round(self.availability, 4),
            "p50_secs": self.hist.quantile(0.5),
            "p99_secs": self.hist.quantile(0.99),
        }


@dataclass
class WorkloadNode:
    """One target: the agent handle plus its HTTP surface."""

    name: str
    agent: object
    client: CorrosionApiClient
    api_addr: str


class MixedWorkload:
    """Drives all four stages until `stop()`; `summary()` collects the
    client-side stats plus the cluster's own /v1/slo + /v1/cluster
    verdicts.

    `nodes` is a live callable (not a frozen dict): churn scenarios
    restart agents mid-run and the driver must always target the
    harness's CURRENT node set."""

    def __init__(
        self,
        nodes: Callable[[], Dict[str, WorkloadNode]],
        op_timeout_secs: float = 5.0,
        write_period_secs: float = 0.05,
        query_period_secs: float = 0.05,
        render_period_secs: float = 0.25,
        seed: int = 0,
        id_base: int = 0,
    ):
        self.nodes = nodes
        self.op_timeout = op_timeout_secs
        self.write_period = write_period_secs
        self.query_period = query_period_secs
        self.render_period = render_period_secs
        self.rng = random.Random(seed)
        self.stats: Dict[str, StageStats] = {
            s: StageStats() for s in ("write", "query", "subscribe", "render")
        }
        self.events_delivered = 0
        self._tasks: List[asyncio.Task] = []
        self._stopping = asyncio.Event()
        # each run must write FRESH pks: an INSERT OR REPLACE of an
        # identical (pk, value) is a CRDT no-op (no change emitted, no
        # event delivered) — back-to-back scenarios reusing ids would
        # silently zero the subscription stage
        self._next_id = id_base
        self._id_base = id_base
        self._template = None

    # -- one op per stage ---------------------------------------------------

    async def _op(self, stage: str, coro) -> bool:
        """Run one op under the deadline with the shared accounting."""
        st = self.stats[stage]
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(coro, self.op_timeout)
        except asyncio.TimeoutError:
            st.record("timeouts")
            return False
        except SubShedError:
            st.record("refusals")
            return False
        except ClientError as e:
            if 400 <= e.status < 600:
                st.record("refusals")
            else:
                st.record("errors")
            return False
        except _TRANSPORT_ERRORS:
            st.record("errors")
            return False
        st.record("ok", time.monotonic() - t0)
        return True

    def _pick(self) -> Optional[WorkloadNode]:
        nodes = list(self.nodes().values())
        return self.rng.choice(nodes) if nodes else None

    async def _write_loop(self) -> None:
        order = 0
        while not self._stopping.is_set():
            nodes = list(self.nodes().values())
            if nodes:
                node = nodes[order % len(nodes)]
                order += 1
                self._next_id += 1
                k = self._next_id
                await self._op(
                    "write",
                    node.client.execute(
                        [[
                            "INSERT OR REPLACE INTO tests (id, text)"
                            " VALUES (?, ?)",
                            [k, f"w-{node.name}-{k}"],
                        ]]
                    ),
                )
            await asyncio.sleep(self.write_period)

    async def _query_loop(self) -> None:
        while not self._stopping.is_set():
            node = self._pick()
            if node is not None:
                k = self.rng.randint(
                    self._id_base + 1, max(self._id_base + 1, self._next_id)
                )
                await self._op(
                    "query",
                    node.client.query_rows(
                        ["SELECT id, text FROM tests WHERE id = ?", [k]]
                    ),
                )
            await asyncio.sleep(self.query_period)

    async def _subscribe_loop(self, name: str) -> None:
        """One node's live subscription: (re)connect until stopped,
        count delivered change events.  A shed is a typed refusal; a
        transport death is an error; either way the loop reconnects —
        the stream must never wedge the driver."""
        st = self.stats["subscribe"]
        while not self._stopping.is_set():
            node = self.nodes().get(name)
            if node is None:
                await asyncio.sleep(0.1)
                continue
            st.attempts += 1
            t0 = time.monotonic()
            got_any = False
            try:
                stream = node.client.subscribe(
                    "SELECT id, text FROM tests", skip_rows=True
                )
                async for ev in stream:
                    if self._stopping.is_set():
                        break
                    if "change" in ev:
                        self.events_delivered += 1
                        if not got_any:
                            got_any = True
                            st.ok += 1
                            st.hist.observe(time.monotonic() - t0)
            except asyncio.CancelledError:
                if not got_any:
                    # harness shutdown before any event arrived: neither
                    # a success nor a failure — don't skew availability
                    st.attempts -= 1
                raise
            except SubShedError:
                st.refusals += 1
            except ClientError:
                st.refusals += 1
            except asyncio.TimeoutError:
                st.timeouts += 1
            except _TRANSPORT_ERRORS:
                st.errors += 1
            else:
                if not got_any:
                    # stream ended cleanly before any event: neither a
                    # success nor a failure — don't skew availability
                    st.attempts -= 1
            if not got_any and not self._stopping.is_set():
                await asyncio.sleep(0.2)

    async def _render_loop(self) -> None:
        from corrosion_tpu.tpl import TemplateState, compile_template

        if self._template is None:
            self._template = compile_template(RENDER_TEMPLATE)
        loop = asyncio.get_running_loop()
        while not self._stopping.is_set():
            node = self._pick()
            if node is not None:
                state = TemplateState(node.api_addr, None, loop, watch=False)

                async def render(s=state):
                    try:
                        out = await asyncio.to_thread(
                            self._template, s.namespace()
                        )
                        assert isinstance(out, str)
                    finally:
                        await s.close()

                await self._op("render", render())
            await asyncio.sleep(self.render_period)

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self._stopping.clear()
        self._tasks = [
            asyncio.ensure_future(self._write_loop()),
            asyncio.ensure_future(self._query_loop()),
            asyncio.ensure_future(self._render_loop()),
        ]
        for name in list(self.nodes()):
            self._tasks.append(
                asyncio.ensure_future(self._subscribe_loop(name))
            )

    async def stop(self) -> None:
        self._stopping.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._tasks.clear()

    # -- collection ---------------------------------------------------------

    async def scrape(self, node: WorkloadNode, path: str) -> Optional[dict]:
        """GET a JSON observability plane from one node (the cluster's
        own scorecard: /v1/slo, /v1/cluster, /v1/status)."""
        try:
            session = await node.client._ensure()
            async with session.get(f"{node.client.base}{path}") as resp:
                if resp.status != 200:
                    return None
                return json.loads(await resp.text())
        except _TRANSPORT_ERRORS + (asyncio.TimeoutError, ValueError):
            return None

    async def summary(self, scrape_node: Optional[WorkloadNode] = None) -> dict:
        out = {
            "stages": {s: st.report() for s, st in self.stats.items()},
            "events_delivered": self.events_delivered,
        }
        if scrape_node is not None:
            slo = await self.scrape(scrape_node, "/v1/slo")
            cluster = await self.scrape(scrape_node, "/v1/cluster")
            out["slo"] = _slo_percentiles(slo)
            out["cluster"] = _cluster_digestion(cluster)
        return out


def _slo_percentiles(slo: Optional[dict]) -> Optional[dict]:
    """Per-stage {p50, p99} out of one /v1/slo response (cumulative
    quantiles — scenario runs snapshot-diff at the harness level)."""
    if not slo:
        return None
    stages = {}
    for stage, rec in (slo.get("stages") or {}).items():
        cum = rec.get("cumulative") or {}
        stages[stage] = {
            "p50": cum.get("p50"),
            "p99": cum.get("p99"),
            "count": cum.get("count"),
        }
    return stages


def _cluster_digestion(cluster: Optional[dict]) -> Optional[dict]:
    if not cluster:
        return None
    div = cluster.get("divergence") or {}
    return {
        "nodes_known": (cluster.get("coverage") or {}).get("known"),
        "nodes_fresh": (cluster.get("coverage") or {}).get("fresh"),
        "divergent": div.get("divergent"),
        "view_groups": div.get("groups"),
    }

"""Composable chaos scenarios over the three fault layers.

An `Injection` is one revertible knob turn at one layer:

- NETWORK — `net/mem.py`'s knobs composed into shapes the single knobs
  can't express: geo-latency matrices (per-directed-link delay),
  asymmetric partitions (`partition_oneway` — the half-open link),
  flap storms (a driver task partitioning/healing on a beat).
- STORE — `chaos/faults.py::StoreFaults` profiles installed on a
  node's `CrdtStore` (slow disk: commit/apply latency; sick disk:
  transient SQLITE_BUSY + I/O errors).
- PROCESS — zombie nodes (`MemNetwork.zombie`: sockets open, event
  loop stalled, nothing ever answers) and kill/restart churn (driver
  task calling harness-supplied stop/start callables, so the restart
  rides the real r17 catch-up plane).

A `Scenario` is a named list of injections; the `ChaosEngine` applies
them, runs their driver tasks, and reverts everything on `restore()` —
registering each step in the process-global `CENSUS` so `/v1/status`
can tell an operator this is a drill.  Scenario shapes follow Potato
(arXiv:2308.12698) heterogeneous/slow-node scenarios and the Prime CCL
(arXiv:2505.14065) bar: every injection must DEGRADE the serving
plane, never deadlock or restart it.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.chaos.faults import CENSUS, StoreFaults

_inj_seq = itertools.count(1)


@dataclass
class Injection:
    """One revertible fault. `driver`, when set, is a coroutine factory
    run as a background task for the injection's lifetime (flap storms,
    churn loops); it is cancelled before `revert` runs."""

    layer: str  # "net" | "store" | "process"
    summary: str
    apply: Callable[[], None]
    revert: Callable[[], None]
    driver: Optional[Callable[[], Awaitable[None]]] = None
    inj_id: str = field(default_factory=lambda: f"inj-{next(_inj_seq)}")


@dataclass
class Scenario:
    scenario_id: str
    injections: List[Injection]


class ChaosEngine:
    """Applies/reverts one scenario at a time and owns its driver tasks.

    `restore()` is the recovery edge every scenario's SLO must return
    to baseline after — the engine guarantees every knob it turned is
    turned back, in reverse order, even when a driver task died."""

    def __init__(self) -> None:
        self._active: Optional[Scenario] = None
        self._tasks: List[asyncio.Task] = []

    @property
    def active(self) -> Optional[str]:
        return self._active.scenario_id if self._active else None

    async def apply(self, scenario: Scenario) -> None:
        if self._active is not None:
            raise RuntimeError(
                f"scenario {self._active.scenario_id!r} still active"
            )
        CENSUS.begin(scenario.scenario_id)
        self._active = scenario
        for inj in scenario.injections:
            inj.apply()
            CENSUS.add(inj.inj_id, f"[{inj.layer}] {inj.summary}", inj.layer)
            if inj.driver is not None:
                self._tasks.append(asyncio.ensure_future(inj.driver()))

    async def restore(self) -> None:
        if self._active is None:
            return
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._tasks.clear()
        for inj in reversed(self._active.injections):
            inj.revert()
            CENSUS.remove(inj.inj_id)
        self._active = None
        CENSUS.end()


# -- injection builders ------------------------------------------------------


def geo_latency(
    net, regions: Dict[str, str], matrix: Dict[Tuple[str, str], float]
) -> Injection:
    """Geo-latency matrix: `regions` maps node addr -> region label,
    `matrix` maps (region, region) -> one-way delay.  Intra-region
    pairs absent from the matrix stay at LAN speed."""

    def apply() -> None:
        for a, ra in regions.items():
            for b, rb in regions.items():
                if a == b:
                    continue
                delay = matrix.get((ra, rb), matrix.get((rb, ra), 0.0))
                if delay:
                    net.set_link_latency(a, b, delay, symmetric=False)

    def revert() -> None:
        net.clear_link_latency()

    return Injection(
        layer="net",
        summary=f"geo-latency matrix over {len(regions)} nodes",
        apply=apply,
        revert=revert,
    )


def asymmetric_partition(net, src: str, dsts: Sequence[str]) -> Injection:
    """Half-open link: `src`'s traffic toward each dst is dropped while
    the reverse direction still flows."""

    def apply() -> None:
        for d in dsts:
            net.partition_oneway(src, d)

    def revert() -> None:
        for d in dsts:
            net.heal(src, d)

    return Injection(
        layer="net",
        summary=f"asymmetric partition {src} -/-> {len(dsts)} peers",
        apply=apply,
        revert=revert,
    )


def flap_storm(
    net, a: str, b: str, period_secs: float = 0.5
) -> Injection:
    """Link flapping: the a<->b link partitions and heals on a beat —
    the pathology that used to synchronize rejoin storms (the r9
    full-jitter announcer fix exists because of it)."""

    async def drive() -> None:
        try:
            while True:
                net.partition(a, b)
                await asyncio.sleep(period_secs)
                net.heal(a, b)
                await asyncio.sleep(period_secs)
        finally:
            net.heal(a, b)

    return Injection(
        layer="net",
        summary=f"flap storm {a}<->{b} @ {period_secs}s",
        apply=lambda: None,
        revert=lambda: net.heal(a, b),
        driver=drive,
    )


def zombie_node(net, addr: str) -> Injection:
    """Process-layer zombie: event loop stalled, sockets open (see
    MemNetwork.zombie)."""

    return Injection(
        layer="process",
        summary=f"zombie {addr} (sockets open, loop stalled)",
        apply=lambda: net.zombie(addr),
        revert=lambda: net.restore(addr),
    )


def churn_storm(
    nodes: Sequence[str],
    stop: Callable[[str], Awaitable[None]],
    start: Callable[[str], Awaitable[None]],
    period_secs: float = 1.0,
) -> Injection:
    """Kill/restart churn: cycles through `nodes`, stopping one, waiting
    a beat, restarting it (through the harness's real boot path, so the
    rejoin rides the r17 catch-up plane), then the next.  The revert
    guarantee is that every node it stopped has been started again."""

    downed: List[str] = []

    async def drive() -> None:
        i = 0
        try:
            while True:
                node = nodes[i % len(nodes)]
                i += 1
                downed.append(node)
                await stop(node)
                await asyncio.sleep(period_secs)
                await start(node)
                downed.remove(node)
                await asyncio.sleep(period_secs)
        finally:
            # restore() cancels this driver mid-cycle: restart anything
            # still down so the revert edge leaves the cluster whole
            # (shielded — the restart must survive the cancellation)
            for node in list(downed):
                with contextlib.suppress(Exception):
                    await asyncio.shield(start(node))
                downed.remove(node)

    return Injection(
        layer="process",
        summary=f"churn storm over {len(nodes)} nodes @ {period_secs}s",
        apply=lambda: None,
        revert=lambda: None,
        driver=drive,
    )


def slow_disk(store, latency_secs: float = 0.05) -> Injection:
    """Slow disk: every commit and remote apply pays `latency_secs` of
    injected fsync time on the worker thread."""

    def apply() -> None:
        store.chaos = StoreFaults(
            commit_latency_secs=latency_secs,
            apply_latency_secs=latency_secs,
        )

    def revert() -> None:
        store.chaos = None

    return Injection(
        layer="store",
        summary=f"slow disk (+{latency_secs * 1000:.0f}ms commit/apply)",
        apply=apply,
        revert=revert,
    )


def sick_disk(
    store,
    busy_rate: float = 0.05,
    io_error_rate: float = 0.02,
    seed: int = 0,
) -> Injection:
    """Sick disk: transient SQLITE_BUSY per writer statement and disk
    I/O errors at COMMIT — the writers must fail typed and isolated,
    the store must stay writable."""

    def apply() -> None:
        store.chaos = StoreFaults(
            statement_busy_rate=busy_rate,
            commit_io_error_rate=io_error_rate,
            seed=seed,
        )

    def revert() -> None:
        store.chaos = None

    return Injection(
        layer="store",
        summary=(
            f"sick disk (busy {busy_rate:.0%}, io {io_error_rate:.0%})"
        ),
        apply=apply,
        revert=revert,
    )

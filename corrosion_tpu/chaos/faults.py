"""Store-layer fault injection + the process-global chaos census.

The network layer has had per-node fault knobs since r9
(`net/mem.py degrade()/partition()`); this module adds the layer no
bench had ever simulated — the DISK.  `StoreFaults` is an injectable
profile a `CrdtStore` consults at its three writer-thread touch points:

- per writer statement (`on_statement`): transient ``SQLITE_BUSY`` —
  the sick disk / lock-contention pathology.  Raised inside one
  writer's sub-transaction of a group commit, it must abort ONLY that
  writer (savepoint isolation) and leave the store writable.
- at COMMIT (`on_commit`): added fsync/commit latency (the slow disk)
  and a transient ``disk I/O error`` that aborts the whole shared
  transaction — the path every writer in the group must surface as a
  typed error, never a hang.
- at remote apply (`on_apply`): the same latency on the ingest path,
  so a slow-disk node lags the cluster instead of just its own clients.

Faults run ON the worker thread that owns the sqlite connection
(`time.sleep` is correct there), and the injector costs one attribute
check when absent (`store.chaos is None` — the default).

`ChaosCensus` is the operator's drill-vs-outage discriminator: the
`ChaosEngine` registers every active injection here and `/v1/status`
serves it, so a node reporting elevated p99s alongside a populated
chaos census is a drill, not a page.  Process-global like the flight
recorder (`runtime/records.FLIGHT`) — an in-process devcluster shares
one census, and a production deployment runs one agent per process.
"""

from __future__ import annotations

import random
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from corrosion_tpu.runtime.metrics import METRICS


@dataclass
class StoreFaults:
    """One node's injected disk pathology (all rates in [0, 1])."""

    commit_latency_secs: float = 0.0  # slow disk: added to every COMMIT
    statement_busy_rate: float = 0.0  # sick disk: SQLITE_BUSY per statement
    commit_io_error_rate: float = 0.0  # sick disk: I/O error at COMMIT
    apply_latency_secs: float = 0.0  # slow disk on the remote-apply path
    seed: int = 0
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        # own RNG: deterministic per scenario seed, and never contended
        # with the network layer's
        self._rng = random.Random(self.seed)

    def on_statement(self) -> None:
        """Writer-statement touch point (WriteTx.execute/executemany)."""
        if (
            self.statement_busy_rate
            and self._rng.random() < self.statement_busy_rate
        ):
            METRICS.counter(
                "corro.chaos.store.faults.total", kind="busy"
            ).inc()
            raise sqlite3.OperationalError(
                "database is locked [chaos-injected]"
            )

    def on_commit(self) -> None:
        """COMMIT touch point (group_tx leader / solo WriteTx.commit)."""
        if self.commit_latency_secs:
            METRICS.counter(
                "corro.chaos.store.faults.total", kind="latency"
            ).inc()
            time.sleep(self.commit_latency_secs)
        if (
            self.commit_io_error_rate
            and self._rng.random() < self.commit_io_error_rate
        ):
            METRICS.counter("corro.chaos.store.faults.total", kind="io").inc()
            raise sqlite3.OperationalError("disk I/O error [chaos-injected]")

    def on_apply(self) -> None:
        """Remote-apply touch point (CrdtStore.apply_changes)."""
        if self.apply_latency_secs:
            METRICS.counter(
                "corro.chaos.store.faults.total", kind="apply"
            ).inc()
            time.sleep(self.apply_latency_secs)


class ChaosCensus:
    """Active-injection registry behind /v1/status's ``chaos`` block.

    Thread contract: mutated by the ChaosEngine (event loop) and by
    scenario driver tasks; read by HTTP handlers and worker threads —
    every access is under the lock and reads return copies."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scenario: Optional[str] = None
        self._injections: Dict[str, str] = {}  # injection id -> summary
        self._since: Optional[float] = None

    def begin(self, scenario: str) -> None:
        with self._lock:
            self._scenario = scenario
            self._since = time.time()

    def add(self, inj_id: str, summary: str, layer: str) -> None:
        with self._lock:
            self._injections[inj_id] = summary
            n = len(self._injections)
        METRICS.counter("corro.chaos.injected.total", layer=layer).inc()
        METRICS.gauge("corro.chaos.injections.active").set(n)

    def remove(self, inj_id: str) -> None:
        with self._lock:
            self._injections.pop(inj_id, None)
            n = len(self._injections)
        METRICS.gauge("corro.chaos.injections.active").set(n)

    def end(self) -> None:
        with self._lock:
            self._scenario = None
            self._since = None
            self._injections.clear()
        METRICS.counter("corro.chaos.restored.total").inc()
        METRICS.gauge("corro.chaos.injections.active").set(0)

    def snapshot(self) -> dict:
        """The /v1/status block: is a drill running, which, what's hurt."""
        with self._lock:
            return {
                "active": bool(self._injections) or self._scenario is not None,
                "scenario": self._scenario,
                "since": self._since,
                "injections": dict(self._injections),
            }


CENSUS = ChaosCensus()

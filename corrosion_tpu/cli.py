"""`corrosion` CLI: the full operator command surface.

Counterpart of `klukai/src/main.rs:569-826`'s command tree:

  agent [--from-snapshot P]  run the agent (optionally cold-bootstrap
                             the database from a snapshot file first)
  backup PATH                VACUUM INTO + scrub per-node state
  restore PATH               swap the db file under full SQLite locks
  snapshot dump|install PATH r17 catch-up plane: serve-side compressed
                             snapshot dump / cold-side install (schema-
                             sha-gated, keeps the local site id)
  cluster rejoin|members|membership-states|set-id
  consul sync                bidirectional Consul <-> store replication
  query SQL                  one-shot query through the HTTP API
  exec SQL...                transaction through the HTTP API
  reload                     re-apply schema files via /v1/migrations
  sync generate|reconcile-gaps
  locks [--top N]
  actor version ACTOR_ID VERSION
  template FILE...           render templates (optionally watch)
  tls ca|server|client generate
  db lock CMD                run CMD while holding every SQLite lock
  subs list|info
  log set|reset

Global flags: -c/--config, --api-addr, --db-path, --admin-path.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from typing import List, Optional

from corrosion_tpu.runtime import otel
from corrosion_tpu.runtime.config import Config, load_config


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corrosion",
        description="TPU-native gossip-based multi-writer distributed store",
    )
    p.add_argument("-c", "--config", default="corrosion.toml")
    p.add_argument("--api-addr", default=None)
    p.add_argument("--db-path", default=None)
    p.add_argument("--admin-path", default=None)
    sub = p.add_subparsers(dest="command", required=True)

    ag = sub.add_parser("agent", help="run the agent")
    # cold-side bootstrap flag (r17): install a snapshot file over the
    # configured db (schema-sha-gated) before the agent boots from it
    ag.add_argument("--from-snapshot", default=None, metavar="PATH")

    b = sub.add_parser("backup", help="back up the database")
    b.add_argument("path")

    r = sub.add_parser("restore", help="restore a backup over the live db")
    r.add_argument("path")
    r.add_argument("--self-actor-id", default=None)

    sn = sub.add_parser(
        "snapshot", help="compressed catch-up snapshots (r17)"
    ).add_subparsers(dest="sub", required=True)
    snd = sn.add_parser("dump", help="build a snapshot file from the db")
    snd.add_argument("path")
    sni = sn.add_parser("install", help="install a snapshot over the db")
    sni.add_argument("path")
    sni.add_argument("--self-actor-id", default=None)

    cluster = sub.add_parser("cluster").add_subparsers(
        dest="sub", required=True
    )
    cluster.add_parser("rejoin")
    cluster.add_parser("members")
    cluster.add_parser("membership-states")
    sid = cluster.add_parser("set-id")
    sid.add_argument("cluster_id", type=int)

    consul = sub.add_parser("consul").add_subparsers(dest="sub", required=True)
    consul.add_parser("sync")

    q = sub.add_parser("query")
    q.add_argument("sql")
    q.add_argument("--columns", action="store_true")
    q.add_argument("--timer", action="store_true")
    q.add_argument("--param", action="append", default=[])
    # server-side statement interrupt in seconds (main.rs:672 Query.timeout)
    q.add_argument("--timeout", type=float, default=None)

    e = sub.add_parser("exec")
    e.add_argument("sql", nargs="+")
    e.add_argument("--timeout", type=float, default=None)

    sub.add_parser("reload", help="re-apply schema files")

    sy = sub.add_parser("sync").add_subparsers(dest="sub", required=True)
    sy.add_parser("generate")
    sy.add_parser("reconcile-gaps")

    lk = sub.add_parser("locks")
    lk.add_argument("--top", type=int, default=None)

    trc = sub.add_parser(
        "traces", help="fetch the node's kept slow traces (GET /v1/traces)"
    )
    trc.add_argument("--n", type=int, default=10, help="slowest-N traces")
    trc.add_argument("--stage", default=None,
                     help="only traces with this stage (write/broadcast/"
                          "apply/match/deliver)")
    trc.add_argument("--actor", default=None)
    trc.add_argument("--table", default=None)
    trc.add_argument("--json", action="store_true",
                     help="raw JSON instead of the table render")

    al = sub.add_parser(
        "alerts", help="fetch the node's alert states (GET /v1/alerts)"
    )
    al.add_argument("--cluster", action="store_true",
                    help="cluster scope: every node's digest-carried "
                         "active alerts + per-rule rollup")
    al.add_argument("--history", action="store_true",
                    help="include the fired/resolved transition history")
    al.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table render")

    pr = sub.add_parser(
        "profile",
        help="fetch the node's continuous profile (GET /v1/profile)",
    )
    pr.add_argument("--window", type=float, default=60.0,
                    help="lookback seconds (default 60)")
    pr.add_argument("--cluster", action="store_true",
                    help="cluster scope: every node's digest-carried "
                         "hotspot frames + merged rollup")
    pr.add_argument("--speedscope", metavar="PATH", default=None,
                    help="write a speedscope.app document to PATH")
    pr.add_argument("--folded", action="store_true",
                    help="print collapsed-stack text (flamegraph input)")
    pr.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table render")

    actor = sub.add_parser("actor").add_subparsers(dest="sub", required=True)
    av = actor.add_parser("version")
    av.add_argument("actor_id")
    av.add_argument("version", type=int)

    t = sub.add_parser("template")
    t.add_argument("files", nargs="+", help="TEMPLATE[:OUTPUT] specs")
    t.add_argument("--watch", action="store_true")

    tls = sub.add_parser("tls").add_subparsers(dest="sub", required=True)
    ca = tls.add_parser("ca").add_subparsers(dest="subsub", required=True)
    cag = ca.add_parser("generate")
    cag.add_argument("--cert-file", default="./ca-cert.pem")
    cag.add_argument("--key-file", default="./ca-key.pem")
    srv = tls.add_parser("server").add_subparsers(dest="subsub", required=True)
    srvg = srv.add_parser("generate")
    srvg.add_argument("ip")
    srvg.add_argument("--ca-cert", default="./ca-cert.pem")
    srvg.add_argument("--ca-key", default="./ca-key.pem")
    srvg.add_argument("--cert-file", default="./server-cert.pem")
    srvg.add_argument("--key-file", default="./server-key.pem")
    cli_ = tls.add_parser("client").add_subparsers(dest="subsub", required=True)
    clig = cli_.add_parser("generate")
    clig.add_argument("--ca-cert", default="./ca-cert.pem")
    clig.add_argument("--ca-key", default="./ca-key.pem")
    clig.add_argument("--cert-file", default="./client-cert.pem")
    clig.add_argument("--key-file", default="./client-key.pem")

    db = sub.add_parser("db").add_subparsers(dest="sub", required=True)
    dblock = db.add_parser("lock")
    dblock.add_argument("cmd")

    subs = sub.add_parser("subs").add_subparsers(dest="sub", required=True)
    subs.add_parser("list")
    si = subs.add_parser("info")
    si.add_argument("--id", default=None)
    si.add_argument("--hash", default=None)

    lg = sub.add_parser("log").add_subparsers(dest="sub", required=True)
    ls = lg.add_parser("set")
    ls.add_argument("filter")
    lg.add_parser("reset")

    dc = sub.add_parser("devcluster", help="spawn a local topology")
    dc.add_argument("topology", help="file of 'A -> B' edges")
    dc.add_argument("--schema", default=None, help="schema .sql file")

    return p


def _load_cfg(args) -> Config:
    try:
        cfg = load_config(args.config)
    except FileNotFoundError:
        cfg = Config()
    if args.api_addr:
        cfg.api.bind_addr = [args.api_addr]
    if args.db_path:
        cfg.db.path = args.db_path
    if args.admin_path:
        cfg.admin.uds_path = args.admin_path
    return cfg


def _api_addr(cfg: Config) -> str:
    return cfg.api.bind_addr[0]


async def _admin_call(cfg: Config, cmd: dict) -> int:
    from corrosion_tpu.admin import AdminClient

    try:
        async with AdminClient(cfg.admin.uds_path) as c:
            r = await c.call(cmd)
    except (ConnectionError, FileNotFoundError, OSError) as e:
        print(f"could not reach admin socket {cfg.admin.uds_path}: {e}",
              file=sys.stderr)
        return 1
    for line in r["logs"]:
        print(line)
    for value in r["json"]:
        print(json.dumps(value, indent=2))
    if not r["ok"]:
        print(f"error: {r['error']}", file=sys.stderr)
        return 1
    return 0


async def _cmd_agent(cfg: Config, from_snapshot: Optional[str] = None) -> int:
    import logging

    from corrosion_tpu.admin import AdminServer
    from corrosion_tpu.agent.run import run, setup, shutdown
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.runtime.metrics import serve_prometheus
    from corrosion_tpu.runtime.tripwire import Tripwire

    logging.basicConfig(
        level=cfg.log.level.upper(),
        format=(
            '{"ts":"%(asctime)s","level":"%(levelname)s",'
            '"logger":"%(name)s","msg":"%(message)s"}'
            if cfg.log.format == "json"
            else "%(asctime)s %(levelname)s %(name)s %(message)s"
        ),
    )

    if from_snapshot:
        # cold-side bootstrap (r17): install before the store opens, so
        # the agent boots straight onto the snapshot's bookkeeping and
        # its first sync rounds are the watermark top-up
        rc = _snapshot_install(cfg, from_snapshot)
        if rc != 0:
            return rc

    tripwire = Tripwire.from_signals()
    agent = await setup(cfg, tripwire=tripwire)
    await run(agent)

    # Admin socket binds before the API listener: external supervisors
    # (devcluster.wait_up) treat "api port accepts" as ready, so everything
    # ready implies must already be bound by then.
    admin = AdminServer(agent, cfg.admin.uds_path)
    await admin.start()

    api = ApiServer(agent)
    await api.start()
    print(f"api listening on {', '.join(api.addrs)}")

    prom_runner = None
    if cfg.telemetry.prometheus_bind_addr:
        prom_runner = await serve_prometheus(cfg.telemetry.prometheus_bind_addr)

    # OTLP span export (main.rs:68-118): config endpoint, or the standard
    # env var so deployments can enable tracing without editing TOML
    otlp_endpoint = cfg.telemetry.open_telemetry_endpoint or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )
    if otlp_endpoint:
        otel.configure(
            otlp_endpoint,
            resource_attrs={"corrosion.actor_id": str(agent.actor_id)},
        )

    consul_task = None
    if cfg.consul.enabled:
        from corrosion_tpu.consul import consul_sync_loop

        consul_task = asyncio.ensure_future(
            consul_sync_loop(agent, cfg.consul, tripwire)
        )

    try:
        print(f"agent {agent.actor_id} up; gossip {agent.actor.addr}")
        await tripwire.wait()
        print("shutting down…")
        if consul_task is not None:
            consul_task.cancel()
        if prom_runner is not None:
            await prom_runner.cleanup()
        await admin.stop()
        await api.stop()
        await shutdown(agent)
        await agent.tracker.wait_all(60.0)
    finally:
        # even a failing shutdown path must flush queued spans — those are
        # exactly the spans that explain the failure
        if otlp_endpoint:
            otel.configure(None)  # shutdown + final flush
    return 0


def _client_error_text(body) -> str:
    """Dig the sqlite/API error line out of a ClientError body (the 400
    shape is {"results": [{"error": ...}], ...})."""
    if isinstance(body, dict):
        for r in body.get("results") or []:
            if isinstance(r, dict) and "error" in r:
                return str(r["error"])
        if "error" in body:
            return str(body["error"])
    return str(body)


async def _cmd_query(cfg: Config, args) -> int:
    import time as _time

    from corrosion_tpu.client import ClientError, CorrosionApiClient

    stmt: object = (
        [args.sql, list(args.param)] if args.param else args.sql
    )
    t0 = _time.monotonic()
    async with CorrosionApiClient(
        _api_addr(cfg), token=cfg.api.authz_bearer
    ) as c:
        try:
            async for ev in c.query(stmt, timeout=args.timeout):
                if "columns" in ev and args.columns:
                    print("|".join(ev["columns"]))
                elif "row" in ev:
                    _rowid, vals = ev["row"]
                    print("|".join(_render(v) for v in vals))
                elif "error" in ev:
                    print(f"error: {ev['error']}", file=sys.stderr)
                    return 1
        except ClientError as e:
            # HTTP-level failure before the stream starts (401, parse
            # 400, …): same clean error line as the exec path
            print(f"error: {_client_error_text(e.body)}", file=sys.stderr)
            return 1
    if args.timer:
        print(f"time: {_time.monotonic() - t0:.6f}s", file=sys.stderr)
    return 0


def _render(v) -> str:
    if v is None:
        return ""
    return str(v)


async def _cmd_exec(cfg: Config, args) -> int:
    from corrosion_tpu.client import ClientError, CorrosionApiClient

    async with CorrosionApiClient(
        _api_addr(cfg), token=cfg.api.authz_bearer
    ) as c:
        try:
            resp = await c.execute(list(args.sql), timeout=args.timeout)
        except ClientError as e:
            # e.g. a --timeout interrupt comes back as HTTP 400 with the
            # sqlite error in the body — print it, don't traceback
            print(f"error: {_client_error_text(e.body)}", file=sys.stderr)
            return 1
    print(json.dumps(resp, indent=2))
    return 0 if "results" in resp else 1


async def _cmd_reload(cfg: Config) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    if not cfg.db.schema_paths:
        print("no schema_paths configured", file=sys.stderr)
        return 1
    async with CorrosionApiClient(
        _api_addr(cfg), token=cfg.api.authz_bearer
    ) as c:
        resp = await c.schema_from_paths(cfg.db.schema_paths)
    print(json.dumps(resp, indent=2))
    return 0


async def _agent_is_live(cfg: Config) -> bool:
    from corrosion_tpu.admin import AdminClient

    try:
        async with AdminClient(cfg.admin.uds_path) as c:
            r = await c.call({"cmd": "ping"})
            return bool(r["ok"])
    except (ConnectionError, FileNotFoundError, OSError):
        return False


def _expected_schema_sha(cfg: Config):
    """Schema sha from the configured declarative schema files (None
    when none are configured — the install then trusts the snapshot)."""
    if not cfg.db.schema_paths:
        return None
    from pathlib import Path

    from corrosion_tpu.store.schema import parse_sql
    from corrosion_tpu.store.snapshot import schema_sha

    sql = "\n".join(Path(p).read_text() for p in cfg.db.schema_paths)
    return schema_sha(parse_sql(sql), exclude=(cfg.slo.canary_table,))


def _existing_site_id(db_path: str):
    import sqlite3 as _sqlite3

    if not os.path.exists(db_path):
        return None
    try:
        conn = _sqlite3.connect(db_path)
        try:
            row = conn.execute(
                "SELECT site_id FROM __crdt_site WHERE id = 1"
            ).fetchone()
        finally:
            conn.close()
    except _sqlite3.Error:
        return None
    return bytes(row[0]) if row else None


def _snapshot_install(cfg: Config, path: str, self_actor_id=None) -> int:
    """Shared by `snapshot install` and `agent --from-snapshot`: the
    cold node keeps its own identity — an existing db's site id (or
    --self-actor-id) is re-pinned into the installed copy."""
    import uuid

    from corrosion_tpu.store.snapshot import (
        SnapshotError,
        install_snapshot_file,
    )

    self_site = None
    if self_actor_id:
        self_site = uuid.UUID(self_actor_id).bytes
    else:
        self_site = _existing_site_id(cfg.db.path)
    try:
        res = install_snapshot_file(
            path,
            cfg.db.path,
            expect_schema_sha=_expected_schema_sha(cfg),
            self_site_id=self_site,
        )
    except SnapshotError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(
        f"installed snapshot over {cfg.db.path}: {res.raw_bytes} bytes,"
        f" {res.watermark_versions} watermark versions"
        f" (delta sync tops up from there)"
    )
    return 0


async def _cmd_snapshot(cfg: Config, args) -> int:
    # both directions need exclusive db access, same rule as restore
    if await _agent_is_live(cfg):
        print(
            "an agent is running on this database; stop it first"
            " (live peers serve snapshots over the sync plane instead)",
            file=sys.stderr,
        )
        return 1
    if args.sub == "dump":
        from corrosion_tpu.store import snapshot as snap
        from corrosion_tpu.store.bookkeeping import Bookie
        from corrosion_tpu.store.crdt import CrdtStore

        store = CrdtStore(cfg.db.path)
        try:
            bookie = Bookie()
            for aid in store.booked_actor_ids():
                bookie.insert(aid, store.load_booked_versions(aid))
            header = snap.build_snapshot_file(
                cfg.db.path,
                args.path,
                store.schema,
                store.site_id.bytes16,
                snap.bookie_watermark(bookie),
                cfg.sync.snapshot_chunk_bytes,
            )
        finally:
            store.close()
        print(
            f"wrote snapshot {args.path}: {header.raw_bytes} bytes raw,"
            f" {header.watermark_total()} watermark versions,"
            f" schema sha {header.schema_sha.hex()[:12]}"
        )
        return 0
    return _snapshot_install(cfg, args.path, args.self_actor_id)


def _cmd_db_lock(cfg: Config, cmd: str) -> int:
    import shlex
    import subprocess
    import time as _time

    from corrosion_tpu.store.restore import lock_all

    print(f"Opening DB file at {cfg.db.path}")
    start = _time.monotonic()
    locks = lock_all(cfg.db.path, timeout=30.0)
    print(f"Lock acquired after {_time.monotonic() - start:.3f}s")
    try:
        argv = shlex.split(cmd)
        print(f"Launching command {cmd}")
        code = subprocess.run(argv).returncode
        print(f"Exited with code: {code}")
        return code
    finally:
        locks.release()


async def _cmd_traces(cfg: Config, args) -> int:
    """Admin fetch of GET /v1/traces: the slowest kept traces with their
    per-stage breakdown, rendered as one fixed-width table per trace
    (or raw JSON with --json)."""
    import aiohttp

    params = {"n": str(args.n)}
    for k in ("stage", "actor", "table"):
        v = getattr(args, k)
        if v:
            params[k] = v
    url = f"http://{_api_addr(cfg)}/v1/traces"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                url, params=params, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                body = await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        print(f"could not reach {url}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    census = body.get("census", {})
    if not census.get("enabled"):
        print("trace plane disabled ([trace] enabled=false)")
        return 0
    print(
        f"kept {census['kept_total']} dropped {census['dropped_total']} "
        f"buffered {census['buffered']} (1/{census['lottery_n']} lottery)"
    )
    for t in body["traces"]:
        chaos = f" chaos={t['chaos']}" if t.get("chaos") else ""
        print(
            f"\ntrace {t['trace_id']}  {t['duration_secs'] * 1e3:.3f} ms  "
            f"reason={t['reason']}  spans={t['n_spans']} "
            f"hops={t['hops']}{chaos}"
        )
        print(f"  {'stage':<10} {'count':>5} {'sum_ms':>10} {'max_ms':>10}")
        for stage, row in t["stages"].items():
            print(
                f"  {stage:<10} {row['count']:>5} "
                f"{row['seconds'] * 1e3:>10.3f} "
                f"{row['max_secs'] * 1e3:>10.3f}"
            )
    return 0


async def _cmd_alerts(cfg: Config, args) -> int:
    """Operator fetch of GET /v1/alerts: rule states, active alerts
    (drill marks, exemplar trace ids), optional history — or the
    cluster rollup with --cluster."""
    import aiohttp

    params = {}
    if args.cluster:
        params["scope"] = "cluster"
    elif not args.history:
        params["history"] = "0"
    url = f"http://{_api_addr(cfg)}/v1/alerts"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                url, params=params, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                body = await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        print(f"could not reach {url}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    if args.cluster:
        cov = body.get("coverage", {})
        print(
            f"cluster alerts from {body.get('actor_id')}: "
            f"{cov.get('known', 0)} node(s) known, "
            f"{cov.get('fresh', 0)} fresh"
        )
        rollup = body.get("rollup", {})
        if not rollup:
            print("no active alerts cluster-wide")
            return 0
        print(f"{'rule':<20} {'sev':<5} {'firing':<24} {'pending':<24} drill")
        for rule, row in sorted(rollup.items()):
            print(
                f"{rule:<20} {row['severity']:<5} "
                f"{','.join(row['firing']) or '-':<24} "
                f"{','.join(row['pending']) or '-':<24} "
                f"{'yes' if row['drill'] else '-'}"
            )
        return 0
    if not body.get("enabled"):
        print("alerting plane disabled ([alerts] enabled=false)")
        return 0
    print(
        f"health score {body.get('health_score')}  "
        f"({len(body.get('active', []))} active)"
    )
    print(f"{'rule':<20} {'sev':<5} {'state':<8} {'value':>12}  notes")
    for r in body.get("rules", []):
        notes = []
        if r.get("drill"):
            notes.append(f"drill={r['drill']}")
        if r.get("trace_ids"):
            notes.append(f"traces={','.join(r['trace_ids'][:2])}")
        if r.get("incident"):
            notes.append("incident")
        v = r.get("value")
        print(
            f"{r['rule']:<20} {r['severity']:<5} {r['state']:<8} "
            f"{v if v is not None else '-':>12}  {' '.join(notes)}"
        )
    for h in body.get("history", []):
        dur = (
            f" after {h['duration_secs']}s"
            if h.get("duration_secs") is not None else ""
        )
        print(
            f"  {h['wall']:.3f} {h['rule']} {h['event']}{dur}"
            + (f" [drill: {h['drill']}]" if h.get("drill") else "")
        )
    return 0


async def _cmd_profile(cfg: Config, args) -> int:
    """Operator fetch of GET /v1/profile: the continuous profiler's
    top self-time frames + statement-shape table (default render),
    collapsed-stack text with --folded, a speedscope.app file with
    --speedscope PATH, or the cluster hotspot rollup with --cluster."""
    import aiohttp

    params = {"window": str(args.window)}
    if args.cluster:
        params["scope"] = "cluster"
    elif args.speedscope:
        params["format"] = "speedscope"
    elif args.folded:
        params["format"] = "folded"
    url = f"http://{_api_addr(cfg)}/v1/profile"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                url, params=params, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                if params.get("format") == "folded":
                    body = await resp.text()
                else:
                    body = await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        print(f"could not reach {url}: {e}", file=sys.stderr)
        return 1
    if args.cluster:
        if args.json:
            print(json.dumps(body, indent=2))
            return 0
        cov = body.get("coverage", {})
        print(
            f"cluster hotspots from {body.get('actor_id')}: "
            f"{cov.get('known', 0)} node(s) known, "
            f"{cov.get('fresh', 0)} fresh"
        )
        rollup = body.get("rollup", [])
        if not rollup:
            print("no hotspot frames cluster-wide")
            return 0
        print(f"{'samples':>8}  frame")
        for row in rollup:
            print(f"{row['samples']:>8}  {row['frame']}")
        return 0
    if args.speedscope:
        with open(args.speedscope, "w") as f:
            json.dump(body, f)
        n = len(body.get("shared", {}).get("frames", []))
        print(f"wrote speedscope profile ({n} frames) to {args.speedscope}")
        return 0
    if args.folded:
        sys.stdout.write(body)
        return 0
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    if not body.get("enabled"):
        print("profiling plane disabled ([profile] enabled=false)")
        return 0
    shed = " SHED" if body.get("shed") else ""
    print(
        f"{body.get('samples', 0)} samples over {args.window:g}s at "
        f"{body.get('hz', 0):g} Hz{shed}, overhead "
        f"{body.get('overhead_pct', 0.0):.3f}%"
    )
    print(f"{'self':>8}  frame")
    for row in body.get("top_self", []):
        print(f"{row['samples']:>8}  {row['frame']}")
    stmt = body.get("stmt", [])
    if stmt:
        print(f"\n{'count':>8} {'total_ms':>10}  statement shape")
        for row in stmt:
            print(
                f"{row['count']:>8} {row['total_secs'] * 1e3:>10.3f}  "
                f"{row['shape']}"
            )
    return 0


async def _cmd_template(cfg: Config, args) -> int:
    from corrosion_tpu.tpl import render_specs, watch_specs

    if args.watch:
        await watch_specs(cfg, args.files)
        return 0
    return await render_specs(cfg, args.files)


async def _amain(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    cfg = _load_cfg(args)
    cmd = args.command

    if cmd == "agent":
        return await _cmd_agent(cfg, from_snapshot=args.from_snapshot)
    if cmd == "snapshot":
        return await _cmd_snapshot(cfg, args)
    if cmd == "backup":
        from corrosion_tpu.store.restore import backup

        backup(cfg.db.path, args.path)
        print(f"backed up database to {args.path}")
        return 0
    if cmd == "restore":
        from corrosion_tpu.admin import AdminClient
        from corrosion_tpu.store.restore import restore, set_self_site_id

        # refuse when an agent is live on the admin socket (main.rs:224-330)
        try:
            async with AdminClient(cfg.admin.uds_path) as c:
                r = await c.call({"cmd": "ping"})
                if r["ok"]:
                    print(
                        "an agent is running on this database; stop it first",
                        file=sys.stderr,
                    )
                    return 1
        except (ConnectionError, FileNotFoundError, OSError):
            pass
        if args.self_actor_id:
            set_self_site_id(args.path, args.self_actor_id)
        res = restore(args.path, cfg.db.path)
        print(
            f"restored {res.new_len} bytes over {res.old_len}"
            f" (wal={res.is_wal})"
        )
        return 0
    if cmd == "cluster":
        if args.sub == "set-id":
            return await _admin_call(
                cfg,
                {"cmd": "cluster", "sub": "set-id",
                 "cluster_id": args.cluster_id},
            )
        return await _admin_call(cfg, {"cmd": "cluster", "sub": args.sub})
    if cmd == "consul":
        from corrosion_tpu.consul import run_consul_sync_cli

        return await run_consul_sync_cli(cfg)
    if cmd == "query":
        return await _cmd_query(cfg, args)
    if cmd == "exec":
        return await _cmd_exec(cfg, args)
    if cmd == "reload":
        return await _cmd_reload(cfg)
    if cmd == "sync":
        return await _admin_call(cfg, {"cmd": "sync", "sub": args.sub})
    if cmd == "locks":
        return await _admin_call(cfg, {"cmd": "locks", "top": args.top})
    if cmd == "traces":
        return await _cmd_traces(cfg, args)
    if cmd == "alerts":
        return await _cmd_alerts(cfg, args)
    if cmd == "profile":
        return await _cmd_profile(cfg, args)
    if cmd == "actor":
        return await _admin_call(
            cfg,
            {"cmd": "actor", "sub": "version",
             "actor_id": args.actor_id, "version": args.version},
        )
    if cmd == "template":
        return await _cmd_template(cfg, args)
    if cmd == "tls":
        from corrosion_tpu import tls as _tls

        if args.sub == "ca":
            _tls.generate_ca(args.cert_file, args.key_file)
            print(f"wrote {args.cert_file}, {args.key_file}")
        elif args.sub == "server":
            _tls.generate_server_cert(
                args.ca_cert, args.ca_key, args.ip,
                args.cert_file, args.key_file,
            )
            print(f"wrote {args.cert_file}, {args.key_file}")
        elif args.sub == "client":
            _tls.generate_client_cert(
                args.ca_cert, args.ca_key,
                args.cert_file, args.key_file,
            )
            print(f"wrote {args.cert_file}, {args.key_file}")
        return 0
    if cmd == "db":
        return _cmd_db_lock(cfg, args.cmd)
    if cmd == "subs":
        if args.sub == "list":
            return await _admin_call(cfg, {"cmd": "subs", "sub": "list"})
        payload = {"cmd": "subs", "sub": "info"}
        if args.id:
            payload["id"] = args.id
        if args.hash:
            payload["hash"] = args.hash
        return await _admin_call(cfg, payload)
    if cmd == "devcluster":
        from pathlib import Path as _P

        from corrosion_tpu.devcluster import run_devcluster_cli

        schema_sql = _P(args.schema).read_text() if args.schema else ""
        return await run_devcluster_cli(cfg, args.topology, schema_sql)
    if cmd == "log":
        if args.sub == "set":
            return await _admin_call(
                cfg, {"cmd": "log", "sub": "set", "filter": args.filter}
            )
        return await _admin_call(cfg, {"cmd": "log", "sub": "reset"})
    print(f"unknown command {cmd}", file=sys.stderr)
    return 2


def main(argv: Optional[List[str]] = None) -> None:
    sys.exit(asyncio.run(_amain(argv)))


if __name__ == "__main__":
    main()

"""The live-query matcher: one materialized, incrementally-maintained
result set per subscription.

Counterpart of `Matcher`/`MatcherHandle` in `klukai-types/src/pubsub.rs`
(`Matcher::new` :556-803, run/cmd_loop :1029-1226, handle_candidates
:1401-1673). Same architecture, re-hosted on the sqlite3-backed CRDT
store:

- each subscription owns its own SQLite db (`sub.sqlite` under
  `<subs_path>/<uuid>/`) with tables `query` (materialized rows,
  `__corro_rowid` PK + unique pk-tuple index), `changes` (ChangeId log),
  `meta`, `columns` (pubsub.rs:893-977);
- the SELECT is rewritten per source table: pk alias columns
  `__corro_pk_<tbl>_<pk>` are prepended for every table, and a
  `(pks) IN temp_<tbl>` membership predicate is AND-injected for the
  driving table; LEFT joins on the driving table become INNER
  (pubsub.rs:616-711, table_to_expr :2123);
- incremental maintenance batches match candidates (table → pk) for
  600 ms / 1000 entries, inserts the changed pks into `temp_<tbl>`,
  runs the rewritten query, and set-differences against the
  materialized `query` table, appending each emitted change to the
  `changes` log with a monotonically increasing ChangeId
  (pubsub.rs:1062-1226,1401-1673);
- the changes log is pruned to the most recent rows every 5 min
  (pubsub.rs:1171-1192); catch-up from a pruned ChangeId fails and the
  client must resubscribe anew.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from corrosion_tpu.api.types import dump_value
from corrosion_tpu.pubsub.parse import ParsedSelect, ParseError, parse_select
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.pack import unpack_columns

CANDIDATE_BATCH_MAX = 1000  # pubsub.rs cmd_loop batch cap
CANDIDATE_BATCH_WAIT = 0.6  # 600 ms (pubsub.rs:1069)
CHANGES_LOG_KEEP = 500  # prune to last 500 (pubsub.rs:1171-1192)
PRUNE_INTERVAL = 300.0  # every 5 min


class MatcherError(Exception):
    pass


@dataclass(frozen=True)
class SubEvent:
    """One row-change event: mirrors QueryEvent::Change."""

    change_id: int
    kind: str  # insert | update | delete
    rowid: int
    values: List[Any]  # JSON-ready cell values


def sql_hash(sql: str) -> str:
    """Dedupe key for subscriptions: also the `corro-query-hash` header
    (the single definition — manager.py re-exports it).

    Wire parity (r6, closes VERDICT r5 missing #4): the reference
    computes `seahash::hash(sql.as_bytes())` and formats it as 16
    lower-hex chars (`klukai-types/src/pubsub.rs:565`, `Matcher::hash`
    → `format!("{:x}", ...)` zero-padded u64); this is the same
    function over the vector-validated `net/seahash.py`, so a
    reference client comparing `corro-query-hash` against its locally
    computed hash now matches.  (Through r5 this was truncated sha256
    — a documented divergence.  No stored artifact carries the hash:
    sub dbs persist the SQL text itself and the manager's by-hash index
    is rebuilt from it on restore, so the swap migrates everything by
    construction.)"""
    from corrosion_tpu.net.seahash import hash_bytes

    return f"{hash_bytes(sql.encode('utf-8')):016x}"


def _pk_alias(table: str, col: str) -> str:
    return f"__corro_pk_{table}_{col}"


class Matcher:
    """Owns the sub db + the rewrite; drives initial fill and diffs.

    All sqlite work happens on executor threads; the async side
    (cmd_loop) only batches candidates and fans events out.
    """

    def __init__(
        self,
        store,
        parsed: ParsedSelect,
        sub_id: str,
        sql: str,
        sub_path: Optional[str],
    ):
        self.store = store
        self.parsed = parsed
        self.id = sub_id
        self.sql = sql
        self.sub_path = sub_path
        self.columns: List[str] = []
        self._conn: Optional[sqlite3.Connection] = None
        self._conn_lock = threading.Lock()
        self.last_change_id = 0

    # -- setup -------------------------------------------------------------

    def _sub_db_file(self) -> str:
        if self.sub_path is None:
            return ":memory:"
        d = Path(self.sub_path) / self.id
        d.mkdir(parents=True, exist_ok=True)
        return str(d / "sub.sqlite")

    def connect(self) -> sqlite3.Connection:
        """Main-db read conn with the sub db ATTACHed writable."""
        conn = sqlite3.connect(
            self.store.path,
            uri=True,
            check_same_thread=False,
            isolation_level=None,  # manual BEGIN/COMMIT
        )
        conn.row_factory = sqlite3.Row
        conn.execute("ATTACH ? AS sub", (self._sub_db_file(),))
        return conn

    def create_sub_db(self) -> None:
        """Create {query, changes, meta, columns} (pubsub.rs:893-977)."""
        conn = self.connect()
        self._conn = conn
        pk_cols = self._pk_alias_cols()
        probe = conn.execute(self._probe_query())
        self.columns = [d[0] for d in probe.description][len(pk_cols):]
        col_defs = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"col_{i}"' for i in range(len(self.columns))]
        )
        uniq = ", ".join(f'"{c}"' for c in pk_cols)
        with self._conn_lock:
            conn.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS sub.query (
                  __corro_rowid INTEGER PRIMARY KEY AUTOINCREMENT,
                  {col_defs}
                );
                CREATE UNIQUE INDEX IF NOT EXISTS sub.query_pks
                  ON query ({uniq});
                CREATE TABLE IF NOT EXISTS sub.changes (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  type TEXT NOT NULL,
                  __corro_rowid INTEGER NOT NULL,
                  data TEXT NOT NULL
                );
                CREATE TABLE IF NOT EXISTS sub.meta (
                  k TEXT PRIMARY KEY, v
                );
                CREATE TABLE IF NOT EXISTS sub.columns (
                  idx INTEGER PRIMARY KEY, name TEXT NOT NULL
                );
                """
            )
            for t in self.parsed.tables:
                cols = ", ".join(
                    f'"{c}"' for c in self.store.schema.table(t.name).pk_cols
                )
                conn.execute(
                    f'CREATE TABLE IF NOT EXISTS sub."temp_{t.name}" ({cols})'
                )
            conn.executemany(
                "INSERT OR REPLACE INTO sub.columns (idx, name) VALUES (?, ?)",
                list(enumerate(self.columns)),
            )
            conn.execute(
                "INSERT OR REPLACE INTO sub.meta (k, v) VALUES ('sql', ?)",
                (self.sql,),
            )
            conn.execute(
                "INSERT OR REPLACE INTO sub.meta (k, v) VALUES"
                " ('state', 'created')"
            )

    def reattach(self) -> None:
        """Reopen an existing sub db (restore path, pubsub.rs:826-861)."""
        conn = self.connect()
        self._conn = conn
        state = conn.execute(
            "SELECT v FROM sub.meta WHERE k = 'state'"
        ).fetchone()
        if state is None or state["v"] != "completed":
            raise MatcherError("sub db incomplete; purge and recreate")
        self.columns = [
            r["name"]
            for r in conn.execute(
                "SELECT name FROM sub.columns ORDER BY idx"
            )
        ]
        row = conn.execute("SELECT MAX(id) AS m FROM sub.changes").fetchone()
        self.last_change_id = int(row["m"] or 0)

    # -- rewrites ----------------------------------------------------------

    def _pk_alias_cols(self) -> List[str]:
        # keyed by ref *alias* (not table name) so self-joins — two refs to
        # one table — get distinct materialized pk columns
        out = []
        for t in self.parsed.tables:
            for c in self.store.schema.table(t.name).pk_cols:
                out.append(_pk_alias(t.alias, c))
        return out

    def _pk_select_prefix(self) -> str:
        parts = []
        for t in self.parsed.tables:
            for c in self.store.schema.table(t.name).pk_cols:
                parts.append(f'"{t.alias}"."{c}" AS "{_pk_alias(t.alias, c)}"')
        return ", ".join(parts)

    def _probe_query(self) -> str:
        """Initial/probe form: pk aliases + user select list, full scan.
        The ORDER BY tail (the only one parse_select admits) shapes the
        initial fill; incremental change events are unordered."""
        p = self.parsed
        where = f" WHERE {p.where_clause}" if p.where_clause else ""
        tail = f" {p.tail}" if p.tail else ""
        return (
            f"SELECT {self._pk_select_prefix()}, {p.select_list}"
            f" FROM {p.from_clause}{where}{tail}"
        )

    def _table_query(self, ref) -> str:
        """Rewritten per-driving-table-ref query with the temp pk predicate
        (pubsub.rs:616-711): restricts re-evaluation to changed pks."""
        p = self.parsed
        driving = ref.name
        pks = self.store.schema.table(driving).pk_cols
        tuple_lhs = ", ".join(f'"{ref.alias}"."{c}"' for c in pks)
        tuple_rhs = ", ".join(f'"{c}"' for c in pks)
        pred = (
            f"({tuple_lhs}) IN (SELECT {tuple_rhs} FROM"
            f' sub."temp_{driving}")'
        )
        from_clause = p.from_clause
        if ref.left_joined:
            # LEFT JOIN driving → INNER so the pk predicate can bind
            from_clause = _left_to_inner(from_clause, ref.alias)
        where = f"({p.where_clause}) AND {pred}" if p.where_clause else pred
        return (
            f"SELECT {self._pk_select_prefix()}, {p.select_list}"
            f" FROM {from_clause} WHERE {where}"
        )

    # -- initial fill ------------------------------------------------------

    def run_initial(self) -> Tuple[List[str], int]:
        """Materialize the full result into sub.query; returns
        (columns, row_count). Subscribers read rows via `snapshot()` —
        the attach-then-snapshot protocol (pubsub.rs:1029-1060)."""
        conn = self._conn
        assert conn is not None
        pk_cols = self._pk_alias_cols()
        ncols = len(self.columns)
        ins_cols = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"col_{i}"' for i in range(ncols)]
        )
        n = 0
        with self._conn_lock:
            conn.execute("BEGIN")
            try:
                for row in conn.execute(self._probe_query()):
                    conn.execute(
                        f"INSERT INTO sub.query ({ins_cols}) VALUES"
                        f" ({', '.join('?' * (len(pk_cols) + ncols))})",
                        tuple(row),
                    )
                    n += 1
                conn.execute(
                    "INSERT OR REPLACE INTO sub.meta (k, v) VALUES"
                    " ('state', 'completed')"
                )
                conn.execute("COMMIT")
            except BaseException:
                conn.execute("ROLLBACK")
                raise
        return self.columns, n

    def all_rows(self) -> List[Tuple[int, List[Any]]]:
        """Current materialized rows (re-attach without `from`)."""
        return self.snapshot()[0]

    def snapshot(self) -> Tuple[List[Tuple[int, List[Any]]], int]:
        """(rows, last_change_id) read atomically under the conn lock —
        no diff can commit between the two, so a subscriber that streams
        these rows then live events > last_change_id misses nothing."""
        conn = self._conn
        assert conn is not None
        ncols = len(self.columns)
        sel = ", ".join(f'"col_{i}"' for i in range(ncols))
        with self._conn_lock:
            rows = conn.execute(
                f"SELECT __corro_rowid, {sel} FROM sub.query"
                " ORDER BY __corro_rowid"
            ).fetchall()
            snap_id = self.last_change_id
        return [(r[0], list(r)[1:]) for r in rows], snap_id

    def materialized_pks(self, table: str) -> List[bytes]:
        """Packed pks of `table` present in the materialized result
        (restore resync: rows deleted while the agent was down exist
        here but not in the live table, and must be re-checked)."""
        from corrosion_tpu.types.pack import pack_columns

        conn = self._conn
        assert conn is not None
        pks: Set[bytes] = set()
        with self._conn_lock:
            for ref in self.parsed.tables:
                if ref.name != table:
                    continue
                aliases = [
                    f'"{_pk_alias(ref.alias, c)}"'
                    for c in self.store.schema.table(table).pk_cols
                ]
                rows = conn.execute(
                    f"SELECT DISTINCT {', '.join(aliases)} FROM sub.query"
                ).fetchall()
                pks.update(pack_columns(tuple(r)) for r in rows)
        return list(pks)

    # -- candidate filtering ----------------------------------------------

    def filter_candidates(
        self, changes: Sequence[Change]
    ) -> Dict[str, Set[bytes]]:
        """Which (table, pk) pairs could affect this query?
        (updates.rs:424-488 `match_changes` filter)."""
        out: Dict[str, Set[bytes]] = {}
        for ch in changes:
            deps = self.parsed.col_deps.get(ch.table)
            if deps is None:
                continue
            if ch.is_sentinel() or ch.cid in deps:
                out.setdefault(ch.table, set()).add(ch.pk)
        return out

    # -- incremental diff --------------------------------------------------

    def handle_candidates(
        self, candidates: Dict[str, Set[bytes]]
    ) -> List[SubEvent]:
        """Diff changed pks against the materialized result
        (pubsub.rs:1401-1673). Runs on an executor thread."""
        conn = self._conn
        assert conn is not None
        pk_cols = self._pk_alias_cols()
        ncols = len(self.columns)
        ins_cols = [f'"{c}"' for c in pk_cols] + [
            f'"col_{i}"' for i in range(ncols)
        ]
        events: List[SubEvent] = []
        start = time.monotonic()
        with self._conn_lock:
            conn.execute("BEGIN")
            try:
                for table, pks in candidates.items():
                    tbl_pks = self.store.schema.table(table).pk_cols
                    conn.execute(f'DELETE FROM sub."temp_{table}"')
                    conn.executemany(
                        f'INSERT INTO sub."temp_{table}" VALUES'
                        f" ({', '.join('?' * len(tbl_pks))})",
                        [tuple(unpack_columns(pk)) for pk in pks],
                    )
                self._expand_left_join_candidates(conn, candidates)
                conn.execute("DROP TABLE IF EXISTS sub.state_results")
                # one select per driving *ref* of a changed table, so a
                # self-joined table re-evaluates through both of its refs
                selects = [
                    self._table_query(ref)
                    for ref in self.parsed.tables
                    if ref.name in candidates
                ]
                conn.execute(
                    "CREATE TABLE sub.state_results AS "
                    + " UNION ".join(selects)
                )
                res_cols = [
                    d[1]
                    for d in conn.execute(
                        "PRAGMA sub.table_info(state_results)"
                    )
                ]
                # state_results columns = pk aliases then user cols in order
                sr_pk = [f'"{c}"' for c in res_cols[: len(pk_cols)]]
                sr_user = [f'"{c}"' for c in res_cols[len(pk_cols):]]

                events.extend(self._diff_updates(conn, pk_cols, sr_pk, sr_user))
                events.extend(
                    self._diff_inserts(conn, pk_cols, ins_cols, sr_pk, sr_user)
                )
                events.extend(
                    self._diff_deletes(conn, candidates, pk_cols)
                )
                for ev in events:
                    conn.execute(
                        "INSERT INTO sub.changes (id, type, __corro_rowid,"
                        " data) VALUES (?, ?, ?, ?)",
                        (
                            ev.change_id,
                            ev.kind,
                            ev.rowid,
                            json.dumps(ev.values, separators=(",", ":")),
                        ),
                    )
                conn.execute("COMMIT")
            except BaseException:
                conn.execute("ROLLBACK")
                raise
        METRICS.histogram("corro.subs.process.time.seconds", id=self.id).observe(time.monotonic() - start)
        return events

    def _next_id(self) -> int:
        self.last_change_id += 1
        return self.last_change_id

    def _expand_left_join_candidates(self, conn, candidates) -> None:
        """A change on the right side of a LEFT JOIN can invalidate a
        NULL-extended row (partner appeared) or require re-creating one
        (last partner vanished). Neither is reachable through the changed
        table's own driving query — NULL pk aliases never match the temp
        predicate — so re-evaluate the affected parent rows through every
        other ref, whose rewritten query preserves the LEFT JOIN."""
        for ref in self.parsed.tables:
            if not ref.left_joined or ref.name not in candidates:
                continue
            tbl_pks = self.store.schema.table(ref.name).pk_cols
            p_aliases = [f'"{_pk_alias(ref.alias, c)}"' for c in tbl_pks]
            null_pred = " AND ".join(f"q.{a} IS NULL" for a in p_aliases)
            quoted_pks = ", ".join(f'"{c}"' for c in tbl_pks)
            in_temp = (
                f"({', '.join('q.' + a for a in p_aliases)}) IN"
                f" (SELECT {quoted_pks}"
                f' FROM sub."temp_{ref.name}")'
            )
            for other in self.parsed.tables:
                if other is ref:
                    continue
                o_pks = self.store.schema.table(other.name).pk_cols
                o_aliases = [
                    f'"{_pk_alias(other.alias, c)}"' for c in o_pks
                ]
                rows = conn.execute(
                    f"SELECT DISTINCT {', '.join('q.' + a for a in o_aliases)}"
                    f" FROM sub.query q WHERE ({null_pred}) OR {in_temp}"
                ).fetchall()
                if not rows:
                    continue
                if other.name not in candidates:
                    # table joins the diff fresh: clear last round's pks
                    conn.execute(f'DELETE FROM sub."temp_{other.name}"')
                    candidates[other.name] = set()
                conn.executemany(
                    f'INSERT INTO sub."temp_{other.name}" VALUES'
                    f" ({', '.join('?' * len(o_pks))})",
                    [tuple(r) for r in rows],
                )

    def _diff_updates(self, conn, pk_cols, sr_pk, sr_user) -> List[SubEvent]:
        """Rows whose pk exists but whose values changed → update."""
        ncols = len(self.columns)
        if ncols == 0:
            return []
        on = " AND ".join(
            f'q."{c}" IS s.{sc}' for c, sc in zip(pk_cols, sr_pk)
        )
        differs = " OR ".join(
            f'q."col_{i}" IS NOT s.{sc}' for i, sc in enumerate(sr_user)
        )
        sets = ", ".join(
            f'"col_{i}" = s.{sc}' for i, sc in enumerate(sr_user)
        )
        # RETURNING may not use the update alias in sqlite: unqualified
        # names resolve against the modified table only
        ret = ", ".join(f'"col_{i}"' for i in range(ncols))
        rows = conn.execute(
            f"UPDATE sub.query AS q SET {sets} FROM sub.state_results s"
            f" WHERE {on} AND ({differs})"
            f" RETURNING __corro_rowid, {ret}"
        ).fetchall()
        return [
            SubEvent(
                self._next_id(),
                "update",
                r[0],
                [dump_value(v) for v in list(r)[1:]],
            )
            for r in rows
        ]

    def _diff_inserts(
        self, conn, pk_cols, ins_cols, sr_pk, sr_user
    ) -> List[SubEvent]:
        missing = " AND ".join(
            f'q."{c}" IS s.{sc}' for c, sc in zip(pk_cols, sr_pk)
        )
        sel = ", ".join(sr_pk + sr_user)
        rows = conn.execute(
            f"INSERT INTO sub.query ({', '.join(ins_cols)})"
            f" SELECT {sel} FROM sub.state_results s"
            f" WHERE NOT EXISTS (SELECT 1 FROM sub.query q WHERE {missing})"
            f" RETURNING __corro_rowid,"
            f" {', '.join(f'col_{i}' for i in range(len(self.columns)))}"
        ).fetchall()
        return [
            SubEvent(
                self._next_id(),
                "insert",
                r[0],
                [dump_value(v) for v in list(r)[1:]],
            )
            for r in rows
        ]

    def _diff_deletes(self, conn, candidates, pk_cols) -> List[SubEvent]:
        """Materialized rows whose driving pks were candidates but which
        no longer appear in state_results → delete."""
        events: List[SubEvent] = []
        ncols = len(self.columns)
        ret = ", ".join(f'"col_{i}"' for i in range(ncols))
        for table in candidates:
            tbl_pks = self.store.schema.table(table).pk_cols
            # a materialized row is affected if ANY ref of the changed
            # table binds a changed pk (self-joins have several refs)
            ref_preds = []
            for ref in self.parsed.tables:
                if ref.name != table:
                    continue
                aliases = [f'"{_pk_alias(ref.alias, c)}"' for c in tbl_pks]
                quoted_pks = ", ".join(f'"{c}"' for c in tbl_pks)
                ref_preds.append(
                    f"({', '.join('q.' + a for a in aliases)}) IN"
                    f" (SELECT {quoted_pks}"
                    f' FROM sub."temp_{table}")'
                )
            in_temp = "(" + " OR ".join(ref_preds) + ")"
            all_aliases = [f'"{c}"' for c in pk_cols]
            not_in_results = (
                f"NOT EXISTS (SELECT 1 FROM sub.state_results s WHERE "
                + " AND ".join(
                    f"q.{a} IS s.{a}" for a in all_aliases
                )
                + ")"
            )
            sel = f", {ret}" if ncols else ""
            rows = conn.execute(
                f"DELETE FROM sub.query AS q WHERE {in_temp} AND"
                f" {not_in_results} RETURNING __corro_rowid{sel}"
            ).fetchall()
            for r in rows:
                events.append(
                    SubEvent(
                        self._next_id(),
                        "delete",
                        r[0],
                        [dump_value(v) for v in list(r)[1:]],
                    )
                )
        return events

    # -- log / catch-up ----------------------------------------------------

    def changes_since(self, from_id: int) -> Optional[List[SubEvent]]:
        """Replay the changes log after `from_id`; None if pruned away."""
        conn = self._conn
        assert conn is not None
        with self._conn_lock:
            row = conn.execute("SELECT MIN(id) AS m FROM sub.changes").fetchone()
            min_id = row["m"]
            if min_id is not None and from_id + 1 < min_id:
                return None  # gap: log pruned past the requested id
            rows = conn.execute(
                "SELECT id, type, __corro_rowid, data FROM sub.changes"
                " WHERE id > ? ORDER BY id",
                (from_id,),
            ).fetchall()
        return [
            SubEvent(r["id"], r["type"], r["__corro_rowid"], json.loads(r["data"]))
            for r in rows
        ]

    def prune_log(self) -> int:
        conn = self._conn
        assert conn is not None
        with self._conn_lock:
            cur = conn.execute(
                "DELETE FROM sub.changes WHERE id <= "
                "(SELECT MAX(id) FROM sub.changes) - ?",
                (CHANGES_LOG_KEEP,),
            )
        return cur.rowcount

    def close(self) -> None:
        if self._conn is not None:
            with contextlib.suppress(sqlite3.Error):
                self._conn.close()
            self._conn = None


def _left_to_inner(from_clause: str, alias: str) -> str:
    """Replace `LEFT [OUTER] JOIN <tbl> [AS] <alias>` with INNER JOIN for
    the driving table (pubsub.rs:688-711)."""
    import re

    pat = re.compile(
        r"LEFT\s+(?:OUTER\s+)?JOIN(?P<rest>\s+\S+(?:\s+AS)?\s+"
        + re.escape(alias)
        + r"\b)",
        re.IGNORECASE,
    )
    def sub(m):
        return "JOIN" + m.group("rest")

    out = pat.sub(sub, from_clause, count=1)
    if out == from_clause:
        # alias == table name, unaliased form
        pat2 = re.compile(
            r"LEFT\s+(?:OUTER\s+)?JOIN(?P<rest>\s+" + re.escape(alias) + r"\b)",
            re.IGNORECASE,
        )
        out = pat2.sub(sub, from_clause, count=1)
    return out


class MatcherHandle:
    """Async face of a Matcher: candidate queue, subscriber fan-out,
    lifecycle task. Mirrors `MatcherHandle` (pubsub.rs:518)."""

    def __init__(self, matcher: Matcher, loop: asyncio.AbstractEventLoop):
        self.matcher = matcher
        self.loop = loop
        self.id = matcher.id
        self.sql = matcher.sql
        self._queue: asyncio.Queue = asyncio.Queue()
        self._subscribers: List[asyncio.Queue] = []
        self._sub_lock = threading.Lock()
        self._task: Optional[asyncio.Task] = None
        self._done = asyncio.Event()
        self.error: Optional[str] = None
        self.created_at = time.time()
        self.processed = 0

    @property
    def hash(self) -> str:
        return sql_hash(self.sql)

    @property
    def columns(self) -> List[str]:
        return self.matcher.columns

    @property
    def last_change_id(self) -> int:
        return self.matcher.last_change_id

    # -- feeding (thread-safe; called from change hooks on any thread) -----

    def match_changes(self, changes: Sequence[Change]) -> None:
        cands = self.matcher.filter_candidates(changes)
        if not cands:
            return
        METRICS.counter("corro.subs.matched.count", id=self.id).inc(sum(len(v) for v in cands.values()))
        self.loop.call_soon_threadsafe(self._queue.put_nowait, cands)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._task = self.loop.create_task(self._cmd_loop())

    async def _cmd_loop(self) -> None:
        """Batch candidates 600 ms / 1000 entries then diff
        (pubsub.rs:1062-1226)."""
        last_prune = time.monotonic()
        try:
            while True:
                batch: Dict[str, Set[bytes]] = {}
                n = 0
                first = await self._queue.get()
                if first is None:
                    break
                deadline = self.loop.time() + CANDIDATE_BATCH_WAIT
                for t, pks in first.items():
                    batch.setdefault(t, set()).update(pks)
                    n += len(pks)
                while n < CANDIDATE_BATCH_MAX:
                    timeout = deadline - self.loop.time()
                    if timeout <= 0:
                        break
                    try:
                        more = await asyncio.wait_for(
                            self._queue.get(), timeout
                        )
                    except asyncio.TimeoutError:
                        break
                    if more is None:
                        self._queue.put_nowait(None)  # re-signal stop
                        break
                    for t, pks in more.items():
                        batch.setdefault(t, set()).update(pks)
                        n += len(pks)
                events = await asyncio.to_thread(
                    self.matcher.handle_candidates, batch
                )
                self.processed += n
                if events:
                    self._fan_out(events)
                if time.monotonic() - last_prune > PRUNE_INTERVAL:
                    await asyncio.to_thread(self.matcher.prune_log)
                    last_prune = time.monotonic()
        except Exception as e:  # matcher died: notify subscribers
            self.error = str(e)
            METRICS.counter("corro.subs.errors.count", id=self.id).inc()
        finally:
            # clean stop AND error both release attached streams
            self._fan_out([None])
            self._done.set()

    def _fan_out(self, events: List[Optional[SubEvent]]) -> None:
        with self._sub_lock:
            subs = list(self._subscribers)
        for q in subs:
            for ev in events:
                q.put_nowait(ev)

    def attach(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        with self._sub_lock:
            self._subscribers.append(q)
        return q

    def detach(self, q: asyncio.Queue) -> None:
        with self._sub_lock:
            with contextlib.suppress(ValueError):
                self._subscribers.remove(q)

    @property
    def subscriber_count(self) -> int:
        with self._sub_lock:
            return len(self._subscribers)

    async def stop(self) -> None:
        self._queue.put_nowait(None)
        if self._task is not None:
            await self._done.wait()
            self._task = None
        await asyncio.to_thread(self.matcher.close)

"""The live-query matcher: one materialized, incrementally-maintained
result set per subscription.

Counterpart of `Matcher`/`MatcherHandle` in `klukai-types/src/pubsub.rs`
(`Matcher::new` :556-803, run/cmd_loop :1029-1226, handle_candidates
:1401-1673). Same architecture, re-hosted on the sqlite3-backed CRDT
store:

- each subscription owns its own SQLite db (`sub.sqlite` under
  `<subs_path>/<uuid>/`) with tables `query` (materialized rows,
  `__corro_rowid` PK + unique pk-tuple index), `changes` (ChangeId log),
  `meta`, `columns` (pubsub.rs:893-977);
- the SELECT is rewritten per source table: pk alias columns
  `__corro_pk_<tbl>_<pk>` are prepended for every table, and a
  `(pks) IN temp_<tbl>` membership predicate is AND-injected for the
  driving table; LEFT joins on the driving table become INNER
  (pubsub.rs:616-711, table_to_expr :2123);
- incremental maintenance batches match candidates (table → pk) for
  600 ms / 1000 entries, inserts the changed pks into `temp_<tbl>`,
  runs the rewritten query, and set-differences against the
  materialized `query` table, appending each emitted change to the
  `changes` log with a monotonically increasing ChangeId
  (pubsub.rs:1062-1226,1401-1673);
- the changes log is pruned to the most recent rows every 5 min
  (pubsub.rs:1171-1192); catch-up from a pruned ChangeId fails and the
  client must resubscribe anew.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from corrosion_tpu.api.types import dump_value
from corrosion_tpu.pubsub.parse import ParsedSelect, ParseError, parse_select
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.pack import unpack_columns

CANDIDATE_BATCH_MAX = 1000  # pubsub.rs cmd_loop batch cap
CANDIDATE_BATCH_WAIT = 0.6  # 600 ms (pubsub.rs:1069)
CHANGES_LOG_KEEP = 500  # prune to last 500 (pubsub.rs:1171-1192)
PRUNE_INTERVAL = 300.0  # every 5 min

# UPDATE/INSERT/DELETE ... RETURNING landed in SQLite 3.35.0; older
# libraries (this image ships 3.34.1) take a SELECT-then-mutate
# fallback in the _diff_* family — same events, one extra read per diff
# statement.  Gated once at import, not per batch.
_HAS_RETURNING = sqlite3.sqlite_version_info >= (3, 35, 0)


class MatcherError(Exception):
    pass


@dataclass(frozen=True)
class SubEvent:
    """One row-change event: mirrors QueryEvent::Change.

    `values_json` is the cells encoded ONCE at diff time (it is also
    exactly what the `sub.changes` log stores) — the NDJSON line every
    subscriber receives is assembled from it by `line()` without
    re-serializing per subscriber, so a 128-subscriber fan-out pays one
    json.dumps, not 128."""

    change_id: int
    kind: str  # insert | update | delete
    rowid: int
    values: List[Any]  # JSON-ready cell values
    values_json: str = ""  # json.dumps(values), computed once

    def line(self) -> str:
        """The full `{"change":[kind,rowid,values,change_id]}` NDJSON
        line, shared across subscribers (kind is a fixed token and
        rowid/change_id are ints, so assembly is plain concatenation)."""
        vj = self.values_json or json.dumps(
            self.values, separators=(",", ":")
        )
        return (
            f'{{"change":["{self.kind}",{self.rowid},{vj},{self.change_id}]}}'
        )


def sql_hash(sql: str) -> str:
    """Dedupe key for subscriptions: also the `corro-query-hash` header
    (the single definition — manager.py re-exports it).

    Wire parity (r6, closes VERDICT r5 missing #4): the reference
    computes `seahash::hash(sql.as_bytes())` and formats it as 16
    lower-hex chars (`klukai-types/src/pubsub.rs:565`, `Matcher::hash`
    → `format!("{:x}", ...)` zero-padded u64); this is the same
    function over the vector-validated `net/seahash.py`, so a
    reference client comparing `corro-query-hash` against its locally
    computed hash now matches.  (Through r5 this was truncated sha256
    — a documented divergence.  No stored artifact carries the hash:
    sub dbs persist the SQL text itself and the manager's by-hash index
    is rebuilt from it on restore, so the swap migrates everything by
    construction.)"""
    from corrosion_tpu.net.seahash import hash_bytes

    return f"{hash_bytes(sql.encode('utf-8')):016x}"


def _pk_alias(table: str, col: str) -> str:
    return f"__corro_pk_{table}_{col}"


@dataclass(frozen=True)
class SubDead:
    """Terminal frame a dying matcher fans out to attached subscribers:
    carries the error so downstream code surfaces a typed error frame
    instead of dereferencing a bare None (`ev.kind` AttributeError).
    A clean stop still fans out None."""

    error: str


class EventBatch(list):
    """One diff's events plus their encoded wire payload, built ONCE
    and shared by every attached subscriber: in the common case (no
    replay filtering) a stream ships `payload()` — the same bytes
    object — so a 128-stream fan-out costs 128 socket writes, not
    128 × len(batch) string joins.  Subclasses list so event-level
    consumers iterate it unchanged.

    r11 latency stamps (set by `_fan_out`, read by the HTTP stream
    write): `event_wall` is when the diff produced these events,
    `origin` the origin node's commit wall clock when a stamp traveled
    with the batch — what event→delivered and the end-to-end total are
    measured against.  r19: `traceparent`/`trace_meta` carry the
    origin's trace context on to the deliver stage span."""

    __slots__ = ("_payload", "event_wall", "origin", "traceparent",
                 "trace_meta")

    def payload(self) -> bytes:
        """All events as NDJSON lines (newline-terminated), lazily
        encoded once.  Only called from the event loop thread, so the
        build is race-free."""
        try:
            return self._payload
        except AttributeError:
            self._payload = (
                "\n".join(ev.line() for ev in self) + "\n"
            ).encode()
            return self._payload


class Matcher:
    """Owns the sub db + the rewrite; drives initial fill and diffs.

    All sqlite work happens on executor threads; the async side
    (cmd_loop) only batches candidates and fans events out.
    """

    def __init__(
        self,
        store,
        parsed: ParsedSelect,
        sub_id: str,
        sql: str,
        sub_path: Optional[str],
    ):
        self.store = store
        self.parsed = parsed
        self.id = sub_id
        self.sql = sql
        self.sub_path = sub_path
        self.columns: List[str] = []
        self._conn: Optional[sqlite3.Connection] = None
        self._conn_lock = threading.Lock()
        self.last_change_id = 0
        # precomputed per-batch SQL (built once by _prepare_plans after
        # the column set is known): stable statement text is what lets
        # sqlite3's per-connection statement cache reuse prepared plans
        # across batches — the old per-batch DROP/CREATE bumped the
        # schema cookie and recompiled everything every 600 ms tick
        self._plans: Dict[str, Any] = {}
        self._state_fill_cache: Dict[frozenset, str] = {}

    # -- setup -------------------------------------------------------------

    def _sub_db_file(self) -> str:
        if self.sub_path is None:
            return ":memory:"
        d = Path(self.sub_path) / self.id
        d.mkdir(parents=True, exist_ok=True)
        return str(d / "sub.sqlite")

    def connect(self) -> sqlite3.Connection:
        """Main-db read conn with the sub db ATTACHed writable."""
        conn = sqlite3.connect(
            self.store.path,
            uri=True,
            check_same_thread=False,
            isolation_level=None,  # manual BEGIN/COMMIT
        )
        conn.row_factory = sqlite3.Row
        conn.execute("ATTACH ? AS sub", (self._sub_db_file(),))
        return conn

    def create_sub_db(self) -> None:
        """Create {query, changes, meta, columns} (pubsub.rs:893-977)."""
        conn = self.connect()
        self._conn = conn
        pk_cols = self._pk_alias_cols()
        probe = conn.execute(self._probe_query())
        self.columns = [d[0] for d in probe.description][len(pk_cols):]
        col_defs = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"col_{i}"' for i in range(len(self.columns))]
        )
        uniq = ", ".join(f'"{c}"' for c in pk_cols)
        with self._conn_lock:
            conn.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS sub.query (
                  __corro_rowid INTEGER PRIMARY KEY AUTOINCREMENT,
                  {col_defs}
                );
                CREATE UNIQUE INDEX IF NOT EXISTS sub.query_pks
                  ON query ({uniq});
                CREATE TABLE IF NOT EXISTS sub.changes (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  type TEXT NOT NULL,
                  __corro_rowid INTEGER NOT NULL,
                  data TEXT NOT NULL
                );
                CREATE TABLE IF NOT EXISTS sub.meta (
                  k TEXT PRIMARY KEY, v
                );
                CREATE TABLE IF NOT EXISTS sub.columns (
                  idx INTEGER PRIMARY KEY, name TEXT NOT NULL
                );
                """
            )
            for t in self.parsed.tables:
                cols = ", ".join(
                    f'"{c}"' for c in self.store.schema.table(t.name).pk_cols
                )
                conn.execute(
                    f'CREATE TABLE IF NOT EXISTS sub."temp_{t.name}" ({cols})'
                )
            self._create_state_results(conn)
            conn.executemany(
                "INSERT OR REPLACE INTO sub.columns (idx, name) VALUES (?, ?)",
                list(enumerate(self.columns)),
            )
            conn.execute(
                "INSERT OR REPLACE INTO sub.meta (k, v) VALUES ('sql', ?)",
                (self.sql,),
            )
            conn.execute(
                "INSERT OR REPLACE INTO sub.meta (k, v) VALUES"
                " ('state', 'created')"
            )
        self._prepare_plans()

    def reattach(self) -> None:
        """Reopen an existing sub db (restore path, pubsub.rs:826-861)."""
        conn = self.connect()
        self._conn = conn
        state = conn.execute(
            "SELECT v FROM sub.meta WHERE k = 'state'"
        ).fetchone()
        if state is None or state["v"] != "completed":
            raise MatcherError("sub db incomplete; purge and recreate")
        self.columns = [
            r["name"]
            for r in conn.execute(
                "SELECT name FROM sub.columns ORDER BY idx"
            )
        ]
        with self._conn_lock:
            # legacy sub dbs carry a CREATE-TABLE-AS state_results whose
            # column names came from the select list; rebuild canonical
            conn.execute("DROP TABLE IF EXISTS sub.state_results")
            for t in self.parsed.tables:
                cols = ", ".join(
                    f'"{c}"' for c in self.store.schema.table(t.name).pk_cols
                )
                conn.execute(
                    f'CREATE TABLE IF NOT EXISTS sub."temp_{t.name}" ({cols})'
                )
            self._create_state_results(conn)
        row = conn.execute("SELECT MAX(id) AS m FROM sub.changes").fetchone()
        self.last_change_id = int(row["m"] or 0)
        self._prepare_plans()

    def _create_state_results(self, conn) -> None:
        """Persistent diff scratch table (canonical column names: pk
        aliases then col_0..col_n) + a pk index.  Created ONCE — batches
        reuse it via DELETE + INSERT...SELECT, never DDL: the old
        per-batch DROP/CREATE both recompiled every cached statement
        (schema cookie bump) and left the diff lookups unindexed, which
        is where the banked bench's O(table) per-batch cost lived."""
        pk_cols = self._pk_alias_cols()
        col_defs = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"col_{i}"' for i in range(len(self.columns))]
        )
        idx_cols = ", ".join(f'"{c}"' for c in pk_cols)
        conn.execute(
            f"CREATE TABLE IF NOT EXISTS sub.state_results ({col_defs})"
        )
        conn.execute(
            "CREATE INDEX IF NOT EXISTS sub.state_results_pks"
            f" ON state_results ({idx_cols})"
        )

    def _prepare_plans(self) -> None:
        """Build every per-batch SQL string once.

        The diff statements are shaped so the measured 3.34 planner
        keeps them O(batch): each is DRIVEN from `state_results` or the
        temp pk tables (batch-sized) with indexed lookups into
        `sub.query` — CROSS JOIN pins the join order for the update
        scan, LEFT JOIN ... IS NULL pins it for the insert-miss scan
        (the previous UPDATE...FROM / INSERT..SELECT..NOT EXISTS shapes
        let the planner flip to a full scan of the materialized table
        per batch).  Mutations are applied by __corro_rowid executemany
        — plan-proof, and independent of RETURNING support."""
        pk_cols = self._pk_alias_cols()
        ncols = len(self.columns)
        p: Dict[str, Any] = {}
        p["temp_clear"] = {}
        p["temp_insert"] = {}
        for t in self.parsed.tables:
            if t.name in p["temp_clear"]:
                continue
            tbl_pks = self.store.schema.table(t.name).pk_cols
            p["temp_clear"][t.name] = f'DELETE FROM sub."temp_{t.name}"'
            p["temp_insert"][t.name] = (
                f'INSERT INTO sub."temp_{t.name}" VALUES'
                f" ({', '.join('?' * len(tbl_pks))})"
            )
        p["state_clear"] = "DELETE FROM sub.state_results"
        state_cols = [f'"{c}"' for c in pk_cols] + [
            f'"col_{i}"' for i in range(ncols)
        ]
        p["state_cols"] = ", ".join(state_cols)

        on = " AND ".join(
            f'q."{c}" IS s."{c}"' for c in pk_cols
        )
        s_user = [f's."col_{i}"' for i in range(ncols)]
        q_user = [f'q."col_{i}"' for i in range(ncols)]
        differs = " OR ".join(
            f"{qc} IS NOT {sc}" for qc, sc in zip(q_user, s_user)
        )
        # updates: read rowid + new values driven from s (CROSS JOIN =
        # no reorder), then apply by rowid
        if ncols:
            p["updates_select"] = (
                f"SELECT q.__corro_rowid, {', '.join(s_user)}"
                f" FROM sub.state_results s CROSS JOIN sub.query q"
                f" ON {on} WHERE {differs}"
            )
            p["updates_apply"] = (
                "UPDATE sub.query SET "
                + ", ".join(f'"col_{i}" = ?' for i in range(ncols))
                + " WHERE __corro_rowid = ?"
            )
        # inserts: rows in s with no pk partner in q (LEFT JOIN pins s
        # as the driving table; the q probe rides the unique pk index)
        p["inserts_select"] = (
            f"SELECT {', '.join(['s.' + c for c in state_cols])}"
            f" FROM sub.state_results s LEFT JOIN sub.query q ON {on}"
            " WHERE q.__corro_rowid IS NULL"
        )
        p["inserts_apply"] = (
            f"INSERT INTO sub.query ({p['state_cols']}) VALUES"
            f" ({', '.join('?' * len(state_cols))})"
        )
        p["max_rowid"] = (
            "SELECT COALESCE(MAX(__corro_rowid), 0) FROM sub.query"
        )
        user_sel = ", ".join(f'"col_{i}"' for i in range(ncols))
        p["inserted_rows"] = (
            f"SELECT __corro_rowid{', ' + user_sel if ncols else ''}"
            " FROM sub.query WHERE __corro_rowid > ?"
            " ORDER BY __corro_rowid"
        )
        # deletes: per changed table — candidates driven by the temp pk
        # list (IN → indexed q lookups), absence checked against the
        # indexed state_results
        p["deletes_select"] = {}
        for table in {t.name for t in self.parsed.tables}:
            tbl_pks = self.store.schema.table(table).pk_cols
            ref_preds = []
            for ref in self.parsed.tables:
                if ref.name != table:
                    continue
                aliases = [f'"{_pk_alias(ref.alias, c)}"' for c in tbl_pks]
                quoted_pks = ", ".join(f'"{c}"' for c in tbl_pks)
                ref_preds.append(
                    f"({', '.join('q.' + a for a in aliases)}) IN"
                    f" (SELECT {quoted_pks}"
                    f' FROM sub."temp_{table}")'
                )
            in_temp = "(" + " OR ".join(ref_preds) + ")"
            not_in_results = (
                "NOT EXISTS (SELECT 1 FROM sub.state_results s WHERE "
                + " AND ".join(f'q."{c}" IS s."{c}"' for c in pk_cols)
                + ")"
            )
            p["deletes_select"][table] = (
                f"SELECT __corro_rowid{', ' + user_sel if ncols else ''}"
                f" FROM sub.query AS q WHERE {in_temp} AND {not_in_results}"
            )
            p.setdefault("deletes_returning", {})[table] = (
                f"DELETE FROM sub.query AS q WHERE {in_temp} AND"
                f" {not_in_results} RETURNING"
                f" __corro_rowid{', ' + user_sel if ncols else ''}"
            )
        p["deletes_apply"] = (
            "DELETE FROM sub.query WHERE __corro_rowid = ?"
        )
        p["log_append"] = (
            "INSERT INTO sub.changes (id, type, __corro_rowid, data)"
            " VALUES (?, ?, ?, ?)"
        )
        self._plans = p
        self._state_fill_cache = {}

    def _state_fill_sql(self, tables: frozenset) -> str:
        """INSERT...SELECT (UNION of per-ref rewritten queries) for one
        candidate-table set, memoized so the statement text — and the
        prepared plan behind it — is stable across batches."""
        sql = self._state_fill_cache.get(tables)
        if sql is None:
            selects = [
                self._table_query(ref)
                for ref in self.parsed.tables
                if ref.name in tables
            ]
            sql = (
                f"INSERT INTO sub.state_results ({self._plans['state_cols']}) "
                + " UNION ".join(selects)
            )
            self._state_fill_cache[tables] = sql
        return sql

    # -- rewrites ----------------------------------------------------------

    def _pk_alias_cols(self) -> List[str]:
        # keyed by ref *alias* (not table name) so self-joins — two refs to
        # one table — get distinct materialized pk columns
        out = []
        for t in self.parsed.tables:
            for c in self.store.schema.table(t.name).pk_cols:
                out.append(_pk_alias(t.alias, c))
        return out

    def _pk_select_prefix(self) -> str:
        parts = []
        for t in self.parsed.tables:
            for c in self.store.schema.table(t.name).pk_cols:
                parts.append(f'"{t.alias}"."{c}" AS "{_pk_alias(t.alias, c)}"')
        return ", ".join(parts)

    def _probe_query(self) -> str:
        """Initial/probe form: pk aliases + user select list, full scan.
        The ORDER BY tail (the only one parse_select admits) shapes the
        initial fill; incremental change events are unordered."""
        p = self.parsed
        where = f" WHERE {p.where_clause}" if p.where_clause else ""
        tail = f" {p.tail}" if p.tail else ""
        return (
            f"SELECT {self._pk_select_prefix()}, {p.select_list}"
            f" FROM {p.from_clause}{where}{tail}"
        )

    def _table_query(self, ref) -> str:
        """Rewritten per-driving-table-ref query with the temp pk predicate
        (pubsub.rs:616-711): restricts re-evaluation to changed pks."""
        p = self.parsed
        driving = ref.name
        pks = self.store.schema.table(driving).pk_cols
        tuple_lhs = ", ".join(f'"{ref.alias}"."{c}"' for c in pks)
        tuple_rhs = ", ".join(f'"{c}"' for c in pks)
        pred = (
            f"({tuple_lhs}) IN (SELECT {tuple_rhs} FROM"
            f' sub."temp_{driving}")'
        )
        from_clause = p.from_clause
        if ref.left_joined:
            # LEFT JOIN driving → INNER so the pk predicate can bind
            from_clause = _left_to_inner(from_clause, ref.alias)
        where = f"({p.where_clause}) AND {pred}" if p.where_clause else pred
        return (
            f"SELECT {self._pk_select_prefix()}, {p.select_list}"
            f" FROM {from_clause} WHERE {where}"
        )

    # -- initial fill ------------------------------------------------------

    def run_initial(self) -> Tuple[List[str], int]:
        """Materialize the full result into sub.query; returns
        (columns, row_count). Subscribers read rows via `snapshot()` —
        the attach-then-snapshot protocol (pubsub.rs:1029-1060)."""
        conn = self._conn
        assert conn is not None
        pk_cols = self._pk_alias_cols()
        ncols = len(self.columns)
        ins_cols = ", ".join(
            [f'"{c}"' for c in pk_cols]
            + [f'"col_{i}"' for i in range(ncols)]
        )
        n = 0
        with self._conn_lock:
            conn.execute("BEGIN")
            try:
                for row in conn.execute(self._probe_query()):
                    conn.execute(
                        f"INSERT INTO sub.query ({ins_cols}) VALUES"
                        f" ({', '.join('?' * (len(pk_cols) + ncols))})",
                        tuple(row),
                    )
                    n += 1
                conn.execute(
                    "INSERT OR REPLACE INTO sub.meta (k, v) VALUES"
                    " ('state', 'completed')"
                )
                conn.execute("COMMIT")
            except BaseException:
                conn.execute("ROLLBACK")
                raise
        return self.columns, n

    def all_rows(self) -> List[Tuple[int, List[Any]]]:
        """Current materialized rows (re-attach without `from`)."""
        return self.snapshot()[0]

    def snapshot(self) -> Tuple[List[Tuple[int, List[Any]]], int]:
        """(rows, last_change_id) read atomically under the conn lock —
        no diff can commit between the two, so a subscriber that streams
        these rows then live events > last_change_id misses nothing."""
        conn = self._conn
        assert conn is not None
        ncols = len(self.columns)
        sel = ", ".join(f'"col_{i}"' for i in range(ncols))
        with self._conn_lock:
            rows = conn.execute(
                f"SELECT __corro_rowid, {sel} FROM sub.query"
                " ORDER BY __corro_rowid"
            ).fetchall()
            snap_id = self.last_change_id
        return [(r[0], list(r)[1:]) for r in rows], snap_id

    def materialized_pks(self, table: str) -> List[bytes]:
        """Packed pks of `table` present in the materialized result
        (restore resync: rows deleted while the agent was down exist
        here but not in the live table, and must be re-checked)."""
        from corrosion_tpu.types.pack import pack_columns

        conn = self._conn
        assert conn is not None
        pks: Set[bytes] = set()
        with self._conn_lock:
            for ref in self.parsed.tables:
                if ref.name != table:
                    continue
                aliases = [
                    f'"{_pk_alias(ref.alias, c)}"'
                    for c in self.store.schema.table(table).pk_cols
                ]
                rows = conn.execute(
                    f"SELECT DISTINCT {', '.join(aliases)} FROM sub.query"
                ).fetchall()
                pks.update(pack_columns(tuple(r)) for r in rows)
        return list(pks)

    # -- candidate filtering ----------------------------------------------

    def filter_candidates(
        self, changes: Sequence[Change]
    ) -> Dict[str, Set[bytes]]:
        """Which (table, pk) pairs could affect this query?
        (updates.rs:424-488 `match_changes` filter)."""
        out: Dict[str, Set[bytes]] = {}
        for ch in changes:
            deps = self.parsed.col_deps.get(ch.table)
            if deps is None:
                continue
            if ch.is_sentinel() or ch.cid in deps:
                out.setdefault(ch.table, set()).add(ch.pk)
        return out

    # -- incremental diff --------------------------------------------------

    def handle_candidates(
        self, candidates: Dict[str, Set[bytes]]
    ) -> List[SubEvent]:
        """Diff changed pks against the materialized result
        (pubsub.rs:1401-1673). Runs on an executor thread.

        Steady-state cost is O(changed pks), independent of the table
        size: every statement here is precomputed text (prepared-plan
        reuse), driven from the batch-sized temp/state tables, and the
        only DML against `sub.query` is rowid-keyed.  A tier-1 trace
        pin (tests/test_pubsub_perf.py) holds the per-batch statement
        count equal across table sizes."""
        from corrosion_tpu.runtime.trace import timed_query

        conn = self._conn
        assert conn is not None
        plans = self._plans
        events: List[SubEvent] = []
        start = time.monotonic()
        # r23 statement profiler: the whole batch diff is ONE shape —
        # its statements are precomputed plans, so per-statement keys
        # would only split a fixed pipeline across meaningless rows
        with self._conn_lock, timed_query(
            "subs batch diff", shape="match:batch"
        ):
            conn.execute("BEGIN")
            try:
                for table, pks in candidates.items():
                    conn.execute(plans["temp_clear"][table])
                    conn.executemany(
                        plans["temp_insert"][table],
                        [tuple(unpack_columns(pk)) for pk in pks],
                    )
                self._expand_left_join_candidates(conn, candidates)
                conn.execute(plans["state_clear"])
                # one select per driving *ref* of a changed table, so a
                # self-joined table re-evaluates through both of its refs
                conn.execute(self._state_fill_sql(frozenset(candidates)))

                events.extend(self._diff_updates(conn))
                events.extend(self._diff_inserts(conn))
                events.extend(self._diff_deletes(conn, candidates))
                if events:
                    conn.executemany(
                        plans["log_append"],
                        [
                            (ev.change_id, ev.kind, ev.rowid, ev.values_json)
                            for ev in events
                        ],
                    )
                conn.execute("COMMIT")
            except BaseException:
                conn.execute("ROLLBACK")
                raise
        METRICS.histogram("corro.subs.process.time.seconds", id=self.id).observe(time.monotonic() - start)
        return events

    def _mk_event(self, kind: str, rowid: int, raw_values) -> SubEvent:
        values = [dump_value(v) for v in raw_values]
        return SubEvent(
            self._next_id(),
            kind,
            rowid,
            values,
            json.dumps(values, separators=(",", ":")),
        )

    def _next_id(self) -> int:
        self.last_change_id += 1
        return self.last_change_id

    def _expand_left_join_candidates(self, conn, candidates) -> None:
        """A change on the right side of a LEFT JOIN can invalidate a
        NULL-extended row (partner appeared) or require re-creating one
        (last partner vanished). Neither is reachable through the changed
        table's own driving query — NULL pk aliases never match the temp
        predicate — so re-evaluate the affected parent rows through every
        other ref, whose rewritten query preserves the LEFT JOIN."""
        for ref in self.parsed.tables:
            if not ref.left_joined or ref.name not in candidates:
                continue
            tbl_pks = self.store.schema.table(ref.name).pk_cols
            p_aliases = [f'"{_pk_alias(ref.alias, c)}"' for c in tbl_pks]
            null_pred = " AND ".join(f"q.{a} IS NULL" for a in p_aliases)
            quoted_pks = ", ".join(f'"{c}"' for c in tbl_pks)
            in_temp = (
                f"({', '.join('q.' + a for a in p_aliases)}) IN"
                f" (SELECT {quoted_pks}"
                f' FROM sub."temp_{ref.name}")'
            )
            for other in self.parsed.tables:
                if other is ref:
                    continue
                o_pks = self.store.schema.table(other.name).pk_cols
                o_aliases = [
                    f'"{_pk_alias(other.alias, c)}"' for c in o_pks
                ]
                rows = conn.execute(
                    f"SELECT DISTINCT {', '.join('q.' + a for a in o_aliases)}"
                    f" FROM sub.query q WHERE ({null_pred}) OR {in_temp}"
                ).fetchall()
                if not rows:
                    continue
                if other.name not in candidates:
                    # table joins the diff fresh: clear last round's pks
                    conn.execute(f'DELETE FROM sub."temp_{other.name}"')
                    candidates[other.name] = set()
                conn.executemany(
                    f'INSERT INTO sub."temp_{other.name}" VALUES'
                    f" ({', '.join('?' * len(o_pks))})",
                    [tuple(r) for r in rows],
                )

    def _diff_updates(self, conn) -> List[SubEvent]:
        """Rows whose pk exists but whose values changed → update.

        SELECT-then-mutate-by-rowid on every SQLite version: the
        single-statement `UPDATE ... FROM ... RETURNING` alternative
        measured O(table) under the 3.34 planner (it flips to a full
        scan of sub.query with an automatic index over state_results),
        and the rows have to be fetched for the events anyway — so the
        plan-pinned CROSS JOIN read + rowid-keyed writes are both the
        portable path and the fast one."""
        if len(self.columns) == 0:
            return []
        rows = conn.execute(self._plans["updates_select"]).fetchall()
        if not rows:
            return []
        conn.executemany(
            self._plans["updates_apply"],
            [tuple(r)[1:] + (r[0],) for r in rows],
        )
        return [self._mk_event("update", r[0], list(r)[1:]) for r in rows]

    def _diff_inserts(self, conn) -> List[SubEvent]:
        """state_results rows with no pk partner in the materialized
        table → insert.  The LEFT JOIN pins state_results as the outer
        loop (O(batch)); inserted rowids are read back as the
        AUTOINCREMENT-contiguous range past the pre-insert MAX (an O(1)
        index peek)."""
        plans = self._plans
        rows = conn.execute(plans["inserts_select"]).fetchall()
        if not rows:
            return []
        max_rowid = conn.execute(plans["max_rowid"]).fetchone()[0]
        conn.executemany(plans["inserts_apply"], [tuple(r) for r in rows])
        inserted = conn.execute(
            plans["inserted_rows"], (max_rowid,)
        ).fetchall()
        return [
            self._mk_event("insert", r[0], list(r)[1:]) for r in inserted
        ]

    def _diff_deletes(self, conn, candidates) -> List[SubEvent]:
        """Materialized rows whose driving pks were candidates but which
        no longer appear in state_results → delete.  Candidate rows are
        reached through the temp pk list (indexed q lookups), the
        absence probe rides the state_results pk index, and the DELETE
        itself is rowid-keyed."""
        events: List[SubEvent] = []
        for table in candidates:
            if _HAS_RETURNING:
                # fast path (>= 3.35): one statement — the candidate
                # predicate keeps the same indexed plan as the SELECT
                rows = conn.execute(
                    self._plans["deletes_returning"][table]
                ).fetchall()
            else:
                rows = conn.execute(
                    self._plans["deletes_select"][table]
                ).fetchall()
                if rows:
                    conn.executemany(
                        self._plans["deletes_apply"],
                        [(r[0],) for r in rows],
                    )
            for r in rows:
                events.append(self._mk_event("delete", r[0], list(r)[1:]))
        return events

    # -- log / catch-up ----------------------------------------------------

    def changes_since(self, from_id: int) -> Optional[List[SubEvent]]:
        """Replay the changes log after `from_id`; None if pruned away."""
        conn = self._conn
        assert conn is not None
        with self._conn_lock:
            row = conn.execute("SELECT MIN(id) AS m FROM sub.changes").fetchone()
            min_id = row["m"]
            if min_id is not None and from_id + 1 < min_id:
                return None  # gap: log pruned past the requested id
            rows = conn.execute(
                "SELECT id, type, __corro_rowid, data FROM sub.changes"
                " WHERE id > ? ORDER BY id",
                (from_id,),
            ).fetchall()
        return [
            SubEvent(
                r["id"],
                r["type"],
                r["__corro_rowid"],
                json.loads(r["data"]),
                r["data"],
            )
            for r in rows
        ]

    def prune_log(self) -> int:
        conn = self._conn
        assert conn is not None
        with self._conn_lock:
            cur = conn.execute(
                "DELETE FROM sub.changes WHERE id <= "
                "(SELECT MAX(id) FROM sub.changes) - ?",
                (CHANGES_LOG_KEEP,),
            )
        return cur.rowcount

    def close(self) -> None:
        if self._conn is not None:
            with contextlib.suppress(sqlite3.Error):
                self._conn.close()
            self._conn = None


def _left_to_inner(from_clause: str, alias: str) -> str:
    """Replace `LEFT [OUTER] JOIN <tbl> [AS] <alias>` with INNER JOIN for
    the driving table (pubsub.rs:688-711)."""
    import re

    pat = re.compile(
        r"LEFT\s+(?:OUTER\s+)?JOIN(?P<rest>\s+\S+(?:\s+AS)?\s+"
        + re.escape(alias)
        + r"\b)",
        re.IGNORECASE,
    )
    def sub(m):
        return "JOIN" + m.group("rest")

    out = pat.sub(sub, from_clause, count=1)
    if out == from_clause:
        # alias == table name, unaliased form
        pat2 = re.compile(
            r"LEFT\s+(?:OUTER\s+)?JOIN(?P<rest>\s+" + re.escape(alias) + r"\b)",
            re.IGNORECASE,
        )
        out = pat2.sub(sub, from_clause, count=1)
    return out


class MatcherHandle:
    """Async face of a Matcher: candidate queue, subscriber fan-out,
    lifecycle task. Mirrors `MatcherHandle` (pubsub.rs:518)."""

    def __init__(
        self,
        matcher: Matcher,
        loop: asyncio.AbstractEventLoop,
        executor=None,
        batch_wait: Optional[float] = None,
        fanout=None,
    ):
        self.matcher = matcher
        self.loop = loop
        self.id = matcher.id
        self.sql = matcher.sql
        # shared bounded DiffExecutor (pubsub/executor.py) when owned by
        # a SubsManager; None falls back to asyncio.to_thread
        self._executor = executor
        # shared coalescing FanoutWriter (pubsub/fanout.py, r16): HTTP
        # stream sinks are served by its single writer task; the queue
        # subscriber path below stays for in-process consumers
        self._fanout = fanout
        # candidate-batching window: config [pubsub] candidate_batch_wait
        # (r12 — the knob the r11 SLO plane named as the ~600 ms p50
        # `match` culprit); None keeps the pubsub.rs-parity default
        self.batch_wait = (
            batch_wait if batch_wait is not None else CANDIDATE_BATCH_WAIT
        )
        self._queue: asyncio.Queue = asyncio.Queue()
        self._subscribers: List[asyncio.Queue] = []
        self._sinks: tuple = ()  # StreamSinks; copy-on-write snapshot
        self._sub_lock = threading.Lock()
        self._task: Optional[asyncio.Task] = None
        self._done = asyncio.Event()
        self.error: Optional[str] = None
        self.created_at = time.time()
        self.processed = 0
        # r16 refcounted lifecycle: `leases` bridges the gap between a
        # handler obtaining the handle and its stream attaching, so the
        # manager's linger reaper can't tear the matcher down in
        # between; on_active/on_idle are set by the owning SubsManager
        # (loop-thread callbacks)
        self.leases = 0
        self.on_active = None
        self.on_idle = None

    @property
    def hash(self) -> str:
        return sql_hash(self.sql)

    @property
    def columns(self) -> List[str]:
        return self.matcher.columns

    @property
    def last_change_id(self) -> int:
        return self.matcher.last_change_id

    def changes_since(self, from_id: int) -> Optional[List[SubEvent]]:
        """Catch-up through the handle: a dead matcher raises a typed
        MatcherError (callers turn it into an error frame) instead of
        replaying from a connection whose diff loop has stopped."""
        if self.error is not None:
            raise MatcherError(f"subscription failed: {self.error}")
        return self.matcher.changes_since(from_id)

    # -- feeding (thread-safe; called from change hooks on any thread) -----

    def match_changes(self, changes: Sequence[Change], stamp=None) -> None:
        """Filter + enqueue. Standalone-handle path: a manager-owned
        handle receives pre-filtered candidates via
        `enqueue_candidates` from the routing index instead."""
        self.enqueue_candidates(
            self.matcher.filter_candidates(changes), stamp
        )

    def enqueue_candidates(
        self, cands: Dict[str, Set[bytes]], stamp=None
    ) -> None:
        """Feed pre-filtered candidate pks (thread-safe).  `stamp` is
        the committed batch's latency stamp (BatchStamp) or None."""
        if not cands:
            return
        METRICS.counter("corro.subs.matched.count", id=self.id).inc(sum(len(v) for v in cands.values()))
        self.loop.call_soon_threadsafe(
            self._queue.put_nowait, (cands, stamp)
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._task = self.loop.create_task(self._cmd_loop())

    async def _cmd_loop(self) -> None:
        """Batch candidates `batch_wait` s / 1000 entries then diff
        (pubsub.rs:1062-1226; window configurable since r12)."""
        last_prune = time.monotonic()
        try:
            while True:
                batch: Dict[str, Set[bytes]] = {}
                n = 0
                first = await self._queue.get()
                if first is None:
                    break
                cands, stamp = first
                deadline = self.loop.time() + self.batch_wait
                for t, pks in cands.items():
                    batch.setdefault(t, set()).update(pks)
                    n += len(pks)
                while n < CANDIDATE_BATCH_MAX:
                    timeout = deadline - self.loop.time()
                    if timeout <= 0:
                        break
                    try:
                        more = await asyncio.wait_for(
                            self._queue.get(), timeout
                        )
                    except asyncio.TimeoutError:
                        break
                    if more is None:
                        self._queue.put_nowait(None)  # re-signal stop
                        break
                    more_cands, more_stamp = more
                    if more_stamp is not None:
                        # coalesced batches keep the OLDEST stamp: the
                        # batch's latency is its worst element's
                        stamp = more_stamp.oldest(stamp)
                    for t, pks in more_cands.items():
                        batch.setdefault(t, set()).update(pks)
                        n += len(pks)
                events = await self._run_blocking(
                    self.matcher.handle_candidates, batch
                )
                self.processed += n
                if events:
                    self._fan_out(events, stamp)
                if time.monotonic() - last_prune > PRUNE_INTERVAL:
                    await self._run_blocking(self.matcher.prune_log)
                    last_prune = time.monotonic()
        except Exception as e:  # matcher died: notify subscribers
            self.error = str(e)
            METRICS.counter("corro.subs.errors.count", id=self.id).inc()
        finally:
            # clean stop AND death both release attached streams — death
            # with a TYPED terminal frame (the error travels with the
            # sentinel so streams surface it instead of dereferencing
            # a bare None)
            self._fan_out_terminal(
                SubDead(self.error) if self.error is not None else None
            )
            self._done.set()

    async def _run_blocking(self, fn, *args):
        if self._executor is not None:
            return await self._executor.run(fn, *args)
        return await asyncio.to_thread(fn, *args)

    def _fan_out(self, events: List[SubEvent], stamp=None) -> None:
        """ONE queue put per subscriber per diff batch: each attached
        stream receives the same EventBatch (shared object — per-event
        encoding happened once in the diff, the wire payload encodes
        once on first ship), wakes once, and ships it in one socket
        write.  Per-event-per-subscriber puts were the 128-stream
        fan-out's dominant loop cost."""
        batch = EventBatch(events)
        batch.event_wall = time.time()
        batch.origin = stamp.origin if stamp is not None else None
        batch.traceparent = stamp.traceparent if stamp is not None else None
        batch.trace_meta = stamp.trace_meta if stamp is not None else None
        if stamp is not None:
            # apply→event: candidate batching window + diff execution
            from corrosion_tpu.runtime.latency import e2e_observe

            delta = e2e_observe("match", batch.event_wall - stamp.applied)
            if stamp.traceparent is not None:
                # r19: the same hop as a stage span on the write's trace
                from corrosion_tpu.runtime.trace import (
                    meta_forced,
                    stage_span,
                )

                stage_span(
                    stamp.traceparent, "subs.match", "match", delta,
                    forced=meta_forced(stamp.trace_meta),
                    sub=self.id, events=len(events),
                )
        with self._sub_lock:
            subs = list(self._subscribers)
            sinks = self._sinks
        for q in subs:
            q.put_nowait(batch)
        if sinks and self._fanout is not None:
            # ONE submit per diff batch regardless of stream count: the
            # shared writer task walks the sinks (pubsub/fanout.py)
            self._fanout.submit(sinks, batch)

    def _fan_out_terminal(self, sentinel) -> None:
        """End-of-stream: a bare None (clean stop) or SubDead frame."""
        with self._sub_lock:
            subs = list(self._subscribers)
            sinks = self._sinks
        for q in subs:
            q.put_nowait(sentinel)
        if sinks and self._fanout is not None:
            self._fanout.submit(sinks, sentinel)

    def attach(self) -> asyncio.Queue:
        """Subscribe to live events.  Queue items are LISTS of SubEvent
        (one per diff batch), a bare None (clean stop) or a SubDead
        terminal frame (matcher death, carries the error)."""
        q: asyncio.Queue = asyncio.Queue()
        with self._sub_lock:
            self._subscribers.append(q)
        if self.on_active is not None:
            self.on_active(self)
        return q

    def detach(self, q: asyncio.Queue) -> None:
        with self._sub_lock:
            with contextlib.suppress(ValueError):
                self._subscribers.remove(q)
        self._maybe_idle()

    def attach_sink(self, sink) -> None:
        """Register a fan-out StreamSink (HTTP serving plane, r16).
        Same attach-before-snapshot protocol as `attach`: the sink
        starts in HOLD mode and is released after the snapshot/replay
        phase established its replay boundary."""
        with self._sub_lock:
            self._sinks = self._sinks + (sink,)
        if self.on_active is not None:
            self.on_active(self)

    def detach_sink(self, sink) -> None:
        sink.mark_closed()
        with self._sub_lock:
            self._sinks = tuple(
                s for s in self._sinks if s is not sink
            )
        self._maybe_idle()

    def lease(self) -> None:
        """Pin the handle between lookup and attach (loop thread)."""
        self.leases += 1
        if self.on_active is not None:
            self.on_active(self)

    def release_lease(self) -> None:
        self.leases = max(0, self.leases - 1)
        self._maybe_idle()

    def _maybe_idle(self) -> None:
        if self.active_refs == 0 and self.on_idle is not None:
            self.on_idle(self)

    @property
    def active_refs(self) -> int:
        with self._sub_lock:
            return len(self._subscribers) + len(self._sinks) + self.leases

    @property
    def subscriber_count(self) -> int:
        with self._sub_lock:
            return len(self._subscribers) + len(self._sinks)

    async def stop(self) -> None:
        self._queue.put_nowait(None)
        if self._task is not None:
            await self._done.wait()
            self._task = None
        await asyncio.to_thread(self.matcher.close)

"""Live-query (subscriptions) and raw-update notification engines.

Counterpart of `klukai-types/src/pubsub.rs` (SubsManager/Matcher, the
reference's largest single component) and `klukai-types/src/updates.rs`
(UpdatesManager).
"""

from corrosion_tpu.pubsub.manager import SubsManager
from corrosion_tpu.pubsub.matcher import Matcher, MatcherHandle
from corrosion_tpu.pubsub.updates import UpdatesManager

__all__ = ["SubsManager", "Matcher", "MatcherHandle", "UpdatesManager"]

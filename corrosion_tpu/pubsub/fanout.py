"""Shared coalescing fan-out writer for live subscription streams.

Through r15 every HTTP subscription stream owned a drain loop: one
asyncio task parked on a per-stream queue, woken once per diff batch,
issuing its own socket write — O(streams) queue puts, task switches and
write calls per batch, ~10-20 µs of loop time per stream.  At the
production shape named on the ROADMAP (10k-100k concurrent streams per
node) that is 1-2 s of event-loop stall per batch before a single
payload byte moves.

r16 replaces the drain loops with ONE writer task per `SubsManager`:

- `MatcherHandle._fan_out` submits `(sinks, batch)` once per diff batch
  (O(1) — no per-subscriber queue put);
- the writer task encodes the batch's NDJSON payload ONCE
  (`EventBatch.payload()`, the bytes every subscriber shares) and walks
  the subscriber sinks in a tight loop issuing SYNCHRONOUS,
  non-blocking socket writes (`StreamSink.write_some`), yielding to the
  loop every `_YIELD_EVERY` sinks so heartbeats stay honest;
- a sink whose transport stops accepting bytes (kernel/transport buffer
  above bound, h2 flow-control window closed) is CLOGGED: payloads
  accumulate on its pending deque — batches that pile up there coalesce
  into one write when the socket drains (the writev-style batching) —
  and the writer retries it every `tick_secs`;
- a clogged sink past `max_lag_bytes`/`max_lag_batches` is SHED: its
  pending buffer is dropped, `corro.subs.shed.total` counts it, and the
  parked HTTP handler is woken with a `SubLagging` terminal so the
  stream ends with a typed `{"lagging": ...}` frame the client resumes
  from (Prime CCL, arXiv:2505.14065: a slow consumer must degrade,
  never stall the collective — the DiffExecutor and sibling streams
  never wait on a laggard's socket).

Transport specifics (what "non-blocking write" means per flavor) live
in the `StreamSink` subclasses in `api/pubsub_http.py`; this module is
transport-agnostic.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Tuple

from corrosion_tpu.runtime.latency import e2e_observe
from corrosion_tpu.runtime.metrics import METRICS

# yield the event loop to other tasks every N sink visits: a 100k-sink
# walk must not starve heartbeats/timers for its full duration
_YIELD_EVERY = 2048
# cap e2e deliver/total histogram observations per batch: one registry
# hit per sink per batch (~2-5 µs each) would dominate the walk at 100k
# streams; a uniform sample across the walk preserves the percentile
# shape (delivery latency varies with walk position, which the stride
# samples evenly)
_OBSERVE_SAMPLE = 256


@dataclass(frozen=True)
class SubLagging:
    """Terminal frame for a shed laggard: the stream was dropped because
    its socket could not keep up, NOT because the query died.  Carries
    the lag at shed time; `api/types.ev_lagging` is the wire form and
    `client.py` resumes from its last change id on receipt."""

    lag_bytes: int
    lag_batches: int


class SinkClosed(Exception):
    """Raised by `write_some` when the peer is gone (transport closing,
    h2 stream reset): routine detach, not an error."""


class StreamSink:
    """One live subscription stream's write side, driven by the shared
    `FanoutWriter`.  Subclasses implement `writable()` (can the
    transport accept bytes NOW without blocking?) and `write_some(data)
    -> int` (synchronous best-effort write, returns bytes accepted,
    raises SinkClosed when the peer is gone).

    Lifecycle: `attach_sink` while HOLDING (snapshot/replay streams
    through the handler directly), `release(replayed_max)` arms live
    delivery, `done` resolves with the terminal (None = clean stop,
    SubDead = matcher death, SubLagging = shed, SinkClosed = peer gone)
    and the parked handler finishes the response."""

    __slots__ = (
        "max_lag_bytes", "max_lag_batches", "pending", "pending_bytes",
        "replayed_max", "hold", "held", "done", "closed", "_oldest_wall",
        "_oldest_origin", "_oldest_tp", "_oldest_meta", "writer",
    )

    def __init__(self, max_lag_bytes: int, max_lag_batches: int):
        self.max_lag_bytes = max_lag_bytes
        self.max_lag_batches = max_lag_batches
        # (payload, offset) pairs; payload objects are SHARED across
        # sinks — a clogged sink costs bookkeeping, not copies
        self.pending: Deque[Tuple[bytes, int]] = deque()
        self.pending_bytes = 0
        self.replayed_max = 0
        self.hold = True
        self.held: List[object] = []  # EventBatch/terminal while holding
        self.done: asyncio.Future = (
            asyncio.get_event_loop().create_future()
        )
        self.closed = False
        # oldest unobserved latency stamps among pending payloads: one
        # conservative (worst-element) observation per flush — the r19
        # trace context rides with them so the deliver stage span
        # stitches to the same write trace
        self._oldest_wall: Optional[float] = None
        self._oldest_origin: Optional[float] = None
        self._oldest_tp: Optional[str] = None
        self._oldest_meta: Optional[int] = None
        self.writer: Optional["FanoutWriter"] = None

    # -- transport interface (overridden per flavor) -----------------------

    def writable(self) -> bool:  # pragma: no cover — interface
        return True

    def write_some(self, data: bytes) -> int:  # pragma: no cover
        return len(data)

    # -- lifecycle ---------------------------------------------------------

    def release(self, replayed_max: int) -> None:
        """End hold mode after the snapshot/replay phase: filter batches
        the replay already covered, then arm live delivery."""
        self.replayed_max = replayed_max
        self.hold = False
        held, self.held = self.held, []
        for item in held:
            self.offer(item)
        if self.pending and self.writer is not None:
            # anything queued while holding flushes on the writer task
            self.writer.poke(self)

    def _resolve(self, outcome) -> None:
        if not self.done.done():
            self.done.set_result(outcome)

    def mark_closed(self) -> None:
        """Handler-side detach (event-loop only — every sink mutation
        lives on the loop thread): drop pending state, stop delivery."""
        self.closed = True
        self.pending.clear()
        self.pending_bytes = 0
        self._resolve(None)

    def _terminal_pending(self) -> bool:
        return bool(self.pending) and self.pending[0][0] is None

    # -- delivery (writer-task side, loop thread) --------------------------

    def offer(self, item) -> None:
        """Queue one EventBatch (or a terminal sentinel) for this sink.
        Shared-payload fast path: when the batch is entirely past the
        replay boundary the ONE bytes object every subscriber shares is
        referenced, not copied."""
        if self.closed or self.done.done():
            return
        if self.hold:
            self.held.append(item)
            return
        if not isinstance(item, list):
            # terminal sentinel (None clean stop / SubDead): queued as a
            # (None, sentinel) marker so it resolves only after the data
            # already queued ahead of it flushes
            self.pending.append((None, item))
            return
        batch = item
        if not batch:
            return
        if batch[0].change_id > self.replayed_max:
            payload = batch.payload()
        elif batch[-1].change_id <= self.replayed_max:
            return  # replay already covered the whole batch
        else:
            lines = [
                ev.line() for ev in batch
                if ev.change_id > self.replayed_max
            ]
            if not lines:
                return
            payload = ("\n".join(lines) + "\n").encode()
        self.pending.append((payload, 0))
        self.pending_bytes += len(payload)
        ew = getattr(batch, "event_wall", None)
        if ew is not None and self._oldest_wall is None:
            self._oldest_wall = ew
            self._oldest_tp = getattr(batch, "traceparent", None)
            self._oldest_meta = getattr(batch, "trace_meta", None)
        og = getattr(batch, "origin", None)
        if og is not None and self._oldest_origin is None:
            self._oldest_origin = og

    def flush(self, observe: bool = True) -> bool:
        """Write as much pending data as the transport accepts RIGHT
        NOW; returns True when fully drained.  Sheds past lag bounds."""
        if self.closed or self.done.done():
            self.pending.clear()
            self.pending_bytes = 0
            return True
        wrote = 0
        shipped = 0
        try:
            while self.pending:
                head, sentinel = self.pending[0]
                if head is None:  # terminal sentinel reached
                    self.pending.popleft()
                    self._resolve(sentinel)
                    self._note_stats(wrote, shipped)
                    return True
                if not self.writable():
                    break
                # coalesce the contiguous run of queued payloads into
                # ONE transport write (the writev-style batching: a sink
                # that fell behind ships every backed-up batch in a
                # single call when its socket drains)
                run: List[bytes] = []
                for p, off in self.pending:
                    if p is None:
                        break
                    run.append(p[off:] if off else p)
                data = run[0] if len(run) == 1 else b"".join(run)
                n = self.write_some(data)
                if n == 0:
                    break
                wrote += 1
                self.pending_bytes -= n
                while n:  # consume n bytes off the head entries
                    p, off = self.pending[0]
                    rem = len(p) - off
                    if n >= rem:
                        self.pending.popleft()
                        shipped += 1
                        n -= rem
                    else:
                        self.pending[0] = (p, off + n)
                        n = 0
        except SinkClosed as e:
            self.pending.clear()
            self.pending_bytes = 0
            self._resolve(e)
            return True
        self._note_stats(wrote, shipped)
        if not self.pending:
            if observe and self._oldest_wall is not None:
                now = time.time()
                delta = e2e_observe("deliver", now - self._oldest_wall)
                if self._oldest_origin is not None:
                    e2e_observe("total", now - self._oldest_origin)
                if self._oldest_tp is not None:
                    # r19 deliver stage span, stride-sampled exactly
                    # like the latency observation it shares a gate
                    # with — a 100k-sink walk never pays per-sink spans
                    from corrosion_tpu.runtime.trace import (
                        meta_forced,
                        stage_span,
                    )

                    stage_span(
                        self._oldest_tp, "subs.deliver", "deliver", delta,
                        forced=meta_forced(self._oldest_meta),
                    )
            self._oldest_wall = None
            self._oldest_origin = None
            self._oldest_tp = None
            self._oldest_meta = None
            return True
        # clogged: shed once past the lag bounds
        data_batches = sum(1 for p, _ in self.pending if p is not None)
        if (
            self.pending_bytes > self.max_lag_bytes
            or data_batches > self.max_lag_batches
        ):
            self.shed()
            return True
        return False

    def shed(self) -> bool:
        """Drop this sink NOW with the typed `SubLagging` terminal the
        r16 client resume path already handles.  Two callers: `flush`
        when the lag bounds trip, and the r22 slo-burn remediation
        actuator (agent/remediation.py) shedding the laggard tier
        before clients time out — same typed degradation either way,
        never a stall.  Returns False when the sink already ended."""
        if self.closed or self.done.done():
            return False
        METRICS.counter("corro.subs.shed.total").inc()
        shed = SubLagging(
            self.pending_bytes,
            sum(1 for p, _ in self.pending if p is not None),
        )
        self.pending.clear()
        self.pending_bytes = 0
        self._resolve(shed)
        return True

    def _note_stats(self, wrote: int, shipped: int) -> None:
        w = self.writer
        if w is not None and (wrote or shipped):
            w._stat_writes += wrote
            w._stat_batches += shipped


class FanoutWriter:
    """The per-manager shared writer task.  `submit` is O(1) for the
    fan-out caller; the walk, coalescing, clog retries and shedding all
    happen here, off the diff loop and off the write path."""

    def __init__(self, tick_secs: float = 0.05):
        self.tick_secs = tick_secs
        self._queue: Deque[Tuple[Tuple[StreamSink, ...], object]] = deque()
        self._clogged: "dict[int, StreamSink]" = {}
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        # flush stats, accumulated by sinks and registry-flushed once
        # per writer round (never one registry hit per sink visit)
        self._stat_writes = 0
        self._stat_batches = 0

    # -- feeding (loop thread) ---------------------------------------------

    def submit(self, sinks: Tuple[StreamSink, ...], item) -> None:
        """One diff batch (or terminal sentinel) for `sinks`."""
        if not sinks:
            return
        self._queue.append((sinks, item))
        self._wake()

    def poke(self, sink: StreamSink) -> None:
        """Re-arm delivery for one sink (post-release catch-up)."""
        self._clogged[id(sink)] = sink
        self._wake()

    def _wake(self) -> None:
        self._event.set()
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def clogged_count(self) -> int:
        return len(self._clogged)

    def shed_clogged(self) -> int:
        """Shed the CURRENT laggard tier (every clogged sink) with the
        typed `SubLagging` terminal; returns how many went.  The r22
        slo-burn actuator's lever: laggards are exactly the sinks whose
        sockets stopped draining, the ones soon to trip the lag bounds
        anyway — shedding them early frees writer rounds for the
        healthy tier before clients time out."""
        n = 0
        for key, sink in list(self._clogged.items()):
            if sink.shed():
                n += 1
            self._clogged.pop(key, None)
        return n

    # -- the writer task ---------------------------------------------------

    async def _run(self) -> None:
        clog_gauge = METRICS.gauge("corro.subs.writer.clogged")
        writes_total = METRICS.counter("corro.subs.writer.writes.total")
        batches_total = METRICS.counter(
            "corro.subs.writer.coalesced.batches.total"
        )
        round_secs = METRICS.histogram("corro.subs.writer.round.seconds")
        while True:
            if not self._queue and not self._clogged:
                self._event.clear()
                await self._event.wait()
            elif not self._queue:
                # clogged sinks wait on window credit / buffer drain:
                # bounded retry tick (laggards tolerate latency by
                # definition — healthy sinks never pass through here)
                self._event.clear()
                try:
                    await asyncio.wait_for(
                        self._event.wait(), self.tick_secs
                    )
                except asyncio.TimeoutError:
                    pass
            visited = 0
            t0 = time.monotonic()
            while self._queue:
                sinks, item = self._queue.popleft()
                n = len(sinks)
                stride = max(1, n // _OBSERVE_SAMPLE)
                for i, sink in enumerate(sinks):
                    sink.writer = self
                    sink.offer(item)
                    if not sink.hold and not sink.flush(
                        observe=(i % stride == 0)
                    ):
                        self._clogged[id(sink)] = sink
                    else:
                        self._clogged.pop(id(sink), None)
                    visited += 1
                    if visited % _YIELD_EVERY == 0:
                        await asyncio.sleep(0)
            for key, sink in list(self._clogged.items()):
                sink.writer = self
                if sink.flush():
                    self._clogged.pop(key, None)
                visited += 1
                if visited % _YIELD_EVERY == 0:
                    await asyncio.sleep(0)
            if visited:
                # the fan-out walk's own cost — what the SUBS_SCALE
                # per-event matcher+encode+write number is built from
                round_secs.observe(time.monotonic() - t0)
            if self._stat_writes:
                writes_total.inc(self._stat_writes)
                self._stat_writes = 0
            if self._stat_batches:
                batches_total.inc(self._stat_batches)
                self._stat_batches = 0
            clog_gauge.set(len(self._clogged))

"""UpdatesManager: per-table raw change notifications.

Counterpart of `klukai-types/src/updates.rs` (`UpdatesManager`,
`UpdateHandle`, `match_changes` :424): clients subscribe to a *table*
(not a query) and receive NotifyEvents classifying each changed row as
insert/update/delete from its causal length (even = deleted, odd =
alive; updates.rs:294-297). Events are batched for 600 ms
(updates.rs:311-422) and a per-pk cl cache guards against out-of-order
delete/update races (updates.rs:329).
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.pack import unpack_columns

BATCH_WAIT = 0.6  # 600 ms flush interval (updates.rs:311)
CL_CACHE_MAX = 65536  # bound the per-pk causal-length cache


def _merge(cur: Optional[Tuple[str, int]], kind: str, cl: int) -> Tuple[str, int]:
    """Later causal length wins; at equal cl a delete beats an update
    (a delete and an update of the same epoch can share a batch)."""
    if cur is None or cl > cur[1] or (cl == cur[1] and kind == "delete"):
        return (kind, cl)
    return cur


class UpdateHandle:
    """One watched table: classification, batching, subscriber fan-out."""

    def __init__(self, table: str, loop: asyncio.AbstractEventLoop):
        self.table = table
        self.loop = loop
        self._queue: asyncio.Queue = asyncio.Queue()
        self._subscribers: List[asyncio.Queue] = []
        self._sub_lock = threading.Lock()
        self._task: Optional[asyncio.Task] = None
        self._done = asyncio.Event()
        # pk -> last seen causal length (the cl cache, updates.rs:329);
        # LRU-bounded, guarded by a lock: hooks fire from worker threads
        # (gossip ingestion) and the loop thread (local writes) at once
        self._cl_cache: "OrderedDict[bytes, int]" = OrderedDict()
        self._cl_lock = threading.Lock()
        self.error: Optional[str] = None

    def start(self) -> None:
        self._task = self.loop.create_task(self._run())

    def match_changes(self, changes: Sequence[Change]) -> None:
        """Thread-safe: classify + enqueue rows touched in this batch."""
        rows: Dict[bytes, Tuple[str, int]] = {}
        with self._cl_lock:
            for ch in changes:
                if ch.table != self.table:
                    continue
                prev = self._cl_cache.get(ch.pk, 0)
                if ch.cl < prev:
                    continue  # stale out-of-order change
                if ch.cl % 2 == 0:
                    kind = "delete"
                elif ch.cl > prev:
                    kind = "insert"  # row (re)created in this causal epoch
                else:
                    kind = "update"
                self._cl_cache[ch.pk] = ch.cl
                self._cl_cache.move_to_end(ch.pk)
                rows[ch.pk] = _merge(rows.get(ch.pk), kind, ch.cl)
            while len(self._cl_cache) > CL_CACHE_MAX:
                self._cl_cache.popitem(last=False)
        if rows:
            METRICS.counter("corro.updates.matched.count", table=self.table).inc(len(rows))
            self.loop.call_soon_threadsafe(self._queue.put_nowait, rows)

    async def _run(self) -> None:
        """Flush batches every 600 ms (updates.rs:311-422)."""
        try:
            await self._run_inner()
        except Exception as e:  # flush task died: mark dead, don't zombie
            self.error = str(e)
            METRICS.counter(
                "corro.updates.errors.count", table=self.table
            ).inc()

    async def _run_inner(self) -> None:
        try:
            while True:
                first = await self._queue.get()
                if first is None:
                    break
                batch: Dict[bytes, Tuple[str, int]] = dict(first)
                deadline = self.loop.time() + BATCH_WAIT
                while True:
                    timeout = deadline - self.loop.time()
                    if timeout <= 0:
                        break
                    try:
                        more = await asyncio.wait_for(self._queue.get(), timeout)
                    except asyncio.TimeoutError:
                        break
                    if more is None:
                        self._queue.put_nowait(None)
                        break
                    for pk, v in more.items():
                        batch[pk] = _merge(batch.get(pk), v[0], v[1])
                events = [
                    (kind, list(unpack_columns(pk)))
                    for pk, (kind, _cl) in batch.items()
                ]
                with self._sub_lock:
                    subs = list(self._subscribers)
                for q in subs:
                    for ev in events:
                        q.put_nowait(ev)
        finally:
            # release attached HTTP streams: None = end-of-stream sentinel
            with self._sub_lock:
                subs = list(self._subscribers)
            for q in subs:
                q.put_nowait(None)
            self._done.set()

    def attach(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        with self._sub_lock:
            self._subscribers.append(q)
        return q

    def detach(self, q: asyncio.Queue) -> None:
        with self._sub_lock:
            with contextlib.suppress(ValueError):
                self._subscribers.remove(q)

    @property
    def subscriber_count(self) -> int:
        with self._sub_lock:
            return len(self._subscribers)

    async def stop(self) -> None:
        self._queue.put_nowait(None)
        if self._task is not None:
            await self._done.wait()
            self._task = None


class UpdatesManager:
    """Registry of per-table update handles (updates.rs:29-61)."""

    def __init__(self, store):
        self.store = store
        self._by_table: Dict[str, UpdateHandle] = {}
        self._lock = asyncio.Lock()

    async def get_or_insert(self, table: str) -> Tuple[UpdateHandle, bool]:
        if table not in self.store.schema.tables:
            raise KeyError(f"unknown table: {table}")
        async with self._lock:
            h = self._by_table.get(table)
            if h is not None and h.error is not None:
                # dead flush task: replace the zombie
                self._by_table.pop(table, None)
                h = None
            if h is not None:
                return h, False
            h = UpdateHandle(table, asyncio.get_running_loop())
            h.start()
            self._by_table[table] = h
            METRICS.gauge("corro.updates.count").set(len(self._by_table))
            return h, True

    def handles(self) -> List[UpdateHandle]:
        return list(self._by_table.values())

    def match_changes(self, changes: Sequence[Change], stamp=None) -> None:
        # `stamp` (the batch latency stamp the change hooks pass) is
        # unused here: NotifyEvents carry no per-event payload to bill
        for h in list(self._by_table.values()):
            if h.error is None:  # dead handles drain nothing; skip
                h.match_changes(changes)

    async def stop_all(self) -> None:
        for t in list(self._by_table):
            h = self._by_table.pop(t)
            await h.stop()

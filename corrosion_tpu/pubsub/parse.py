"""SELECT analysis for the live-query matcher.

Counterpart of the AST walk in `klukai-types/src/pubsub.rs:1735-2050`
(`extract_select_columns`): the reference parses the subscription SELECT
with sqlite3-parser and collects, per source table, the referenced
columns and aliases, so committed changes can be filtered down to the
subscriptions they might affect, and so the query can be rewritten with
pk alias columns + a pk-membership predicate per driving table
(`pubsub.rs:616-658,2123`).

We do the same with a small tokenizer instead of a full AST: split the
statement into top-level clauses (SELECT list, FROM, WHERE, tail),
resolve table references + aliases in FROM/JOIN, and attribute column
identifiers to tables (qualified `alias.col` exactly; bare identifiers
to whichever source table has the column). Anything unresolvable makes
the dependency set conservative (all columns), never unsound.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from corrosion_tpu.store.schema import Schema


class ParseError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"""
      (?P<ws>\s+)
    | (?P<comment>--[^\n]*|/\*.*?\*/)
    | (?P<str>'(?:[^']|'')*')
    | (?P<qid>"(?:[^"]|"")*"|\[[^\]]*\]|`(?:[^`]|``)*`)
    | (?P<num>\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+)
    | (?P<id>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<param>[?][0-9]*|[:@$][A-Za-z0-9_]+)
    | (?P<op><=|>=|<>|!=|==|\|\||[-+*/%<>=(),.;])
    """,
    re.VERBOSE | re.DOTALL,
)


@dataclass(frozen=True)
class Token:
    kind: str
    text: str

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise ParseError(f"cannot tokenize SQL at offset {pos}: {sql[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup or "op"
        if kind in ("ws", "comment"):
            continue
        out.append(Token(kind, m.group()))
    return out


def _unquote(tok: Token) -> str:
    t = tok.text
    if tok.kind == "qid":
        if t.startswith('"'):
            return t[1:-1].replace('""', '"')
        if t.startswith("["):
            return t[1:-1]
        if t.startswith("`"):
            return t[1:-1].replace("``", "`")
    return t


# clauses that end the FROM clause at depth 0
_FROM_ENDERS = {"WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "WINDOW"}
_JOIN_WORDS = {"JOIN", "LEFT", "RIGHT", "FULL", "INNER", "OUTER", "CROSS", "NATURAL"}
_RESERVED = _FROM_ENDERS | _JOIN_WORDS | {
    "SELECT", "FROM", "AS", "ON", "USING", "AND", "OR", "NOT", "IN", "IS",
    "NULL", "LIKE", "GLOB", "BETWEEN", "CASE", "WHEN", "THEN", "ELSE", "END",
    "DISTINCT", "ALL", "BY", "ASC", "DESC", "COLLATE", "EXISTS", "CAST",
    "UNION", "INTERSECT", "EXCEPT", "VALUES", "WITH", "INDEXED",
}


@dataclass
class TableRef:
    name: str  # schema table name
    alias: str  # alias (or name when unaliased) as written
    left_joined: bool = False


@dataclass
class ParsedSelect:
    sql: str
    select_list: str  # text between SELECT and FROM (incl. DISTINCT)
    from_clause: str  # text after FROM up to WHERE/GROUP/...
    where_clause: Optional[str]  # text after WHERE (excl.) up to tail
    tail: str  # GROUP BY/HAVING/ORDER BY/LIMIT ... ("" if none)
    tables: List[TableRef] = field(default_factory=list)
    # table name -> referenced column names (non-pk); pks tracked separately
    col_deps: Dict[str, Set[str]] = field(default_factory=dict)

    def table_names(self) -> List[str]:
        return [t.name for t in self.tables]

    def routing_keys(self):
        """(table, cid) pairs the SubsManager's inverted change-routing
        index files this query under — one per referenced column, plus
        the sentinel per table (row create/delete reaches every query on
        the table regardless of projected columns).  This is
        `Matcher.filter_candidates`'s match predicate, factored to the
        parse layer so the router and the filter cannot drift."""
        from corrosion_tpu.types.change import SENTINEL

        for table, deps in self.col_deps.items():
            yield table, SENTINEL
            for cid in deps:
                yield table, cid


def _split_clauses(tokens: List[Token], sql: str) -> Tuple[str, str, Optional[str], str]:
    """Split a SELECT into (select_list, from, where, tail) at paren depth 0."""
    if not tokens or tokens[0].upper not in ("SELECT", "WITH"):
        raise ParseError("subscription statement must be a SELECT")
    if tokens[0].upper == "WITH":
        raise ParseError("WITH/CTE subscriptions are not supported")

    depth = 0
    idx_from = idx_where = idx_tail = None
    for i, tok in enumerate(tokens):
        if tok.text == "(":
            depth += 1
        elif tok.text == ")":
            depth -= 1
        elif depth == 0 and tok.kind == "id":
            u = tok.upper
            if u == "FROM" and idx_from is None:
                idx_from = i
            elif u == "WHERE" and idx_from is not None and idx_where is None:
                idx_where = i
            elif (
                u in ("GROUP", "HAVING", "ORDER", "LIMIT", "WINDOW")
                and idx_from is not None
                and idx_tail is None
            ):
                idx_tail = i
            elif u in ("UNION", "INTERSECT", "EXCEPT") and idx_from is not None:
                raise ParseError("compound (UNION/...) subscriptions are not supported")
    if idx_from is None:
        raise ParseError("subscription SELECT must have a FROM clause")

    def text(a: int, b: Optional[int]) -> str:
        return _join_tokens(tokens[a : b if b is not None else len(tokens)])

    sel = text(1, idx_from)
    from_end = idx_where if idx_where is not None else idx_tail
    frm = text(idx_from + 1, from_end)
    where = None
    if idx_where is not None:
        where = text(idx_where + 1, idx_tail)
    tail = text(idx_tail, None) if idx_tail is not None else ""
    # only ORDER BY survives as a tail (it shapes the initial fill); the
    # incremental diff model cannot honor aggregation or row limits
    tail_head = tokens[idx_tail].upper if idx_tail is not None else ""
    if tail_head in ("GROUP", "HAVING", "LIMIT", "WINDOW"):
        raise ParseError(
            f"{tail_head} is not supported in subscriptions"
        )
    if tail and "LIMIT" in tail.upper().split():
        raise ParseError("LIMIT is not supported in subscriptions")
    return sel, frm, where, tail


def _join_tokens(tokens: List[Token]) -> str:
    parts: List[str] = []
    prev: Optional[Token] = None
    for tok in tokens:
        if prev is not None:
            if tok.text in (",", ")", ".", ";") or prev.text in ("(", "."):
                pass
            else:
                parts.append(" ")
        parts.append(tok.text)
        prev = tok
    return "".join(parts).strip().rstrip(";").strip()


def _parse_from(from_clause: str, schema: Schema) -> List[TableRef]:
    """Resolve table refs + aliases in the FROM/JOIN clause."""
    tokens = tokenize(from_clause)
    refs: List[TableRef] = []
    i = 0
    depth = 0
    expect_table = True
    pending_left = False
    while i < len(tokens):
        tok = tokens[i]
        if tok.text == "(":
            if expect_table and depth == 0:
                raise ParseError("subquery in FROM is not supported for subscriptions")
            depth += 1
            i += 1
            continue
        if tok.text == ")":
            depth -= 1
            i += 1
            continue
        if depth > 0:
            i += 1
            continue
        u = tok.upper if tok.kind == "id" else None
        if u in _JOIN_WORDS:
            if u == "LEFT":
                pending_left = True
            if u == "JOIN":
                expect_table = True
            i += 1
            continue
        if u in ("ON", "USING"):
            expect_table = False
            i += 1
            continue
        if tok.text == ",":
            expect_table = True
            i += 1
            continue
        if expect_table and tok.kind in ("id", "qid") and (u is None or u not in _RESERVED):
            name = _unquote(tok)
            if name not in schema.tables:
                raise ParseError(f"unknown table in subscription: {name}")
            alias = name
            j = i + 1
            if j < len(tokens) and tokens[j].kind == "id" and tokens[j].upper == "AS":
                j += 1
            if (
                j < len(tokens)
                and tokens[j].kind in ("id", "qid")
                and tokens[j].upper not in _RESERVED
            ):
                alias = _unquote(tokens[j])
                i = j
            refs.append(TableRef(name=name, alias=alias, left_joined=pending_left))
            pending_left = False
            expect_table = False
            i += 1
            continue
        i += 1
    if not refs:
        raise ParseError("no tables found in FROM clause")
    return refs


def _collect_col_deps(
    tokens: List[Token], refs: List[TableRef], schema: Schema
) -> Dict[str, Set[str]]:
    """Attribute column identifiers to source tables.

    Qualified `alias.col` goes to the aliased table; bare identifiers go
    to every source table that has such a column (conservative). A bare
    `*` marks every column of every table as referenced
    (pubsub.rs:1834-1860 equivalent behavior).
    """
    by_alias = {r.alias: r.name for r in refs}
    deps: Dict[str, Set[str]] = {r.name: set() for r in refs}

    def mark_all() -> None:
        for r in refs:
            deps[r.name].update(schema.table(r.name).columns)

    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok.text == "*" and (i == 0 or tokens[i - 1].text != "."):
            def is_operand(t: Token, opening: str) -> bool:
                if t.kind == "id" and t.upper in _RESERVED:
                    return False
                return t.kind in ("num", "str", "id", "qid", "param") or t.text == opening

            prev_is_operand = i > 0 and is_operand(tokens[i - 1], ")")
            next_is_operand = i + 1 < len(tokens) and is_operand(tokens[i + 1], "(")
            if not (prev_is_operand and next_is_operand):  # projection *, not multiply
                mark_all()
            i += 1
            continue
        if tok.kind in ("id", "qid") and tok.upper not in _RESERVED:
            name = _unquote(tok)
            # qualified: alias . col  /  alias . *
            if i + 2 < len(tokens) and tokens[i + 1].text == ".":
                col_tok = tokens[i + 2]
                tbl = by_alias.get(name)
                if tbl is not None:
                    if col_tok.text == "*":
                        deps[tbl].update(schema.table(tbl).columns)
                    elif col_tok.kind in ("id", "qid"):
                        deps[tbl].add(_unquote(col_tok))
                i += 3
                continue
            # function call name?
            if i + 1 < len(tokens) and tokens[i + 1].text == "(":
                i += 1
                continue
            if name in by_alias:
                i += 1
                continue
            # bare column
            for r in refs:
                cols = set(schema.table(r.name).columns)
                if name in cols:
                    deps[r.name].add(name)
            i += 1
            continue
        i += 1
    return deps


def parse_select(sql: str, schema: Schema) -> ParsedSelect:
    tokens = tokenize(sql)
    sel, frm, where, tail = _split_clauses(tokens, sql)
    refs = _parse_from(frm, schema)
    seen: Dict[str, int] = {}
    for r in refs:
        seen[r.name] = seen.get(r.name, 0) + 1
        if seen[r.name] > 1 and r.alias == r.name:
            raise ParseError(f"self-join of {r.name} requires aliases")
    deps = _collect_col_deps(tokens, refs, schema)
    # pk columns always matter: row create/delete reaches every query on
    # the table regardless of projected columns (updates.rs:424-488)
    for r in refs:
        deps[r.name].update(schema.table(r.name).pk_cols)
    return ParsedSelect(
        sql=sql,
        select_list=sel,
        from_clause=frm,
        where_clause=where,
        tail=tail,
        tables=refs,
        col_deps=deps,
    )

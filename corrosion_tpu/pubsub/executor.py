"""Shared bounded executor for live-query diff work.

Through r9 every `MatcherHandle` ran `handle_candidates` via
`asyncio.to_thread`, i.e. the event loop's DEFAULT ThreadPoolExecutor
(min(32, cpus+4) workers shared with file I/O, DNS, and every other
to_thread in the process).  Under many live subscriptions a write burst
makes every matcher submit at once: the default pool both spawns far
more diff threads than sqlite can use (GIL + one write lock per sub db)
and lets pubsub starve unrelated to_thread users.  The reference keeps
matcher work on a dedicated runtime (`MatcherHandle::cmd_loop` tasks on
tokio's blocking pool, pubsub.rs:1029).

`DiffExecutor` is one small dedicated pool per `SubsManager`: diffs
queue here, concurrency is capped, and the queue depth / wait time are
observable (`corro.subs.executor.*`) so sub-count overload shows up as
a rising gauge instead of an invisible thread pile-up.
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from corrosion_tpu.runtime.metrics import METRICS

# diff work is sqlite-C-heavy (GIL released inside the library) but one
# sub db admits one writer: a few workers overlap distinct matchers'
# diffs without minting a thread per subscription
DEFAULT_DIFF_WORKERS = 4


class DiffExecutor:
    """Lazily-started bounded ThreadPoolExecutor with depth telemetry.

    `depth` counts submitted-but-unfinished jobs (queued + running);
    anything above `max_workers` is backpressure — matchers waiting for
    a worker while their candidate queues keep batching (the batching
    keeps per-event cost amortized, so a deep queue degrades latency,
    not correctness)."""

    def __init__(self, max_workers: int = DEFAULT_DIFF_WORKERS):
        # sized by [subs] diff_workers since r16 (SubsManager passes it)
        self.max_workers = max_workers
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        self._depth = 0
        # instrument handles resolved once: at 10k-100k streams the
        # per-diff registry lookups (name+label hashing) were three
        # avoidable dict probes per submission on the event loop
        self._g_depth = METRICS.gauge("corro.subs.executor.depth")
        self._c_submitted = METRICS.counter(
            "corro.subs.executor.submitted.total"
        )
        self._h_wait = METRICS.histogram("corro.subs.executor.wait.seconds")

    def _ensure(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.max_workers,
                    thread_name_prefix="corro-subs-diff",
                )
            return self._pool

    @property
    def depth(self) -> int:
        return self._depth

    async def run(self, fn, *args):
        """Run `fn(*args)` on the shared pool; awaits the result."""
        loop = asyncio.get_running_loop()
        pool = self._ensure()
        submitted = time.monotonic()
        with self._lock:
            self._depth += 1
            depth = self._depth
        self._g_depth.set(depth)
        self._c_submitted.inc()

        def job():
            # time spent queued behind other matchers' diffs — the
            # backpressure signal a sub-count overload raises first
            self._h_wait.observe(time.monotonic() - submitted)
            return fn(*args)

        try:
            return await loop.run_in_executor(pool, job)
        finally:
            with self._lock:
                self._depth -= 1
                depth = self._depth
            self._g_depth.set(depth)

    def shutdown(self) -> None:
        """Stop the pool (running jobs finish; a later `run` restarts)."""
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

"""SubsManager: dedupe, lifecycle, restore and change ROUTING of
live-query matchers.

Counterpart of `SubsManager` in `klukai-types/src/pubsub.rs:54-256`:
subscriptions are deduped by SQL text hash (`:565`), `get_or_insert`
(`:115`) returns an existing matcher when one already runs the same
query, and `restore` (`:164`) re-attaches matchers persisted under
`<subs_path>/<uuid>/sub.sqlite` on agent start
(`klukai-agent/src/agent/setup.rs:296-349`).

Routing (r10): the change hook used to call every matcher's
`filter_candidates` for every committed batch — O(subs × changes)
Python work under the GIL, on the WRITE path.  The manager now keeps an
inverted index `table → {cid | sentinel} → (handles…)` rebuilt on
(un)subscribe, so `match_changes` does one dict hop per change and
feeds each hit matcher a pre-filtered candidate pk set directly:
O(changes + hits), subscription count out of the write path.  A change
routes to a matcher iff the matcher's parsed column deps contain its
(table, cid) — or it is a sentinel (row create/delete), which reaches
every matcher on the table — exactly `Matcher.filter_candidates`'s
predicate, amortized across matchers.
"""

from __future__ import annotations

import asyncio
import contextlib
import shutil
import sqlite3
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from corrosion_tpu.pubsub.executor import DiffExecutor
from corrosion_tpu.pubsub.matcher import (
    Matcher,
    MatcherError,
    MatcherHandle,
    sql_hash,
)
from corrosion_tpu.pubsub.parse import ParseError, parse_select
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import SENTINEL, Change

# table -> cid (or SENTINEL) -> handles whose queries the change affects
Router = Dict[str, Dict[str, Tuple[MatcherHandle, ...]]]


class SubsManager:
    """Registry of running matchers, keyed by id and by SQL hash."""

    def __init__(
        self,
        store,
        subs_path: Optional[str] = None,
        batch_wait: Optional[float] = None,
    ):
        self.store = store
        self.subs_path = subs_path
        # matcher candidate-batching window ([pubsub] candidate_batch_wait,
        # r12); None keeps the per-matcher pubsub.rs-parity default
        self.batch_wait = batch_wait
        self._by_id: Dict[str, MatcherHandle] = {}
        self._by_hash: Dict[str, str] = {}  # sql hash -> id
        self._lock = asyncio.Lock()
        # immutable snapshot, swapped whole on (un)subscribe: worker
        # threads read it lock-free mid-rebuild and see old or new,
        # never a half-built index
        self._router: Router = {}
        self.executor = DiffExecutor()

    def _rebuild_router(self) -> None:
        idx: Dict[str, Dict[str, Set[MatcherHandle]]] = {}
        for handle in self._by_id.values():
            # routing_keys = filter_candidates's predicate, factored to
            # the parse layer (sentinel per table + every dep column)
            for table, cid in handle.matcher.parsed.routing_keys():
                idx.setdefault(table, {}).setdefault(cid, set()).add(
                    handle
                )
        self._router = {
            table: {cid: tuple(hs) for cid, hs in by_cid.items()}
            for table, by_cid in idx.items()
        }
        METRICS.gauge("corro.subs.router.tables").set(len(self._router))

    def get(self, sub_id: str) -> Optional[MatcherHandle]:
        return self._by_id.get(sub_id)

    def get_by_sql(self, sql: str) -> Optional[MatcherHandle]:
        sid = self._by_hash.get(sql_hash(sql))
        return self._by_id.get(sid) if sid else None

    def handles(self) -> List[MatcherHandle]:
        return list(self._by_id.values())

    async def get_or_insert(self, sql: str) -> Tuple[MatcherHandle, bool]:
        """Return (handle, created). When created, the initial query has
        materialized into the sub db; subscribers read rows through
        `handle.matcher.snapshot()` (attach-then-snapshot protocol)."""
        async with self._lock:
            existing = self.get_by_sql(sql)
            if existing is not None:
                if existing.error is None:
                    return existing, False
                # dead matcher: tear it down fully before replacing
                await self._remove_locked(existing.id, purge=True)
            parsed = parse_select(sql, self.store.schema)
            sub_id = str(uuid.uuid4())
            matcher = Matcher(self.store, parsed, sub_id, sql, self.subs_path)
            loop = asyncio.get_running_loop()

            def build():
                matcher.create_sub_db()
                return matcher.run_initial()

            try:
                await asyncio.to_thread(build)
            except (sqlite3.Error, MatcherError) as e:
                matcher.close()
                await asyncio.to_thread(self._purge_dir, sub_id)
                raise ParseError(str(e)) from e
            handle = MatcherHandle(
                matcher, loop, executor=self.executor,
                batch_wait=self.batch_wait,
            )
            handle.start()
            self._by_id[sub_id] = handle
            self._by_hash[sql_hash(sql)] = sub_id
            self._rebuild_router()
            METRICS.gauge("corro.subs.count").set(len(self._by_id))
            return handle, True

    async def restore(self) -> int:
        """Re-attach matchers persisted on disk; purge incomplete ones.
        A restored matcher re-checks every pk of its source tables so
        changes applied while the agent was down surface as events (the
        reference catches up via `match_changes_from_db_version`)."""
        if self.subs_path is None:
            return 0
        root = Path(self.subs_path)
        if not root.exists():
            return 0
        n = 0
        for d in sorted(root.iterdir()):
            db = d / "sub.sqlite"
            if not d.is_dir() or not db.exists():
                continue
            try:
                sql = await asyncio.to_thread(self._read_meta_sql, db)
                parsed = parse_select(sql, self.store.schema)
                matcher = Matcher(self.store, parsed, d.name, sql, self.subs_path)
                await asyncio.to_thread(matcher.reattach)
            except (sqlite3.Error, MatcherError, ParseError, KeyError):
                # purge off-loop: an incomplete sub dir can hold a
                # multi-MB sub.sqlite and rmtree would stall the loop
                await asyncio.to_thread(
                    shutil.rmtree, d, ignore_errors=True
                )
                continue
            handle = MatcherHandle(
                matcher, asyncio.get_running_loop(), executor=self.executor,
                batch_wait=self.batch_wait,
            )
            handle.start()
            self._by_id[d.name] = handle
            self._by_hash[sql_hash(sql)] = d.name
            await asyncio.to_thread(self._resync, handle)
            n += 1
        self._rebuild_router()
        METRICS.gauge("corro.subs.count").set(len(self._by_id))
        return n

    def _resync(self, handle: MatcherHandle) -> None:
        """Enqueue a full pk sweep of every source table as candidates:
        live pks ∪ materialized pks, so rows inserted OR deleted while
        the agent was down both get re-checked (the reference catches up
        via match_changes_from_db_version, updates.rs:490)."""
        from corrosion_tpu.types.pack import pack_columns

        with self.store.pooled_read() as conn:
            for t in handle.matcher.parsed.tables:
                pks = self.store.schema.table(t.name).pk_cols
                sel = ", ".join(f'"{c}"' for c in pks)
                rows = conn.execute(f'SELECT {sel} FROM "{t.name}"').fetchall()
                cands = {pack_columns(tuple(r)) for r in rows}
                cands.update(handle.matcher.materialized_pks(t.name))
                if cands:
                    handle.loop.call_soon_threadsafe(
                        handle._queue.put_nowait, ({t.name: cands}, None)
                    )

    def _read_meta_sql(self, db: Path) -> str:
        conn = sqlite3.connect(db)
        try:
            row = conn.execute(
                "SELECT v FROM meta WHERE k = 'sql'"
            ).fetchone()
            if row is None:
                raise KeyError("no sql in sub meta")
            return row[0]
        finally:
            conn.close()

    # -- feeding -----------------------------------------------------------

    def match_changes(self, changes: Sequence[Change], stamp=None) -> None:
        """Change hook: route committed changes through the inverted
        index (updates.rs:424-488). Thread-safe. One dict hop per
        change, candidate pk sets accumulated per hit matcher —
        `filter_candidates` never runs here, and matchers whose
        (table, cid) index misses do no work at all. Dead matchers are
        skipped (their queue has no consumer) and torn down from the
        loop.  `stamp` (runtime/latency.py BatchStamp) rides with the
        candidates so the matcher can attribute apply→event time."""
        router = self._router
        if not router:
            return
        per: Dict[MatcherHandle, Dict[str, Set[bytes]]] = {}
        matched = 0
        fanout = 0
        for ch in changes:
            by_cid = router.get(ch.table)
            if by_cid is None:
                continue
            handles = by_cid.get(
                SENTINEL if ch.is_sentinel() else ch.cid
            )
            if not handles:
                continue
            matched += 1
            fanout += len(handles)
            for h in handles:
                per.setdefault(h, {}).setdefault(
                    ch.table, set()
                ).add(ch.pk)
        METRICS.counter("corro.subs.router.changes.total").inc(len(changes))
        if matched:
            METRICS.counter("corro.subs.router.matched.total").inc(matched)
            METRICS.counter("corro.subs.router.fanout.total").inc(fanout)
        for handle, cands in per.items():
            if handle.error is not None:
                handle.loop.call_soon_threadsafe(
                    self._schedule_removal, handle.id
                )
                continue
            handle.enqueue_candidates(cands, stamp)

    def _schedule_removal(self, sub_id: str) -> None:
        asyncio.ensure_future(self.remove(sub_id, purge=True))

    # -- teardown ----------------------------------------------------------

    async def remove(self, sub_id: str, purge: bool = False) -> None:
        async with self._lock:
            await self._remove_locked(sub_id, purge)

    async def _remove_locked(self, sub_id: str, purge: bool = False) -> None:
        handle = self._by_id.pop(sub_id, None)
        if handle is None:
            return
        self._by_hash.pop(sql_hash(handle.sql), None)
        self._rebuild_router()
        await handle.stop()
        if purge:
            await asyncio.to_thread(self._purge_dir, sub_id)
        METRICS.gauge("corro.subs.count").set(len(self._by_id))

    def _purge_dir(self, sub_id: str) -> None:
        if self.subs_path is not None:
            shutil.rmtree(Path(self.subs_path) / sub_id, ignore_errors=True)

    async def stop_all(self) -> None:
        for sid in list(self._by_id):
            await self.remove(sid)
        self.executor.shutdown()

"""SubsManager: dedupe, lifecycle, restore and change ROUTING of
live-query matchers.

Counterpart of `SubsManager` in `klukai-types/src/pubsub.rs:54-256`:
subscriptions are deduped by SQL text hash (`:565`), `get_or_insert`
(`:115`) returns an existing matcher when one already runs the same
query, and `restore` (`:164`) re-attaches matchers persisted under
`<subs_path>/<uuid>/sub.sqlite` on agent start
(`klukai-agent/src/agent/setup.rs:296-349`).

Routing (r10): the change hook used to call every matcher's
`filter_candidates` for every committed batch — O(subs × changes)
Python work under the GIL, on the WRITE path.  The manager now keeps an
inverted index `table → {cid | sentinel} → (handles…)` rebuilt on
(un)subscribe, so `match_changes` does one dict hop per change and
feeds each hit matcher a pre-filtered candidate pk set directly:
O(changes + hits), subscription count out of the write path.  A change
routes to a matcher iff the matcher's parsed column deps contain its
(table, cid) — or it is a sentinel (row create/delete), which reaches
every matcher on the table — exactly `Matcher.filter_candidates`'s
predicate, amortized across matchers.

Serving-plane lifecycle (r16): matchers are REFCOUNTED.  Subscribing
streams dedupe onto one matcher per distinct query — keyed by the exact
SQL hash (the wire-parity `corro-query-hash`) AND by a canonical
token-normalized form, so whitespace/comment variants of the same query
share a matcher — and the last stream's detach arms a linger timer
(`[subs] matcher_linger_secs`); a reconnect inside the window re-uses
the warm matcher + changes log, after it the matcher and its sub db are
reaped.  `admission_reject` bounds total live streams per node
(`[subs] max_streams`), and `fanout` is the shared coalescing writer
every HTTP stream sink is served by (pubsub/fanout.py).
"""

from __future__ import annotations

import asyncio
import contextlib
import shutil
import sqlite3
import time
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from corrosion_tpu.pubsub.executor import DiffExecutor
from corrosion_tpu.pubsub.fanout import FanoutWriter
from corrosion_tpu.pubsub.matcher import (
    Matcher,
    MatcherError,
    MatcherHandle,
    sql_hash,
)
from corrosion_tpu.pubsub.parse import ParseError, parse_select
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import SENTINEL, Change

# table -> cid (or SENTINEL) -> handles whose queries the change affects
Router = Dict[str, Dict[str, Tuple[MatcherHandle, ...]]]


def canonical_sql(sql: str) -> str:
    """Token-normalized query text: whitespace and comments collapse so
    textual variants of one query hash alike.  Keywords keep their case
    (identifier semantics stay untouched); unparseable text falls back
    to a stripped literal (it will fail parse_select downstream with
    its own error)."""
    from corrosion_tpu.pubsub.parse import _join_tokens, tokenize

    try:
        return _join_tokens(tokenize(sql))
    except ParseError:
        return sql.strip()


class SubsManager:
    """Registry of running matchers, keyed by id and by SQL hash."""

    def __init__(
        self,
        store,
        subs_path: Optional[str] = None,
        batch_wait: Optional[float] = None,
        cfg=None,
    ):
        from corrosion_tpu.runtime.config import SubsConfig

        self.store = store
        self.subs_path = subs_path
        # matcher candidate-batching window ([pubsub] candidate_batch_wait,
        # r12); None keeps the per-matcher pubsub.rs-parity default
        self.batch_wait = batch_wait
        # [subs] serving-plane knobs (admission, lag bounds, linger)
        self.cfg = cfg if cfg is not None else SubsConfig()
        self._by_id: Dict[str, MatcherHandle] = {}
        self._by_hash: Dict[str, str] = {}  # exact sql hash -> id
        self._by_canon: Dict[str, str] = {}  # canonical sql hash -> id
        self._lock = asyncio.Lock()
        # immutable snapshot, swapped whole on (un)subscribe: worker
        # threads read it lock-free mid-rebuild and see old or new,
        # never a half-built index
        self._router: Router = {}
        self.executor = DiffExecutor(self.cfg.diff_workers)
        self.fanout = FanoutWriter(self.cfg.writer_tick_secs)
        # r22 refuse-bulk deadline (monotonic): while set in the future,
        # NEW streams get the typed admission 503 — the store-faults
        # remediation actuator (agent/remediation.py) arms it so a sick
        # node stops taking on serving work it will only shed; existing
        # reads and the matchers' own queries are untouched
        self.refuse_until: float = 0.0

    def _rebuild_router(self) -> None:
        idx: Dict[str, Dict[str, Set[MatcherHandle]]] = {}
        for handle in self._by_id.values():
            # routing_keys = filter_candidates's predicate, factored to
            # the parse layer (sentinel per table + every dep column)
            for table, cid in handle.matcher.parsed.routing_keys():
                idx.setdefault(table, {}).setdefault(cid, set()).add(
                    handle
                )
        self._router = {
            table: {cid: tuple(hs) for cid, hs in by_cid.items()}
            for table, by_cid in idx.items()
        }
        METRICS.gauge("corro.subs.router.tables").set(len(self._router))

    def get(self, sub_id: str) -> Optional[MatcherHandle]:
        return self._by_id.get(sub_id)

    def get_by_sql(self, sql: str) -> Optional[MatcherHandle]:
        sid = self._by_hash.get(sql_hash(sql))
        if sid is None:
            sid = self._by_canon.get(sql_hash(canonical_sql(sql)))
        return self._by_id.get(sid) if sid else None

    def handles(self) -> List[MatcherHandle]:
        return list(self._by_id.values())

    # -- serving-plane census / admission (r16) ----------------------------

    def stream_count(self) -> int:
        """Live streams across every matcher (HTTP sinks + in-process
        queue subscribers).  O(matchers) — matchers are the deduped
        axis, k distinct queries, not the 100k stream axis."""
        return sum(h.subscriber_count for h in self._by_id.values())

    def admission_reject(self) -> Optional[str]:
        """None = admit; otherwise the typed rejection reason.  Counted
        so a fleet hitting its admission ceiling is visible."""
        if self.refuse_until and time.monotonic() < self.refuse_until:
            METRICS.counter("corro.subs.admission.rejected.total").inc()
            return (
                "node refusing new streams"
                " (remediation refuse-bulk; store faulting)"
            )
        mx = self.cfg.max_streams
        if mx and self.stream_count() >= mx:
            METRICS.counter("corro.subs.admission.rejected.total").inc()
            return (
                f"stream limit reached ({mx} live streams;"
                " [subs] max_streams)"
            )
        return None

    def make_sink(self):
        """A base StreamSink bounded by this manager's lag config —
        HTTP flavors subclass in api/pubsub_http.py; tests attach these
        directly."""
        from corrosion_tpu.pubsub.fanout import StreamSink

        return StreamSink(self.cfg.max_lag_bytes, self.cfg.max_lag_batches)

    # -- refcounted matcher lifecycle (r16) --------------------------------

    def _note_active(self, handle: MatcherHandle) -> None:
        t = getattr(handle, "_linger_timer", None)
        if t is not None:
            t.cancel()
            handle._linger_timer = None
        METRICS.gauge("corro.subs.streams").set(self.stream_count())

    def _note_idle(self, handle: MatcherHandle) -> None:
        """Last ref detached: arm the linger reaper.  A reconnect (or a
        new subscriber deduping onto this matcher) inside the window
        cancels it and reuses the warm matcher + changes log."""
        self._note_active(handle)  # reset any armed timer first
        loop = asyncio.get_event_loop()
        handle._linger_timer = loop.call_later(
            max(0.0, self.cfg.matcher_linger_secs),
            lambda: asyncio.ensure_future(self._reap(handle)),
        )

    async def _reap(self, handle: MatcherHandle) -> None:
        async with self._lock:
            if (
                self._by_id.get(handle.id) is not handle
                or handle.active_refs > 0
            ):
                return
            await self._remove_locked(handle.id, purge=True)

    def _adopt(self, handle: MatcherHandle) -> None:
        handle.on_active = self._note_active
        handle.on_idle = self._note_idle

    async def get_or_insert(
        self, sql: str, lease: bool = False
    ) -> Tuple[MatcherHandle, bool]:
        """Return (handle, created). When created, the initial query has
        materialized into the sub db; subscribers read rows through
        `handle.matcher.snapshot()` (attach-then-snapshot protocol).
        `lease=True` pins the handle against the linger reaper until the
        caller attaches (release with `handle.release_lease()`)."""
        async with self._lock:
            existing = self.get_by_sql(sql)
            if existing is not None:
                if existing.error is None:
                    METRICS.counter("corro.subs.dedupe.hits.total").inc()
                    if lease:
                        existing.lease()
                    else:
                        self._note_active(existing)
                    return existing, False
                # dead matcher: tear it down fully before replacing
                await self._remove_locked(existing.id, purge=True)
            parsed = parse_select(sql, self.store.schema)
            sub_id = str(uuid.uuid4())
            matcher = Matcher(self.store, parsed, sub_id, sql, self.subs_path)
            loop = asyncio.get_running_loop()

            def build():
                matcher.create_sub_db()
                return matcher.run_initial()

            try:
                await asyncio.to_thread(build)
            except (sqlite3.Error, MatcherError) as e:
                matcher.close()
                await asyncio.to_thread(self._purge_dir, sub_id)
                raise ParseError(str(e)) from e
            handle = MatcherHandle(
                matcher, loop, executor=self.executor,
                batch_wait=self.batch_wait, fanout=self.fanout,
            )
            self._adopt(handle)
            handle.start()
            self._by_id[sub_id] = handle
            self._by_hash[sql_hash(sql)] = sub_id
            self._by_canon[sql_hash(canonical_sql(sql))] = sub_id
            self._rebuild_router()
            METRICS.gauge("corro.subs.count").set(len(self._by_id))
            if lease:
                handle.lease()
            else:
                # an unleased, never-attached matcher must not live
                # forever: the linger clock starts at creation
                self._note_idle(handle)
            return handle, True

    async def restore(self) -> int:
        """Re-attach matchers persisted on disk; purge incomplete ones.
        A restored matcher re-checks every pk of its source tables so
        changes applied while the agent was down surface as events (the
        reference catches up via `match_changes_from_db_version`)."""
        if self.subs_path is None:
            return 0
        root = Path(self.subs_path)
        if not root.exists():
            return 0
        n = 0
        for d in sorted(root.iterdir()):
            db = d / "sub.sqlite"
            if not d.is_dir() or not db.exists():
                continue
            try:
                sql = await asyncio.to_thread(self._read_meta_sql, db)
                parsed = parse_select(sql, self.store.schema)
                matcher = Matcher(self.store, parsed, d.name, sql, self.subs_path)
                await asyncio.to_thread(matcher.reattach)
            except (sqlite3.Error, MatcherError, ParseError, KeyError):
                # purge off-loop: an incomplete sub dir can hold a
                # multi-MB sub.sqlite and rmtree would stall the loop
                await asyncio.to_thread(
                    shutil.rmtree, d, ignore_errors=True
                )
                continue
            handle = MatcherHandle(
                matcher, asyncio.get_running_loop(), executor=self.executor,
                batch_wait=self.batch_wait, fanout=self.fanout,
            )
            self._adopt(handle)
            handle.start()
            self._by_id[d.name] = handle
            self._by_hash[sql_hash(sql)] = d.name
            self._by_canon[sql_hash(canonical_sql(sql))] = d.name
            await asyncio.to_thread(self._resync, handle)
            # restored matchers start with zero attached streams: the
            # linger clock decides whether anyone still wants them
            self._note_idle(handle)
            n += 1
        self._rebuild_router()
        METRICS.gauge("corro.subs.count").set(len(self._by_id))
        return n

    def _resync(self, handle: MatcherHandle) -> None:
        """Enqueue a full pk sweep of every source table as candidates:
        live pks ∪ materialized pks, so rows inserted OR deleted while
        the agent was down both get re-checked (the reference catches up
        via match_changes_from_db_version, updates.rs:490)."""
        from corrosion_tpu.types.pack import pack_columns

        with self.store.pooled_read() as conn:
            for t in handle.matcher.parsed.tables:
                pks = self.store.schema.table(t.name).pk_cols
                sel = ", ".join(f'"{c}"' for c in pks)
                rows = conn.execute(f'SELECT {sel} FROM "{t.name}"').fetchall()
                cands = {pack_columns(tuple(r)) for r in rows}
                cands.update(handle.matcher.materialized_pks(t.name))
                if cands:
                    handle.loop.call_soon_threadsafe(
                        handle._queue.put_nowait, ({t.name: cands}, None)
                    )

    def _read_meta_sql(self, db: Path) -> str:
        conn = sqlite3.connect(db)
        try:
            row = conn.execute(
                "SELECT v FROM meta WHERE k = 'sql'"
            ).fetchone()
            if row is None:
                raise KeyError("no sql in sub meta")
            return row[0]
        finally:
            conn.close()

    # -- feeding -----------------------------------------------------------

    def match_changes(self, changes: Sequence[Change], stamp=None) -> None:
        """Change hook: route committed changes through the inverted
        index (updates.rs:424-488). Thread-safe. One dict hop per
        change, candidate pk sets accumulated per hit matcher —
        `filter_candidates` never runs here, and matchers whose
        (table, cid) index misses do no work at all. Dead matchers are
        skipped (their queue has no consumer) and torn down from the
        loop.  `stamp` (runtime/latency.py BatchStamp) rides with the
        candidates so the matcher can attribute apply→event time."""
        router = self._router
        if not router:
            return
        per: Dict[MatcherHandle, Dict[str, Set[bytes]]] = {}
        matched = 0
        fanout = 0
        for ch in changes:
            by_cid = router.get(ch.table)
            if by_cid is None:
                continue
            handles = by_cid.get(
                SENTINEL if ch.is_sentinel() else ch.cid
            )
            if not handles:
                continue
            matched += 1
            fanout += len(handles)
            for h in handles:
                per.setdefault(h, {}).setdefault(
                    ch.table, set()
                ).add(ch.pk)
        METRICS.counter("corro.subs.router.changes.total").inc(len(changes))
        if matched:
            METRICS.counter("corro.subs.router.matched.total").inc(matched)
            METRICS.counter("corro.subs.router.fanout.total").inc(fanout)
        for handle, cands in per.items():
            if handle.error is not None:
                handle.loop.call_soon_threadsafe(
                    self._schedule_removal, handle.id
                )
                continue
            handle.enqueue_candidates(cands, stamp)

    def _schedule_removal(self, sub_id: str) -> None:
        asyncio.ensure_future(self.remove(sub_id, purge=True))

    # -- teardown ----------------------------------------------------------

    async def drain(self) -> int:
        """Drain every matcher home off this node (r22 store-faults
        actuator): each handle stops CLEANLY — attached streams get the
        bare-None terminal frame, so clients end with a typed stop and
        re-subscribe elsewhere (or here, post-revert) via the resume
        path.  Sub dbs are NOT purged: a recovered node re-attaches
        them through `restore()`.  Returns how many homes drained."""
        n = 0
        for sid in list(self._by_id):
            await self.remove(sid)
            n += 1
        return n

    async def remove(self, sub_id: str, purge: bool = False) -> None:
        async with self._lock:
            await self._remove_locked(sub_id, purge)

    async def _remove_locked(self, sub_id: str, purge: bool = False) -> None:
        handle = self._by_id.pop(sub_id, None)
        if handle is None:
            return
        t = getattr(handle, "_linger_timer", None)
        if t is not None:
            t.cancel()
            handle._linger_timer = None
        handle.on_active = handle.on_idle = None
        self._by_hash.pop(sql_hash(handle.sql), None)
        canon = sql_hash(canonical_sql(handle.sql))
        if self._by_canon.get(canon) == sub_id:
            self._by_canon.pop(canon, None)
        self._rebuild_router()
        await handle.stop()
        if purge:
            await asyncio.to_thread(self._purge_dir, sub_id)
        METRICS.gauge("corro.subs.count").set(len(self._by_id))

    def _purge_dir(self, sub_id: str) -> None:
        if self.subs_path is not None:
            shutil.rmtree(Path(self.subs_path) / sub_id, ignore_errors=True)

    async def stop_all(self) -> None:
        for sid in list(self._by_id):
            await self.remove(sid)
        self.fanout.stop()
        self.executor.shutdown()

"""SubsManager: dedupe, lifecycle and restore of live-query matchers.

Counterpart of `SubsManager` in `klukai-types/src/pubsub.rs:54-256`:
subscriptions are deduped by SQL text hash (`:565`), `get_or_insert`
(`:115`) returns an existing matcher when one already runs the same
query, and `restore` (`:164`) re-attaches matchers persisted under
`<subs_path>/<uuid>/sub.sqlite` on agent start
(`klukai-agent/src/agent/setup.rs:296-349`).
"""

from __future__ import annotations

import asyncio
import contextlib
import shutil
import sqlite3
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.pubsub.matcher import (
    Matcher,
    MatcherError,
    MatcherHandle,
    sql_hash,
)
from corrosion_tpu.pubsub.parse import ParseError, parse_select
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.change import Change


class SubsManager:
    """Registry of running matchers, keyed by id and by SQL hash."""

    def __init__(self, store, subs_path: Optional[str] = None):
        self.store = store
        self.subs_path = subs_path
        self._by_id: Dict[str, MatcherHandle] = {}
        self._by_hash: Dict[str, str] = {}  # sql hash -> id
        self._lock = asyncio.Lock()

    def get(self, sub_id: str) -> Optional[MatcherHandle]:
        return self._by_id.get(sub_id)

    def get_by_sql(self, sql: str) -> Optional[MatcherHandle]:
        sid = self._by_hash.get(sql_hash(sql))
        return self._by_id.get(sid) if sid else None

    def handles(self) -> List[MatcherHandle]:
        return list(self._by_id.values())

    async def get_or_insert(self, sql: str) -> Tuple[MatcherHandle, bool]:
        """Return (handle, created). When created, the initial query has
        materialized into the sub db; subscribers read rows through
        `handle.matcher.snapshot()` (attach-then-snapshot protocol)."""
        async with self._lock:
            existing = self.get_by_sql(sql)
            if existing is not None:
                if existing.error is None:
                    return existing, False
                # dead matcher: tear it down fully before replacing
                await self._remove_locked(existing.id, purge=True)
            parsed = parse_select(sql, self.store.schema)
            sub_id = str(uuid.uuid4())
            matcher = Matcher(self.store, parsed, sub_id, sql, self.subs_path)
            loop = asyncio.get_running_loop()

            def build():
                matcher.create_sub_db()
                return matcher.run_initial()

            try:
                await asyncio.to_thread(build)
            except (sqlite3.Error, MatcherError) as e:
                matcher.close()
                self._purge_dir(sub_id)
                raise ParseError(str(e)) from e
            handle = MatcherHandle(matcher, loop)
            handle.start()
            self._by_id[sub_id] = handle
            self._by_hash[sql_hash(sql)] = sub_id
            METRICS.gauge("corro.subs.count").set(len(self._by_id))
            return handle, True

    async def restore(self) -> int:
        """Re-attach matchers persisted on disk; purge incomplete ones.
        A restored matcher re-checks every pk of its source tables so
        changes applied while the agent was down surface as events (the
        reference catches up via `match_changes_from_db_version`)."""
        if self.subs_path is None:
            return 0
        root = Path(self.subs_path)
        if not root.exists():
            return 0
        n = 0
        for d in sorted(root.iterdir()):
            db = d / "sub.sqlite"
            if not d.is_dir() or not db.exists():
                continue
            try:
                sql = self._read_meta_sql(db)
                parsed = parse_select(sql, self.store.schema)
                matcher = Matcher(self.store, parsed, d.name, sql, self.subs_path)
                await asyncio.to_thread(matcher.reattach)
            except (sqlite3.Error, MatcherError, ParseError, KeyError):
                shutil.rmtree(d, ignore_errors=True)
                continue
            handle = MatcherHandle(matcher, asyncio.get_running_loop())
            handle.start()
            self._by_id[d.name] = handle
            self._by_hash[sql_hash(sql)] = d.name
            await asyncio.to_thread(self._resync, handle)
            n += 1
        METRICS.gauge("corro.subs.count").set(len(self._by_id))
        return n

    def _resync(self, handle: MatcherHandle) -> None:
        """Enqueue a full pk sweep of every source table as candidates:
        live pks ∪ materialized pks, so rows inserted OR deleted while
        the agent was down both get re-checked (the reference catches up
        via match_changes_from_db_version, updates.rs:490)."""
        from corrosion_tpu.types.pack import pack_columns

        with self.store.pooled_read() as conn:
            for t in handle.matcher.parsed.tables:
                pks = self.store.schema.table(t.name).pk_cols
                sel = ", ".join(f'"{c}"' for c in pks)
                rows = conn.execute(f'SELECT {sel} FROM "{t.name}"').fetchall()
                cands = {pack_columns(tuple(r)) for r in rows}
                cands.update(handle.matcher.materialized_pks(t.name))
                if cands:
                    handle.loop.call_soon_threadsafe(
                        handle._queue.put_nowait, {t.name: cands}
                    )

    def _read_meta_sql(self, db: Path) -> str:
        conn = sqlite3.connect(db)
        try:
            row = conn.execute(
                "SELECT v FROM meta WHERE k = 'sql'"
            ).fetchone()
            if row is None:
                raise KeyError("no sql in sub meta")
            return row[0]
        finally:
            conn.close()

    # -- feeding -----------------------------------------------------------

    def match_changes(self, changes: Sequence[Change]) -> None:
        """Change hook: route committed changes to every matcher
        (updates.rs:424-488). Thread-safe. Dead matchers are skipped
        (their queue has no consumer) and torn down from the loop."""
        for handle in list(self._by_id.values()):
            if handle.error is not None:
                handle.loop.call_soon_threadsafe(self._schedule_removal, handle.id)
                continue
            handle.match_changes(changes)

    def _schedule_removal(self, sub_id: str) -> None:
        asyncio.ensure_future(self.remove(sub_id, purge=True))

    # -- teardown ----------------------------------------------------------

    async def remove(self, sub_id: str, purge: bool = False) -> None:
        async with self._lock:
            await self._remove_locked(sub_id, purge)

    async def _remove_locked(self, sub_id: str, purge: bool = False) -> None:
        handle = self._by_id.pop(sub_id, None)
        if handle is None:
            return
        self._by_hash.pop(sql_hash(handle.sql), None)
        await handle.stop()
        if purge:
            self._purge_dir(sub_id)
        METRICS.gauge("corro.subs.count").set(len(self._by_id))

    def _purge_dir(self, sub_id: str) -> None:
        if self.subs_path is not None:
            shutil.rmtree(Path(self.subs_path) / sub_id, ignore_errors=True)

    async def stop_all(self) -> None:
        for sid in list(self._by_id):
            await self.remove(sid)

"""Compact binary primary-key encoding in the cr-sqlite wire format.

Format (reference `klukai-types/src/pubsub.rs:2257-2410`):
    [num_columns:u8, ...per value: (intlen<<3 | type):u8,
                     big-endian signed int of `intlen` bytes (int value or
                     text/blob length), then raw bytes for text/blob]
Floats are always 8 big-endian IEEE bytes with intlen 0. NULL has no payload.
Type tags are the ColumnType values in `values.py` (Integer=1, Float=2,
Text=3, Blob=4, Null=5).

Compatibility contract: the DECODER reads any reference-encoded bytes to
exactly the values the reference itself would read (including its
sign-extension of 1-byte 0x80..0xFF). The ENCODER deviates on one point:
positive values whose top encoded bit would be set get one extra byte
(see `_num_bytes_needed`), because the reference's unsigned-width encode
plus sign-extending decode never round-trips such values — upstream,
integer pks in 128..255 (each sign-boundary band) and 128..255-byte
text/blob pks are silently dropped by the subscription matcher. The
consequence: OUR packed bytes for those values differ from the
reference's, and since packed pk bytes are the CRDT row identity, a
mixed old/new-encoder cluster would see such rows as distinct. All nodes
of a cluster must run the same encoder (wire-level interop with
reference nodes already requires QUIC, which this build does not speak).
"""

from __future__ import annotations

import struct
from typing import List, Sequence

from corrosion_tpu.types.values import (
    SqliteValue,
    TYPE_BLOB,
    TYPE_INTEGER,
    TYPE_NULL,
    TYPE_REAL,
    TYPE_TEXT,
    value_type,
)


def _num_bytes_needed(val: int) -> int:
    """Bytes for a big-endian signed int — the reference's byte-mask
    probing (pubsub.rs:2315-2340: negatives always take 8 bytes, 0 takes
    0 bytes) PLUS one audited deviation: a positive value whose top
    encoded bit would be set gets one extra byte. The reference's
    encoder/decoder pair is asymmetric there — `put_int(128, 1)` emits
    0x80 which sign-extending `get_int` reads back as -128 — so integer
    pks in 128..255 (and each higher sign-boundary band) and text/blob
    pks 128..255 bytes long do not round-trip upstream (their matcher
    temp-table path drops such rows). Widening the encode keeps every
    value bijective while the decoder stays bug-compatible: any byte
    string a reference node could emit still decodes to exactly what the
    reference itself would decode.

    Compatibility note (packed pk bytes are CRDT row IDENTITY): the
    widened encoding changes the stored pk bytes for sign-boundary-band
    values (ints 128..255 and each higher band, 128..255-byte
    text/blob) relative to BOTH reference nodes and pre-widening builds
    of this repo. In a mixed cluster such rows exist under two
    identities until every writer runs the widened encoder — and a
    persisted store created by a pre-widening build keeps its
    old-identity rows: new writes to the same logical pk form a second
    row rather than merging. For an upgraded-in-place store, repack the
    affected rows once (identity changed iff decode->re-encode differs:
    SELECT, DELETE, re-INSERT under the new encoder), or re-seed the
    store from a fresh sync off an upgraded peer."""
    u = val & 0xFFFFFFFFFFFFFFFF
    for n in range(8, 0, -1):
        if u >> ((n - 1) * 8) & 0xFF:
            if val > 0 and n < 8 and (u >> ((n - 1) * 8)) & 0x80:
                return n + 1  # top bit would sign-flip on decode
            return n
    return 0


def _put_int(buf: bytearray, val: int, nbytes: int) -> None:
    u = val & 0xFFFFFFFFFFFFFFFF
    buf += u.to_bytes(8, "big")[8 - nbytes :] if nbytes else b""


def _get_int(data: memoryview, pos: int, nbytes: int) -> int:
    if nbytes == 0:
        return 0
    raw = bytes(data[pos : pos + nbytes])
    val = int.from_bytes(raw, "big", signed=True)
    return val


def pack_columns(values: Sequence[SqliteValue]) -> bytes:
    if len(values) > 0xFF:
        raise ValueError("too many columns to pack")
    buf = bytearray([len(values)])
    for v in values:
        t = value_type(v)
        if t == TYPE_NULL:
            buf.append(TYPE_NULL)
        elif t == TYPE_INTEGER:
            v = int(v)
            n = _num_bytes_needed(v)
            buf.append((n << 3) | TYPE_INTEGER)
            _put_int(buf, v, n)
        elif t == TYPE_REAL:
            buf.append(TYPE_REAL)
            buf += struct.pack(">d", v)
        elif t == TYPE_TEXT:
            raw = v.encode("utf-8")
            n = _num_bytes_needed(len(raw)) if raw else 0
            buf.append((n << 3) | TYPE_TEXT)
            _put_int(buf, len(raw), n)
            buf += raw
        else:  # blob
            raw = bytes(v)
            n = _num_bytes_needed(len(raw)) if raw else 0
            buf.append((n << 3) | TYPE_BLOB)
            _put_int(buf, len(raw), n)
            buf += raw
    return bytes(buf)


def unpack_columns(data: bytes) -> List[SqliteValue]:
    mv = memoryview(data)
    if not mv:
        raise ValueError("empty pk buffer")
    n = mv[0]
    pos = 1
    out: List[SqliteValue] = []
    for _ in range(n):
        if pos >= len(mv):
            raise ValueError("truncated pk buffer")
        tb = mv[pos]
        pos += 1
        t = tb & 0x07
        intlen = tb >> 3
        if t == TYPE_NULL:
            out.append(None)
        elif t == TYPE_INTEGER:
            out.append(_get_int(mv, pos, intlen))
            pos += intlen
        elif t == TYPE_REAL:
            out.append(struct.unpack(">d", mv[pos : pos + 8])[0])
            pos += 8
        elif t == TYPE_TEXT:
            ln = _get_int(mv, pos, intlen)
            pos += intlen
            out.append(bytes(mv[pos : pos + ln]).decode("utf-8"))
            pos += ln
        elif t == TYPE_BLOB:
            ln = _get_int(mv, pos, intlen)
            pos += intlen
            out.append(bytes(mv[pos : pos + ln]))
            pos += ln
        else:
            raise ValueError(f"bad column type tag {t}")
    if pos != len(mv):
        raise ValueError("trailing bytes in pk buffer")
    return out

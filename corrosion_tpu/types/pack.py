"""Compact binary primary-key encoding, byte-compatible with cr-sqlite.

Format (reference `klukai-types/src/pubsub.rs:2257-2410`):
    [num_columns:u8, ...per value: (intlen<<3 | type):u8,
                     big-endian signed int of `intlen` bytes (int value or
                     text/blob length), then raw bytes for text/blob]
Floats are always 8 big-endian IEEE bytes with intlen 0. NULL has no payload.
Type tags are the ColumnType values in `values.py` (Integer=1, Float=2,
Text=3, Blob=4, Null=5).
"""

from __future__ import annotations

import struct
from typing import List, Sequence

from corrosion_tpu.types.values import (
    SqliteValue,
    TYPE_BLOB,
    TYPE_INTEGER,
    TYPE_NULL,
    TYPE_REAL,
    TYPE_TEXT,
    value_type,
)


def _num_bytes_needed(val: int) -> int:
    """Bytes needed for a big-endian signed int, matching the reference's
    byte-mask probing (pubsub.rs:2315-2340). Note the reference checks raw
    byte occupancy of the two's-complement u64 pattern, so negatives always
    take 8 bytes and 0 takes 0 bytes."""
    u = val & 0xFFFFFFFFFFFFFFFF
    for n in range(8, 0, -1):
        if u >> ((n - 1) * 8) & 0xFF:
            return n
    return 0


def _put_int(buf: bytearray, val: int, nbytes: int) -> None:
    u = val & 0xFFFFFFFFFFFFFFFF
    buf += u.to_bytes(8, "big")[8 - nbytes :] if nbytes else b""


def _get_int(data: memoryview, pos: int, nbytes: int) -> int:
    if nbytes == 0:
        return 0
    raw = bytes(data[pos : pos + nbytes])
    val = int.from_bytes(raw, "big", signed=True)
    return val


def pack_columns(values: Sequence[SqliteValue]) -> bytes:
    if len(values) > 0xFF:
        raise ValueError("too many columns to pack")
    buf = bytearray([len(values)])
    for v in values:
        t = value_type(v)
        if t == TYPE_NULL:
            buf.append(TYPE_NULL)
        elif t == TYPE_INTEGER:
            v = int(v)
            n = _num_bytes_needed(v)
            buf.append((n << 3) | TYPE_INTEGER)
            _put_int(buf, v, n)
        elif t == TYPE_REAL:
            buf.append(TYPE_REAL)
            buf += struct.pack(">d", v)
        elif t == TYPE_TEXT:
            raw = v.encode("utf-8")
            n = _num_bytes_needed(len(raw)) if raw else 0
            buf.append((n << 3) | TYPE_TEXT)
            _put_int(buf, len(raw), n)
            buf += raw
        else:  # blob
            raw = bytes(v)
            n = _num_bytes_needed(len(raw)) if raw else 0
            buf.append((n << 3) | TYPE_BLOB)
            _put_int(buf, len(raw), n)
            buf += raw
    return bytes(buf)


def unpack_columns(data: bytes) -> List[SqliteValue]:
    mv = memoryview(data)
    if not mv:
        raise ValueError("empty pk buffer")
    n = mv[0]
    pos = 1
    out: List[SqliteValue] = []
    for _ in range(n):
        if pos >= len(mv):
            raise ValueError("truncated pk buffer")
        tb = mv[pos]
        pos += 1
        t = tb & 0x07
        intlen = tb >> 3
        if t == TYPE_NULL:
            out.append(None)
        elif t == TYPE_INTEGER:
            out.append(_get_int(mv, pos, intlen))
            pos += intlen
        elif t == TYPE_REAL:
            out.append(struct.unpack(">d", mv[pos : pos + 8])[0])
            pos += 8
        elif t == TYPE_TEXT:
            ln = _get_int(mv, pos, intlen)
            pos += intlen
            out.append(bytes(mv[pos : pos + ln]).decode("utf-8"))
            pos += ln
        elif t == TYPE_BLOB:
            ln = _get_int(mv, pos, intlen)
            pos += intlen
            out.append(bytes(mv[pos : pos + ln]))
            pos += ln
        else:
            raise ValueError(f"bad column type tag {t}")
    if pos != len(mv):
        raise ValueError("trailing bytes in pk buffer")
    return out

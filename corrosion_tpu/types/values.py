"""SQLite-style dynamic values.

Counterpart of `klukai-types/src/api.rs:463` (`SqliteValue`). On the Python
side values are native: None | int | float | str | bytes. This module holds
the type-tag constants shared by the pk pack format (`pack.py`) and the wire
codec (`codec.py`), plus helpers for JSON (serde-untagged-compatible) and
stable hashing of floats (reference hashes f64 via integer_decode,
`api.rs:484-500`; we use the IEEE bit pattern which is equally stable).
"""

from __future__ import annotations

import base64
from typing import Union

SqliteValue = Union[None, int, float, str, bytes]

# ColumnType tags (api.rs:342-348); also used by pack_columns.
TYPE_INTEGER = 1
TYPE_REAL = 2  # "Float"
TYPE_TEXT = 3
TYPE_BLOB = 4
TYPE_NULL = 5

# pack_columns uses a 3-bit type field, so NULL's tag 5 fits; the same
# constants serve both formats.


def value_type(v: SqliteValue) -> int:
    if v is None:
        return TYPE_NULL
    if isinstance(v, bool):
        return TYPE_INTEGER
    if isinstance(v, int):
        return TYPE_INTEGER
    if isinstance(v, float):
        return TYPE_REAL
    if isinstance(v, str):
        return TYPE_TEXT
    if isinstance(v, (bytes, bytearray, memoryview)):
        return TYPE_BLOB
    raise TypeError(f"unsupported sqlite value: {type(v)!r}")


def to_json_value(v: SqliteValue):
    """serde-untagged JSON shape; blobs become base64 strings with a marker."""
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"blob": base64.b64encode(bytes(v)).decode()}
    return v


def from_json_value(v) -> SqliteValue:
    if isinstance(v, dict) and set(v) == {"blob"}:
        return base64.b64decode(v["blob"])
    if isinstance(v, bool):
        return int(v)
    return v


def hash_key(v: SqliteValue):
    """Hashable, type-discriminated key for dedupe/cache maps."""
    t = value_type(v)
    if t == TYPE_REAL:
        import struct

        return (t, struct.pack(">d", v))
    if t == TYPE_BLOB:
        return (t, bytes(v))
    return (t, v)


def cmp_values(a: SqliteValue, b: SqliteValue) -> int:
    """Total order over sqlite values, matching SQLite's cross-type ordering:
    NULL < INTEGER/REAL < TEXT < BLOB. Used for LWW tie-breaking on equal
    col_version (cr-sqlite: "largest value wins").
    """
    ranks = {TYPE_NULL: 0, TYPE_INTEGER: 1, TYPE_REAL: 1, TYPE_TEXT: 2, TYPE_BLOB: 3}
    ta, tb = value_type(a), value_type(b)
    ra, rb = ranks[ta], ranks[tb]
    if ra != rb:
        return -1 if ra < rb else 1
    if ra == 0:
        return 0
    if isinstance(a, (bytes, bytearray, memoryview)):
        a, b = bytes(a), bytes(b)
    if a < b:
        return -1
    if a > b:
        return 1
    return 0

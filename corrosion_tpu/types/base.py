"""Base newtypes and the hybrid logical clock.

Counterparts of the reference's `klukai-types/src/base.rs` (CrsqlDbVersion /
CrsqlSeq u64 newtypes) and its uhlc-based HLC (`Timestamp` NTP64 wrapper,
`klukai-types/src/broadcast.rs:383`). We keep versions/seqs as plain ints at
API boundaries (Python ints are arbitrary precision; wire codecs clamp to
u64) and provide a compact HLC with the same 300 ms max-delta semantics
(`klukai-agent/src/agent/setup.rs:101-106`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

# Versions and sequences are plain non-negative ints on the Python side.
DbVersion = int
Seq = int

_FRAC = 1 << 32  # NTP64: upper 32 bits = seconds, lower 32 = fraction


@dataclass(frozen=True, order=True)
class Timestamp:
    """NTP64 timestamp (uhlc-compatible): u64 = secs<<32 | frac."""

    ntp64: int = 0

    @classmethod
    def from_unix(cls, secs: float) -> "Timestamp":
        whole = int(secs)
        frac = int((secs - whole) * _FRAC) & 0xFFFFFFFF
        return cls((whole << 32) | frac)

    @classmethod
    def now(cls) -> "Timestamp":
        return cls.from_unix(time.time())

    @classmethod
    def zero(cls) -> "Timestamp":
        return cls(0)

    def is_zero(self) -> bool:
        return self.ntp64 == 0

    @property
    def secs(self) -> int:
        return self.ntp64 >> 32

    @property
    def subsec_nanos(self) -> int:
        return ((self.ntp64 & 0xFFFFFFFF) * 1_000_000_000) >> 32

    def to_unix(self) -> float:
        return self.secs + (self.ntp64 & 0xFFFFFFFF) / _FRAC

    def __str__(self) -> str:  # humantime-ish, for logs
        return f"{self.to_unix():.6f}"


class HLClock:
    """Hybrid logical clock over NTP64 timestamps.

    Mirrors uhlc behavior used by the reference: timestamps are monotonic,
    `update_with_timestamp` refuses (but records) peer timestamps further
    than `max_delta` in the future, matching the 300 ms configured at
    `klukai-agent/src/agent/setup.rs:101-106`.
    """

    def __init__(self, max_delta_ms: int = 300):
        self._last = 0
        self._max_delta = (max_delta_ms << 32) // 1000
        self._lock = threading.Lock()

    def new_timestamp(self) -> Timestamp:
        with self._lock:
            now = Timestamp.now().ntp64
            self._last = max(self._last + 1, now)
            return Timestamp(self._last)

    def update_with_timestamp(self, ts: Timestamp) -> bool:
        """Merge a peer timestamp. Returns False if rejected (too far ahead)."""
        with self._lock:
            now = Timestamp.now().ntp64
            if ts.ntp64 > now + self._max_delta:
                return False
            self._last = max(self._last, ts.ntp64)
            return True

    def peek(self) -> Timestamp:
        with self._lock:
            return Timestamp(self._last)

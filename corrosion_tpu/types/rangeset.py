"""Inclusive integer range sets with adjacency coalescing.

Behavioral counterpart of `rangemap::RangeInclusiveSet<u64>` as used across
the reference for version-gap and seq-gap bookkeeping (`klukai-types/src/
agent.rs:1068-1246`, `sync.rs:126-248`). Ranges are closed [start, end];
inserting [1,2] then [3,4] coalesces to [1,4] (integer adjacency), exactly
like rangemap with StepLite — the sync set-algebra depends on this.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator, List, Optional, Tuple

Range = Tuple[int, int]


class RangeSet:
    """Sorted, disjoint, coalesced list of inclusive [start, end] ranges."""

    __slots__ = ("_starts", "_ends")

    def __init__(self, ranges: Optional[Iterable[Range]] = None):
        self._starts: List[int] = []
        self._ends: List[int] = []
        if ranges:
            for s, e in ranges:
                self.insert(s, e)

    # -- core ops ---------------------------------------------------------

    def insert(self, start: int, end: int) -> None:
        if end < start:
            return
        # find all ranges overlapping or adjacent to [start-1, end+1]
        i = bisect_left(self._ends, start - 1)
        j = bisect_right(self._starts, end + 1)
        if i < j:  # merge with [i, j)
            start = min(start, self._starts[i])
            end = max(end, self._ends[j - 1])
        del self._starts[i:j]
        del self._ends[i:j]
        self._starts.insert(i, start)
        self._ends.insert(i, end)

    def remove(self, start: int, end: int) -> None:
        if end < start:
            return
        i = bisect_left(self._ends, start)
        j = bisect_right(self._starts, end)
        if i >= j:
            return
        left_keep = None
        right_keep = None
        if self._starts[i] < start:
            left_keep = (self._starts[i], start - 1)
        if self._ends[j - 1] > end:
            right_keep = (end + 1, self._ends[j - 1])
        del self._starts[i:j]
        del self._ends[i:j]
        if right_keep:
            self._starts.insert(i, right_keep[0])
            self._ends.insert(i, right_keep[1])
        if left_keep:
            self._starts.insert(i, left_keep[0])
            self._ends.insert(i, left_keep[1])

    def contains(self, v: int) -> bool:
        i = bisect_right(self._starts, v) - 1
        return i >= 0 and self._ends[i] >= v

    def contains_range(self, start: int, end: int) -> bool:
        i = bisect_right(self._starts, start) - 1
        return i >= 0 and self._starts[i] <= start and self._ends[i] >= end

    def overlapping(self, start: int, end: int) -> Iterator[Range]:
        """Yield stored ranges intersecting [start, end] (uncropped, like
        rangemap's overlapping())."""
        i = bisect_left(self._ends, start)
        while i < len(self._starts) and self._starts[i] <= end:
            yield (self._starts[i], self._ends[i])
            i += 1

    def gaps(self, start: int, end: int) -> Iterator[Range]:
        """Yield maximal sub-ranges of [start, end] not covered by the set."""
        cur = start
        for s, e in self.overlapping(start, end):
            if s > cur:
                yield (cur, min(s - 1, end))
            cur = max(cur, e + 1)
            if cur > end:
                break
        if cur <= end:
            yield (cur, end)

    # -- conveniences -----------------------------------------------------

    def __iter__(self) -> Iterator[Range]:
        return iter(zip(self._starts, self._ends))

    def __len__(self) -> int:
        return len(self._starts)

    def __bool__(self) -> bool:
        return bool(self._starts)

    def __eq__(self, other) -> bool:
        return isinstance(other, RangeSet) and list(self) == list(other)

    def __repr__(self) -> str:
        return f"RangeSet({list(self)})"

    def is_empty(self) -> bool:
        return not self._starts

    def count_values(self) -> int:
        return sum(e - s + 1 for s, e in self)

    def min(self) -> Optional[int]:
        return self._starts[0] if self._starts else None

    def max(self) -> Optional[int]:
        return self._ends[-1] if self._ends else None

    def copy(self) -> "RangeSet":
        rs = RangeSet()
        rs._starts = list(self._starts)
        rs._ends = list(self._ends)
        return rs

    def union(self, other: "RangeSet") -> "RangeSet":
        rs = self.copy()
        for s, e in other:
            rs.insert(s, e)
        return rs

    def difference(self, other: "RangeSet") -> "RangeSet":
        rs = self.copy()
        for s, e in other:
            rs.remove(s, e)
        return rs

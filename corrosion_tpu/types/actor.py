"""Node identity: ActorId (UUID = site id), ClusterId, Actor.

Counterpart of `klukai-types/src/actor.rs:26,134,219`. An actor's id doubles
as its CRDT site id; the Actor carries a gossip address, an HLC timestamp
(newest timestamp wins address conflicts, `actor.rs:191`), a cluster id, and
a bump counter used by `renew()` for auto-rejoin after being declared down
(`actor.rs:199-206`).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field, replace

from corrosion_tpu.types.base import Timestamp


@dataclass(frozen=True, order=True)
class ActorId:
    bytes16: bytes = b"\x00" * 16

    def __post_init__(self):
        if len(self.bytes16) != 16:
            raise ValueError("ActorId must be 16 bytes")

    @classmethod
    def new_random(cls) -> "ActorId":
        return cls(uuid.uuid4().bytes)

    @classmethod
    def from_uuid_str(cls, s: str) -> "ActorId":
        return cls(uuid.UUID(s).bytes)

    @classmethod
    def zero(cls) -> "ActorId":
        return cls(b"\x00" * 16)

    def as_uuid(self) -> uuid.UUID:
        return uuid.UUID(bytes=self.bytes16)

    def to_ordinal(self) -> int:
        """First byte — used for compact per-site ordinals in clock storage."""
        return self.bytes16[0]

    def __str__(self) -> str:
        return str(self.as_uuid())

    def short(self) -> str:
        return str(self.as_uuid())[:8]


@dataclass(frozen=True, order=True)
class ClusterId:
    value: int = 0  # u16

    def __post_init__(self):
        if not (0 <= self.value <= 0xFFFF):
            raise ValueError("ClusterId must fit u16")


@dataclass(frozen=True)
class Actor:
    id: ActorId
    addr: str  # "host:port" gossip address
    ts: Timestamp = field(default_factory=Timestamp.zero)
    cluster_id: ClusterId = field(default_factory=ClusterId)
    bump: int = 0  # u16 renewal counter

    def renew(self) -> "Actor":
        """New identity for rejoin after being declared down (actor.rs:199)."""
        return replace(self, ts=Timestamp.now(), bump=(self.bump + 1) & 0xFFFF)

    def wins_addr_conflict(self, other: "Actor") -> bool:
        """Same-address conflict resolution: newest timestamp wins (actor.rs:191)."""
        return self.ts > other.ts

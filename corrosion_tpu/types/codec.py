"""Binary wire codecs modeled byte-for-byte on the reference's speedy layouts.

The reference serializes peer wire types with speedy 0.8 (little-endian):
  - derived enums: u32 LE variant tag (speedy's default)
  - hand-written enum codecs (Changeset, SyncNeedV1, SyncStateV1,
    SqliteValue): u8 tags / u64 ("usize") lengths exactly as in
    `klukai-types/src/broadcast.rs:285-375`, `sync.rs:258-346,371-437`,
    `api.rs:657-707`
  - Vec/String/HashMap: u32 LE length prefix; Option: u8 presence byte
  - uuid/[u8;16]: 16 raw bytes; u64/i64/f64: LE fixed width

Frames on uni/bi streams are length-delimited with a u32 BE length prefix
(tokio LengthDelimitedCodec default), max frame 100 MiB
(`klukai-agent/src/agent/uni.rs:50` / `api/peer/mod.rs`).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.types.actor import ActorId, ClusterId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import (
    Change,
    ChangeV1,
    ChangesetEmpty,
    ChangesetEmptySet,
    ChangesetFull,
)
from corrosion_tpu.types.values import (
    SqliteValue,
    value_type,
    TYPE_BLOB,
    TYPE_INTEGER,
    TYPE_NULL,
    TYPE_REAL,
    TYPE_TEXT,
)

MAX_FRAME = 100 * 1024 * 1024


class Writer:
    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def u8(self, v: int):
        self.buf.append(v & 0xFF)

    def u16(self, v: int):
        self.buf += struct.pack("<H", v)

    def u32(self, v: int):
        self.buf += struct.pack("<I", v)

    def u64(self, v: int):
        self.buf += struct.pack("<Q", v)

    def i64(self, v: int):
        self.buf += struct.pack("<q", v)

    def f64(self, v: float):
        self.buf += struct.pack("<d", v)

    def raw(self, b: bytes):
        self.buf += b

    def string(self, s: str):
        raw = s.encode("utf-8")
        self.u32(len(raw))
        self.buf += raw

    def vec_u8(self, b: bytes):
        self.u32(len(b))
        self.buf += b

    def opt(self, v, write_fn):
        if v is None:
            self.u8(0)
        else:
            self.u8(1)
            write_fn(v)

    def uvarint(self, v: int):
        """LEB128 unsigned varint — the compact-integer encoding the
        r12 telemetry-digest codec uses (runtime/digest.py); NOT part of
        any reference speedy layout."""
        if v < 0:
            raise ValueError(f"uvarint of negative {v}")
        while v >= 0x80:
            self.buf.append((v & 0x7F) | 0x80)
            v >>= 7
        self.buf.append(v)

    def bytes(self) -> bytes:
        return bytes(self.buf)


class Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = memoryview(data)
        self.pos = 0

    def _take(self, n: int) -> memoryview:
        if self.pos + n > len(self.data):
            raise ValueError("truncated buffer")
        mv = self.data[self.pos : self.pos + n]
        self.pos += n
        return mv

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def raw(self, n: int) -> bytes:
        return bytes(self._take(n))

    def string(self) -> str:
        return self.raw(self.u32()).decode("utf-8")

    def vec_u8(self) -> bytes:
        return self.raw(self.u32())

    def opt(self, read_fn):
        return read_fn() if self.u8() else None

    def uvarint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.u8()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise ValueError("uvarint too long")

    def eof(self) -> bool:
        return self.pos >= len(self.data)


# -- SqliteValue (api.rs:657-707, u8 tags) --------------------------------


def write_value(w: Writer, v: SqliteValue) -> None:
    t = value_type(v)
    if t == TYPE_NULL:
        w.u8(0)
    elif t == TYPE_INTEGER:
        w.u8(1)
        w.i64(int(v))
    elif t == TYPE_REAL:
        w.u8(2)
        w.f64(v)
    elif t == TYPE_TEXT:
        w.u8(3)
        w.string(v)
    else:
        w.u8(4)
        w.vec_u8(bytes(v))


def read_value(r: Reader) -> SqliteValue:
    tag = r.u8()
    if tag == 0:
        return None
    if tag == 1:
        return r.i64()
    if tag == 2:
        return r.f64()
    if tag == 3:
        return r.string()
    if tag == 4:
        return r.vec_u8()
    raise ValueError(f"unknown SqliteValue tag {tag}")


# -- Change (derive order: change.rs:19-29) --------------------------------


_CHANGE_TAIL = struct.Struct("<qQQ")


def write_change_fields(
    w: Writer,
    table: str,
    pk: bytes,
    cid: str,
    val: SqliteValue,
    col_version: int,
    db_version: int,
    seq: int,
    site_id: bytes,
    cl: int,
) -> None:
    """One change cell from raw fields — the single source of truth for
    the cell layout, shared by `write_change` and the r15 fused local
    commit (`finalize_group` builds `Change.wire_cell` in the same pass
    that emits the Change)."""
    w.string(table)
    w.vec_u8(pk)
    w.string(cid)
    write_value(w, val)
    buf = w.buf
    buf += _CHANGE_TAIL.pack(col_version, db_version, seq)
    buf += site_id
    buf += struct.pack("<q", cl)


_LEN32 = struct.Struct("<I")
# the whole fixed-width cell suffix in one pack: col_version/db_version/
# seq (<qQQ, _CHANGE_TAIL) + 16 raw site-id bytes + <q cl — "<" packing
# has no alignment, so the bytes equal _CHANGE_TAIL.pack + site + pack
_CELL_SUFFIX = struct.Struct("<qQQ16sq")


def write_change_cells(cells, site_id: bytes) -> List[bytes]:
    """Batch form of `write_change_fields` (r21 columnar finalize
    phase B): encode MANY change cells in one pass over a shared
    buffer — length-prefixed table/cid headers are interned once per
    distinct string (a 10-row commit repeats each cid 10 times), the
    fixed-width tail is a single struct pack per cell, and the per-cell
    Writer allocation + 4-call encode sequence of the per-cell path
    disappears.  ``cells`` yields
    ``(table, pk, cid, val, col_version, db_version, seq, cl)`` tuples
    sharing one ``site_id``; returns the per-cell wire bytes in order,
    byte-identical to `write_change_fields` (pinned in test_codec.py
    and by the finalize equivalence suite)."""
    w = Writer()
    buf = w.buf
    pack_len = _LEN32.pack
    pack_suffix = _CELL_SUFFIX.pack
    # tables and cids share the cache: both encode as u32 len + utf-8
    hdrs: Dict[str, bytes] = {}
    bounds = [0]
    mark = bounds.append
    for table, pk, cid, val, col_version, db_version, seq, cl in cells:
        h = hdrs.get(table)
        if h is None:
            raw = table.encode("utf-8")
            hdrs[table] = h = pack_len(len(raw)) + raw
        buf += h
        buf += pack_len(len(pk))
        buf += pk
        h = hdrs.get(cid)
        if h is None:
            raw = cid.encode("utf-8")
            hdrs[cid] = h = pack_len(len(raw)) + raw
        buf += h
        write_value(w, val)
        buf += pack_suffix(col_version, db_version, seq, site_id, cl)
        mark(len(buf))
    mv = memoryview(buf)
    return [bytes(mv[a:b]) for a, b in zip(bounds, bounds[1:])]


def write_change(w: Writer, c: Change) -> None:
    # hot path (every broadcast/sync encode walks one of these per cell
    # when no wire_body is cached): a change carrying its r15 cached
    # cell bytes splices them verbatim; otherwise the fixed-width tail
    # is fused into single packs — byte layout identical either way
    # (pinned in test_codec.py goldens + test_capture.py)
    if c.wire_cell is not None:
        w.buf += c.wire_cell
        return
    write_change_fields(
        w, c.table, c.pk, c.cid, c.val, c.col_version, c.db_version,
        c.seq, c.site_id, c.cl,
    )


def read_change(r: Reader) -> Change:
    return Change(
        table=r.string(),
        pk=r.vec_u8(),
        cid=r.string(),
        val=read_value(r),
        col_version=r.i64(),
        db_version=r.u64(),
        seq=r.u64(),
        site_id=r.raw(16),
        cl=r.i64(),
    )


# -- Changeset (hand-written u8 tags, broadcast.rs:285-375) ----------------


def write_changeset(w: Writer, cs) -> None:
    if isinstance(cs, ChangesetEmpty):
        w.u8(0)
        w.u64(cs.versions[0])
        w.u64(cs.versions[1])
        w.opt(cs.ts, lambda ts: w.u64(ts.ntp64))
    elif isinstance(cs, ChangesetFull):
        w.u8(1)
        w.u64(cs.version)
        w.u32(len(cs.changes))
        for c in cs.changes:
            write_change(w, c)
        w.u64(cs.seqs[0])
        w.u64(cs.seqs[1])
        w.u64(cs.last_seq)
        w.u64(cs.ts.ntp64)
    elif isinstance(cs, ChangesetEmptySet):
        w.u8(2)
        w.u64(len(cs.versions))  # usize
        for s, e in cs.versions:
            w.u64(s)
            w.u64(e)
        w.u64(cs.ts.ntp64)
    else:
        raise TypeError(f"not a changeset: {cs!r}")


def read_changeset(r: Reader):
    tag = r.u8()
    if tag == 0:
        start, end = r.u64(), r.u64()
        ts = r.opt(lambda: Timestamp(r.u64()))
        return ChangesetEmpty(versions=(start, end), ts=ts)
    if tag == 1:
        version = r.u64()
        changes = tuple(read_change(r) for _ in range(r.u32()))
        seqs = (r.u64(), r.u64())
        last_seq = r.u64()
        ts = Timestamp(r.u64())
        return ChangesetFull(version, changes, seqs, last_seq, ts)
    if tag == 2:
        n = r.u64()
        versions = tuple((r.u64(), r.u64()) for _ in range(n))
        ts = Timestamp(r.u64())
        return ChangesetEmptySet(versions, ts)
    raise ValueError(f"unknown Changeset tag {tag}")


def write_change_v1(w: Writer, cv: ChangeV1) -> None:
    w.raw(cv.actor_id.bytes16)
    write_changeset(w, cv.changeset)


def read_change_v1(r: Reader) -> ChangeV1:
    return ChangeV1(actor_id=ActorId(r.raw(16)), changeset=read_changeset(r))


# -- envelope extension (r11 latency plane, r12 telemetry digests) ---------
#
# A version-gated OPTIONAL trailing block appended after the last field
# old decoders read.  Compatibility is structural in both directions:
# old peers stop reading before the ext (trailing bytes are ignored, the
# same default_on_eof tolerance the cluster_id field already relies on),
# and new peers treat eof-before-ext as "no ext".  The block is only
# written when it has content, so pre-r11 byte layouts are reproduced
# exactly for unstamped payloads (golden tests stay valid).
#
#   ext v1 := u8 version(=1) · opt<f64 origin_ts> · opt<string traceparent>
#   ext v2 := u8 version(=2) · opt<f64 origin_ts> · opt<string traceparent>
#             · vec<u8> digest          (r12: an encoded telemetry digest,
#                                        runtime/digest.py — opaque here)
#   ext v3 := u8 version(=3) · opt<f64 origin_ts> · opt<string traceparent>
#             · vec<u8> digest · opt<u8 trace_meta>
#                                        (r19: tail-sampling trace meta —
#                                         bit 0 forced-keep from the origin's
#                                         head decision, bits 2..7 relay hop
#                                         count; runtime/trace.py owns the
#                                         bit layout)
#
# v2 is only written when a digest rides along, so v1 readers (which
# read the stamps and ignore anything after) parse v2 payloads, and
# digest-free payloads stay byte-identical to the r11 layout.  v3 is
# only written when trace meta rides along: a pre-v3 peer reads the
# stamps (and the digest — 3 passes its `>= v2` gate; a meta-only v3
# payload writes an EMPTY digest vec, which the v3 reader normalizes
# back to None) and leaves the trailing meta byte unread, while a v3
# reader over a v1/v2 body hits eof before the meta and yields None —
# the same structural tolerance in both directions as v1/v2.

_ENVELOPE_EXT_V1 = 1
_ENVELOPE_EXT_V2 = 2
_ENVELOPE_EXT_V3 = 3


def _write_envelope_ext(
    w: Writer,
    origin_ts: Optional[float],
    traceparent: Optional[str],
    digest: Optional[bytes] = None,
    trace_meta: Optional[int] = None,
) -> None:
    if (
        origin_ts is None
        and traceparent is None
        and digest is None
        and trace_meta is None
    ):
        return
    if trace_meta is not None:
        w.u8(_ENVELOPE_EXT_V3)
    elif digest is not None:
        w.u8(_ENVELOPE_EXT_V2)
    else:
        w.u8(_ENVELOPE_EXT_V1)
    w.opt(origin_ts, w.f64)
    w.opt(traceparent, w.string)
    if digest is not None or trace_meta is not None:
        w.vec_u8(digest if digest is not None else b"")
    if trace_meta is not None:
        w.opt(trace_meta, w.u8)


def _read_envelope_ext(
    r: Reader,
) -> Tuple[Optional[float], Optional[str], Optional[bytes], Optional[int]]:
    if r.eof():
        return None, None, None, None
    ver = r.u8()
    if ver < _ENVELOPE_EXT_V1:  # pragma: no cover — never written
        return None, None, None, None
    origin_ts = r.opt(r.f64)
    traceparent = r.opt(r.string)
    digest = (
        r.vec_u8() if ver >= _ENVELOPE_EXT_V2 and not r.eof() else None
    )
    trace_meta = (
        r.opt(r.u8) if ver >= _ENVELOPE_EXT_V3 and not r.eof() else None
    )
    # a meta-only v3 payload carries an empty digest vec as padding;
    # consumers (observatory.receive) must never see b"" as a digest
    return origin_ts, traceparent, digest or None, trace_meta


def _with_ext(
    cv: ChangeV1,
    origin_ts: Optional[float],
    traceparent: Optional[str],
    wire_body: Optional[bytes] = None,
    trace_meta: Optional[int] = None,
) -> ChangeV1:
    if (
        origin_ts is None
        and traceparent is None
        and wire_body is None
        and trace_meta is None
    ):
        return cv
    from dataclasses import replace

    return replace(
        cv,
        origin_ts=origin_ts,
        traceparent=traceparent,
        trace_meta=trace_meta,
        wire_body=wire_body if wire_body is not None else cv.wire_body,
    )


# -- UniPayload / BiPayload (derived, u32 tags) ----------------------------
#
# r14 encode-once: the `actor_id + changeset` body dominates every uni
# payload's bytes and never changes between transmissions — so it is
# serialized ONCE (at local commit, or captured from the received frame
# on decode) and carried on `ChangeV1.wire_body`; `encode_uni_prefix`
# splices the shared bytes instead of re-walking the changeset, and only
# the cheap trailing envelope ext (origin stamp / traceparent / per-
# transmission digest) is re-written per send.  `encode_uni_payload`
# output is byte-identical either way (pinned in test_codec.py).


def encode_change_v1_body(cv: ChangeV1) -> bytes:
    """The shareable uni/sync body: actor_id + changeset, speedy layout."""
    w = Writer()
    write_change_v1(w, cv)
    return w.bytes()


def with_wire_body(cv: ChangeV1) -> ChangeV1:
    """Return `cv` carrying its encoded body (encode-once stamp point)."""
    if cv.wire_body is not None:
        return cv
    from dataclasses import replace

    return replace(cv, wire_body=encode_change_v1_body(cv))


# r16 write-path round-3 opener: chunked uni bodies are SPLICED from
# cached per-cell bytes, never re-walked.  A ChangesetFull body is
#   actor16 · u8(1) · u64(version) · u32(n) · cells… ·
#   u64 seqs0 · u64 seqs1 · u64 last_seq · u64 ts
# so given each change's `wire_cell` (stamped by finalize_group at local
# commit, or built here once for decoded relays) every chunk body is a
# header pack + a join of cached cells + a tail pack — byte-identical to
# `encode_change_v1_body` over the equivalent ChangesetFull (pinned in
# test_codec.py).

_CHUNK_HEAD = struct.Struct("<BQI")
_CHUNK_TAIL = struct.Struct("<QQQQ")


def _cell_bytes(c: Change) -> bytes:
    cell = c.wire_cell
    if cell is None:
        w = Writer()
        write_change_fields(
            w, c.table, c.pk, c.cid, c.val, c.col_version, c.db_version,
            c.seq, c.site_id, c.cl,
        )
        cell = w.bytes()
        # backfill the cache (compare=False field on a frozen
        # dataclass): a relayed change re-chunked once never rebuilds
        # its cell for later transmissions
        object.__setattr__(c, "wire_cell", cell)
    return cell


def chunked_change_v1(
    actor_id: ActorId,
    version: int,
    changes,
    last_seq: int,
    ts,
    origin_ts: Optional[float] = None,
    traceparent: Optional[str] = None,
    max_bytes: int = 8 * 1024,  # MAX_CHANGES_BYTE_SIZE (change.rs:179)
    seq_range: Optional[Tuple[int, int]] = None,
    trace_meta: Optional[int] = None,
) -> List[ChangeV1]:
    """Split one version's ordered changes into broadcast-sized
    ChangeV1 chunks, each carrying its spliced `wire_body`.  Grouping is
    `chunk_changes` verbatim (same estimator, same seq-coverage rules),
    so receivers buffer the partials and apply when the range closes.
    `seq_range` is the SOURCE changeset's claimed coverage — pass it
    when re-chunking an already-partial changeset so no chunk claims
    seqs it does not carry; default (0, last_seq) = a complete local
    commit."""
    from corrosion_tpu.types.change import ChangesetFull, chunk_changes

    lo, hi = seq_range if seq_range is not None else (0, last_seq)
    actor16 = actor_id.bytes16
    out: List[ChangeV1] = []
    for chunk, seqs in chunk_changes(
        changes, hi, max_bytes=max_bytes, range_start=lo
    ):
        parts = [actor16, _CHUNK_HEAD.pack(1, version, len(chunk))]
        parts.extend(_cell_bytes(c) for c in chunk)
        parts.append(_CHUNK_TAIL.pack(seqs[0], seqs[1], last_seq, ts.ntp64))
        out.append(
            ChangeV1(
                actor_id=actor_id,
                changeset=ChangesetFull(
                    version=version,
                    changes=tuple(chunk),
                    seqs=seqs,
                    last_seq=last_seq,
                    ts=ts,
                ),
                origin_ts=origin_ts,
                traceparent=traceparent,
                trace_meta=trace_meta,
                wire_body=b"".join(parts),
            )
        )
    return out


def _write_body(w: Writer, cv: ChangeV1) -> None:
    if cv.wire_body is not None:
        from corrosion_tpu.runtime.metrics import METRICS

        METRICS.counter("corro.codec.encode.shared.total").inc()
        w.raw(cv.wire_body)
    else:
        write_change_v1(w, cv)


def encode_uni_prefix(
    cv: ChangeV1, cluster_id: ClusterId = ClusterId(0)
) -> bytes:
    """Everything up to (excluding) the envelope ext: variant header +
    shared body + cluster id.  Reused across a payload's
    re-transmissions, which only re-write the trailing ext."""
    w = Writer()
    w.u32(0)  # UniPayload::V1
    w.u32(0)  # UniPayloadV1::Broadcast
    w.u32(0)  # BroadcastV1::Change
    _write_body(w, cv)
    w.u16(cluster_id.value)
    return w.bytes()


def encode_uni_from_prefix(
    prefix: bytes,
    origin_ts: Optional[float],
    traceparent: Optional[str],
    digest: Optional[bytes] = None,
    trace_meta: Optional[int] = None,
) -> bytes:
    w = Writer()
    w.raw(prefix)
    _write_envelope_ext(w, origin_ts, traceparent, digest, trace_meta)
    return w.bytes()


def encode_uni_payload(
    cv: ChangeV1,
    cluster_id: ClusterId = ClusterId(0),
    digest: Optional[bytes] = None,
) -> bytes:
    """`digest` (r12): an encoded telemetry digest piggybacking the
    broadcast plane (agent/observatory.py) — rides the trailing envelope
    ext, never changes digest-free bytes."""
    return encode_uni_from_prefix(
        encode_uni_prefix(cv, cluster_id),
        cv.origin_ts,
        cv.traceparent,
        digest,
        cv.trace_meta,
    )


def decode_uni_payload_ext(
    data: bytes,
) -> Tuple[ChangeV1, ClusterId, Optional[bytes]]:
    """Like `decode_uni_payload` but also surfaces the piggybacked
    telemetry digest bytes (None when the payload carries none)."""
    r = Reader(data)
    if r.u32() != 0 or r.u32() != 0 or r.u32() != 0:
        raise ValueError("unknown UniPayload variant")
    body_start = r.pos
    cv = read_change_v1(r)
    # encode-once (r14): the receiver already holds the encoded body —
    # keep it so a relay wraps these bytes instead of re-serializing
    body = bytes(r.data[body_start : r.pos])
    cluster_id = ClusterId(r.u16()) if not r.eof() else ClusterId(0)  # default_on_eof
    origin_ts, traceparent, digest, trace_meta = _read_envelope_ext(r)
    return (
        _with_ext(cv, origin_ts, traceparent, wire_body=body,
                  trace_meta=trace_meta),
        cluster_id,
        digest,
    )


def decode_uni_payload(data: bytes) -> Tuple[ChangeV1, ClusterId]:
    cv, cluster_id, _digest = decode_uni_payload_ext(data)
    return cv, cluster_id


@dataclass(frozen=True)
class SyncTraceContext:
    traceparent: Optional[str] = None
    tracestate: Optional[str] = None


def encode_bi_payload_sync_start(
    actor_id: ActorId,
    trace: SyncTraceContext = SyncTraceContext(),
    cluster_id: ClusterId = ClusterId(0),
) -> bytes:
    w = Writer()
    w.u32(0)  # BiPayload::V1
    w.u32(0)  # BiPayloadV1::SyncStart
    w.raw(actor_id.bytes16)
    w.opt(trace.traceparent, w.string)
    w.opt(trace.tracestate, w.string)
    w.u16(cluster_id.value)
    return w.bytes()


def decode_bi_payload(data: bytes) -> Tuple[ActorId, SyncTraceContext, ClusterId]:
    r = Reader(data)
    if r.u32() != 0 or r.u32() != 0:
        raise ValueError("unknown BiPayload variant")
    actor_id = ActorId(r.raw(16))
    trace = SyncTraceContext(
        traceparent=r.opt(r.string) if not r.eof() else None,
        tracestate=r.opt(r.string) if not r.eof() else None,
    )
    cluster_id = ClusterId(r.u16()) if not r.eof() else ClusterId(0)
    return actor_id, trace, cluster_id


# -- BiPayloadV1::SnapshotReq (r17 catch-up plane, version-gated) ----------
#
# A SECOND bi-stream op beside SyncStart: a cold node requesting the
# serving peer's cached compressed snapshot (agent/catchup.py).  The
# gate is structural: variant tag 1 makes a pre-r17 server raise the
# same "unknown BiPayload variant" ValueError its serve path already
# maps to a counted, closed session — the requester reads EOF and falls
# back to pure delta sync.  New servers keep decoding tag-0 SyncStart
# frames from old clients unchanged.

_BI_SYNC_START = 0
_BI_SNAPSHOT_REQ = 1


@dataclass(frozen=True)
class SnapshotReq:
    """What a cold node sends: who it is, which cluster, and the schema
    generation it runs (the server refuses on sha mismatch instead of
    shipping an uninstallable snapshot).  `traceparent` (r19) is a
    TRAILING optional field, eof-tolerant both ways like the SyncStart
    trace context: an r17 server stops reading at cluster_id and never
    sees it; an r19 reader over an r17 frame hits eof and yields None —
    so a cold-node bootstrap stitches into one readable trace."""

    actor_id: ActorId
    schema_sha: bytes
    cluster_id: ClusterId = ClusterId(0)
    traceparent: Optional[str] = None


def encode_bi_payload_snapshot_req(req: SnapshotReq) -> bytes:
    w = Writer()
    w.u32(0)  # BiPayload::V1
    w.u32(_BI_SNAPSHOT_REQ)  # BiPayloadV1::SnapshotReq (r17)
    w.raw(req.actor_id.bytes16)
    w.vec_u8(req.schema_sha)
    w.u16(req.cluster_id.value)
    if req.traceparent is not None:
        # only written when present: r17 request bytes stay identical
        w.opt(req.traceparent, w.string)
    return w.bytes()


def decode_bi_payload_any(data: bytes):
    """Dispatching decoder for the bi-stream's first frame:
    ("sync", (actor_id, trace, cluster_id)) or ("snapshot", SnapshotReq).
    Unknown variants raise ValueError (the version gate)."""
    r = Reader(data)
    if r.u32() != 0:
        raise ValueError("unknown BiPayload version")
    tag = r.u32()
    if tag == _BI_SYNC_START:
        actor_id = ActorId(r.raw(16))
        trace = SyncTraceContext(
            traceparent=r.opt(r.string) if not r.eof() else None,
            tracestate=r.opt(r.string) if not r.eof() else None,
        )
        cluster_id = ClusterId(r.u16()) if not r.eof() else ClusterId(0)
        return "sync", (actor_id, trace, cluster_id)
    if tag == _BI_SNAPSHOT_REQ:
        actor_id = ActorId(r.raw(16))
        sha = r.vec_u8()
        cluster_id = ClusterId(r.u16()) if not r.eof() else ClusterId(0)
        traceparent = r.opt(r.string) if not r.eof() else None
        return "snapshot", SnapshotReq(
            actor_id=actor_id, schema_sha=sha, cluster_id=cluster_id,
            traceparent=traceparent,
        )
    raise ValueError("unknown BiPayload variant")


# -- Sync messages (sync.rs) ----------------------------------------------


@dataclass
class SyncState:
    """SyncStateV1: what this node has and what it's missing, per origin."""

    actor_id: ActorId
    heads: Dict[ActorId, int]
    need: Dict[ActorId, List[Tuple[int, int]]]
    partial_need: Dict[ActorId, Dict[int, List[Tuple[int, int]]]]
    last_cleared_ts: Optional[Timestamp] = None


@dataclass(frozen=True)
class NeedFull:
    versions: Tuple[int, int]

    def count(self) -> int:
        return self.versions[1] - self.versions[0] + 1


@dataclass(frozen=True)
class NeedPartial:
    version: int
    seqs: Tuple[Tuple[int, int], ...]

    def count(self) -> int:
        return 1


@dataclass(frozen=True)
class NeedEmpty:
    ts: Optional[Timestamp] = None

    def count(self) -> int:
        return 1


@dataclass(frozen=True)
class SyncRejection:
    reason: int  # 0 = MaxConcurrencyReached, 1 = DifferentCluster

    MAX_CONCURRENCY = 0
    DIFFERENT_CLUSTER = 1


# SyncMessage variants (SyncMessageV1 derived tags)
_SYNC_STATE, _SYNC_CHANGESET, _SYNC_CLOCK, _SYNC_REJECTION, _SYNC_REQUEST = range(5)


def _write_sync_state(w: Writer, st: SyncState) -> None:
    w.raw(st.actor_id.bytes16)
    w.u32(len(st.heads))
    for aid, head in st.heads.items():
        w.raw(aid.bytes16)
        w.u64(head)
    w.u64(len(st.need))  # usize in the manual impl
    for aid, ranges in st.need.items():
        w.raw(aid.bytes16)
        w.u64(len(ranges))
        for s, e in ranges:
            w.u64(s)
            w.u64(e)
    w.u64(len(st.partial_need))
    for aid, versions in st.partial_need.items():
        w.raw(aid.bytes16)
        w.u64(len(versions))
        for version, seq_ranges in versions.items():
            w.u64(version)
            w.u64(len(seq_ranges))
            for s, e in seq_ranges:
                w.u64(s)
                w.u64(e)
    w.opt(st.last_cleared_ts, lambda ts: w.u64(ts.ntp64))


def _read_sync_state(r: Reader) -> SyncState:
    actor_id = ActorId(r.raw(16))
    heads = {ActorId(r.raw(16)): r.u64() for _ in range(r.u32())}
    need = {}
    for _ in range(r.u64()):
        aid = ActorId(r.raw(16))
        need[aid] = [(r.u64(), r.u64()) for _ in range(r.u64())]
    partial_need = {}
    for _ in range(r.u64()):
        aid = ActorId(r.raw(16))
        versions = {}
        for _ in range(r.u64()):
            v = r.u64()
            versions[v] = [(r.u64(), r.u64()) for _ in range(r.u64())]
        partial_need[aid] = versions
    last_cleared_ts = r.opt(lambda: Timestamp(r.u64()))
    return SyncState(actor_id, heads, need, partial_need, last_cleared_ts)


def _write_need(w: Writer, n) -> None:
    if isinstance(n, NeedFull):
        w.u8(0)
        w.u64(n.versions[0])
        w.u64(n.versions[1])
    elif isinstance(n, NeedPartial):
        w.u8(1)
        w.u64(n.version)
        w.u64(len(n.seqs))
        for s, e in n.seqs:
            w.u64(s)
            w.u64(e)
    elif isinstance(n, NeedEmpty):
        w.u8(2)
        w.opt(n.ts, lambda ts: w.u64(ts.ntp64))
    else:
        raise TypeError(f"not a need: {n!r}")


def _read_need(r: Reader):
    tag = r.u8()
    if tag == 0:
        return NeedFull((r.u64(), r.u64()))
    if tag == 1:
        version = r.u64()
        seqs = tuple((r.u64(), r.u64()) for _ in range(r.u64()))
        return NeedPartial(version, seqs)
    if tag == 2:
        return NeedEmpty(r.opt(lambda: Timestamp(r.u64())))
    raise ValueError(f"unknown SyncNeedV1 tag {tag}")


def encode_sync_msg(msg) -> bytes:
    """msg: SyncState | ChangeV1 | Timestamp | SyncRejection | request list."""
    w = Writer()
    w.u32(0)  # SyncMessage::V1
    if isinstance(msg, SyncState):
        w.u32(_SYNC_STATE)
        _write_sync_state(w, msg)
    elif isinstance(msg, ChangeV1):
        w.u32(_SYNC_CHANGESET)
        _write_body(w, msg)  # encode-once: shared body bytes when stamped
        # next to the W3C traceparent that already rides SyncStart:
        # the origin wall stamp (freshness-gated by the sync server)
        # and, since r19, the tail-sampling trace meta
        _write_envelope_ext(
            w, msg.origin_ts, msg.traceparent, trace_meta=msg.trace_meta
        )
    elif isinstance(msg, Timestamp):
        w.u32(_SYNC_CLOCK)
        w.u64(msg.ntp64)
    elif isinstance(msg, SyncRejection):
        w.u32(_SYNC_REJECTION)
        w.u32(msg.reason)
    elif isinstance(msg, list):  # SyncRequestV1
        w.u32(_SYNC_REQUEST)
        w.u32(len(msg))
        for aid, needs in msg:
            w.raw(aid.bytes16)
            w.u32(len(needs))
            for n in needs:
                _write_need(w, n)
    else:
        raise TypeError(f"not a sync message: {msg!r}")
    return w.bytes()


def decode_sync_msg(data: bytes):
    r = Reader(data)
    if r.u32() != 0:
        raise ValueError("unknown SyncMessage version")
    tag = r.u32()
    if tag == _SYNC_STATE:
        return _read_sync_state(r)
    if tag == _SYNC_CHANGESET:
        cv = read_change_v1(r)
        origin_ts, traceparent, _digest, trace_meta = _read_envelope_ext(r)
        return _with_ext(cv, origin_ts, traceparent, trace_meta=trace_meta)
    if tag == _SYNC_CLOCK:
        return Timestamp(r.u64())
    if tag == _SYNC_REJECTION:
        return SyncRejection(r.u32())
    if tag == _SYNC_REQUEST:
        out = []
        for _ in range(r.u32()):
            aid = ActorId(r.raw(16))
            needs = [_read_need(r) for _ in range(r.u32())]
            out.append((aid, needs))
        return out
    raise ValueError(f"unknown SyncMessageV1 tag {tag}")


# -- length-delimited framing (u32 BE, tokio LengthDelimitedCodec default) --


def frame(payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME:
        raise ValueError("frame too large")
    return struct.pack(">I", len(payload)) + payload


def deframe(buf: bytes, pos: int = 0) -> Tuple[Optional[bytes], int]:
    """Try to read one frame at `pos`; returns (payload|None, new_pos)."""
    if len(buf) - pos < 4:
        return None, pos
    (n,) = struct.unpack_from(">I", buf, pos)
    if n > MAX_FRAME:
        raise ValueError("frame too large")
    if len(buf) - pos - 4 < n:
        return None, pos
    return bytes(buf[pos + 4 : pos + 4 + n]), pos + 4 + n

"""Wire types, identifiers, and codecs (counterpart of klukai-types)."""

from corrosion_tpu.types.base import DbVersion, Seq, Timestamp, HLClock
from corrosion_tpu.types.actor import ActorId, ClusterId, Actor
from corrosion_tpu.types.values import (
    SqliteValue,
    TYPE_NULL,
    TYPE_INTEGER,
    TYPE_REAL,
    TYPE_TEXT,
    TYPE_BLOB,
)
from corrosion_tpu.types.pack import pack_columns, unpack_columns
from corrosion_tpu.types.rangeset import RangeSet
from corrosion_tpu.types.change import Change, Changeset, ChangeV1, chunk_changes

__all__ = [
    "DbVersion",
    "Seq",
    "Timestamp",
    "HLClock",
    "ActorId",
    "ClusterId",
    "Actor",
    "SqliteValue",
    "TYPE_NULL",
    "TYPE_INTEGER",
    "TYPE_REAL",
    "TYPE_TEXT",
    "TYPE_BLOB",
    "pack_columns",
    "unpack_columns",
    "RangeSet",
    "Change",
    "Changeset",
    "ChangeV1",
    "chunk_changes",
]

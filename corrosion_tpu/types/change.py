"""The CRDT change unit and changesets.

Counterpart of `klukai-types/src/change.rs` (Change, ChunkedChanges,
MAX_CHANGES_BYTE_SIZE) and the Changeset/ChangeV1 wire enums from
`klukai-types/src/broadcast.rs:98-283`.

A `Change` is one column-level CRDT delta: a (table, pk, column) cell with
its value and clock metadata. `cl` is the causal length of the row: odd =
alive, even = deleted. Row create/delete travels as a change whose cid is
the `SENTINEL` column id ("-1"); a sentinel change with even cl is a delete.
A version's changes are sequenced 0..=last_seq; changesets may carry a
sub-range (partial version) — receivers buffer partials until the seq range
closes (reference `agent/util.rs:1070-1203`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional, Tuple

from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.values import SqliteValue

# cr-sqlite sentinel column id (observable in crsql_changes rows; the
# reference checks `ColumnName::is_crsql_sentinel` == "-1", api.rs:790).
# A sentinel change tracks row create/delete: its row's causal length `cl`
# is odd while alive, even when deleted.
SENTINEL = "-1"

MAX_CHANGES_BYTE_SIZE = 8 * 1024  # change.rs:179


@dataclass(frozen=True)
class Change:
    table: str
    pk: bytes  # pack_columns-encoded primary key
    cid: str  # column name or sentinel
    val: SqliteValue
    col_version: int
    db_version: int
    seq: int
    site_id: bytes  # 16 bytes == ActorId
    cl: int  # causal length (odd=alive, even=deleted)
    ts: Timestamp = field(default=Timestamp(0), compare=False)
    # r15 fused encode: this change's speedy cell bytes (the exact
    # `write_change` output), built in the SAME pass that emits the
    # Change at local commit (`CrdtStore.finalize_group`), so every
    # changeset encode splices cached bytes instead of re-walking the
    # values.  Pure cache: never part of identity, never required.
    wire_cell: Optional[bytes] = field(
        default=None, compare=False, repr=False
    )

    def estimated_byte_size(self) -> int:
        # change.rs:34-52: rough wire-size estimate
        val_sz = (
            len(self.val)
            if isinstance(self.val, (str, bytes))
            else 8
            if self.val is not None
            else 0
        )
        return len(self.table) + len(self.pk) + len(self.cid) + val_sz + 8 * 5 + 16

    def is_sentinel(self) -> bool:
        return self.cid == SENTINEL

    def is_delete(self) -> bool:
        return self.cid == SENTINEL and self.cl % 2 == 0


@dataclass(frozen=True)
class ChangesetEmpty:
    """Versions known to carry no changes (cleared/compacted)."""

    versions: Tuple[int, int]  # inclusive range
    ts: Optional[Timestamp] = None


@dataclass(frozen=True)
class ChangesetEmptySet:
    versions: Tuple[Tuple[int, int], ...]
    ts: Timestamp = Timestamp(0)


@dataclass(frozen=True)
class ChangesetFull:
    version: int
    changes: Tuple[Change, ...]
    seqs: Tuple[int, int]  # inclusive seq range carried here
    last_seq: int  # final seq of the full version
    ts: Timestamp = Timestamp(0)

    def is_complete(self) -> bool:
        return self.seqs == (0, self.last_seq)

    def is_empty(self) -> bool:
        return not self.changes


Changeset = object  # union: ChangesetEmpty | ChangesetEmptySet | ChangesetFull


@dataclass(frozen=True)
class ChangeV1:
    actor_id: ActorId
    changeset: object  # Changeset union
    # r11 latency-plane envelope metadata (compare=False: identity is
    # the change content; these ride the version-gated trailing ext of
    # the broadcast/sync envelopes — types/codec.py — and old peers
    # simply never see them).  `origin_ts` is the wall clock at the
    # ORIGIN node's commit, the stamp every corro.e2e.* stage histogram
    # measures against; `traceparent` stitches cross-node spans on the
    # eager broadcast path (sync already carries one in SyncStart).
    origin_ts: Optional[float] = field(default=None, compare=False)
    traceparent: Optional[str] = field(default=None, compare=False)
    # r19 tail-sampling trace meta (one byte on the wire, the envelope
    # ext v3 gate): bit 0 = forced-keep — the ORIGIN's head decision
    # (lottery win) so every node on the path keeps the same trace
    # without coordination; bits 2..7 = relay hop count, bumped by the
    # re-broadcast path.  Bit layout owned by runtime/trace.py.
    trace_meta: Optional[int] = field(default=None, compare=False)
    # r14 encode-once: the speedy-encoded `actor_id + changeset` body
    # (types/codec.py `encode_change_v1_body`).  Stamped ONCE at local
    # commit and on broadcast decode (the receiver already holds the
    # bytes), then reused verbatim by every uni/sync encode instead of
    # re-serializing the changeset per transmission/relay.  Pure cache:
    # never part of identity, never required to be present.
    wire_body: Optional[bytes] = field(
        default=None, compare=False, repr=False
    )

    @property
    def versions(self) -> Tuple[int, int]:
        cs = self.changeset
        if isinstance(cs, ChangesetFull):
            return (cs.version, cs.version)
        if isinstance(cs, ChangesetEmpty):
            return cs.versions
        raise TypeError("EmptySet has multiple ranges")


def chunk_changes(
    changes: Iterable[Change],
    last_seq: int,
    max_bytes: int = MAX_CHANGES_BYTE_SIZE,
    max_bytes_fn=None,
    range_start: int = 0,
) -> Iterator[Tuple[List[Change], Tuple[int, int]]]:
    """Group ordered same-version changes into chunks of ≤ max_bytes,
    preserving contiguous seq coverage across gaps (change.rs:65-177):
    each emitted seq range starts where the previous ended + 1, and the
    final range extends to `last_seq`.

    `max_bytes_fn`, when given, is consulted per chunk — the sync
    server's adaptive sizing (halve on slow sends, regrow ×1.5;
    peer/mod.rs:808-869) shrinks or grows the target between chunks of
    the same version.

    `range_start` (r16): where the FIRST chunk's claimed seq coverage
    begins — 0 for a complete version (the default), or the source
    changeset's own `seqs[0]` when re-chunking an already-partial
    changeset (broadcast oversize splitting): a partial must never claim
    coverage of seqs it does not carry.

    Yields (chunk, (seq_start, seq_end)).
    """
    buf: List[Change] = []
    size = 0
    last_emitted_end: Optional[int] = None
    it = iter(changes)
    for ch in it:
        buf.append(ch)
        size += ch.estimated_byte_size()
        if size >= (max_bytes_fn() if max_bytes_fn is not None else max_bytes):
            end = buf[-1].seq
            yield buf, (range_start, end)
            last_emitted_end = end
            range_start = end + 1
            buf, size = [], 0
    if buf:
        yield buf, (range_start, last_seq)
    elif last_emitted_end is not None and last_emitted_end < last_seq:
        yield [], (range_start, last_seq)
    elif last_emitted_end is None:
        # no changes at all: single empty full range
        yield [], (range_start, last_seq)

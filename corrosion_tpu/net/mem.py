"""In-memory network: loopback-free delivery between in-process nodes.

The reference exercises multi-node behavior with real loopback QUIC in one
process (`klukai-tests/src/lib.rs:63-89`); our equivalent removes the
kernel from the loop entirely: a `MemNetwork` routes datagrams/streams
between registered nodes with optional per-link latency, loss and
partitions — the fault-injection surface the reference delegates to
Antithesis. The same network object is the seam where TPU-simulated member
blocks (corrosion_tpu.models.cluster) ARE bridged in as virtual peers:
`models/bridge.KernelPeerBridge` (tests/test_bridge.py runs a real agent
against a kernel-simulated population end-to-end).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

from corrosion_tpu.net.transport import (
    BiHandler,
    BiStream,
    DatagramHandler,
    Listener,
    Transport,
    TransportError,
    UniHandler,
)

log = logging.getLogger(__name__)

MAX_DATAGRAM = 1452  # quinn datagram ceiling on typical MTU


def _spawn_logged(coro, what: str, src: str, dst: str) -> None:
    """Detached handler delivery that is LOUD on failure: a silent 'Task
    exception was never retrieved' once hid a broken FEED path as a 4x
    convergence slowdown. Shared by all three lanes."""

    async def run():
        try:
            await coro
        except Exception:  # noqa: BLE001
            log.exception("%s handler failed (%s -> %s)", what, src, dst)

    asyncio.ensure_future(run())


@dataclass
class LinkFaults:
    """Per-network fault knobs (applied to every link unless partitioned),
    plus r9 per-NODE asymmetric knobs keyed by addr — the degraded-node
    scenario (one slow/lossy peer among healthy ones) that network-global
    loss/latency cannot express.  Per-node knobs apply to the node's
    OUTBOUND traffic: a degraded node's sends are slow/lossy/duplicated
    while traffic TO it flows normally — the asymmetry Lifeguard
    (arXiv:1707.00788) exploits.  Loss/duplication hit datagrams only
    (streams stay reliable, like real UDP vs TCP); node_latency also
    slows the node's uni/bi stream sends."""

    latency: float = 0.0  # one-way delay seconds
    jitter: float = 0.0
    datagram_loss: float = 0.0  # [0,1) — datagrams only; streams are reliable
    node_latency: Dict[str, float] = field(default_factory=dict)
    # addr -> extra one-way delay (s) on everything the node sends
    node_datagram_loss: Dict[str, float] = field(default_factory=dict)
    # addr -> outbound datagram loss [0,1]; combines with the global
    # loss as max(global, node) — one effective per-datagram probability
    node_duplicate: Dict[str, float] = field(default_factory=dict)
    # addr -> probability an outbound datagram is delivered TWICE
    # (dup-prone NIC/retry pathology; exercises SWIM idempotency)
    link_latency: Dict[Tuple[str, str], float] = field(default_factory=dict)
    # (src, dst) -> extra one-way delay (s) on that DIRECTED link — the
    # geo-latency matrix (r18 chaos): per-region RTTs that neither the
    # global nor the per-node knob can express (a node is "far" from
    # some peers and "near" others).  Composes additively with both.


class _MemBiStream(BiStream):
    def __init__(self, peer_addr: str, net: "MemNetwork"):
        self._peer = peer_addr
        self._net = net
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._closed = False
        self.other: Optional["_MemBiStream"] = None

    async def send(self, payload: bytes) -> None:
        if self._closed or self.other is None:
            raise TransportError("stream closed")
        # the sending side's own addr is the remote end's peer label
        await self._net._delay(self.other._peer, self._peer)
        if self._net._stalled(self.other._peer, self._peer):
            # zombie endpoint: the payload sits in a kernel buffer no
            # stalled event loop will ever read — send() "succeeds",
            # nothing is delivered, the peer's recv() hangs
            return
        self.other._inbox.put_nowait(payload)

    async def recv(self) -> Optional[bytes]:
        item = await self._inbox.get()
        if item is _EOF:
            return None
        return item

    async def finish(self) -> None:
        if self.other is not None and not self.other._closed:
            self.other._inbox.put_nowait(_EOF)

    def close(self) -> None:
        self._closed = True
        self._inbox.put_nowait(_EOF)
        if self.other is not None and not self.other._closed:
            self.other._closed = True
            self.other._inbox.put_nowait(_EOF)

    @property
    def peer(self) -> str:
        return self._peer


_EOF = object()


@dataclass
class _Node:
    on_datagram: DatagramHandler
    on_uni: UniHandler
    on_bi: BiHandler


class MemNetwork:
    """Registry + router. One per simulated cluster."""

    def __init__(self, seed: int = 0, faults: Optional[LinkFaults] = None):
        self._nodes: Dict[str, _Node] = {}
        self._rng = random.Random(seed)
        self.faults = faults or LinkFaults()
        self._partitions: Set[Tuple[str, str]] = set()
        self._down: Set[str] = set()
        self._zombies: Set[str] = set()

    # -- topology faults --------------------------------------------------

    def partition(self, a: str, b: str) -> None:
        self._partitions.add((a, b))
        self._partitions.add((b, a))

    def partition_oneway(self, a: str, b: str) -> None:
        """Asymmetric partition (r18 chaos): a's traffic to b is dropped
        while b still reaches a — the half-open link that makes b keep
        believing a is fine (b's probes go unanswered only one way)."""
        self._partitions.add((a, b))

    def heal(self, a: str, b: str) -> None:
        self._partitions.discard((a, b))
        self._partitions.discard((b, a))

    def take_down(self, addr: str) -> None:
        """Simulate a crashed node: all delivery to it fails."""
        self._down.add(addr)

    def bring_up(self, addr: str) -> None:
        self._down.discard(addr)

    def degrade(
        self,
        addr: str,
        latency: float = 0.0,
        datagram_loss: float = 0.0,
        duplicate: float = 0.0,
    ) -> None:
        """Mark one node flaky WITHOUT taking it down: its outbound
        traffic gets `latency` extra delay, datagrams drop with
        `datagram_loss` and duplicate with `duplicate` (see LinkFaults
        per-node knobs)."""
        self.faults.node_latency[addr] = latency
        self.faults.node_datagram_loss[addr] = datagram_loss
        self.faults.node_duplicate[addr] = duplicate

    def restore(self, addr: str) -> None:
        """Clear a node's degradation (including zombie state)."""
        self.faults.node_latency.pop(addr, None)
        self.faults.node_datagram_loss.pop(addr, None)
        self.faults.node_duplicate.pop(addr, None)
        self._zombies.discard(addr)

    def zombie(self, addr: str) -> None:
        """Mark a node a ZOMBIE (r18 chaos): its process looks alive at
        the transport layer — connections are accepted, streams open,
        sends land in its kernel buffers — but its event loop is stalled,
        so no handler ever runs and no byte ever comes back.  Distinct
        from `take_down` (connection refused) and `degrade` (slow but
        answering): the zombie is the peer that makes unbounded
        `await stream.recv()` hang forever — the bug class the
        timeout-discipline rule exists for.  Cleared by `restore`."""
        self._zombies.add(addr)

    def is_zombie(self, addr: str) -> bool:
        return addr in self._zombies

    def set_link_latency(
        self, a: str, b: str, secs: float, symmetric: bool = True
    ) -> None:
        """Set the geo-matrix delay of the a→b link (and b→a when
        symmetric).  0 clears the entry."""
        for pair in ((a, b), (b, a)) if symmetric else ((a, b),):
            if secs > 0:
                self.faults.link_latency[pair] = secs
            else:
                self.faults.link_latency.pop(pair, None)

    def clear_link_latency(self) -> None:
        self.faults.link_latency.clear()

    def _reachable(self, src: str, dst: str) -> bool:
        if dst in self._down or src in self._down:
            return False
        if (src, dst) in self._partitions:
            return False
        return dst in self._nodes

    def _stalled(self, src: str, dst: str) -> bool:
        """True when delivery src→dst must be silently withheld because
        one endpoint is a zombie: a stalled receiver never drains its
        socket, a stalled sender never writes to its own."""
        return src in self._zombies or dst in self._zombies

    async def _delay(
        self, src: Optional[str] = None, dst: Optional[str] = None
    ) -> None:
        f = self.faults
        extra = f.node_latency.get(src, 0.0) if src else 0.0
        if src and dst:
            extra += f.link_latency.get((src, dst), 0.0)
        if f.latency or f.jitter or extra:
            await asyncio.sleep(
                f.latency + extra + self._rng.random() * f.jitter
            )
        else:
            await asyncio.sleep(0)

    # -- node registration -------------------------------------------------

    def listener(self, addr: str) -> "MemListener":
        return MemListener(addr, self)

    def transport(self, addr: str) -> "MemTransport":
        return MemTransport(addr, self)


class MemListener(Listener):
    def __init__(self, addr: str, net: MemNetwork):
        self._addr = addr
        self._net = net

    def serve(self, on_datagram, on_uni, on_bi) -> None:
        self._net._nodes[self._addr] = _Node(on_datagram, on_uni, on_bi)

    @property
    def addr(self) -> str:
        return self._addr

    async def close(self) -> None:
        self._net._nodes.pop(self._addr, None)


class MemTransport(Transport):
    def __init__(self, src: str, net: MemNetwork):
        self._src = src
        self._net = net

    async def send_datagram(self, addr: str, data: bytes) -> None:
        if len(data) > MAX_DATAGRAM:
            raise TransportError(f"datagram too large: {len(data)}")
        net = self._net
        if not net._reachable(self._src, addr):
            return  # datagrams are fire-and-forget: silent loss
        # one effective loss probability: global iid floor raised by the
        # sender's per-node outbound loss (degraded-node asymmetry)
        loss = max(
            net.faults.datagram_loss,
            net.faults.node_datagram_loss.get(self._src, 0.0),
        )
        if loss and net._rng.random() < loss:
            return
        if net._stalled(self._src, addr):
            return  # zombie endpoint: datagrams die in a stalled socket
        node = net._nodes[addr]
        src = self._src

        async def deliver():
            await net._delay(src, addr)
            await node.on_datagram(src, data)

        # detached delivery like real UDP: the sender never blocks on the
        # receiver's handler (RTT is observed by the SWIM ack path instead)
        _spawn_logged(deliver(), "datagram", self._src, addr)
        dup = net.faults.node_duplicate.get(self._src, 0.0)
        if dup and net._rng.random() < dup:

            async def deliver_again():
                await net._delay(src, addr)
                await node.on_datagram(src, data)

            _spawn_logged(deliver_again(), "datagram-dup", self._src, addr)

    async def send_uni(self, addr: str, payload: bytes) -> None:
        net = self._net
        if not net._reachable(self._src, addr):
            raise TransportError(f"unreachable: {addr}")
        node = net._nodes[addr]
        start = time.monotonic()
        await net._delay(self._src, addr)
        if net._stalled(self._src, addr):
            # zombie endpoint: the stream opens (no error — the kernel
            # accepts), the payload is never read
            return
        # deliver as an independent task, like a uni-stream read loop
        _spawn_logged(node.on_uni(self._src, payload), "uni", self._src, addr)
        self.observe_rtt(addr, 2 * (time.monotonic() - start))

    async def open_bi(self, addr: str) -> BiStream:
        net = self._net
        if not net._reachable(self._src, addr):
            raise TransportError(f"unreachable: {addr}")
        node = net._nodes[addr]
        local = _MemBiStream(addr, net)
        remote = _MemBiStream(self._src, net)
        local.other, remote.other = remote, local
        await net._delay(self._src, addr)
        if net._stalled(self._src, addr):
            # zombie endpoint: the TCP/QUIC handshake is answered by the
            # kernel of the stalled process, so open_bi SUCCEEDS — but
            # the application handler never runs.  The caller gets a
            # stream that accepts sends and never answers: exactly the
            # peer shape that must trip recv deadlines + the PeerCircuit
            # breaker, never stall a sync round.
            return local
        _spawn_logged(node.on_bi(remote), "bi", self._src, addr)
        return local

"""In-memory network: loopback-free delivery between in-process nodes.

The reference exercises multi-node behavior with real loopback QUIC in one
process (`klukai-tests/src/lib.rs:63-89`); our equivalent removes the
kernel from the loop entirely: a `MemNetwork` routes datagrams/streams
between registered nodes with optional per-link latency, loss and
partitions — the fault-injection surface the reference delegates to
Antithesis. The same network object is the seam where TPU-simulated member
blocks (corrosion_tpu.models.cluster) ARE bridged in as virtual peers:
`models/bridge.KernelPeerBridge` (tests/test_bridge.py runs a real agent
against a kernel-simulated population end-to-end).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

from corrosion_tpu.net.transport import (
    BiHandler,
    BiStream,
    DatagramHandler,
    Listener,
    Transport,
    TransportError,
    UniHandler,
)

log = logging.getLogger(__name__)

MAX_DATAGRAM = 1452  # quinn datagram ceiling on typical MTU


def _spawn_logged(coro, what: str, src: str, dst: str) -> None:
    """Detached handler delivery that is LOUD on failure: a silent 'Task
    exception was never retrieved' once hid a broken FEED path as a 4x
    convergence slowdown. Shared by all three lanes."""

    async def run():
        try:
            await coro
        except Exception:  # noqa: BLE001
            log.exception("%s handler failed (%s -> %s)", what, src, dst)

    asyncio.ensure_future(run())


@dataclass
class LinkFaults:
    """Per-network fault knobs (applied to every link unless partitioned)."""

    latency: float = 0.0  # one-way delay seconds
    jitter: float = 0.0
    datagram_loss: float = 0.0  # [0,1) — datagrams only; streams are reliable


class _MemBiStream(BiStream):
    def __init__(self, peer_addr: str, net: "MemNetwork"):
        self._peer = peer_addr
        self._net = net
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._closed = False
        self.other: Optional["_MemBiStream"] = None

    async def send(self, payload: bytes) -> None:
        if self._closed or self.other is None:
            raise TransportError("stream closed")
        await self._net._delay()
        self.other._inbox.put_nowait(payload)

    async def recv(self) -> Optional[bytes]:
        item = await self._inbox.get()
        if item is _EOF:
            return None
        return item

    async def finish(self) -> None:
        if self.other is not None and not self.other._closed:
            self.other._inbox.put_nowait(_EOF)

    def close(self) -> None:
        self._closed = True
        self._inbox.put_nowait(_EOF)
        if self.other is not None and not self.other._closed:
            self.other._closed = True
            self.other._inbox.put_nowait(_EOF)

    @property
    def peer(self) -> str:
        return self._peer


_EOF = object()


@dataclass
class _Node:
    on_datagram: DatagramHandler
    on_uni: UniHandler
    on_bi: BiHandler


class MemNetwork:
    """Registry + router. One per simulated cluster."""

    def __init__(self, seed: int = 0, faults: Optional[LinkFaults] = None):
        self._nodes: Dict[str, _Node] = {}
        self._rng = random.Random(seed)
        self.faults = faults or LinkFaults()
        self._partitions: Set[Tuple[str, str]] = set()
        self._down: Set[str] = set()

    # -- topology faults --------------------------------------------------

    def partition(self, a: str, b: str) -> None:
        self._partitions.add((a, b))
        self._partitions.add((b, a))

    def heal(self, a: str, b: str) -> None:
        self._partitions.discard((a, b))
        self._partitions.discard((b, a))

    def take_down(self, addr: str) -> None:
        """Simulate a crashed node: all delivery to it fails."""
        self._down.add(addr)

    def bring_up(self, addr: str) -> None:
        self._down.discard(addr)

    def _reachable(self, src: str, dst: str) -> bool:
        if dst in self._down or src in self._down:
            return False
        if (src, dst) in self._partitions:
            return False
        return dst in self._nodes

    async def _delay(self) -> None:
        f = self.faults
        if f.latency or f.jitter:
            await asyncio.sleep(f.latency + self._rng.random() * f.jitter)
        else:
            await asyncio.sleep(0)

    # -- node registration -------------------------------------------------

    def listener(self, addr: str) -> "MemListener":
        return MemListener(addr, self)

    def transport(self, addr: str) -> "MemTransport":
        return MemTransport(addr, self)


class MemListener(Listener):
    def __init__(self, addr: str, net: MemNetwork):
        self._addr = addr
        self._net = net

    def serve(self, on_datagram, on_uni, on_bi) -> None:
        self._net._nodes[self._addr] = _Node(on_datagram, on_uni, on_bi)

    @property
    def addr(self) -> str:
        return self._addr

    async def close(self) -> None:
        self._net._nodes.pop(self._addr, None)


class MemTransport(Transport):
    def __init__(self, src: str, net: MemNetwork):
        self._src = src
        self._net = net

    async def send_datagram(self, addr: str, data: bytes) -> None:
        if len(data) > MAX_DATAGRAM:
            raise TransportError(f"datagram too large: {len(data)}")
        net = self._net
        if not net._reachable(self._src, addr):
            return  # datagrams are fire-and-forget: silent loss
        if net.faults.datagram_loss and net._rng.random() < net.faults.datagram_loss:
            return
        node = net._nodes[addr]

        async def deliver():
            await net._delay()
            await node.on_datagram(self._src, data)

        # detached delivery like real UDP: the sender never blocks on the
        # receiver's handler (RTT is observed by the SWIM ack path instead)
        _spawn_logged(deliver(), "datagram", self._src, addr)

    async def send_uni(self, addr: str, payload: bytes) -> None:
        net = self._net
        if not net._reachable(self._src, addr):
            raise TransportError(f"unreachable: {addr}")
        node = net._nodes[addr]
        start = time.monotonic()
        await net._delay()
        # deliver as an independent task, like a uni-stream read loop
        _spawn_logged(node.on_uni(self._src, payload), "uni", self._src, addr)
        self.observe_rtt(addr, 2 * (time.monotonic() - start))

    async def open_bi(self, addr: str) -> BiStream:
        net = self._net
        if not net._reachable(self._src, addr):
            raise TransportError(f"unreachable: {addr}")
        node = net._nodes[addr]
        local = _MemBiStream(addr, net)
        remote = _MemBiStream(self._src, net)
        local.other, remote.other = remote, local
        await net._delay()
        _spawn_logged(node.on_bi(remote), "bi", self._src, addr)
        return local

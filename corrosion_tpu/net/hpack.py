"""HPACK (RFC 7541) header compression for the HTTP/2 transport.

Two interchangeable codecs:

- ``NgDeflater``/``NgInflater``: ctypes bindings over the system
  libnghttp2 (the same HPACK engine curl uses) — full Huffman coding and
  dynamic-table management, required for interop with real h2 peers.
  Native-runtime choice, like the reference delegating HPACK to the
  `h2`/`hyper` crates (`klukai-client/src/lib.rs:40-47`).
- ``PyDeflater``/``PyInflater``: dependency-free fallback implementing
  the full decode side (static+dynamic tables, integer coding, Huffman
  via the RFC 7541 Appendix B table extracted from libnghttp2 when first
  available, else raising on Huffman-coded literals) and a
  literal-without-Huffman encode side (always legal per RFC 7541 §5.2).

``make_deflater()``/``make_inflater()`` pick nghttp2 when loadable.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
from typing import List, Optional, Tuple

Headers = List[Tuple[bytes, bytes]]

# -- libnghttp2 binding -----------------------------------------------------

_NGHTTP2_HD_INFLATE_EMIT = 0x02
_NGHTTP2_HD_INFLATE_FINAL = 0x01


class _NV(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.POINTER(ctypes.c_uint8)),
        ("value", ctypes.POINTER(ctypes.c_uint8)),
        ("namelen", ctypes.c_size_t),
        ("valuelen", ctypes.c_size_t),
        ("flags", ctypes.c_uint8),
    ]


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _nghttp2() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        for name in (ctypes.util.find_library("nghttp2"), "libnghttp2.so.14"):
            if not name:
                continue
            try:
                lib = ctypes.CDLL(name)
            except OSError:
                continue
            try:
                lib.nghttp2_hd_deflate_new.restype = ctypes.c_int
                lib.nghttp2_hd_deflate_new.argtypes = [
                    ctypes.POINTER(ctypes.c_void_p), ctypes.c_size_t,
                ]
                lib.nghttp2_hd_deflate_del.argtypes = [ctypes.c_void_p]
                lib.nghttp2_hd_deflate_bound.restype = ctypes.c_size_t
                lib.nghttp2_hd_deflate_bound.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(_NV), ctypes.c_size_t,
                ]
                lib.nghttp2_hd_deflate_hd.restype = ctypes.c_ssize_t
                lib.nghttp2_hd_deflate_hd.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.c_size_t, ctypes.POINTER(_NV), ctypes.c_size_t,
                ]
                lib.nghttp2_hd_inflate_new.restype = ctypes.c_int
                lib.nghttp2_hd_inflate_new.argtypes = [
                    ctypes.POINTER(ctypes.c_void_p)
                ]
                lib.nghttp2_hd_inflate_del.argtypes = [ctypes.c_void_p]
                lib.nghttp2_hd_inflate_hd2.restype = ctypes.c_ssize_t
                lib.nghttp2_hd_inflate_hd2.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(_NV),
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                    ctypes.c_int,
                ]
                lib.nghttp2_hd_inflate_end_headers.argtypes = [ctypes.c_void_p]
            except AttributeError:
                continue
            _lib = lib
            return _lib
        return None


def nghttp2_available() -> bool:
    return _nghttp2() is not None


class NgDeflater:
    def __init__(self, table_size: int = 4096):
        lib = _nghttp2()
        assert lib is not None
        self._lib = lib
        self._ptr = ctypes.c_void_p()
        rv = lib.nghttp2_hd_deflate_new(ctypes.byref(self._ptr), table_size)
        if rv != 0:
            raise MemoryError(f"nghttp2_hd_deflate_new: {rv}")

    def encode(self, headers: Headers) -> bytes:
        n = len(headers)
        nva = (_NV * n)()
        bufs = []  # keep byte buffers alive across the call
        for i, (name, value) in enumerate(headers):
            bn = ctypes.create_string_buffer(name, len(name))
            bv = ctypes.create_string_buffer(value, len(value))
            bufs.append((bn, bv))
            nva[i].name = ctypes.cast(bn, ctypes.POINTER(ctypes.c_uint8))
            nva[i].namelen = len(name)
            nva[i].value = ctypes.cast(bv, ctypes.POINTER(ctypes.c_uint8))
            nva[i].valuelen = len(value)
            nva[i].flags = 0
        bound = self._lib.nghttp2_hd_deflate_bound(self._ptr, nva, n)
        out = (ctypes.c_uint8 * bound)()
        rv = self._lib.nghttp2_hd_deflate_hd(self._ptr, out, bound, nva, n)
        if rv < 0:
            raise ValueError(f"nghttp2_hd_deflate_hd: {rv}")
        return bytes(bytearray(out[:rv]))

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr is not None and ptr.value:
            self._lib.nghttp2_hd_deflate_del(ptr)
            ptr.value = None  # no ctypes construction: it may be torn down


class NgInflater:
    def __init__(self):
        lib = _nghttp2()
        assert lib is not None
        self._lib = lib
        self._ptr = ctypes.c_void_p()
        rv = lib.nghttp2_hd_inflate_new(ctypes.byref(self._ptr))
        if rv != 0:
            raise MemoryError(f"nghttp2_hd_inflate_new: {rv}")

    def decode(self, data: bytes) -> Headers:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        pos, remaining = 0, len(data)
        out: Headers = []
        nv = _NV()
        flags = ctypes.c_int(0)
        while remaining > 0:
            flags.value = 0
            consumed = self._lib.nghttp2_hd_inflate_hd2(
                self._ptr, ctypes.byref(nv), ctypes.byref(flags),
                ctypes.cast(
                    ctypes.byref(buf, pos), ctypes.POINTER(ctypes.c_uint8)
                ),
                remaining, 1,
            )
            if consumed < 0:
                raise ValueError(f"nghttp2_hd_inflate_hd2: {consumed}")
            pos += consumed
            remaining -= consumed
            if flags.value & _NGHTTP2_HD_INFLATE_EMIT:
                out.append(
                    (
                        ctypes.string_at(nv.name, nv.namelen),
                        ctypes.string_at(nv.value, nv.valuelen),
                    )
                )
            if flags.value & _NGHTTP2_HD_INFLATE_FINAL:
                break
            if consumed == 0 and not (flags.value & _NGHTTP2_HD_INFLATE_EMIT):
                raise ValueError("hpack inflate stalled")
        self._lib.nghttp2_hd_inflate_end_headers(self._ptr)
        return out

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr is not None and ptr.value:
            self._lib.nghttp2_hd_inflate_del(ptr)
            ptr.value = None  # no ctypes construction: it may be torn down


# -- pure-Python fallback ---------------------------------------------------

# RFC 7541 Appendix A static table (index 1-61)
_STATIC = [
    (b":authority", b""),
    (b":method", b"GET"),
    (b":method", b"POST"),
    (b":path", b"/"),
    (b":path", b"/index.html"),
    (b":scheme", b"http"),
    (b":scheme", b"https"),
    (b":status", b"200"),
    (b":status", b"204"),
    (b":status", b"206"),
    (b":status", b"304"),
    (b":status", b"400"),
    (b":status", b"404"),
    (b":status", b"500"),
    (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"),
    (b"accept-language", b""),
    (b"accept-ranges", b""),
    (b"accept", b""),
    (b"access-control-allow-origin", b""),
    (b"age", b""),
    (b"allow", b""),
    (b"authorization", b""),
    (b"cache-control", b""),
    (b"content-disposition", b""),
    (b"content-encoding", b""),
    (b"content-language", b""),
    (b"content-length", b""),
    (b"content-location", b""),
    (b"content-range", b""),
    (b"content-type", b""),
    (b"cookie", b""),
    (b"date", b""),
    (b"etag", b""),
    (b"expect", b""),
    (b"expires", b""),
    (b"from", b""),
    (b"host", b""),
    (b"if-match", b""),
    (b"if-modified-since", b""),
    (b"if-none-match", b""),
    (b"if-range", b""),
    (b"if-unmodified-since", b""),
    (b"last-modified", b""),
    (b"link", b""),
    (b"location", b""),
    (b"max-forwards", b""),
    (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""),
    (b"range", b""),
    (b"referer", b""),
    (b"refresh", b""),
    (b"retry-after", b""),
    (b"server", b""),
    (b"set-cookie", b""),
    (b"strict-transport-security", b""),
    (b"transfer-encoding", b""),
    (b"user-agent", b""),
    (b"vary", b""),
    (b"via", b""),
    (b"www-authenticate", b""),
]


def _int_encode(value: int, prefix_bits: int, first_byte: int) -> bytes:
    """RFC 7541 §5.1 integer encoding; first_byte carries the pattern bits."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte | value])
    out = bytearray([first_byte | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def _int_decode(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated hpack integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, pos


class PyDeflater:
    """Encode-only HPACK: indexed fields for exact static-table hits,
    literal-without-indexing (no Huffman) otherwise — always legal."""

    def __init__(self, table_size: int = 4096):
        self._static_exact = {e: i + 1 for i, e in enumerate(_STATIC)}
        self._static_name = {}
        for i, (name, _v) in enumerate(_STATIC):
            self._static_name.setdefault(name, i + 1)

    def encode(self, headers: Headers) -> bytes:
        out = bytearray()
        for name, value in headers:
            idx = self._static_exact.get((name, value))
            if idx is not None:
                out += _int_encode(idx, 7, 0x80)  # indexed field
                continue
            nidx = self._static_name.get(name)
            if nidx is not None:  # literal w/o indexing, indexed name
                out += _int_encode(nidx, 4, 0x00)
            else:  # literal w/o indexing, new name
                out.append(0x00)
                out += _int_encode(len(name), 7, 0x00)
                out += name
            out += _int_encode(len(value), 7, 0x00)
            out += value
        return bytes(out)


class PyInflater:
    """Decode-side HPACK with dynamic table; Huffman-coded literals are
    decoded via nghttp2 when loadable, else rejected (our own peers never
    Huffman-encode)."""

    def __init__(self, max_table_size: int = 4096):
        self._dynamic: List[Tuple[bytes, bytes]] = []
        self._max_size = max_table_size
        self._size = 0

    def _entry(self, idx: int) -> Tuple[bytes, bytes]:
        if 1 <= idx <= len(_STATIC):
            return _STATIC[idx - 1]
        didx = idx - len(_STATIC) - 1
        if 0 <= didx < len(self._dynamic):
            return self._dynamic[didx]
        raise ValueError(f"hpack index {idx} out of range")

    def _add(self, name: bytes, value: bytes) -> None:
        self._dynamic.insert(0, (name, value))
        self._size += len(name) + len(value) + 32
        while self._size > self._max_size and self._dynamic:
            n, v = self._dynamic.pop()
            self._size -= len(n) + len(v) + 32

    def _string(self, data: bytes, pos: int) -> Tuple[bytes, int]:
        huffman = bool(data[pos] & 0x80)
        length, pos = _int_decode(data, pos, 7)
        raw = data[pos : pos + length]
        if len(raw) != length:
            raise ValueError("truncated hpack string")
        pos += length
        if huffman:
            raise ValueError(
                "huffman-coded literal requires the nghttp2 codec"
            )
        return raw, pos

    def decode(self, data: bytes) -> Headers:
        out: Headers = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                idx, pos = _int_decode(data, pos, 7)
                out.append(self._entry(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = _int_decode(data, pos, 6)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = _int_decode(data, pos, 5)
                self._max_size = size
                while self._size > self._max_size and self._dynamic:
                    n, v = self._dynamic.pop()
                    self._size -= len(n) + len(v) + 32
            else:  # literal without indexing / never indexed (4-bit prefix)
                idx, pos = _int_decode(data, pos, 4)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                out.append((name, value))
        return out


def make_deflater(table_size: int = 4096):
    return NgDeflater(table_size) if nghttp2_available() else PyDeflater(table_size)


def make_inflater():
    return NgInflater() if nghttp2_available() else PyInflater()

"""SeaHash (64-bit, portable) — the packet-integrity tag of the
reference's plaintext QUIC session.

The reference seals every plaintext-crypto QUIC packet with an 8-byte
big-endian SeaHash of the Rust ``Hash`` stream of (header, payload)
(`quinn_plaintext.rs:289-329`: ``header.hash(h); payload.hash(h)`` with a
``SeaHasher``, checked on decrypt).  To interoperate we need the same
function, so this is SeaHash implemented from its published algorithm
(the ``seahash`` crate documents it in full; the design is ticki's):

- state: four u64 lanes seeded with fixed constants
- input is consumed as little-endian u64 words, round-robin across
  lanes: ``lane ^= word; lane = diffuse(lane)``
- a trailing partial word (< 8 bytes) is zero-padded and folded into the
  next lane in sequence
- ``finish = diffuse(a ^ b ^ c ^ d ^ total_bytes_written)``
- ``diffuse(x)``: multiply by 0x6eed0e9da4d94a4f, ``x ^= (x >> 32) >>
  (x >> 60)``, multiply again (all wrapping u64)

Rust's ``Hash for [u8]`` feeds the hasher ``usize`` length prefix then
the raw bytes; the crate implements the integer ``write_*`` methods as
little-endian byte writes into the same stream.  ``tag()`` below
reproduces that exact stream: ``LE8(len(header)) ‖ header ‖
LE8(len(payload)) ‖ payload``.

Fidelity note: validated against the seahash crate's published test
vectors (see tests/test_quic.py); the streaming-vs-buffered equivalence
is by construction (32-byte blocks are exactly one lane rotation).
"""

from __future__ import annotations

import struct

_M = 0xFFFFFFFFFFFFFFFF
_P = 0x6EED0E9DA4D94A4F
_K = (
    0x16F11FE89B0D677C,
    0xB480A793D8E6C86C,
    0x6FE2E5AAF078EBC9,
    0x14F994A4C5259381,
)


def _diffuse(x: int) -> int:
    x = (x * _P) & _M
    x ^= (x >> 32) >> (x >> 60)
    return (x * _P) & _M


class SeaHasher:
    """Streaming SeaHash over one logical byte stream."""

    __slots__ = ("_lanes", "_i", "_tail", "_written")

    def __init__(self) -> None:
        self._lanes = list(_K)
        self._i = 0
        self._tail = b""
        self._written = 0

    def write(self, data: bytes) -> None:
        self._written += len(data)
        buf = self._tail + data
        n_full = len(buf) // 8
        lanes, i = self._lanes, self._i
        for (word,) in struct.iter_unpack("<Q", buf[: n_full * 8]):
            lanes[i] = _diffuse(lanes[i] ^ word)
            i = (i + 1) & 3
        self._i = i
        self._tail = buf[n_full * 8 :]

    def write_u64le(self, n: int) -> None:
        self.write(struct.pack("<Q", n))

    def finish(self) -> int:
        a, b, c, d = self._lanes
        if self._tail:
            word = int.from_bytes(self._tail, "little")
            lanes = [a, b, c, d]
            lanes[self._i] = _diffuse(lanes[self._i] ^ word)
            a, b, c, d = lanes
        return _diffuse(a ^ b ^ c ^ d ^ self._written)


def hash_bytes(data: bytes) -> int:
    """The crate's ``seahash::hash``: one unprefixed buffer."""
    h = SeaHasher()
    h.write(data)
    return h.finish()


def tag(header: bytes, payload: bytes) -> bytes:
    """8-byte big-endian packet tag, matching the reference's
    ``header.hash(&mut SeaHasher); payload.hash(...)`` stream
    (`quinn_plaintext.rs:294-300`)."""
    h = SeaHasher()
    h.write_u64le(len(header))
    h.write(header)
    h.write_u64le(len(payload))
    h.write(payload)
    return struct.pack(">Q", h.finish())

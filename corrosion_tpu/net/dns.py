"""Bootstrap address resolution, including the `host:port@dns_server`
custom-resolver syntax.

Counterpart of `klukai-agent/src/agent/bootstrap.rs:60-156`: each
bootstrap entry may be
  - `ip:port`                      — used as-is,
  - `host:port`                    — resolved via the system resolver
                                     (A + AAAA),
  - `host:port@dns_ip[:dns_port]`  — resolved by querying that DNS server
                                     directly (the reference builds a
                                     hickory resolver pointed at it).

The custom-server path speaks minimal DNS over UDP (one A and one AAAA
query, RD bit set) — no external resolver library in the image.
"""

from __future__ import annotations

import asyncio
import contextlib
import ipaddress
import logging
import secrets
import socket
import struct
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

DNS_TIMEOUT_S = 3.0

QTYPE_A = 1
QTYPE_AAAA = 28


def split_bootstrap(entry: str) -> Tuple[str, Optional[str]]:
    """`host:port[@dns]` → (host:port, dns or None)."""
    if "@" in entry:
        hostport, dns = entry.split("@", 1)
        return hostport, dns
    return entry, None


def _split_hostport(hostport: str) -> Tuple[str, int]:
    if hostport.startswith("["):  # [v6]:port
        host, _, port = hostport[1:].partition("]:")
        return host, int(port)
    host, _, port = hostport.rpartition(":")
    if not host:
        raise ValueError(f"bootstrap entry {hostport!r} missing port")
    return host, int(port)


def _is_ip(host: str) -> bool:
    try:
        ipaddress.ip_address(host)
        return True
    except ValueError:
        return False


def encode_query(qid: int, name: str, qtype: int) -> bytes:
    """One-question DNS query with RD set."""
    out = struct.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0)
    for label in name.rstrip(".").split("."):
        raw = label.encode("idna") if not label.isascii() else label.encode()
        if not 0 < len(raw) < 64:
            raise ValueError(f"bad DNS label {label!r}")
        out += bytes([len(raw)]) + raw
    out += b"\x00" + struct.pack(">HH", qtype, 1)  # IN
    return out


def _skip_name(buf: bytes, off: int) -> int:
    while True:
        if off >= len(buf):
            raise ValueError("truncated DNS name")
        n = buf[off]
        if n == 0:
            return off + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            return off + 2
        off += 1 + n


def decode_answers(buf: bytes, qid: int, qtype: int) -> List[str]:
    """IP strings from a DNS response's answer section."""
    if len(buf) < 12:
        raise ValueError("short DNS response")
    rid, flags, qd, an, _, _ = struct.unpack(">HHHHHH", buf[:12])
    if rid != qid:
        raise ValueError("DNS response id mismatch")
    if flags & 0x000F != 0:  # RCODE
        return []
    off = 12
    for _ in range(qd):
        off = _skip_name(buf, off) + 4
    out: List[str] = []
    for _ in range(an):
        off = _skip_name(buf, off)
        rtype, _, _, rdlen = struct.unpack(">HHIH", buf[off : off + 10])
        off += 10
        rdata = buf[off : off + rdlen]
        off += rdlen
        if rtype == qtype == QTYPE_A and rdlen == 4:
            out.append(socket.inet_ntop(socket.AF_INET, rdata))
        elif rtype == qtype == QTYPE_AAAA and rdlen == 16:
            out.append(socket.inet_ntop(socket.AF_INET6, rdata))
    return out


class _UdpQuery(asyncio.DatagramProtocol):
    def __init__(self):
        self.reply: asyncio.Future = asyncio.get_event_loop().create_future()

    def datagram_received(self, data, addr):
        if not self.reply.done():
            self.reply.set_result(data)

    def error_received(self, exc):
        if not self.reply.done():
            self.reply.set_exception(exc)


async def query_server(
    dns_host: str, dns_port: int, name: str, qtype: int
) -> List[str]:
    qid = secrets.randbits(16)
    loop = asyncio.get_event_loop()
    transport, proto = await loop.create_datagram_endpoint(
        _UdpQuery, remote_addr=(dns_host, dns_port)
    )
    try:
        transport.sendto(encode_query(qid, name, qtype))
        buf = await asyncio.wait_for(proto.reply, DNS_TIMEOUT_S)
        return decode_answers(buf, qid, qtype)
    finally:
        transport.close()


async def resolve_entry(entry: str) -> List[str]:
    """One bootstrap entry → list of `ip:port` strings (dedup, order
    preserved). Failures resolve to [] and are logged — a dead bootstrap
    entry must not break the announce loop."""
    try:
        hostport, dns = split_bootstrap(entry)
        try:
            host, port = _split_hostport(hostport)
        except ValueError:
            # not host:port shaped — an opaque transport label (e.g. the
            # in-memory test network's "nodeN"); pass through untouched
            return [entry]
        if _is_ip(host):
            return [hostport]
        ips: List[str] = []
        if dns is not None:
            try:
                dns_host, dns_port = _split_hostport(dns)
            except ValueError:
                dns_host, dns_port = dns, 53
            results = await asyncio.gather(
                query_server(dns_host, dns_port, host, QTYPE_A),
                query_server(dns_host, dns_port, host, QTYPE_AAAA),
                return_exceptions=True,
            )
            for qtype, res in zip((QTYPE_A, QTYPE_AAAA), results):
                if isinstance(res, BaseException):
                    log.warning(
                        "DNS query %s (qtype %d) via %s failed: %s",
                        host, qtype, dns, res,
                    )
                else:
                    ips.extend(res)
        else:
            with contextlib.suppress(socket.gaierror):
                infos = await asyncio.get_event_loop().getaddrinfo(
                    host, port, type=socket.SOCK_DGRAM
                )
                ips.extend(info[4][0] for info in infos)
        seen = set()
        out = []
        for ip in ips:
            if ip in seen:
                continue
            seen.add(ip)
            out.append(f"[{ip}]:{port}" if ":" in ip else f"{ip}:{port}")
        return out
    except (ValueError, OSError) as e:
        log.warning("could not resolve bootstrap entry %r: %s", entry, e)
        return []


async def resolve_bootstrap(entries: List[str]) -> List[str]:
    """All entries resolved concurrently so one unreachable DNS server
    can't stall the announce loop beyond a single query timeout."""
    results = await asyncio.gather(
        *(resolve_entry(e) for e in entries)
    )
    out: List[str] = []
    for addrs in results:
        out.extend(addrs)
    return out

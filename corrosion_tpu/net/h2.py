"""HTTP/2 (RFC 9113) transport: cleartext prior-knowledge server + client.

The reference speaks HTTP/2 end-to-end: its client is h2-only (hyper with
`http2_only(true)`, 10 s keep-alive PINGs — `klukai-client/src/lib.rs:33-47`)
and its axum/hyper API server negotiates h2c.  This image ships no Python
h2 stack, so this module implements the protocol directly on asyncio:

- full frame layer: DATA / HEADERS / PRIORITY / RST_STREAM / SETTINGS /
  PING / GOAWAY / WINDOW_UPDATE / CONTINUATION, padding, header-block
  reassembly;
- both flow-control directions: outbound sends respect the peer's
  connection + stream windows and MAX_FRAME_SIZE (blocking until
  WINDOW_UPDATE), inbound DATA is credited back eagerly so peers never
  stall (bodies land in per-stream queues);
- HPACK via `net/hpack.py` (libnghttp2 when present — interop-grade with
  Huffman — else the pure-Python codec);
- server: multiplexed streams dispatched concurrently to an async handler
  with streaming request and response bodies (NDJSON subscriptions ride
  one stream each, multiplexed over one connection);
- client: request multiplexing over a shared connection with keep-alive
  PINGs every 10 s like the reference's.

Interop is tested against curl's nghttp2 (`--http2-prior-knowledge`) in
tests/test_h2.py.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

from corrosion_tpu.net import hpack

log = logging.getLogger(__name__)

# frame types (RFC 9113 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1  # DATA, HEADERS
FLAG_ACK = 0x1  # SETTINGS, PING
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# settings ids
SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

# error codes
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
FLOW_CONTROL_ERROR = 0x3
STREAM_CLOSED = 0x5
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8
COMPRESSION_ERROR = 0x9

# an assembled header block (HEADERS + CONTINUATIONs) larger than this is
# a hostile peer, not a real request (nghttp2's default header-list cap
# is 64 KiB; 1 MiB leaves generous headroom)
MAX_HEADER_BLOCK = 1 << 20

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
DEFAULT_WINDOW = 65535
MAX_FRAME_SIZE_DEFAULT = 16384

Headers = List[Tuple[bytes, bytes]]


class H2Error(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


class StreamReset(Exception):
    """Peer reset the stream (RST_STREAM) or the connection died."""


def _frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return (
        struct.pack(">I", len(payload))[1:]
        + bytes([ftype, flags])
        + struct.pack(">I", stream_id & 0x7FFFFFFF)
        + payload
    )


class _Stream:
    """Per-stream receive state + send window."""

    def __init__(self, sid: int, send_window: int):
        self.sid = sid
        self.headers: Optional[Headers] = None
        self.trailers: Optional[Headers] = None
        self.body: asyncio.Queue = asyncio.Queue()  # bytes | None(eof)
        self.headers_evt = asyncio.Event()
        self.send_window = send_window
        self.window_evt = asyncio.Event()
        self.reset_code: Optional[int] = None
        self.recv_closed = False

    def fail(self, code: int) -> None:
        self.reset_code = code
        self.headers_evt.set()
        self.window_evt.set()
        self.body.put_nowait(None)


class H2Connection:
    """Shared connection machinery: frame IO, settings, flow control."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        is_server: bool,
    ):
        self.reader = reader
        self.writer = writer
        self.is_server = is_server
        self.deflater = hpack.make_deflater()
        self.inflater = hpack.make_inflater()
        self.streams: Dict[int, _Stream] = {}
        self.send_window = DEFAULT_WINDOW  # connection-level, peer's credit
        self.window_evt = asyncio.Event()
        self.peer_max_frame = MAX_FRAME_SIZE_DEFAULT
        self.peer_initial_window = DEFAULT_WINDOW
        # what WE advertise as the per-stream initial window (a serving
        # bench draining 100k streams raises it so the server's fan-out
        # writer isn't throttled to 64 KiB per round trip)
        self.recv_window = DEFAULT_WINDOW
        self.recv_credit = 0  # connection-level bytes to acknowledge
        self._write_lock = asyncio.Lock()
        self._hpack_lock = asyncio.Lock()
        self.closed = False
        self.goaway_sent = False
        self._ping_waiters: Dict[bytes, asyncio.Event] = {}

    # -- writing -----------------------------------------------------------

    async def _send(self, raw: bytes) -> None:
        async with self._write_lock:
            if self.closed:
                raise StreamReset("connection closed")
            self.writer.write(raw)
            await self.writer.drain()

    async def send_settings(self, ack: bool = False, initial: bool = False) -> None:
        if ack:
            await self._send(_frame(SETTINGS, FLAG_ACK, 0, b""))
            return
        payload = b""
        if initial:
            payload = struct.pack(
                ">HIHI",
                SETTINGS_MAX_CONCURRENT_STREAMS, 256,
                SETTINGS_INITIAL_WINDOW_SIZE, self.recv_window,
            )
        await self._send(_frame(SETTINGS, 0, 0, payload))

    async def send_headers(
        self, sid: int, headers: Headers, end_stream: bool
    ) -> None:
        # hpack encoder state is connection-ordered: serialize encode+send
        async with self._hpack_lock:
            block = self.deflater.encode(headers)
            limit = self.peer_max_frame
            # a block over the peer's MAX_FRAME_SIZE must be split into
            # CONTINUATIONs (RFC 9113 §4.2: oversized = connection error)
            first, rest = block[:limit], block[limit:]
            flags = FLAG_END_STREAM if end_stream else 0
            if not rest:
                flags |= FLAG_END_HEADERS
            raw = _frame(HEADERS, flags, sid, first)
            while rest:
                chunk, rest = rest[:limit], rest[limit:]
                raw += _frame(
                    CONTINUATION,
                    FLAG_END_HEADERS if not rest else 0,
                    sid, chunk,
                )
            await self._send(raw)

    async def send_data(self, sid: int, data: bytes, end_stream: bool) -> None:
        """Send respecting both windows and the peer's max frame size."""
        stream = self.streams.get(sid)
        view = memoryview(data)
        while True:
            if stream is not None and stream.reset_code is not None:
                raise StreamReset(f"stream {sid} reset: {stream.reset_code}")
            if self.closed:
                raise StreamReset("connection closed")
            if len(view) == 0:
                if end_stream:
                    await self._send(_frame(DATA, FLAG_END_STREAM, sid, b""))
                return
            avail = min(len(view), self.send_window, self.peer_max_frame)
            if stream is not None:
                avail = min(avail, stream.send_window)
            if avail <= 0:
                # wait for WINDOW_UPDATE on whichever window is empty
                if self.send_window <= 0:
                    self.window_evt.clear()
                    await self.window_evt.wait()
                elif stream is not None:
                    stream.window_evt.clear()
                    await stream.window_evt.wait()
                continue
            chunk = bytes(view[:avail])
            view = view[avail:]
            self.send_window -= len(chunk)
            if stream is not None:
                stream.send_window -= len(chunk)
            last = len(view) == 0 and end_stream
            await self._send(
                _frame(DATA, FLAG_END_STREAM if last else 0, sid, chunk)
            )
            if last:
                return

    def send_data_nowait(self, sid: int, data: bytes) -> int:
        """Best-effort SYNCHRONOUS data write for the subscription
        fan-out plane (r16): consume whatever the connection + stream
        send windows currently allow, frame it, and append it to the
        transport WITHOUT awaiting drain or WINDOW_UPDATEs.  Returns
        the number of payload bytes accepted (0 when a window is
        closed) — the caller keeps the remainder and retries when
        credit returns.  Never sends END_STREAM.  Frame-atomic
        interleaving with `_send` is safe: every writer call appends
        whole frames."""
        stream = self.streams.get(sid)
        if self.closed:
            raise StreamReset("connection closed")
        if stream is not None and stream.reset_code is not None:
            raise StreamReset(f"stream {sid} reset: {stream.reset_code}")
        view = memoryview(data)
        sent = 0
        while sent < len(data):
            avail = min(len(data) - sent, self.send_window, self.peer_max_frame)
            if stream is not None:
                avail = min(avail, stream.send_window)
            if avail <= 0:
                break
            chunk = bytes(view[sent : sent + avail])
            self.send_window -= avail
            if stream is not None:
                stream.send_window -= avail
            self.writer.write(_frame(DATA, 0, sid, chunk))
            sent += avail
        return sent

    async def send_rst(self, sid: int, code: int) -> None:
        try:
            await self._send(_frame(RST_STREAM, 0, sid, struct.pack(">I", code)))
        except (StreamReset, ConnectionError, OSError):
            pass

    async def send_goaway(self, code: int = NO_ERROR) -> None:
        if self.goaway_sent:
            return
        self.goaway_sent = True
        last = max(self.streams, default=0)
        try:
            await self._send(
                _frame(GOAWAY, 0, 0, struct.pack(">II", last, code))
            )
        except (StreamReset, ConnectionError, OSError):
            pass

    async def ping(self, timeout: float = 5.0) -> bool:
        """RTT probe / keep-alive; True iff the ACK came back in time."""
        import os as _os

        data = _os.urandom(8)
        evt = asyncio.Event()
        self._ping_waiters[data] = evt
        try:
            await self._send(_frame(PING, 0, 0, data))
            await asyncio.wait_for(evt.wait(), timeout)
            return True
        except (asyncio.TimeoutError, StreamReset, ConnectionError, OSError):
            return False
        finally:
            self._ping_waiters.pop(data, None)

    async def _credit_recv(self, sid: int, n: int) -> None:
        """Replenish inbound windows eagerly: receivers buffer per-stream,
        so the transport window never back-pressures the peer."""
        if n <= 0:
            return
        self.recv_credit += n
        updates = b""
        if self.recv_credit >= DEFAULT_WINDOW // 2:
            updates += _frame(
                WINDOW_UPDATE, 0, 0, struct.pack(">I", self.recv_credit)
            )
            self.recv_credit = 0
        updates += _frame(WINDOW_UPDATE, 0, sid, struct.pack(">I", n))
        await self._send(updates)

    # -- reading -----------------------------------------------------------

    async def read_frame(self) -> Tuple[int, int, int, bytes]:
        header = await self.reader.readexactly(9)
        length = int.from_bytes(header[:3], "big")
        ftype, flags = header[3], header[4]
        sid = int.from_bytes(header[5:9], "big") & 0x7FFFFFFF
        # we never raise SETTINGS_MAX_FRAME_SIZE, so the peer must stay
        # within the default; enforcing it here (RFC 9113 §4.2) is also
        # what stops a single 16 MiB HEADERS/DATA frame from being
        # buffered wholesale — the frame-level DoS bound
        if length > MAX_FRAME_SIZE_DEFAULT:
            raise H2Error(FRAME_SIZE_ERROR, "oversized frame")
        payload = await self.reader.readexactly(length) if length else b""
        return ftype, flags, sid, payload

    async def read_header_block(
        self, flags: int, payload: bytes, sid: int
    ) -> Tuple[bytes, int]:
        """Strip padding/priority; append CONTINUATIONs until END_HEADERS.

        Returns the block plus the effective flags: END_STREAM can only
        appear on the initial HEADERS frame, so it is preserved across
        CONTINUATIONs (whose own flag bits carry only END_HEADERS).
        CONTINUATIONs must stay on the same stream, and the assembled
        block is size-capped — an endless-CONTINUATION peer is a DoS."""
        end_stream = flags & FLAG_END_STREAM
        if flags & FLAG_PADDED:
            if not payload:
                raise H2Error(PROTOCOL_ERROR, "bad padding")
            pad = payload[0]
            payload = payload[1:]
            if pad > len(payload):
                raise H2Error(PROTOCOL_ERROR, "bad padding")
            payload = payload[: len(payload) - pad]
        if flags & FLAG_PRIORITY:
            payload = payload[5:]
        block = payload
        while not flags & FLAG_END_HEADERS:
            ftype, flags, csid, cont = await self.read_frame()
            if ftype != CONTINUATION or csid != sid:
                raise H2Error(PROTOCOL_ERROR, "expected CONTINUATION")
            block += cont
            if len(block) > MAX_HEADER_BLOCK:
                raise H2Error(FRAME_SIZE_ERROR, "header block too large")
        return block, flags | end_stream

    def _strip_data_padding(self, flags: int, payload: bytes) -> bytes:
        if flags & FLAG_PADDED:
            if not payload:
                raise H2Error(PROTOCOL_ERROR, "bad padding")
            pad = payload[0]
            payload = payload[1:]
            if pad > len(payload):
                raise H2Error(PROTOCOL_ERROR, "bad padding")
            payload = payload[: len(payload) - pad]
        return payload

    def apply_settings(self, payload: bytes) -> None:
        if len(payload) % 6:
            raise H2Error(FRAME_SIZE_ERROR, "bad SETTINGS length")
        for off in range(0, len(payload), 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == SETTINGS_MAX_FRAME_SIZE:
                if not 16384 <= value <= 2 ** 24 - 1:
                    raise H2Error(PROTOCOL_ERROR, "bad MAX_FRAME_SIZE")
                self.peer_max_frame = value
            elif ident == SETTINGS_INITIAL_WINDOW_SIZE:
                if value > 2 ** 31 - 1:
                    raise H2Error(FLOW_CONTROL_ERROR, "bad INITIAL_WINDOW")
                delta = value - self.peer_initial_window
                self.peer_initial_window = value
                for s in self.streams.values():
                    s.send_window += delta
                    if s.send_window > 0:
                        s.window_evt.set()

    def handle_rst_stream(self, sid: int, payload: bytes) -> None:
        """Validate + apply a peer RST_STREAM (RFC 9113 §6.4)."""
        if len(payload) != 4:
            raise H2Error(FRAME_SIZE_ERROR, "bad RST_STREAM")
        if sid == 0:
            raise H2Error(PROTOCOL_ERROR, "RST_STREAM on stream 0")
        stream = self.streams.get(sid)
        if stream is not None:
            stream.fail(struct.unpack(">I", payload)[0])

    def handle_window_update(self, sid: int, payload: bytes) -> None:
        if len(payload) != 4:
            raise H2Error(FRAME_SIZE_ERROR, "bad WINDOW_UPDATE")
        inc = struct.unpack(">I", payload)[0] & 0x7FFFFFFF
        if sid == 0:
            self.send_window += inc
            if self.send_window > 0:
                self.window_evt.set()
        else:
            s = self.streams.get(sid)
            if s is not None:
                s.send_window += inc
                if s.send_window > 0:
                    s.window_evt.set()

    def fail_all(self) -> None:
        self.closed = True
        self.window_evt.set()
        for s in self.streams.values():
            s.fail(CANCEL)
        for evt in self._ping_waiters.values():
            evt.set()

    async def close(self) -> None:
        self.fail_all()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass


async def _body_iter(stream: _Stream) -> AsyncIterator[bytes]:
    while True:
        chunk = await stream.body.get()
        if chunk is None:
            if stream.reset_code not in (None, NO_ERROR):
                raise StreamReset(f"stream reset: {stream.reset_code}")
            return
        yield chunk


# -- server -----------------------------------------------------------------


class H2Request:
    """One server-side stream: request view + response emitters."""

    def __init__(self, conn: "H2Connection", stream: _Stream):
        self._conn = conn
        self._stream = stream
        hdrs = stream.headers or []
        pseudo = {k: v for k, v in hdrs if k.startswith(b":")}
        self.method = pseudo.get(b":method", b"").decode()
        self.path = pseudo.get(b":path", b"/").decode()
        self.authority = pseudo.get(b":authority", b"").decode()
        self.headers: Dict[str, str] = {
            k.decode(): v.decode() for k, v in hdrs if not k.startswith(b":")
        }
        self._sent_headers = False

    def body(self) -> AsyncIterator[bytes]:
        return _body_iter(self._stream)

    async def read_body(self) -> bytes:
        return b"".join([chunk async for chunk in self.body()])

    async def send_headers(
        self,
        status: int,
        headers: Optional[Dict[str, str]] = None,
        end_stream: bool = False,
    ) -> None:
        hs: Headers = [(b":status", str(status).encode())]
        for k, v in (headers or {}).items():
            hs.append((k.lower().encode(), v.encode()))
        await self._conn.send_headers(self._stream.sid, hs, end_stream)
        self._sent_headers = True

    async def send_data(self, data: bytes, end_stream: bool = False) -> None:
        await self._conn.send_data(self._stream.sid, data, end_stream)

    async def respond(
        self, status: int, body: bytes = b"",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        hs = dict(headers or {})
        hs.setdefault("content-length", str(len(body)))
        await self.send_headers(status, hs, end_stream=not body)
        if body:
            await self.send_data(body, end_stream=True)


Handler = Callable[[H2Request], "asyncio.Future"]


class H2Server:
    """h2c prior-knowledge server: one asyncio task per connection, one per
    stream; graceful close sends GOAWAY (util.rs's axum graceful layer)."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self._conns):
            await conn.send_goaway(NO_ERROR)
            await conn.close()

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        await self.handle_connection(reader, writer)

    async def handle_connection(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        preface_consumed: bool = False,
    ) -> None:
        """Serve one h2c connection; a protocol-sniffing front listener
        passes preface_consumed=True after eating the 24-byte preface."""
        conn = H2Connection(reader, writer, is_server=True)
        self._conns.add(conn)
        tasks: Dict[int, asyncio.Task] = {}
        last_sid = 0  # client stream ids are strictly increasing (§5.1.1)
        seen_sids: set = set()  # sids that actually carried a request:
        # distinguishes late trailers (valid) from HEADERS/DATA on a
        # never-opened closed stream (PROTOCOL_ERROR, §5.1.1)
        try:
            if not preface_consumed:
                preface = await asyncio.wait_for(
                    reader.readexactly(len(PREFACE)), 10.0
                )
                if preface != PREFACE:
                    return
            await conn.send_settings(initial=True)
            while True:
                ftype, flags, sid, payload = await conn.read_frame()
                if ftype == HEADERS:
                    if sid == 0 or sid % 2 == 0:
                        # §5.1.1: clients use odd ids; stream 0 carries
                        # no HEADERS — connection error, not leniency
                        raise H2Error(
                            PROTOCOL_ERROR, "HEADERS on stream 0/even"
                        )
                    block, flags = await conn.read_header_block(flags, payload, sid)
                    existing = conn.streams.get(sid)
                    if existing is not None or sid in seen_sids:
                        # trailers — on an open stream, or late ones for a
                        # stream whose handler already finished. Decode
                        # either way: HPACK state is connection-ordered.
                        async with conn._hpack_lock:
                            trailers = conn.inflater.decode(block)
                        if existing is not None:
                            existing.trailers = trailers
                            if (
                                flags & FLAG_END_STREAM
                                and not existing.recv_closed
                            ):
                                existing.recv_closed = True
                                existing.body.put_nowait(None)
                        continue
                    if sid <= last_sid:
                        # a lower-numbered id that never carried a
                        # request is "closed" (§5.1.1): HEADERS on it is
                        # a connection error
                        raise H2Error(
                            PROTOCOL_ERROR, "HEADERS on never-opened stream"
                        )
                    last_sid = sid
                    seen_sids.add(sid)
                    stream = _Stream(sid, conn.peer_initial_window)
                    async with conn._hpack_lock:
                        stream.headers = conn.inflater.decode(block)
                    conn.streams[sid] = stream
                    if flags & FLAG_END_STREAM:
                        stream.recv_closed = True
                        stream.body.put_nowait(None)
                    req = H2Request(conn, stream)
                    task = asyncio.ensure_future(
                        self._run_stream(conn, req, stream)
                    )
                    tasks[sid] = task
                    # prune on completion: one long-lived multiplexed
                    # connection must not accumulate finished tasks
                    task.add_done_callback(
                        lambda _t, s=sid: tasks.pop(s, None)
                    )
                elif ftype == DATA:
                    if sid == 0 or sid % 2 == 0 or sid not in seen_sids:
                        # DATA on stream 0, a server-id stream, or a
                        # stream that never carried a request: §6.1 /
                        # §5.1.1 connection error (silently dropping it
                        # would also corrupt flow-control accounting on
                        # a misbehaving peer). DATA for a *finished*
                        # request stream stays lenient below.
                        raise H2Error(PROTOCOL_ERROR, "DATA on idle stream")
                    stream = conn.streams.get(sid)
                    data = conn._strip_data_padding(flags, payload)
                    if stream is not None and not stream.recv_closed:
                        stream.body.put_nowait(data)
                        if flags & FLAG_END_STREAM:
                            stream.recv_closed = True
                            stream.body.put_nowait(None)
                    await conn._credit_recv(sid, len(payload))
                elif ftype == SETTINGS:
                    if not flags & FLAG_ACK:
                        conn.apply_settings(payload)
                        await conn.send_settings(ack=True)
                elif ftype == WINDOW_UPDATE:
                    conn.handle_window_update(sid, payload)
                elif ftype == RST_STREAM:
                    conn.handle_rst_stream(sid, payload)
                    t = tasks.pop(sid, None)
                    if t is not None:
                        t.cancel()
                elif ftype == PING:
                    if not flags & FLAG_ACK:
                        await conn._send(_frame(PING, FLAG_ACK, 0, payload))
                elif ftype == GOAWAY:
                    return
                elif ftype in (PRIORITY, PUSH_PROMISE, CONTINUATION):
                    pass  # PRIORITY ignored; others invalid here
        except (
            asyncio.IncompleteReadError, asyncio.TimeoutError,
            ConnectionError, OSError,
        ):
            pass
        except ValueError as e:
            # undecodable HPACK block: RFC 9113 §4.3 — GOAWAY, not an
            # abrupt close with an unretrieved task exception
            log.debug("h2 compression error: %s", e)
            await conn.send_goaway(COMPRESSION_ERROR)
        except H2Error as e:
            log.debug("h2 connection error: %s", e)
            await conn.send_goaway(e.code)
        finally:
            conn.fail_all()
            for t in tasks.values():
                t.cancel()
            self._conns.discard(conn)
            try:
                writer.close()
            except (ConnectionError, OSError):
                pass

    async def _run_stream(
        self, conn: H2Connection, req: H2Request, stream: _Stream
    ) -> None:
        try:
            await self.handler(req)
        except (StreamReset, asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001 — handler crash = 500 or RST
            log.exception("h2 handler error %s %s", req.method, req.path)
            if not req._sent_headers:
                try:
                    await req.respond(500, b"internal error")
                except (StreamReset, ConnectionError, OSError):
                    pass
            else:
                await conn.send_rst(stream.sid, CANCEL)
        finally:
            conn.streams.pop(stream.sid, None)
            if (
                not stream.recv_closed
                and stream.reset_code is None
                and not conn.closed
            ):
                # response finished before the request did: RST with
                # NO_ERROR so the peer stops sending (RFC 9113 §8.1)
                asyncio.ensure_future(conn.send_rst(stream.sid, NO_ERROR))


# -- client -----------------------------------------------------------------


class H2Response:
    def __init__(self, conn: H2Connection, stream: _Stream):
        self._conn = conn
        self._stream = stream
        hdrs = stream.headers or []
        self.status = int(
            {k: v for k, v in hdrs}.get(b":status", b"0").decode() or 0
        )
        self.headers: Dict[str, str] = {
            k.decode(): v.decode() for k, v in hdrs if not k.startswith(b":")
        }

    def body(self) -> AsyncIterator[bytes]:
        return _body_iter(self._stream)

    async def read(self) -> bytes:
        return b"".join([chunk async for chunk in self.body()])

    async def aclose(self) -> None:
        """Abandon the response early: RST the stream so the server stops
        sending (an unconsumed NDJSON stream would otherwise flow forever)."""
        s = self._stream
        if not s.recv_closed and s.reset_code is None:
            await self._conn.send_rst(s.sid, CANCEL)
            s.reset_code = NO_ERROR  # local cancel: clean end for readers
            s.recv_closed = True
            s.body.put_nowait(None)
        self._conn.streams.pop(s.sid, None)


class H2Client:
    """Multiplexing h2c client; the reference's hyper client config
    (`lib.rs:38-47`): prior knowledge, keep-alive PING every 10 s."""

    def __init__(
        self, host: str, port: int, keepalive_s: float = 10.0,
        connect_timeout: float = 3.0,
        recv_window: int = DEFAULT_WINDOW,
        conn_recv_window: int = DEFAULT_WINDOW,
    ):
        self.host = host
        self.port = port
        self.keepalive_s = keepalive_s
        self.connect_timeout = connect_timeout
        # receive-window sizing (r16): `recv_window` is advertised as
        # the per-stream initial window, `conn_recv_window` grows the
        # connection window past the RFC-fixed 65535 start via an
        # immediate WINDOW_UPDATE — a client multiplexing thousands of
        # live subscription streams over one connection needs both or
        # the server stalls on 64 KiB of unacked data per round trip
        self.recv_window = max(DEFAULT_WINDOW, recv_window)
        self.conn_recv_window = max(DEFAULT_WINDOW, conn_recv_window)
        self._conn: Optional[H2Connection] = None
        self._next_sid = 1
        self._reader_task: Optional[asyncio.Task] = None
        self._ping_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()

    async def _ensure(self) -> H2Connection:
        async with self._lock:
            if self._conn is not None and not self._conn.closed:
                return self._conn
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                self.connect_timeout,
            )
            conn = H2Connection(reader, writer, is_server=False)
            conn.recv_window = self.recv_window
            writer.write(PREFACE)
            await conn.send_settings(initial=True)
            extra = self.conn_recv_window - DEFAULT_WINDOW
            if extra > 0:
                await conn._send(
                    _frame(WINDOW_UPDATE, 0, 0, struct.pack(">I", extra))
                )
            self._conn = conn
            self._next_sid = 1
            self._reader_task = asyncio.ensure_future(self._read_loop(conn))
            self._ping_task = asyncio.ensure_future(self._keepalive(conn))
            return conn

    async def _read_loop(self, conn: H2Connection) -> None:
        try:
            while True:
                ftype, flags, sid, payload = await conn.read_frame()
                if ftype == HEADERS:
                    block, flags = await conn.read_header_block(flags, payload, sid)
                    stream = conn.streams.get(sid)
                    async with conn._hpack_lock:
                        decoded = conn.inflater.decode(block)
                    if stream is None:
                        continue
                    if stream.headers is None:
                        stream.headers = decoded
                        stream.headers_evt.set()
                    else:
                        stream.trailers = decoded
                    if flags & FLAG_END_STREAM:
                        stream.recv_closed = True
                        stream.body.put_nowait(None)
                elif ftype == DATA:
                    stream = conn.streams.get(sid)
                    data = conn._strip_data_padding(flags, payload)
                    if stream is not None and not stream.recv_closed:
                        stream.body.put_nowait(data)
                        if flags & FLAG_END_STREAM:
                            stream.recv_closed = True
                            stream.body.put_nowait(None)
                    await conn._credit_recv(sid, len(payload))
                elif ftype == SETTINGS:
                    if not flags & FLAG_ACK:
                        conn.apply_settings(payload)
                        await conn.send_settings(ack=True)
                elif ftype == WINDOW_UPDATE:
                    conn.handle_window_update(sid, payload)
                elif ftype == RST_STREAM:
                    conn.handle_rst_stream(sid, payload)
                elif ftype == PING:
                    if flags & FLAG_ACK:
                        evt = conn._ping_waiters.get(payload)
                        if evt is not None:
                            evt.set()
                    else:
                        await conn._send(_frame(PING, FLAG_ACK, 0, payload))
                elif ftype == GOAWAY:
                    return
        except (
            asyncio.IncompleteReadError, ConnectionError, OSError, H2Error,
            ValueError, asyncio.CancelledError,
        ):
            pass
        finally:
            conn.fail_all()

    async def _keepalive(self, conn: H2Connection) -> None:
        try:
            while not conn.closed:
                await asyncio.sleep(self.keepalive_s)
                if not await conn.ping(self.keepalive_s / 2):
                    conn.fail_all()
                    return
        except asyncio.CancelledError:
            pass

    async def request(
        self,
        method: str,
        path: str,
        headers: Optional[Dict[str, str]] = None,
        body: bytes = b"",
    ) -> H2Response:
        conn = await self._ensure()
        async with self._lock:
            sid = self._next_sid
            self._next_sid += 2
        stream = _Stream(sid, conn.peer_initial_window)
        conn.streams[sid] = stream
        hs: Headers = [
            (b":method", method.encode()),
            (b":scheme", b"http"),
            (b":authority", f"{self.host}:{self.port}".encode()),
            (b":path", path.encode()),
        ]
        for k, v in (headers or {}).items():
            hs.append((k.lower().encode(), v.encode()))
        try:
            await conn.send_headers(sid, hs, end_stream=not body)
            if body:
                await conn.send_data(sid, body, end_stream=True)
            await stream.headers_evt.wait()
        except (StreamReset, ConnectionError, OSError) as e:
            conn.streams.pop(sid, None)
            raise StreamReset(str(e)) from e
        except asyncio.CancelledError:
            # caller timed out / was cancelled: deregister and RST so the
            # server stops and late frames aren't queued into an orphan
            conn.streams.pop(sid, None)
            if not conn.closed:
                asyncio.ensure_future(conn.send_rst(sid, CANCEL))
            raise
        if stream.reset_code is not None:
            conn.streams.pop(sid, None)
            raise StreamReset(f"stream reset: {stream.reset_code}")
        return H2Response(conn, stream)

    async def close(self) -> None:
        for t in (self._ping_task, self._reader_task):
            if t is not None:
                t.cancel()
        if self._conn is not None:
            await self._conn.send_goaway(NO_ERROR)
            await self._conn.close()
            self._conn = None

"""Network layer: the 3-lane Transport seam and its implementations.

The reference consumes exactly three transport primitives — unreliable
datagrams (SWIM packets), fire-and-forget uni-streams (broadcast frames)
and bi-streams (sync sessions) — behind `Transport`
(`klukai-agent/src/transport.rs:26,81,108,140`). This package keeps that
seam: `MemNetwork` delivers in-process (tests, devcluster-in-one-process,
and the bridge into the TPU-simulated member blocks), `TcpTransport`
speaks real sockets (UDP datagrams + lane-tagged TCP streams).
"""

from corrosion_tpu.net.transport import (
    BiStream,
    Listener,
    Transport,
    TransportError,
)
from corrosion_tpu.net.mem import MemNetwork, MemTransport

__all__ = [
    "BiStream",
    "Listener",
    "Transport",
    "TransportError",
    "MemNetwork",
    "MemTransport",
]

"""Real-socket transport: UDP datagrams + lane-tagged TCP streams.

The reference's three QUIC lanes (`transport.rs`: datagrams = SWIM,
uni-streams = broadcast, bi-streams = sync) map onto plain sockets here:

  - datagrams  → one UDP socket per node (SWIM packets are ≤1178 B, well
    under any MTU — `broadcast/mod.rs:957`)
  - uni / bi   → TCP connections opened with a single lane byte
    (`U`/`B`), then u32-BE length-delimited frames (the reference's
    LengthDelimitedCodec layout, ≤100 MiB/frame)

Like the reference's client side, uni-lane connections are cached per
destination and re-established once on failure (`transport.rs:108-140`),
and RTT observations from connection reuse feed the members rings
(`transport.rs:220`). QUIC itself isn't reproduced — no aioquic in the
image and the kernel TCP path is the idiomatic substitute; the seam means
a QUIC implementation can slot in without touching the runtime.

TLS (`api/peer/mod.rs:152-373`): pass ssl contexts (built by
`corrosion_tpu.tls.build_ssl_contexts`) to `TcpListener.bind` and
`TcpTransport`. With TLS on, NO plaintext UDP socket is bound — SWIM
datagrams ride a third lane byte (`D`) on a cached TLS connection as
length-delimited frames, so the whole gossip plane (datagrams, uni,
bi) is encrypted and, with mtls, client-authenticated. Plaintext is the
explicit opt-in (`gossip.plaintext = true`), matching the reference's
quinn_plaintext session (`quinn_plaintext.rs:23-35`).
"""

from __future__ import annotations

import asyncio
import struct
import time
from typing import Dict, Optional, Tuple

from corrosion_tpu.net.transport import (
    BiStream,
    Listener,
    Transport,
    TransportError,
)
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.codec import MAX_FRAME

LANE_UNI = b"U"
LANE_BI = b"B"
LANE_DGRAM = b"D"  # TLS mode only: datagrams as frames on a TLS conn
CONNECT_TIMEOUT = 5.0  # transport.rs: 5s connect timeout


def split_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]  # [::1]:8080 — sockets want the bare literal
    return host, int(port)


async def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    if len(payload) > MAX_FRAME:
        raise TransportError(f"frame too large: {len(payload)}")
    writer.write(struct.pack(">I", len(payload)) + payload)
    await writer.drain()


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (n,) = struct.unpack(">I", header)
    if n > MAX_FRAME:
        raise TransportError(f"frame too large: {n}")
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None


class TcpBiStream(BiStream):
    def __init__(self, reader, writer, peer: str):
        self._reader = reader
        self._writer = writer
        self._peer = peer

    async def send(self, payload: bytes) -> None:
        try:
            await _write_frame(self._writer, payload)
        except (ConnectionError, RuntimeError) as e:
            raise TransportError(str(e)) from e

    async def recv(self) -> Optional[bytes]:
        return await _read_frame(self._reader)

    async def finish(self) -> None:
        try:
            self._writer.write_eof()
            await self._writer.drain()
        except (ConnectionError, RuntimeError, OSError):
            pass

    def close(self) -> None:
        self._writer.close()

    @property
    def peer(self) -> str:
        return self._peer


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, owner: "TcpListener"):
        self._owner = owner

    def datagram_received(self, data: bytes, addr) -> None:
        METRICS.counter("corro.transport.udp_rx.datagrams").inc()
        METRICS.counter("corro.transport.udp_rx.bytes").inc(len(data))
        handler = self._owner._on_datagram
        if handler is not None:
            asyncio.ensure_future(handler(f"{addr[0]}:{addr[1]}", data))


class TcpListener(Listener):
    """Bound UDP socket + TCP server sharing one port number."""

    def __init__(self):
        self._on_datagram = None
        self._on_uni = None
        self._on_bi = None
        self._udp_transport = None
        self._tcp_server: Optional[asyncio.AbstractServer] = None
        self._addr = ""
        self._ssl = None

    @classmethod
    async def bind(
        cls, host: str = "127.0.0.1", port: int = 0, ssl_context=None
    ) -> "TcpListener":
        self = cls()
        self._ssl = ssl_context
        loop = asyncio.get_running_loop()
        if ssl_context is None:
            self._udp_transport, _ = await loop.create_datagram_endpoint(
                lambda: _UdpProtocol(self), local_addr=(host, port)
            )
            bound = self._udp_transport.get_extra_info("sockname")
            # share the port number between UDP (datagrams) and TCP (streams)
            self._tcp_server = await asyncio.start_server(
                self._on_tcp_conn, host, bound[1]
            )
            self._addr = f"{bound[0]}:{bound[1]}"
        else:
            # TLS: the gossip plane accepts NOTHING in plaintext — no UDP
            # socket at all; datagrams arrive as D-lane frames
            self._tcp_server = await asyncio.start_server(
                self._on_tcp_conn, host, port, ssl=ssl_context
            )
            bound = self._tcp_server.sockets[0].getsockname()
            self._addr = f"{bound[0]}:{bound[1]}"
        return self

    def serve(self, on_datagram, on_uni, on_bi) -> None:
        self._on_datagram = on_datagram
        self._on_uni = on_uni
        self._on_bi = on_bi

    async def _on_tcp_conn(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        peer_addr = f"{peer[0]}:{peer[1]}" if peer else "?"
        METRICS.counter("corro.transport.accepted").inc()
        try:
            lane = await reader.readexactly(1)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        if lane == LANE_UNI:
            # long-lived: read frames until EOF, handing each to the handler
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                METRICS.counter(
                    "corro.transport.frames.received", lane="U"
                ).inc()
                METRICS.counter(
                    "corro.transport.bytes.received", lane="U"
                ).inc(len(frame) + 4)
                if self._on_uni is not None:
                    await self._on_uni(peer_addr, frame)
            writer.close()
        elif lane == LANE_DGRAM:
            # TLS-mode datagram lane: each frame is one SWIM packet.
            # Handlers run isolated (like the UDP path's ensure_future):
            # a handler exception or slow reply-send must neither kill
            # this read loop nor head-of-line-block the peer's packets
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                METRICS.counter(
                    "corro.transport.datagram.recv.total"
                ).inc()
                METRICS.counter(
                    "corro.transport.datagram.bytes.recv.total"
                ).inc(len(frame) + 4)
                if self._on_datagram is not None:
                    asyncio.ensure_future(self._on_datagram(peer_addr, frame))
            writer.close()
        elif lane == LANE_BI:
            if self._on_bi is not None:
                await self._on_bi(TcpBiStream(reader, writer, peer_addr))
        else:
            writer.close()

    @property
    def addr(self) -> str:
        return self._addr

    async def close(self) -> None:
        if self._udp_transport is not None:
            self._udp_transport.close()
        if self._tcp_server is not None:
            self._tcp_server.close()
            # 3.12's wait_closed also waits for in-flight connection
            # handlers (which live as long as cached uni conns) — bound it
            try:
                await asyncio.wait_for(self._tcp_server.wait_closed(), 1.0)
            except asyncio.TimeoutError:
                pass


class TcpTransport(Transport):
    """Client side: shares the listener's UDP socket so replies carry the
    right source address; caches one uni-lane TCP connection per peer.

    Deliberate deviation from the reference: quinn's client side spreads
    connections over 8 UDP sockets hashed by peer to dilute per-socket
    kernel buffer pressure (transport.rs:57-71). Here the gossip plane is
    one asyncio datagram endpoint per node — SWIM packets are ≤1178 B at
    ~1/s/peer, the asyncio loop drains the socket on every wakeup, and
    the single bound port doubles as the node's reply identity; sharding
    sends across extra sockets would buy nothing at this layer while
    complicating addr-based peer bookkeeping."""

    def __init__(self, listener: TcpListener, ssl_context=None,
                 idle_timeout: float = 30.0):
        self._listener = listener
        self._ssl = ssl_context
        # gossip.idle_timeout_secs (peer/mod.rs:125-127 max_idle_timeout):
        # cached lane connections unused this long are reaped, so dead
        # peers don't pin sockets the way an expired QUIC path wouldn't
        self.idle_timeout = idle_timeout
        self._conns: Dict[Tuple[str, bytes], asyncio.StreamWriter] = {}
        self._locks: Dict[Tuple[str, bytes], asyncio.Lock] = {}
        self._last_use: Dict[Tuple[str, bytes], float] = {}

    def reap_idle(self, now: Optional[float] = None) -> int:
        """Close cached lane connections idle longer than idle_timeout.
        Runs opportunistically on every cached send; callable directly.
        Keys whose lane lock is held are in active use and skipped."""
        now = time.monotonic() if now is None else now
        reaped = 0
        for key in list(self._conns):
            lock = self._locks.get(key)
            if lock is not None and lock.locked():
                continue
            if now - self._last_use.get(key, now) > self.idle_timeout:
                writer = self._conns.pop(key)
                self._last_use.pop(key, None)
                self._locks.pop(key, None)  # unheld (checked above): a
                # dead peer must not pin a Lock per (addr, lane) forever
                writer.close()
                reaped += 1
        if reaped:
            METRICS.counter("corro.transport.conns.idle_closed").inc(reaped)
            METRICS.gauge("corro.transport.conns.cached").set(len(self._conns))
        return reaped

    async def send_datagram(self, addr: str, data: bytes) -> None:
        if self._ssl is not None:
            # TLS mode: datagrams ride an encrypted D-lane connection, but
            # keep UDP's fire-and-forget contract — the SWIM probe loop
            # must never stall 5 s on a dead peer's TLS connect. Sends run
            # as background tasks; if the lane is already busy (previous
            # send still connecting), the packet is DROPPED — datagrams
            # are unreliable by contract and SWIM resends
            conn_key = (addr, LANE_DGRAM)
            lock = self._locks.setdefault(conn_key, asyncio.Lock())
            if lock.locked():
                METRICS.counter("corro.transport.datagram.dropped").inc()
                return

            async def _bg():
                try:
                    await self._send_cached(addr, LANE_DGRAM, data)
                    METRICS.counter("corro.transport.datagram.sent").inc()
                except (TransportError, ConnectionError, OSError):
                    METRICS.counter("corro.transport.datagram.failed").inc()

            asyncio.ensure_future(_bg())
            return
        udp = self._listener._udp_transport
        if udp is None:
            raise TransportError("transport closed")
        host, port = split_addr(addr)
        udp.sendto(data, (host, port))
        METRICS.counter("corro.transport.datagram.sent").inc()
        METRICS.counter("corro.transport.udp_tx.datagrams").inc()
        METRICS.counter("corro.transport.udp_tx.bytes").inc(len(data))

    async def _connect(self, addr: str, lane: bytes):
        host, port = split_addr(addr)
        start = time.monotonic()
        try:
            if self._ssl is not None:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(
                        host, port, ssl=self._ssl, server_hostname=host
                    ),
                    CONNECT_TIMEOUT,
                )
            else:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), CONNECT_TIMEOUT
                )
        except (OSError, asyncio.TimeoutError) as e:
            METRICS.counter("corro.transport.connect.failed").inc()
            raise TransportError(f"connect {addr}: {e}") from e
        elapsed = time.monotonic() - start
        METRICS.counter("corro.transport.connect.total").inc()
        METRICS.histogram("corro.transport.connect.seconds").observe(elapsed)
        self.observe_rtt(addr, elapsed)
        writer.write(lane)
        await writer.drain()
        return reader, writer

    async def _send_cached(self, addr: str, lane: bytes, payload: bytes) -> None:
        """Send one frame on the cached per-(peer, lane) connection with
        one reconnect retry, like transport.rs:108-139."""
        conn_key = (addr, lane)
        self.reap_idle()
        # acquire-and-revalidate: asyncio.Lock reports unlocked in the
        # window between release and a queued waiter resuming, so
        # reap_idle can pop a Lock that still has waiters; a waiter that
        # acquired the orphaned Lock must detect the swap and queue on
        # the current one, else two tasks interleave _write_frame on one
        # socket
        while True:
            lock = self._locks.setdefault(conn_key, asyncio.Lock())
            await lock.acquire()
            if self._locks.get(conn_key) is lock:
                break
            lock.release()
        try:
            for attempt in (0, 1):
                writer = self._conns.get(conn_key)
                if writer is None or writer.is_closing():
                    _, writer = await self._connect(addr, lane)
                    self._conns[conn_key] = writer
                self._last_use[conn_key] = time.monotonic()
                try:
                    await _write_frame(writer, payload)
                    METRICS.counter(
                        "corro.transport.frames.sent", lane=lane.decode()
                    ).inc()
                    METRICS.counter(
                        "corro.transport.bytes.sent", lane=lane.decode()
                    ).inc(len(payload) + 4)
                    METRICS.gauge("corro.transport.conns.cached").set(
                        len(self._conns)
                    )
                    return
                except (TransportError, ConnectionError, RuntimeError):
                    self._conns.pop(conn_key, None)
                    self._last_use.pop(conn_key, None)
                    writer.close()
                    METRICS.counter(
                        "corro.transport.send.retried", lane=lane.decode()
                    ).inc()
                    if attempt:
                        raise
        finally:
            lock.release()

    async def send_uni(self, addr: str, payload: bytes) -> None:
        await self._send_cached(addr, LANE_UNI, payload)

    async def open_bi(self, addr: str) -> BiStream:
        reader, writer = await self._connect(addr, LANE_BI)
        METRICS.counter("corro.transport.bi.opened").inc()
        return TcpBiStream(reader, writer, addr)

    async def close(self) -> None:
        for writer in self._conns.values():
            writer.close()
        self._conns.clear()
        self._last_use.clear()
        self._locks.clear()

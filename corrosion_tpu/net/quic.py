"""Plaintext QUIC: RFC 9000 framing with the reference's no-op crypto.

The reference's gossip plane is QUIC (quinn) with a custom plaintext
crypto session for trusted networks (`quinn_plaintext.rs:23-35`): packets
keep full QUIC framing — long/short headers, packet numbers, CRYPTO /
STREAM / DATAGRAM frames, flow control — but nothing is encrypted, header
protection is a no-op, and each packet is sealed with an 8-byte SeaHash
integrity tag over (header, payload) (`quinn_plaintext.rs:289-345`).
This module implements that wire protocol natively so the three gossip
lanes can ride real QUIC:

  datagrams   → DATAGRAM frames (RFC 9221)          — SWIM packets
  uni streams → one stream per broadcast payload    — epidemic broadcast
  bi streams  → one stream per sync session         — anti-entropy

mirroring `transport.rs:81-140` / `handlers.rs:54-190`.  The subset:

  - QUIC v1 long headers (Initial, Handshake) + 1-RTT short headers;
    no Retry, no 0-RTT, no version negotiation, no migration (quinn's
    PATH_CHALLENGE is answered, but paths are pinned to the 4-tuple)
  - handshake = the plaintext session's: the client's Initial CRYPTO
    stream carries exactly its transport parameters, the server's
    Handshake CRYPTO stream carries its own (`quinn_plaintext.rs:
    176-220` write_handshake/read_handshake), then HANDSHAKE_DONE
  - packet protection = identity + the SeaHash tag (tag_len 8, checked
    on receive, packet dropped on mismatch like quinn's CryptoError)
  - ACK + PTO-based retransmission of CRYPTO/STREAM data, connection
    and stream flow control, MAX_STREAMS replenishment, idle timeout

Interop status (documented honestly): there is no Rust toolchain in the
build image, so this stack is exercised against itself end-to-end (both
endpoints through real UDP sockets) and against byte-layout fixtures;
the wire format follows RFC 9000/9221 and the reference's tag scheme so
a real quinn+quinn_plaintext peer is expected to accept it, but that
final step is unverified here.  The SeaHash tag primitive IS verified
against the seahash crate's published vectors (tests/test_quic.py).

Endpoint shape (transport.rs:57-71, api/peer/mod.rs:121-150): like the
reference, outbound dials spread across 8 hashed client sockets when
gossip.client_addr has port 0 (the default), or use 1 socket bound to a
fixed client_addr — `QuicTransport(client_endpoints=[...])`, picked by
SeaHash of the peer addr mod the socket count, diluting per-socket
kernel buffers under kernel-path pressure exactly as the reference's
comment intends (the hash input differs: Rust hashes the SocketAddr
struct via its Hash impl, we hash the canonical "host:port" bytes —
both are stable per-peer, which is all the spread needs).  GSO: bulk
flushes coalesce consecutive equal-size datagrams to one sendmsg with a
UDP_SEGMENT cmsg (quinn's transport.enable_segmentation_offload,
`api/peer/mod.rs:121-150` gso knob) — capability-probed at runtime, with
a per-datagram fallback where the kernel or socket refuses (non-Linux,
older kernels).  gossip.max_mtu IS honored (QuicEndpoint.bind(mtu=...),
advertised + enforced).
"""

from __future__ import annotations

import asyncio
import errno
import logging
import os
import socket
import struct
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from corrosion_tpu.net import seahash
from corrosion_tpu.net.transport import (
    BiStream,
    Listener,
    Transport,
    TransportError,
)
from corrosion_tpu.runtime.metrics import METRICS

log = logging.getLogger(__name__)

QUIC_V1 = 0x00000001
CID_LEN = 8  # quinn's default random CID length; ours is fixed, peers' vary
TAG_LEN = 8  # quinn_plaintext.rs:331-334
MAX_UDP = 1452
MIN_INITIAL = 1200  # RFC 9000 §14.1: client Initial datagrams are padded

# Linux UDP generalized segmentation offload: one sendmsg carries many
# equal-size datagrams, split by the kernel (quinn's GSO path).  The
# socket-level constants predate their CPython exposure, so fall back to
# the stable kernel values when the build's socket module lacks them.
SOL_UDP = getattr(socket, "SOL_UDP", 17)
UDP_SEGMENT = getattr(socket, "UDP_SEGMENT", 103)
GSO_MAX_SEGS = 64  # kernel UDP_MAX_SEGMENTS
GSO_MAX_BYTES = 65000  # stay inside one IP datagram's payload bound

# packet-number spaces
S_INIT, S_HS, S_APP = 0, 1, 2
# long-header packet types (first byte bits 4-5)
T_INITIAL, T_0RTT, T_HANDSHAKE, T_RETRY = 0, 1, 2, 3

# frame types (RFC 9000 §19, RFC 9221)
F_PADDING = 0x00
F_PING = 0x01
F_ACK = 0x02
F_ACK_ECN = 0x03
F_RESET_STREAM = 0x04
F_STOP_SENDING = 0x05
F_CRYPTO = 0x06
F_NEW_TOKEN = 0x07
F_STREAM_BASE = 0x08  # 0x08..0x0f | OFF 0x04 | LEN 0x02 | FIN 0x01
F_MAX_DATA = 0x10
F_MAX_STREAM_DATA = 0x11
F_MAX_STREAMS_BIDI = 0x12
F_MAX_STREAMS_UNI = 0x13
F_DATA_BLOCKED = 0x14
F_STREAM_DATA_BLOCKED = 0x15
F_STREAMS_BLOCKED_BIDI = 0x16
F_STREAMS_BLOCKED_UNI = 0x17
F_NEW_CONNECTION_ID = 0x18
F_RETIRE_CONNECTION_ID = 0x19
F_PATH_CHALLENGE = 0x1A
F_PATH_RESPONSE = 0x1B
F_CLOSE_TRANSPORT = 0x1C
F_CLOSE_APP = 0x1D
F_HANDSHAKE_DONE = 0x1E
F_DATAGRAM = 0x30  # no length (fills packet)
F_DATAGRAM_LEN = 0x31

# transport parameter ids (RFC 9000 §18.2 + RFC 9221)
TP_ODCID = 0x00
TP_IDLE = 0x01
TP_MAX_UDP = 0x03
TP_MAX_DATA = 0x04
TP_MSD_BIDI_LOCAL = 0x05
TP_MSD_BIDI_REMOTE = 0x06
TP_MSD_UNI = 0x07
TP_MAX_STREAMS_BIDI = 0x08
TP_MAX_STREAMS_UNI = 0x09
TP_ACK_DELAY_EXP = 0x0A
TP_MAX_ACK_DELAY = 0x0B
TP_ISCID = 0x0F
TP_MAX_DATAGRAM = 0x20

# local limits, shaped like the reference's endpoint config
# (api/peer/mod.rs:121-150: 32 bidi, 256 uni streams)
LOCAL_MAX_STREAMS_BIDI = 32
LOCAL_MAX_STREAMS_UNI = 256
LOCAL_MAX_DATA = 16 << 20
LOCAL_MAX_STREAM_DATA = 4 << 20
LOCAL_MAX_DATAGRAM = 65527

CONNECT_TIMEOUT = 5.0  # transport.rs: 5s connect timeout
MAX_PTO_COUNT = 8


class QuicError(TransportError):
    pass


# ---------------------------------------------------------------------------
# varints (RFC 9000 §16)


def vint(n: int) -> bytes:
    if n < 0x40:
        return bytes([n])
    if n < 0x4000:
        return struct.pack(">H", 0x4000 | n)
    if n < 0x40000000:
        return struct.pack(">I", 0x80000000 | n)
    if n < 0x4000000000000000:
        return struct.pack(">Q", 0xC000000000000000 | n)
    raise ValueError("varint too large")


def read_vint(data: bytes, pos: int) -> Tuple[int, int]:
    if pos >= len(data):
        raise QuicError("truncated varint")
    first = data[pos]
    ln = 1 << (first >> 6)
    if pos + ln > len(data):
        raise QuicError("truncated varint")
    n = first & 0x3F
    for i in range(1, ln):
        n = (n << 8) | data[pos + i]
    return n, pos + ln


# ---------------------------------------------------------------------------
# transport parameters (RFC 9000 §18)


def encode_transport_params(params: Dict[int, object]) -> bytes:
    out = bytearray()
    for pid, val in params.items():
        body: bytes
        if isinstance(val, bytes):
            body = val
        elif val is None:  # zero-length (flag-style) parameter
            body = b""
        else:
            body = vint(int(val))
        out += vint(pid) + vint(len(body)) + body
    return bytes(out)


def decode_transport_params(data: bytes) -> Dict[int, bytes]:
    out: Dict[int, bytes] = {}
    pos = 0
    while pos < len(data):
        pid, pos = read_vint(data, pos)
        ln, pos = read_vint(data, pos)
        if pos + ln > len(data):
            raise QuicError("truncated transport parameter")
        out[pid] = bytes(data[pos : pos + ln])
        pos += ln
    return out


def _tp_int(raw: Dict[int, bytes], pid: int, default: int) -> int:
    if pid not in raw:
        return default
    val, _ = read_vint(raw[pid], 0)
    return val


# ---------------------------------------------------------------------------
# packet numbers (RFC 9000 §17.1, §A)


def decode_pn(truncated: int, nbytes: int, expected: int) -> int:
    pn_win = 1 << (nbytes * 8)
    pn_hwin = pn_win // 2
    candidate = (expected & ~(pn_win - 1)) | truncated
    if candidate <= expected - pn_hwin and candidate < (1 << 62) - pn_win:
        return candidate + pn_win
    if candidate > expected + pn_hwin and candidate >= pn_win:
        return candidate - pn_win
    return candidate


# ---------------------------------------------------------------------------
# ack ranges


class PnRanges:
    """Received packet numbers as sorted disjoint inclusive ranges."""

    __slots__ = ("ranges",)

    def __init__(self) -> None:
        self.ranges: List[List[int]] = []

    def add(self, pn: int) -> bool:
        """Insert; returns False if already present (duplicate packet)."""
        rs = self.ranges
        for i, r in enumerate(rs):
            if r[0] - 1 <= pn <= r[1] + 1:
                if r[0] <= pn <= r[1]:
                    return False
                if pn == r[0] - 1:
                    r[0] = pn
                else:
                    r[1] = pn
                    if i + 1 < len(rs) and rs[i + 1][0] == pn + 1:
                        r[1] = rs[i + 1][1]
                        del rs[i + 1]
                if i > 0 and rs[i - 1][1] == r[0] - 1:
                    rs[i - 1][1] = r[1]
                    del rs[i]
                return True
            if pn < r[0] - 1:
                rs.insert(i, [pn, pn])
                return True
        rs.append([pn, pn])
        return True

    @property
    def largest(self) -> int:
        return self.ranges[-1][1] if self.ranges else -1

    def ack_frame(self) -> bytes:
        """Encode an ACK frame for everything seen (ack_delay 0)."""
        rs = self.ranges
        largest = rs[-1][1]
        out = bytearray(vint(F_ACK))
        out += vint(largest)
        out += vint(0)  # ack delay
        out += vint(len(rs) - 1)  # additional range count
        out += vint(largest - rs[-1][0])  # first range
        prev_lo = rs[-1][0]
        for r in reversed(rs[:-1]):
            out += vint(prev_lo - r[1] - 2)  # gap
            out += vint(r[1] - r[0])  # range length
            prev_lo = r[0]
        return bytes(out)


def parse_ack_frame(data: bytes, pos: int, ecn: bool) -> Tuple[List[Tuple[int, int]], int]:
    """Returns (acked inclusive ranges high→low, new pos)."""
    largest, pos = read_vint(data, pos)
    _delay, pos = read_vint(data, pos)
    count, pos = read_vint(data, pos)
    first, pos = read_vint(data, pos)
    ranges = [(largest - first, largest)]
    lo = largest - first
    for _ in range(count):
        gap, pos = read_vint(data, pos)
        rlen, pos = read_vint(data, pos)
        hi = lo - gap - 2
        lo = hi - rlen
        ranges.append((lo, hi))
    if ecn:
        for _ in range(3):
            _, pos = read_vint(data, pos)
    return ranges, pos


# ---------------------------------------------------------------------------
# reassembly (CRYPTO and STREAM receive sides)


class Reassembler:
    __slots__ = ("segments", "delivered", "fin_at")

    def __init__(self) -> None:
        self.segments: Dict[int, bytes] = {}
        self.delivered = 0
        self.fin_at: Optional[int] = None

    def feed(self, off: int, data: bytes, fin: bool = False) -> bytes:
        if fin:
            self.fin_at = off + len(data)
        if data and off + len(data) > self.delivered:
            self.segments[off] = data
        out = bytearray()
        while True:
            for seg_off in sorted(self.segments):
                seg = self.segments[seg_off]
                if seg_off <= self.delivered < seg_off + len(seg):
                    out += seg[self.delivered - seg_off :]
                    self.delivered = seg_off + len(seg)
                    del self.segments[seg_off]
                    break
                if seg_off + len(seg) <= self.delivered:
                    del self.segments[seg_off]
                    break
            else:
                break
        return bytes(out)

    @property
    def finished(self) -> bool:
        return self.fin_at is not None and self.delivered >= self.fin_at


# ---------------------------------------------------------------------------
# packet spaces


class _SentPacket:
    __slots__ = ("frames", "sent_at", "ack_eliciting", "size")

    def __init__(self, frames, sent_at, ack_eliciting, size):
        self.frames = frames  # retransmittable frame descriptors
        self.sent_at = sent_at
        self.ack_eliciting = ack_eliciting
        self.size = size


class _Space:
    __slots__ = (
        "next_pn", "largest_acked", "sent", "recv", "ack_pending",
        "crypto_recv", "crypto_sent_off", "crypto_pending",
    )

    def __init__(self) -> None:
        self.next_pn = 0
        self.largest_acked = -1
        self.sent: Dict[int, _SentPacket] = {}
        self.recv = PnRanges()
        self.ack_pending = False
        self.crypto_recv = Reassembler()
        self.crypto_sent_off = 0
        self.crypto_pending: List[Tuple[int, bytes]] = []  # (off, data)


# ---------------------------------------------------------------------------
# streams


class RecvStream:
    __slots__ = ("sid", "asm", "frames", "_buf", "consumed", "reset",
                 "max_advert")

    def __init__(self, sid: int) -> None:
        self.sid = sid
        self.asm = Reassembler()
        self.frames: asyncio.Queue = asyncio.Queue()
        self._buf = b""
        self.consumed = 0
        self.reset = False
        self.max_advert = LOCAL_MAX_STREAM_DATA

    def feed(self, off: int, data: bytes, fin: bool) -> int:
        """Feed wire data; push complete u32-delimited frames; returns
        newly consumable byte count (for flow-control credit)."""
        before = self.asm.delivered
        self._buf += self.asm.feed(off, data, fin)
        grown = self.asm.delivered - before
        self.consumed += grown
        while len(self._buf) >= 4:
            (n,) = struct.unpack(">I", self._buf[:4])
            if len(self._buf) < 4 + n:
                break
            self.frames.put_nowait(self._buf[4 : 4 + n])
            self._buf = self._buf[4 + n :]
        if self.asm.finished:
            self.frames.put_nowait(None)
        return grown


class SendStream:
    __slots__ = ("sid", "conn", "offset", "fin_sent", "pending", "credit",
                 "highwater")

    def __init__(self, sid: int, conn: "QuicConnection",
                 credit: int = 0) -> None:
        self.sid = sid
        self.conn = conn
        self.offset = 0
        self.fin_sent = False
        self.pending: List[Tuple[int, bytes, bool]] = []  # (off, data, fin)
        self.credit = credit  # peer's stream receive window (abs offset)
        self.highwater = 0  # highest offset sent (retx doesn't re-count)

    def write(self, data: bytes, fin: bool = False) -> None:
        self.pending.append((self.offset, data, fin))
        self.offset += len(data)
        if fin:
            self.fin_sent = True

    async def send_frame(self, payload: bytes, fin: bool = False) -> None:
        """One u32-BE length-delimited frame (the lanes' unit)."""
        self.write(struct.pack(">I", len(payload)) + payload, fin=fin)
        await self.conn.flush()

    async def finish(self) -> None:
        if not self.fin_sent:
            self.write(b"", fin=True)
            await self.conn.flush()


# ---------------------------------------------------------------------------
# connection


class QuicBiStream(BiStream):
    """Transport-seam adapter: u32-framed bidirectional stream."""

    def __init__(self, conn: "QuicConnection", sid: int,
                 send: SendStream, recv: RecvStream) -> None:
        self._conn = conn
        self._sid = sid
        self._send = send
        self._recv = recv
        self._eof = False

    async def send(self, payload: bytes) -> None:
        await self._send.send_frame(payload)

    async def recv(self) -> Optional[bytes]:
        if self._eof or (self._recv.reset and self._recv.frames.empty()):
            return None
        frame = await self._recv.frames.get()
        if frame is None:
            self._eof = True
        return frame

    async def finish(self) -> None:
        await self._send.finish()

    def close(self) -> None:
        self._recv.frames.put_nowait(None)

    @property
    def peer(self) -> str:
        return self._conn.peer_addr


class QuicConnection:
    def __init__(self, endpoint: "QuicEndpoint", peer: Tuple[str, int],
                 is_client: bool) -> None:
        self.endpoint = endpoint
        self.peer = peer
        self.peer_addr = f"{peer[0]}:{peer[1]}"
        self.is_client = is_client
        self.scid = os.urandom(CID_LEN)
        self.dcid = os.urandom(CID_LEN)  # client: becomes server odcid
        self.odcid = self.dcid if is_client else b""
        self.spaces = [_Space(), _Space(), _Space()]
        self.established = asyncio.Event()
        self.closed = asyncio.Event()
        self.close_reason: Optional[str] = None
        self.handshake_confirmed = False
        self._hs_done_sent = False
        self._server_flight_sent = False
        self.peer_params: Optional[Dict[int, bytes]] = None
        # flow control
        self.max_data_local = LOCAL_MAX_DATA
        self.data_consumed = 0
        self.max_data_remote = 0
        self.data_sent = 0
        self.max_datagram_remote = 0
        # streams
        self.send_streams: Dict[int, SendStream] = {}
        self.recv_streams: Dict[int, RecvStream] = {}
        self._next_uni = 0
        self._next_bidi = 0
        self.peer_max_streams_uni = 0
        self.peer_max_streams_bidi = 0
        self._streams_event = asyncio.Event()
        self.local_max_streams_uni = LOCAL_MAX_STREAMS_UNI
        self.local_max_streams_bidi = LOCAL_MAX_STREAMS_BIDI
        self._remote_uni_opened = 0
        self._remote_bidi_opened = 0
        self._max_remote_sid = {2: -1, 3: -1, 0: -1, 1: -1}  # by kind bits
        self._stream_unacked: Dict[int, int] = {}
        self._bi_waiters: Dict[int, asyncio.Future] = {}
        # datagrams queued until established
        self._dgram_queue: List[bytes] = []
        self.pending_other: List[bytes] = []  # encoded 1-RTT control frames
        self._retx_task: Optional[asyncio.Task] = None
        self.pto_count = 0
        self.srtt: Optional[float] = None
        self.last_recv = time.monotonic()
        self.idle_timeout = 30.0
        # gossip.max_mtu (the reference's fixed-MTU knob,
        # api/peer/mod.rs:121-150): caps every datagram this end builds
        self.mtu = min(endpoint.mtu, MAX_UDP)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._retx_task = asyncio.ensure_future(self._timer_loop())

    def local_transport_params(self) -> bytes:
        params: Dict[int, object] = {
            TP_IDLE: int(self.idle_timeout * 1000),
            TP_MAX_UDP: self.mtu,
            TP_MAX_DATA: LOCAL_MAX_DATA,
            TP_MSD_BIDI_LOCAL: LOCAL_MAX_STREAM_DATA,
            TP_MSD_BIDI_REMOTE: LOCAL_MAX_STREAM_DATA,
            TP_MSD_UNI: LOCAL_MAX_STREAM_DATA,
            TP_MAX_STREAMS_BIDI: LOCAL_MAX_STREAMS_BIDI,
            TP_MAX_STREAMS_UNI: LOCAL_MAX_STREAMS_UNI,
            TP_ACK_DELAY_EXP: 3,
            TP_MAX_ACK_DELAY: 25,
            TP_ISCID: self.scid,
            TP_MAX_DATAGRAM: LOCAL_MAX_DATAGRAM,
        }
        if not self.is_client:
            params[TP_ODCID] = self.odcid
        return encode_transport_params(params)

    def _apply_peer_params(self, raw: Dict[int, bytes]) -> None:
        self.peer_params = raw
        self.max_data_remote = _tp_int(raw, TP_MAX_DATA, 0)
        self.peer_max_streams_uni = _tp_int(raw, TP_MAX_STREAMS_UNI, 0)
        self.peer_max_streams_bidi = _tp_int(raw, TP_MAX_STREAMS_BIDI, 0)
        self.max_datagram_remote = _tp_int(raw, TP_MAX_DATAGRAM, 0)
        self.msd_uni_remote = _tp_int(raw, TP_MSD_UNI, 0)
        self.msd_bidi_remote = _tp_int(raw, TP_MSD_BIDI_REMOTE, 0)
        self.msd_bidi_local_remote = _tp_int(raw, TP_MSD_BIDI_LOCAL, 0)
        idle_ms = _tp_int(raw, TP_IDLE, 0)
        if idle_ms:
            self.idle_timeout = min(self.idle_timeout, idle_ms / 1000.0)
        if self.is_client and TP_ISCID in raw:
            # must match the SCID the server's packets carry (§7.3)
            if raw[TP_ISCID] != self.dcid:
                log.debug("quic: server iscid mismatch")
        self._streams_event.set()

    async def connect(self) -> None:
        """Client side: send Initial CRYPTO(transport params), await
        handshake completion (plaintext session: the whole handshake is
        one TP exchange, quinn_plaintext.rs:176-220)."""
        sp = self.spaces[S_INIT]
        tp = self.local_transport_params()
        sp.crypto_pending.append((0, tp))
        sp.crypto_sent_off = len(tp)
        self._connect_started = time.monotonic()
        await self.flush()
        await asyncio.wait_for(self.established.wait(), CONNECT_TIMEOUT)

    def close(self, reason: str = "", app: bool = False,
              send_frame: bool = True) -> None:
        if self.closed.is_set():
            return
        self.close_reason = reason or None
        if send_frame and self.peer_params is not None:
            frame = bytearray(vint(F_CLOSE_APP if app else F_CLOSE_TRANSPORT))
            frame += vint(0)  # error code
            if not app:
                frame += vint(0)  # offending frame type
            msg = reason.encode()[:64]
            frame += vint(len(msg)) + msg
            try:
                pkt = self._build_packet(S_APP, bytes(frame))
                if pkt:
                    self.endpoint._sendto(pkt, self.peer)
            except (QuicError, OSError):
                pass
        self.closed.set()
        self.established.set()  # wake connect() waiters; they check closed
        for rs in self.recv_streams.values():
            rs.frames.put_nowait(None)
        for fut in self._bi_waiters.values():
            if not fut.done():
                fut.cancel()
        if self._retx_task is not None:
            self._retx_task.cancel()
        self.endpoint._forget(self)

    # -- stream API --------------------------------------------------------

    def _stream_id(self, uni: bool) -> int:
        base = 2 if uni else 0
        if not self.is_client:
            base += 1
        if uni:
            sid = base + 4 * self._next_uni
            self._next_uni += 1
        else:
            sid = base + 4 * self._next_bidi
            self._next_bidi += 1
        return sid

    async def _await_stream_credit(self, uni: bool) -> None:
        deadline = time.monotonic() + CONNECT_TIMEOUT
        while True:
            count = self._next_uni if uni else self._next_bidi
            limit = self.peer_max_streams_uni if uni else self.peer_max_streams_bidi
            if count < limit:
                return
            self._streams_event.clear()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise QuicError("stream credit exhausted")
            # peer replenishes via MAX_STREAMS
            blocked = vint(
                F_STREAMS_BLOCKED_UNI if uni else F_STREAMS_BLOCKED_BIDI
            ) + vint(limit)
            self.pending_other.append(blocked)
            await self.flush()
            try:
                await asyncio.wait_for(self._streams_event.wait(), remaining)
            except asyncio.TimeoutError:
                raise QuicError("stream credit exhausted") from None

    async def open_uni(self) -> SendStream:
        await self._ready()
        await self._await_stream_credit(uni=True)
        sid = self._stream_id(uni=True)
        st = SendStream(sid, self, credit=self.msd_uni_remote)
        self.send_streams[sid] = st
        return st

    async def open_bi(self) -> QuicBiStream:
        await self._ready()
        await self._await_stream_credit(uni=False)
        sid = self._stream_id(uni=False)
        st = SendStream(sid, self, credit=self.msd_bidi_remote)
        self.send_streams[sid] = st
        rs = RecvStream(sid)
        self.recv_streams[sid] = rs
        return QuicBiStream(self, sid, st, rs)

    async def send_datagram(self, data: bytes) -> None:
        await self._ready()
        # the bound must match the flush gate (MAX_UDP - 96 headroom for
        # packet overhead): an admitted-but-unsendable datagram would
        # block the queue head forever
        if len(data) + 3 > min(self.max_datagram_remote or 0, self.mtu - 96):
            raise QuicError("datagram too large for peer")
        self._dgram_queue.append(data)
        await self.flush()

    async def _ready(self) -> None:
        if not self.established.is_set():
            await asyncio.wait_for(self.established.wait(), CONNECT_TIMEOUT)
        if self.closed.is_set():
            raise QuicError(f"connection closed: {self.close_reason}")

    # -- packet build ------------------------------------------------------

    def _build_packet(self, space: int, frames: bytes,
                      track: Optional[List] = None,
                      ack_eliciting: bool = False,
                      pad_to: int = 0) -> bytes:
        sp = self.spaces[space]
        pn = sp.next_pn
        sp.next_pn += 1
        pn_bytes = struct.pack(">I", pn & 0xFFFFFFFF)
        if pad_to:
            # pad INSIDE the packet so the datagram reaches pad_to
            overhead = self._header_overhead(space) + len(pn_bytes) + TAG_LEN
            want = pad_to - overhead - len(frames)
            if want > 0:
                frames = frames + b"\x00" * want
        if space == S_APP:
            first = 0x40 | 0x03  # short, fixed bit, pn_len 4
            header = bytes([first]) + self.dcid + pn_bytes
        else:
            ptype = T_INITIAL if space == S_INIT else T_HANDSHAKE
            first = 0xC0 | (ptype << 4) | 0x03
            header = bytearray([first])
            header += struct.pack(">I", QUIC_V1)
            header += bytes([len(self.dcid)]) + self.dcid
            header += bytes([len(self.scid)]) + self.scid
            if ptype == T_INITIAL:
                header += vint(0)  # token length
            header += vint(len(pn_bytes) + len(frames) + TAG_LEN)
            header += pn_bytes
            header = bytes(header)
        pkt = header + frames + seahash.tag(header, frames)
        sp.sent[pn] = _SentPacket(
            track or [], time.monotonic(), ack_eliciting, len(pkt)
        )
        return pkt

    def _header_overhead(self, space: int) -> int:
        if space == S_APP:
            return 1 + len(self.dcid)
        n = 1 + 4 + 1 + len(self.dcid) + 1 + len(self.scid)
        if space == S_INIT:
            n += 1  # token length varint (0)
        n += 4  # length varint worst case handled by MAX_UDP slack
        return n

    async def flush(self) -> None:
        self._flush_sync()

    def _flush_sync(self) -> None:
        """Assemble and send datagrams for all spaces with pending work."""
        if self.closed.is_set():
            return
        budget = 10  # datagrams per flush; retx loop resumes if more
        outbox: List[bytes] = []
        while budget > 0:
            datagram = bytearray()
            for space in (S_INIT, S_HS, S_APP):
                if space == S_APP and self.peer_params is None:
                    break
                frames, track, eliciting = self._frames_for_space(space)
                if not frames:
                    continue
                # RFC 9000 §14.1: datagrams with ack-eliciting client
                # Initials are padded to 1200 (the +16 covers the gap
                # between the worst-case and actual length-varint size)
                pad = (
                    MIN_INITIAL + 16
                    if space == S_INIT and self.is_client and eliciting
                    else 0
                )
                datagram += self._build_packet(
                    space, frames, track=track, ack_eliciting=eliciting,
                    pad_to=pad,
                )
            if not datagram:
                break
            outbox.append(bytes(datagram))
            budget -= 1
        if outbox:
            self.endpoint._send_batch(outbox, self.peer)

    def _frames_for_space(self, space: int):
        sp = self.spaces[space]
        frames = bytearray()
        track: List = []
        eliciting = False
        # ACKs first
        if sp.ack_pending and sp.recv.ranges:
            frames += sp.recv.ack_frame()
            sp.ack_pending = False
        # CRYPTO retransmit/initial data
        max_chunk = 1100
        while sp.crypto_pending:
            off, data = sp.crypto_pending.pop(0)
            if len(data) > max_chunk:
                sp.crypto_pending.insert(0, (off + max_chunk, data[max_chunk:]))
                data = data[:max_chunk]
            frames += vint(F_CRYPTO) + vint(off) + vint(len(data)) + data
            track.append(("crypto", space, off, data))
            eliciting = True
            break  # one chunk per packet keeps under MTU
        if space == S_APP:
            if not self._hs_done_sent and not self.is_client \
                    and self.handshake_confirmed:
                frames += vint(F_HANDSHAKE_DONE)
                track.append(("hsdone",))
                eliciting = True
                self._hs_done_sent = True
            # control frames (flow-control credit updates etc.): tracked
            # for retransmission — a lost MAX_DATA/MAX_STREAMS would
            # otherwise deadlock the peer until idle timeout (values are
            # monotone maxima, so re-sending a stale one is harmless)
            while self.pending_other and len(frames) < self.mtu - 200:
                fr = self.pending_other.pop(0)
                frames += fr
                track.append(("other", fr))
                eliciting = True
            # datagrams
            while self._dgram_queue:
                d = self._dgram_queue[0]
                if len(frames) + len(d) + 3 > self.mtu - 96:
                    break
                self._dgram_queue.pop(0)
                frames += vint(F_DATAGRAM_LEN) + vint(len(d)) + d
                eliciting = True  # DATAGRAM is ack-eliciting (not retx'd)
            # stream data, gated by packet room + stream & connection
            # flow-control credit (peer replenishes via MAX_STREAM_DATA /
            # MAX_DATA; receipt re-flushes, so stalled chunks resume)
            for st in list(self.send_streams.values()):
                while st.pending:
                    off, data, fin = st.pending[0]
                    room = self.mtu - 96 - len(frames)
                    credit = min(
                        st.credit - off,
                        self.max_data_remote - self.data_sent,
                    )
                    room = min(room, credit) if data else room
                    if room <= 0:
                        break
                    if len(data) > room:
                        st.pending[0] = (off + room, data[room:], fin)
                        data, fin_now = data[:room], False
                    else:
                        st.pending.pop(0)
                        fin_now = fin
                    ftype = F_STREAM_BASE | 0x04 | 0x02 | (0x01 if fin_now else 0)
                    frames += (
                        vint(ftype) + vint(st.sid) + vint(off)
                        + vint(len(data)) + data
                    )
                    track.append(("stream", st.sid, off, data, fin_now))
                    self._stream_unacked[st.sid] = (
                        self._stream_unacked.get(st.sid, 0) + 1
                    )
                    # flow control counts highest offsets, not bytes on
                    # the wire: retransmits don't consume credit (§4.1)
                    new_bytes = max(0, off + len(data) - st.highwater)
                    st.highwater = max(st.highwater, off + len(data))
                    self.data_sent += new_bytes
                    eliciting = True
                if len(frames) > self.mtu - 200:
                    break
        if not frames:
            return b"", [], False
        return bytes(frames), track, eliciting

    # -- receive path ------------------------------------------------------

    def handle_datagram(self, data: bytes) -> None:
        self.last_recv = time.monotonic()
        pos = 0
        while pos < len(data):
            consumed = self._handle_packet(data, pos)
            if consumed <= 0:
                break
            pos += consumed
        # respond (ACKs and any unblocked data) in one flush
        self._flush_sync()

    def _handle_packet(self, data: bytes, start: int) -> int:
        try:
            return self._parse_packet(data, start)
        except QuicError as e:
            log.debug("quic: dropping packet from %s: %s", self.peer_addr, e)
            return -1

    def _parse_packet(self, data: bytes, start: int) -> int:
        first = data[start]
        if first & 0x80:  # long header
            if start + 7 > len(data):
                raise QuicError("truncated long header")
            version = struct.unpack_from(">I", data, start + 1)[0]
            if version != QUIC_V1:
                raise QuicError(f"unsupported version {version:#x}")
            pos = start + 5
            dcl = data[pos]; pos += 1
            dcid = data[pos : pos + dcl]; pos += dcl
            scl = data[pos]; pos += 1
            scid = data[pos : pos + scl]; pos += scl
            ptype = (first >> 4) & 0x03
            if ptype == T_INITIAL:
                tlen, pos = read_vint(data, pos)
                pos += tlen
                space = S_INIT
            elif ptype == T_HANDSHAKE:
                space = S_HS
            else:
                raise QuicError(f"unsupported long packet type {ptype}")
            length, pos = read_vint(data, pos)
            pn_len = (first & 0x03) + 1
            header_end = pos + pn_len
            pkt_end = pos + length
            if pkt_end > len(data) or header_end > pkt_end:
                raise QuicError("truncated long packet")
            # the server's first flight fixes our dcid (§7.2)
            if self.is_client and scid and self.dcid == self.odcid:
                self.dcid = bytes(scid)
        else:  # short header: dcid is OUR scid (fixed CID_LEN)
            pos = start + 1
            dcid = data[pos : pos + CID_LEN]
            pos += CID_LEN
            pn_len = (first & 0x03) + 1
            header_end = pos + pn_len
            pkt_end = len(data)
            space = S_APP
            if header_end > pkt_end:
                raise QuicError("truncated short packet")
        header = bytes(data[start:header_end])
        body = bytes(data[header_end:pkt_end])
        if len(body) < TAG_LEN:
            raise QuicError("packet shorter than tag")
        payload, tag = body[:-TAG_LEN], body[-TAG_LEN:]
        if seahash.tag(header, payload) != tag:
            METRICS.counter("corro.quic.tag_mismatch").inc()
            raise QuicError("integrity tag mismatch")
        sp = self.spaces[space]
        truncated = int.from_bytes(header[-pn_len:], "big")
        pn = decode_pn(truncated, pn_len, sp.recv.largest + 1)
        if not sp.recv.add(pn):
            return pkt_end - start  # duplicate
        eliciting = self._handle_frames(space, payload)
        if eliciting:
            sp.ack_pending = True
        return pkt_end - start

    def _handle_frames(self, space: int, payload: bytes) -> bool:
        pos = 0
        eliciting = False
        sp = self.spaces[space]
        while pos < len(payload):
            ftype, pos = read_vint(payload, pos)
            if ftype == F_PADDING:
                continue
            if ftype == F_PING:
                eliciting = True
                continue
            if ftype in (F_ACK, F_ACK_ECN):
                ranges, pos = parse_ack_frame(payload, pos, ftype == F_ACK_ECN)
                self._on_ack(space, ranges)
                continue
            if ftype == F_CRYPTO:
                off, pos = read_vint(payload, pos)
                ln, pos = read_vint(payload, pos)
                data = payload[pos : pos + ln]
                pos += ln
                eliciting = True
                self._on_crypto(space, off, data)
                continue
            if F_STREAM_BASE <= ftype <= F_STREAM_BASE | 0x07:
                sid, pos = read_vint(payload, pos)
                off = 0
                if ftype & 0x04:
                    off, pos = read_vint(payload, pos)
                if ftype & 0x02:
                    ln, pos = read_vint(payload, pos)
                else:
                    ln = len(payload) - pos
                data = payload[pos : pos + ln]
                pos += ln
                eliciting = True
                self._on_stream(sid, off, data, bool(ftype & 0x01))
                continue
            if ftype in (F_DATAGRAM, F_DATAGRAM_LEN):
                if ftype == F_DATAGRAM_LEN:
                    ln, pos = read_vint(payload, pos)
                else:
                    ln = len(payload) - pos
                data = payload[pos : pos + ln]
                pos += ln
                eliciting = True
                self.endpoint._on_datagram_frame(self, bytes(data))
                continue
            if ftype == F_HANDSHAKE_DONE:
                eliciting = True
                self.handshake_confirmed = True
                self.spaces[S_HS].sent.clear()
                continue
            if ftype == F_MAX_DATA:
                val, pos = read_vint(payload, pos)
                self.max_data_remote = max(self.max_data_remote, val)
                eliciting = True
                continue
            if ftype == F_MAX_STREAM_DATA:
                sid, pos = read_vint(payload, pos)
                val, pos = read_vint(payload, pos)
                st = self.send_streams.get(sid)
                if st is not None:
                    st.credit = max(st.credit, val)
                eliciting = True
                continue
            if ftype in (F_MAX_STREAMS_BIDI, F_MAX_STREAMS_UNI):
                val, pos = read_vint(payload, pos)
                if ftype == F_MAX_STREAMS_UNI:
                    self.peer_max_streams_uni = max(self.peer_max_streams_uni, val)
                else:
                    self.peer_max_streams_bidi = max(self.peer_max_streams_bidi, val)
                self._streams_event.set()
                eliciting = True
                continue
            if ftype in (F_DATA_BLOCKED, F_STREAMS_BLOCKED_BIDI,
                         F_STREAMS_BLOCKED_UNI, F_RETIRE_CONNECTION_ID):
                _v, pos = read_vint(payload, pos)
                eliciting = True
                continue
            if ftype == F_STREAM_DATA_BLOCKED:
                _v, pos = read_vint(payload, pos)
                _v, pos = read_vint(payload, pos)
                eliciting = True
                continue
            if ftype == F_NEW_CONNECTION_ID:
                _seq, pos = read_vint(payload, pos)
                _ret, pos = read_vint(payload, pos)
                cl = payload[pos]; pos += 1 + cl + 16
                eliciting = True
                continue
            if ftype == F_NEW_TOKEN:
                ln, pos = read_vint(payload, pos)
                pos += ln
                eliciting = True
                continue
            if ftype == F_PATH_CHALLENGE:
                sample = payload[pos : pos + 8]
                pos += 8
                self.pending_other.append(vint(F_PATH_RESPONSE) + bytes(sample))
                eliciting = True
                continue
            if ftype == F_PATH_RESPONSE:
                pos += 8
                eliciting = True
                continue
            if ftype in (F_RESET_STREAM,):
                sid, pos = read_vint(payload, pos)
                _err, pos = read_vint(payload, pos)
                _fin, pos = read_vint(payload, pos)
                rs = self.recv_streams.get(sid)
                if rs is not None:
                    rs.reset = True
                    rs.frames.put_nowait(None)
                eliciting = True
                continue
            if ftype == F_STOP_SENDING:
                _sid, pos = read_vint(payload, pos)
                _err, pos = read_vint(payload, pos)
                eliciting = True
                continue
            if ftype in (F_CLOSE_TRANSPORT, F_CLOSE_APP):
                _err, pos = read_vint(payload, pos)
                if ftype == F_CLOSE_TRANSPORT:
                    _ft, pos = read_vint(payload, pos)
                rlen, pos = read_vint(payload, pos)
                reason = payload[pos : pos + rlen].decode("utf-8", "replace")
                pos += rlen
                self.close(f"peer closed: {reason}", send_frame=False)
                return False
            raise QuicError(f"unknown frame type {ftype:#x}")
        return eliciting

    def _on_crypto(self, space: int, off: int, data: bytes) -> None:
        sp = self.spaces[space]
        ready = sp.crypto_recv.feed(off, data)
        if not ready:
            return
        if self.is_client and space == S_HS:
            # server TPs arrive on the handshake CRYPTO stream
            self._apply_peer_params(decode_transport_params(ready))
            rtt = time.monotonic() - getattr(self, "_connect_started", time.monotonic())
            self.srtt = rtt
            self.endpoint._observe_rtt(self.peer_addr, rtt)
            self.established.set()
        elif not self.is_client and space == S_INIT:
            self._apply_peer_params(decode_transport_params(ready))
            self._send_server_flight()

    def _send_server_flight(self) -> None:
        if self._server_flight_sent:
            return
        self._server_flight_sent = True
        tp = self.local_transport_params()
        hs = self.spaces[S_HS]
        hs.crypto_pending.append((0, tp))
        hs.crypto_sent_off = len(tp)
        # Initial-space ACK goes out with the same flush
        self.spaces[S_INIT].ack_pending = True
        self.established.set()

    def _open_remote_stream(self, sid: int, kind: int) -> RecvStream:
        rs = RecvStream(sid)
        self.recv_streams[sid] = rs
        if kind >= 2:  # uni
            self._remote_uni_opened += 1
            self.endpoint._on_uni_stream(self, rs)
        else:
            self._remote_bidi_opened += 1
            # our send half of THEIR bidi stream: limited by the
            # window they advertise for streams they initiated
            send = SendStream(
                sid, self, credit=getattr(self, "msd_bidi_local_remote", 0)
            )
            self.send_streams[sid] = send
            self.endpoint._on_bi_stream(
                self, QuicBiStream(self, sid, send, rs)
            )
        self._maybe_replenish_streams()
        return rs

    def _on_stream(self, sid: int, off: int, data: bytes, fin: bool) -> None:
        # low bits: 0 client-bidi, 1 server-bidi, 2 client-uni, 3 server-uni
        kind = sid & 0x03
        is_uni = kind >= 2
        initiated_by_client = kind in (0, 2)
        remote_initiated = initiated_by_client == (not self.is_client)
        rs = self.recv_streams.get(sid)
        if rs is None:
            if not remote_initiated:
                # our bidi's return half is pre-registered; anything else
                # on our own send side (or a finished local stream's late
                # retransmit) is dropped
                return
            if sid <= self._max_remote_sid[kind]:
                # a sid at/below the high-water that's no longer in the
                # map was opened and finished: stale retransmit, drop
                # (recreating it would re-dispatch a handled payload)
                return
            # §3.2: a higher sid implicitly opens every lower stream of
            # its kind — create them so reordered first-frames still land
            # on live streams rather than being mistaken for stale ones
            lo = self._max_remote_sid[kind] + 4 if \
                self._max_remote_sid[kind] >= 0 else kind
            self._max_remote_sid[kind] = sid
            for s in range(lo, sid, 4):
                if s not in self.recv_streams:
                    self._open_remote_stream(s, kind)
            rs = self._open_remote_stream(sid, kind)
        grown = rs.feed(off, data, fin)
        if rs.asm.finished:
            # the lane reader holds its own reference; dropping the map
            # entry bounds long-lived connections (one uni stream per
            # broadcast) and makes late retransmits identifiable above
            self.recv_streams.pop(sid, None)
        self.data_consumed += grown
        if self.data_consumed > self.max_data_local // 2:
            self.max_data_local += LOCAL_MAX_DATA
            self.pending_other.append(vint(F_MAX_DATA) + vint(self.max_data_local))
        # per-stream window replenishment (long-lived bi sync streams can
        # move more than the initial window in one direction)
        if rs.consumed > rs.max_advert // 2 and not rs.asm.finished:
            rs.max_advert += LOCAL_MAX_STREAM_DATA
            self.pending_other.append(
                vint(F_MAX_STREAM_DATA) + vint(sid) + vint(rs.max_advert)
            )

    def _maybe_replenish_streams(self) -> None:
        if self._remote_uni_opened > self.local_max_streams_uni // 2:
            self.local_max_streams_uni += LOCAL_MAX_STREAMS_UNI
            self.pending_other.append(
                vint(F_MAX_STREAMS_UNI) + vint(self.local_max_streams_uni)
            )
        if self._remote_bidi_opened > self.local_max_streams_bidi // 2:
            self.local_max_streams_bidi += LOCAL_MAX_STREAMS_BIDI
            self.pending_other.append(
                vint(F_MAX_STREAMS_BIDI) + vint(self.local_max_streams_bidi)
            )

    def _gc_send_stream(self, sid: int) -> None:
        """Drop a drained send stream: fin sent, nothing pending, nothing
        in flight — bounds send_streams on long-lived connections (one
        uni stream per broadcast payload)."""
        st = self.send_streams.get(sid)
        if (
            st is not None and st.fin_sent and not st.pending
            and self._stream_unacked.get(sid, 0) == 0
        ):
            self.send_streams.pop(sid, None)
            self._stream_unacked.pop(sid, None)

    def _on_ack(self, space: int, ranges: List[Tuple[int, int]]) -> None:
        sp = self.spaces[space]
        now = time.monotonic()
        for lo, hi in ranges:
            for pn in [p for p in sp.sent if lo <= p <= hi]:
                pkt = sp.sent.pop(pn)
                for fr in pkt.frames:
                    if fr[0] == "stream":
                        sid = fr[1]
                        self._stream_unacked[sid] = max(
                            0, self._stream_unacked.get(sid, 0) - 1
                        )
                        self._gc_send_stream(sid)
                if pn == ranges[0][1]:  # largest acked: RTT sample
                    rtt = now - pkt.sent_at
                    self.srtt = rtt if self.srtt is None \
                        else 0.875 * self.srtt + 0.125 * rtt
                    # dialer-side only (transport.rs rtt_tx feeds from the
                    # client connect path): on inbound conns peer_addr is
                    # the dialer's ephemeral spread socket, not a member
                    # identity — keying members.rtts / per-addr metrics by
                    # it would grow without bound and never hit the ring
                    if self.is_client:
                        self.endpoint._observe_rtt(self.peer_addr, rtt)
            sp.largest_acked = max(sp.largest_acked, hi)
        self.pto_count = 0
        if not self.is_client and space == S_HS:
            # client ACKed our handshake flight: address validated,
            # handshake confirmed server-side (§4.1.2)
            self.handshake_confirmed = True
            self.spaces[S_INIT].sent.clear()

    # -- timers ------------------------------------------------------------

    def _pto(self) -> float:
        base = (self.srtt or 0.1) * 2 + 0.05
        return min(8.0, max(0.2, base)) * (1 << min(self.pto_count, 6))

    async def _timer_loop(self) -> None:
        try:
            while not self.closed.is_set():
                await asyncio.sleep(min(self._pto() / 2, 0.5))
                now = time.monotonic()
                if now - self.last_recv > self.idle_timeout:
                    self.close("idle timeout", send_frame=False)
                    return
                pto = self._pto()
                fired = False
                for space in (S_INIT, S_HS, S_APP):
                    sp = self.spaces[space]
                    for pn in list(sp.sent):
                        pkt = sp.sent.get(pn)
                        if pkt is None or now - pkt.sent_at < pto:
                            continue
                        sp.sent.pop(pn, None)
                        if not pkt.frames:
                            continue
                        fired = True
                        # quinn path-stats analog (corro.transport.path.*):
                        # a PTO-expired packet is declared lost
                        METRICS.counter("corro.transport.path.lost_packets").inc()
                        for fr in pkt.frames:
                            self._requeue(space, fr)
                if fired:
                    self.pto_count += 1
                    if self.pto_count > MAX_PTO_COUNT:
                        self.close("retransmission limit", send_frame=False)
                        return
                    self._flush_sync()
        except asyncio.CancelledError:
            pass

    def _requeue(self, space: int, fr: tuple) -> None:
        if fr[0] == "crypto":
            _, sp_idx, off, data = fr
            self.spaces[sp_idx].crypto_pending.append((off, data))
        elif fr[0] == "stream":
            _, sid, off, data, fin = fr
            self._stream_unacked[sid] = max(
                0, self._stream_unacked.get(sid, 0) - 1
            )
            st = self.send_streams.get(sid)
            if st is not None:
                st.pending.append((off, data, fin))
        elif fr[0] == "hsdone":
            self._hs_done_sent = False
        elif fr[0] == "other":
            self.pending_other.append(fr[1])


# ---------------------------------------------------------------------------
# endpoint


def gso_groups(grams: List[bytes]) -> List[Tuple[int, List[bytes]]]:
    """Greedy-group consecutive datagrams for UDP_SEGMENT coalescing.

    A valid GSO batch is N equal-size segments plus at most one shorter
    trailer, within the kernel's segment-count and total-size bounds.
    Returns [(segment_size, [datagrams...])]; singleton groups mean "send
    plain".  Order is preserved — QUIC tolerates reordering but there is
    no reason to introduce any.
    """
    groups: List[Tuple[int, List[bytes]]] = []
    i = 0
    while i < len(grams):
        seg = len(grams[i])
        total = seg
        j = i + 1
        while (j < len(grams) and len(grams[j]) == seg
               and j - i < GSO_MAX_SEGS and total + seg <= GSO_MAX_BYTES):
            total += seg
            j += 1
        if (j < len(grams) and len(grams[j]) < seg
                and j - i < GSO_MAX_SEGS
                and total + len(grams[j]) <= GSO_MAX_BYTES):
            j += 1  # shorter trailer rides the same batch
        groups.append((seg, grams[i:j]))
        i = j
    return groups


class _UdpProto(asyncio.DatagramProtocol):
    def __init__(self, endpoint: "QuicEndpoint") -> None:
        self.endpoint = endpoint

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            self.endpoint._on_udp(data, addr)
        except Exception:  # noqa: BLE001 — a bad packet must not kill the loop
            log.exception("quic: error handling datagram from %s", addr)


class QuicEndpoint(Listener):
    """One UDP socket serving and dialing plaintext-QUIC connections.

    Like the reference's gossip endpoint, a single socket accepts inbound
    connections (`handlers.rs:54-190`) while the Transport dials outbound
    from the same identity."""

    def __init__(self, mtu: int = MAX_UDP,
                 accept_inbound: bool = True) -> None:
        self.mtu = min(mtu, MAX_UDP)
        # dial-only spread sockets (quinn client endpoints accept no
        # inbound): a stray Initial must not spawn a server-role
        # connection + timer on an unauthenticated open port
        self.accept_inbound = accept_inbound
        self._udp_transport = None
        self._addr = ""
        self.conns_by_scid: Dict[bytes, QuicConnection] = {}
        self.conns_by_odcid: Dict[bytes, QuicConnection] = {}
        self.conns_by_peer: Dict[Tuple[str, int], QuicConnection] = {}
        self._on_dgram = None
        self._on_uni = None
        self._on_bi = None
        self._rtt_sink: Optional[Callable[[str, float], None]] = None
        self._handler_tasks: set = set()
        # UDP GSO: assumed available until a sendmsg says otherwise
        # (Linux ≥4.18; EINVAL/ENOTSUP flips this off permanently)
        self._gso_ok = sys.platform == "linux"
        self._gso_sock: Optional[socket.socket] = None
        self._gso_fail_streak = 0

    @classmethod
    async def bind(cls, host: str = "127.0.0.1", port: int = 0,
                   mtu: int = MAX_UDP,
                   accept_inbound: bool = True) -> "QuicEndpoint":
        self = cls(mtu=mtu, accept_inbound=accept_inbound)
        loop = asyncio.get_event_loop()
        self._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProto(self), local_addr=(host, port)
        )
        sock = self._udp_transport.get_extra_info("sockname")
        self._addr = f"{host}:{sock[1]}"
        # asyncio's TransportSocket hides sendmsg; dup the fd into a real
        # socket object for the GSO path (shares the bound UDP socket)
        if self._gso_ok:
            raw = self._udp_transport.get_extra_info("socket")
            fd = -1
            try:
                fd = os.dup(raw.fileno())
                self._gso_sock = socket.socket(fileno=fd)
                self._gso_sock.setblocking(False)
            except (OSError, AttributeError):
                self._gso_ok = False
                if fd >= 0 and self._gso_sock is None:
                    os.close(fd)
        return self

    # Listener interface
    def serve(self, on_datagram, on_uni, on_bi) -> None:
        self._on_dgram = on_datagram
        self._on_uni = on_uni
        self._on_bi = on_bi

    @property
    def addr(self) -> str:
        return self._addr

    async def close(self) -> None:
        for conn in list(self.conns_by_scid.values()):
            conn.close("endpoint closed")
        if self._udp_transport is not None:
            self._udp_transport.close()
        if self._gso_sock is not None:
            self._gso_sock.close()
            self._gso_sock = None
        for t in list(self._handler_tasks):
            t.cancel()

    # -- plumbing ----------------------------------------------------------

    def _sendto(self, data: bytes, peer: Tuple[str, int]) -> None:
        if self._udp_transport is not None:
            self._udp_transport.sendto(data, peer)
            METRICS.counter("corro.quic.udp_tx.bytes").inc(len(data))

    def _send_batch(self, grams: List[bytes], peer: Tuple[str, int]) -> None:
        """Send a flush's datagrams, GSO-coalescing where the kernel allows.

        Falls back to per-datagram transport sends when GSO is probed
        unsupported, the batch doesn't coalesce, the asyncio transport has
        buffered writes pending (a raw sendmsg would jump that queue), or
        the socket would block (the transport path buffers for us).
        """
        if self._udp_transport is None:
            return
        sock = self._gso_sock
        if (not self._gso_ok or len(grams) < 2 or sock is None
                or self._udp_transport.get_write_buffer_size() > 0):
            if self._gso_ok and sock is not None and len(grams) >= 2:
                METRICS.counter("corro.quic.gso.diverted").inc()
            for g in grams:
                self._sendto(g, peer)
            return
        blocked = False  # once one group buffers, the rest must follow it
        gso_sent = gso_failed = False
        for seg, group in gso_groups(grams):
            # a singleton/fallback group may itself have buffered into the
            # transport; a raw sendmsg after that would jump the queue
            if not blocked and self._udp_transport.get_write_buffer_size():
                blocked = True
            if blocked or len(group) < 2 or not self._gso_ok:
                if blocked and len(group) >= 2 and self._gso_ok:
                    METRICS.counter("corro.quic.gso.diverted").inc()
                for g in group:
                    self._sendto(g, peer)
                continue
            cmsg = [(SOL_UDP, UDP_SEGMENT, struct.pack("@H", seg))]
            try:
                sock.sendmsg([b"".join(group)], cmsg, 0, peer)
            except BlockingIOError:
                # this group goes to the transport's write buffer; a later
                # raw sendmsg would jump ahead of it, so stop GSO here
                blocked = True
                METRICS.counter("corro.quic.gso.diverted").inc()
                for g in group:
                    self._sendto(g, peer)
                continue
            except OSError as e:
                if e.errno in (errno.EINVAL, errno.ENOTSUP, errno.EOPNOTSUPP):
                    # kernel or socket refuses GSO itself — disable for
                    # this endpoint's lifetime
                    log.debug("quic: GSO unsupported (%s); disabling", e)
                    self._gso_ok = False
                else:
                    # transient send error (ENOBUFS, EPERM, ...): fall
                    # back and keep GSO armed for now
                    gso_failed = True
                    log.debug("quic: GSO send failed (%s); falling back", e)
                for g in group:
                    self._sendto(g, peer)
                continue
            gso_sent = True
            METRICS.counter("corro.quic.udp_tx.bytes").inc(
                sum(len(g) for g in group)
            )
            METRICS.counter("corro.quic.gso.batches").inc()
            METRICS.counter("corro.quic.gso.segments").inc(len(group))
        # failure accounting is per FLUSH, not per group: one ENOBUFS
        # burst inside a single flush is a moment of buffer pressure, but
        # three consecutive flushes failing with zero successes looks
        # deterministic (e.g. route-state EMSGSIZE) — stop paying a
        # doomed syscall per flush at that point
        if gso_sent:
            self._gso_fail_streak = 0
        elif gso_failed:
            self._gso_fail_streak += 1
            if self._gso_fail_streak >= 3 and self._gso_ok:
                log.debug(
                    "quic: GSO failed %d consecutive flushes; disabling",
                    self._gso_fail_streak,
                )
                self._gso_ok = False

    def _observe_rtt(self, addr: str, rtt: float) -> None:
        if self._rtt_sink is not None:
            self._rtt_sink(addr, rtt)

    def _forget(self, conn: QuicConnection) -> None:
        self.conns_by_scid.pop(conn.scid, None)
        if conn.odcid:
            self.conns_by_odcid.pop(conn.odcid, None)
        if self.conns_by_peer.get(conn.peer) is conn:
            self.conns_by_peer.pop(conn.peer, None)

    async def connect(self, addr: str) -> QuicConnection:
        host, _, port = addr.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]
        peer = (host, int(port))
        conn = QuicConnection(self, peer, is_client=True)
        self.conns_by_scid[conn.scid] = conn
        self.conns_by_peer[peer] = conn
        conn.start()
        try:
            await conn.connect()
        except asyncio.TimeoutError:
            conn.close("connect timeout", send_frame=False)
            raise QuicError(f"connect {addr}: timeout") from None
        if conn.closed.is_set():
            raise QuicError(f"connect {addr}: {conn.close_reason}")
        return conn

    def _on_udp(self, data: bytes, addr) -> None:
        peer = (addr[0], addr[1])
        conn = self._route(data, peer)
        if conn is None:
            return
        conn.handle_datagram(data)

    def _route(self, data: bytes, peer) -> Optional[QuicConnection]:
        if not data:
            return None
        first = data[0]
        if first & 0x80:  # long header: explicit dcid
            if len(data) < 7:
                return None
            dcl = data[5]
            dcid = bytes(data[6 : 6 + dcl])
            conn = self.conns_by_scid.get(dcid)
            if conn is not None:
                return conn
            conn = self.conns_by_odcid.get(dcid)
            if conn is not None:
                return conn
            ptype = (first >> 4) & 0x03
            if ptype == T_INITIAL and self.accept_inbound:
                # new inbound connection (server role); lanes without a
                # serve() handler simply drop their payloads
                scl_pos = 6 + dcl
                scl = data[scl_pos]
                client_scid = bytes(data[scl_pos + 1 : scl_pos + 1 + scl])
                conn = QuicConnection(self, peer, is_client=False)
                conn.odcid = dcid
                conn.dcid = client_scid
                self.conns_by_scid[conn.scid] = conn
                self.conns_by_odcid[dcid] = conn
                self.conns_by_peer.setdefault(peer, conn)
                conn.start()
                return conn
            return None
        # short header: dcid = our fixed-length scid
        dcid = bytes(data[1 : 1 + CID_LEN])
        conn = self.conns_by_scid.get(dcid)
        if conn is not None:
            return conn
        return self.conns_by_peer.get(peer)

    # -- lane dispatch -----------------------------------------------------

    def _spawn(self, coro) -> None:
        t = asyncio.ensure_future(coro)
        self._handler_tasks.add(t)
        t.add_done_callback(self._handler_tasks.discard)

    def _on_datagram_frame(self, conn: QuicConnection, data: bytes) -> None:
        if self._on_dgram is not None:
            self._spawn(self._on_dgram(conn.peer_addr, data))

    def _on_uni_stream(self, conn: QuicConnection, rs: RecvStream) -> None:
        if self._on_uni is None:
            return

        async def reader():
            while True:
                frame = await rs.frames.get()
                if frame is None:
                    return
                await self._on_uni(conn.peer_addr, frame)

        self._spawn(reader())

    def _on_bi_stream(self, conn: QuicConnection, bi: QuicBiStream) -> None:
        if self._on_bi is not None:
            self._spawn(self._on_bi(bi))


# ---------------------------------------------------------------------------
# Transport seam


class QuicTransport(Transport):
    """Client half over a shared QuicEndpoint: cached connections per
    peer with one reconnect retry, RTT observations into the members
    rings — the shape of `transport.rs:81-230`.

    When `client_endpoints` is given, outbound dials spread across those
    dial-only sockets, picked by SeaHash of the peer addr mod the socket
    count (`transport.rs:170-173` measured_connect) — the reference's
    8-endpoint kernel-buffer dilution.  Without it, dials originate from
    the serving endpoint (single-socket identity mode, used by tests and
    standalone endpoints).  Peers never reply to the dialing socket's
    source addr — SWIM replies go to the payload-embedded advertised
    addr — so dial-only sockets need no serve() handlers."""

    def __init__(self, endpoint: QuicEndpoint,
                 idle_timeout: float = 30.0,
                 client_endpoints: Optional[List[QuicEndpoint]] = None,
                 ) -> None:
        self._endpoint = endpoint
        self._client_eps = list(client_endpoints or [])
        for ep in (endpoint, *self._client_eps):
            ep._rtt_sink = lambda addr, rtt: self.observe_rtt(addr, rtt)
        self._idle_timeout = idle_timeout
        self._conns: Dict[str, QuicConnection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}

    def _dial_endpoint(self, addr: str) -> QuicEndpoint:
        if not self._client_eps:
            return self._endpoint
        idx = seahash.hash_bytes(addr.encode()) % len(self._client_eps)
        return self._client_eps[idx]

    async def _conn(self, addr: str) -> QuicConnection:
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed.is_set():
            return conn
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed.is_set():
                return conn
            conn = await self._dial_endpoint(addr).connect(addr)
            conn.idle_timeout = self._idle_timeout
            self._conns[addr] = conn
            METRICS.counter("corro.quic.connect.total").inc()
            return conn

    async def send_datagram(self, addr: str, data: bytes) -> None:
        for attempt in (0, 1):
            conn = await self._conn(addr)
            try:
                await conn.send_datagram(data)
                METRICS.counter("corro.transport.datagram.sent").inc()
                return
            except QuicError:
                self._conns.pop(addr, None)
                if attempt:
                    METRICS.counter("corro.transport.datagram.failed").inc()
                    raise

    async def send_uni(self, addr: str, payload: bytes) -> None:
        for attempt in (0, 1):
            conn = await self._conn(addr)
            try:
                st = await conn.open_uni()
                await st.send_frame(payload, fin=True)
                METRICS.counter(
                    "corro.transport.frames.sent", lane="U"
                ).inc()
                return
            except QuicError:
                self._conns.pop(addr, None)
                if attempt:
                    raise

    async def open_bi(self, addr: str) -> BiStream:
        conn = await self._conn(addr)
        bi = await conn.open_bi()
        METRICS.counter("corro.transport.bi.opened").inc()
        return bi

    async def close(self) -> None:
        for conn in list(self._conns.values()):
            conn.close("transport closed")
        for ep in self._client_eps:
            await ep.close()
        self._conns.clear()

"""SWIM datagram wire format.

The reference serializes foca messages with bincode
(`broadcast/mod.rs:140`); those layouts are internal to foca, so this
codec defines our own compact equivalent carrying the same information:
a header (message kind, probe number, sender Actor), an optional target
Actor (indirect probes), and a piggybacked list of membership updates —
foca's cluster-update dissemination section. Packets must stay under the
SWIM packet budget (1178 B, `broadcast/mod.rs:957`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional

from corrosion_tpu.types.actor import Actor, ActorId, ClusterId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.codec import Reader, Writer

MAX_PACKET = 1178  # broadcast/mod.rs:957


class MsgKind(IntEnum):
    PING = 0
    ACK = 1
    PING_REQ = 2  # ask a third party to probe target for us
    INDIRECT_PING = 3  # the third party's probe, carries origin
    INDIRECT_ACK = 4  # target's reply routed back via the third party
    FORWARDED_ACK = 5  # third party forwarding the ack to the origin
    ANNOUNCE = 6  # join request
    FEED = 7  # membership snapshot reply to an announce
    LEAVE = 8  # graceful departure


class MemberState(IntEnum):
    ALIVE = 0
    SUSPECT = 1
    DOWN = 2


@dataclass(frozen=True)
class MemberUpdate:
    """One piggybacked membership assertion."""

    actor: Actor
    incarnation: int
    state: MemberState


@dataclass
class SwimMessage:
    kind: MsgKind
    probe_no: int
    sender: Actor
    target: Optional[Actor] = None  # PING_REQ/INDIRECT_*: who to probe
    origin: Optional[Actor] = None  # INDIRECT_*: who asked
    updates: List[MemberUpdate] = field(default_factory=list)
    # r12 cluster observatory: an encoded telemetry digest
    # (runtime/digest.py) riding a version-gated TRAILING ext — opaque
    # bytes here, same compat discipline as the broadcast envelope ext
    # (types/codec.py): digest-free packets are byte-identical to the
    # pre-r12 layout and old decoders stop reading before the ext
    digest: Optional[bytes] = None


# trailing-ext version byte (only written when a digest rides along)
_SWIM_EXT_V1 = 1


def write_actor(w: Writer, a: Actor) -> None:
    w.raw(a.id.bytes16)
    w.string(a.addr)
    w.u64(a.ts.ntp64)
    w.u16(a.cluster_id.value)
    w.u16(a.bump)


def read_actor(r: Reader) -> Actor:
    id_ = ActorId(bytes(r.raw(16)))
    addr = r.string()
    ts = Timestamp(r.u64())
    cluster_id = ClusterId(r.u16())
    bump = r.u16()
    return Actor(id=id_, addr=addr, ts=ts, cluster_id=cluster_id, bump=bump)


def actor_wire_size(a: Actor) -> int:
    return 16 + 4 + len(a.addr.encode()) + 8 + 2 + 2


def update_wire_size(u: MemberUpdate) -> int:
    return actor_wire_size(u.actor) + 4 + 1


def fill_updates(msg: SwimMessage, sample) -> None:
    """Append piggybacked updates from `sample` while the ENCODED packet
    stays under MAX_PACKET. Budgeting off the actual encoded size keeps
    the arithmetic exact for every message shape (target/origin actors
    included) and in one audited place."""
    budget = MAX_PACKET - len(encode_swim(msg)) - 8
    for u in sample:
        size = update_wire_size(u)
        if budget - size < 0:
            break
        msg.updates.append(u)
        budget -= size


def encode_swim(msg: SwimMessage) -> bytes:
    w = Writer()
    w.u8(int(msg.kind))
    w.u32(msg.probe_no)
    write_actor(w, msg.sender)
    w.opt(msg.target, lambda a: write_actor(w, a))
    w.opt(msg.origin, lambda a: write_actor(w, a))
    w.u16(len(msg.updates))
    for u in msg.updates:
        write_actor(w, u.actor)
        w.u32(u.incarnation)
        w.u8(int(u.state))
    if msg.digest is not None:
        w.u8(_SWIM_EXT_V1)
        w.vec_u8(msg.digest)
    return w.bytes()


def decode_swim(data: bytes) -> SwimMessage:
    r = Reader(data)
    kind = MsgKind(r.u8())
    probe_no = r.u32()
    sender = read_actor(r)
    target = read_actor(r) if r.u8() else None
    origin = read_actor(r) if r.u8() else None
    n = r.u16()
    updates = [
        MemberUpdate(read_actor(r), r.u32(), MemberState(r.u8()))
        for _ in range(n)
    ]
    digest = None
    if not r.eof() and r.u8() >= _SWIM_EXT_V1 and not r.eof():
        digest = r.vec_u8()
    return SwimMessage(
        kind=kind,
        probe_no=probe_no,
        sender=sender,
        target=target,
        origin=origin,
        updates=updates,
        digest=digest,
    )

"""Transport seam: datagram / uni-stream / bi-stream.

Behavioral counterpart of `klukai-agent/src/transport.rs:26-443`: the rest
of the runtime only ever calls `send_datagram` (SWIM), `send_uni`
(broadcast) and `open_bi` (sync) — everything else (connection caching,
retries, RTT observation) lives behind this interface. Server-side, a
`Listener` receives the three lanes as callbacks, mirroring the accept
loop in `klukai-agent/src/agent/handlers.rs:54-190`.

Addresses are plain strings (`"host:port"` for real sockets, opaque labels
for the in-memory network).
"""

from __future__ import annotations

import abc
from typing import Awaitable, Callable, Optional

from corrosion_tpu.runtime.metrics import METRICS


class TransportError(Exception):
    pass


class BiStream(abc.ABC):
    """One bidirectional framed stream (sync session lane).

    Frames are length-delimited payloads (u32 BE prefix on the wire
    implementations, matching tokio's LengthDelimitedCodec default used at
    `klukai-agent/src/agent/bi.rs:21`).
    """

    @abc.abstractmethod
    async def send(self, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def recv(self) -> Optional[bytes]:
        """Next frame, or None once the peer finished its side."""

    @abc.abstractmethod
    async def finish(self) -> None:
        """Half-close our send side (quinn SendStream::finish)."""

    @abc.abstractmethod
    def close(self) -> None:
        """Tear down both directions."""

    @property
    @abc.abstractmethod
    def peer(self) -> str: ...


# server-side lane handlers
DatagramHandler = Callable[[str, bytes], Awaitable[None]]
UniHandler = Callable[[str, bytes], Awaitable[None]]  # one frame at a time
BiHandler = Callable[[BiStream], Awaitable[None]]


class Listener(abc.ABC):
    """Server half: owns the bound address and dispatches the three lanes."""

    @abc.abstractmethod
    def serve(
        self,
        on_datagram: DatagramHandler,
        on_uni: UniHandler,
        on_bi: BiHandler,
    ) -> None: ...

    @property
    @abc.abstractmethod
    def addr(self) -> str: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Transport(abc.ABC):
    """Client half: the only networking surface the runtime consumes."""

    @abc.abstractmethod
    async def send_datagram(self, addr: str, data: bytes) -> None: ...

    @abc.abstractmethod
    async def send_uni(self, addr: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def open_bi(self, addr: str) -> BiStream: ...

    async def close(self) -> None:  # pragma: no cover - trivial default
        pass

    # RTT observations feed Members' rings (transport.rs:220)
    def observe_rtt(self, addr: str, rtt: float) -> None:
        METRICS.histogram("corro.transport.rtt.seconds", addr=addr).observe(rtt)
        if self._rtt_sink is not None:
            self._rtt_sink(addr, rtt)

    _rtt_sink: Optional[Callable[[str, float], None]] = None

    def set_rtt_sink(self, sink: Callable[[str, float], None]) -> None:
        self._rtt_sink = sink

"""Device-mesh sharding for the member axis (ICI-scaled SWIM)."""

from corrosion_tpu.parallel.mesh import (
    member_mesh,
    shard_member_state,
    shard_swim_state,
    sharded_pview_tick,
    sharded_tick,
)

__all__ = [
    "member_mesh",
    "shard_member_state",
    "shard_swim_state",
    "sharded_pview_tick",
    "sharded_tick",
]

"""Device-mesh sharding for the member axis (ICI-scaled SWIM)."""

from corrosion_tpu.parallel.mesh import (
    host_member_spec,
    member_mesh,
    multihost_member_mesh,
    shard_member_state,
    shard_swim_state,
    sharded_pview_tick,
    sharded_tick,
)

__all__ = [
    "host_member_spec",
    "member_mesh",
    "multihost_member_mesh",
    "shard_member_state",
    "shard_swim_state",
    "sharded_pview_tick",
    "sharded_tick",
]

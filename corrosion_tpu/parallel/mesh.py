"""Member-axis sharding over a jax.sharding.Mesh.

The reference scales clusters by spawning more processes; the TPU design
shards the *member dimension* across devices (SURVEY.md §2.6): every
per-member array — and the [N, N] view matrix's observer axis — is laid
out `P("members", ...)` so each device owns a contiguous block of
observers. Cross-shard message delivery (gossip scatter-max, feed-window
gathers of other shards' view rows) compiles to XLA collectives over ICI;
we annotate shardings and let the compiler insert them rather than
hand-writing NCCL-style exchanges.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops import swim, swim_pview

MEMBER_AXIS = "members"


def member_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(MEMBER_AXIS,))


def _sharding_for(mesh: Mesh, ndim: int) -> NamedSharding:
    # observer axis sharded, every other axis replicated-dim
    spec = [MEMBER_AXIS] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def shard_member_state(state, mesh: Mesh):
    """Lay every per-member array of a state NamedTuple out row-sharded
    over the mesh (works for both `swim.SwimState` and
    `swim_pview.PViewState` — every array's leading axis is the member
    dimension). Scalars (the tick counter) stay replicated.

    The placement rule lives ONLY in `_state_shardings`; this just
    device_puts against it."""
    shardings = _state_shardings(state, mesh)
    return type(state)(
        **{
            name: jax.device_put(arr, getattr(shardings, name))
            for name, arr in state._asdict().items()
        }
    )


# back-compat alias (r1/r2 name)
shard_swim_state = shard_member_state


def _state_shardings(state, mesh: Mesh):
    out = {}
    for name, arr in state._asdict().items():
        if getattr(arr, "ndim", 0) == 0:
            out[name] = NamedSharding(mesh, P())
        else:
            out[name] = _sharding_for(mesh, arr.ndim)
    return type(state)(**out)


def sharded_tick(params: swim.SwimParams, mesh: Mesh, k: int = 1):
    """A jitted k-tick step whose outputs are constrained to the member
    sharding (inputs carry their shardings; XLA inserts the ICI
    collectives for the cross-shard gather/scatter in delivery and feed).
    With k>1 the ticks run as one lax.scan dispatch — the multi-chip
    convergence driver's shape (host syncs only between scans)."""

    example = jax.eval_shape(
        lambda: swim.init_state(params, jax.random.PRNGKey(0))
    )
    out_shardings = _state_shardings(example, mesh)

    def _tick(state: swim.SwimState, rng: jax.Array) -> swim.SwimState:
        if k == 1:
            return swim.tick_impl(state, rng, params)
        return swim._tick_n_impl(state, rng, params, k)

    return jax.jit(_tick, out_shardings=out_shardings)


def sharded_pview_tick(params: swim_pview.PViewParams, mesh: Mesh, k: int = 1):
    """Sharded k-tick step for the bounded partial-view kernel
    (`ops/swim_pview.py`): every state array is row-sharded over the
    member axis; the O(N·K) slot table is what carries the member count
    past the dense kernel's [N, N] memory wall (262k+ on a v5e-8)."""

    example = jax.eval_shape(
        lambda: swim_pview.init_state(params, jax.random.PRNGKey(0))
    )
    out_shardings = _state_shardings(example, mesh)

    def _tick(state, rng):
        if k == 1:
            return swim_pview.tick_impl(state, rng, params)
        return swim_pview._tick_n_impl(state, rng, params, k)

    return jax.jit(_tick, out_shardings=out_shardings)

"""Member-axis sharding over a jax.sharding.Mesh.

The reference scales clusters by spawning more processes; the TPU design
shards the *member dimension* across devices (SURVEY.md §2.6): every
per-member array — and the [N, N] view matrix's observer axis — is laid
out `P("members", ...)` so each device owns a contiguous block of
observers. Cross-shard message delivery (gossip scatter-max, feed-window
gathers of other shards' view rows) compiles to XLA collectives over ICI;
we annotate shardings and let the compiler insert them rather than
hand-writing NCCL-style exchanges.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops import swim, swim_pview

MEMBER_AXIS = "members"
HOST_AXIS = "hosts"


def member_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(MEMBER_AXIS,))


def multihost_member_mesh(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Mesh:
    """Member-axis mesh spanning EVERY process of a multi-host job.

    This is the DCN story (the reference scales out with one QUIC mesh
    per process; we scale the member axis over hosts): jax.distributed
    connects the processes (coordinator via args or the standard
    JAX_COORDINATOR_ADDRESS / Cloud TPU metadata), after which
    `jax.devices()` lists every chip in the job. The mesh is shaped
    [hosts, members] with the HOST axis outermost, so a sharding of
    `P((HOST_AXIS, MEMBER_AXIS))` keeps each host's member block
    contiguous on its own chips: the per-tick gossip/feed collectives
    between co-located chips ride ICI, and only the cross-host slices of
    the delivery all-to-all cross DCN — the layout rule from the scaling
    playbook (collectives on the fast axis innermost).

    In a single-process job this degrades to the ordinary member mesh
    (no jax.distributed needed). The multi-process path is exercised for
    real in CI: tests/test_dcn_multiprocess.py joins two local processes
    (4 virtual CPU devices each) through jax.distributed and asserts the
    cross-process sharded tick stays bit-identical to the single-process
    flat-mesh run.
    """
    import os
    from collections import Counter

    already = jax.distributed.is_initialized()
    # auto-init when the caller passed coordinates OR the standard env
    # carries them (jax.distributed.initialize reads the env itself);
    # a bare single-process run must NOT attempt cluster discovery
    wants_init = (
        coordinator_address is not None
        or num_processes is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS") is not None
    )
    if not already and wants_init:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    # group by owning process — jax.devices() order is NOT guaranteed
    # process-contiguous, and a positional reshape could put two hosts'
    # chips in one mesh row (ICI row becomes a DCN row, silently)
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    counts = Counter(d.process_index for d in devs)
    per_host = set(counts.values())
    if len(per_host) != 1:
        raise ValueError(
            f"uneven device count per host: {dict(counts)} — a rectangular "
            "[hosts, members] mesh needs equal chips per process"
        )
    grid = np.array(devs).reshape(len(counts), per_host.pop())
    return Mesh(grid, axis_names=(HOST_AXIS, MEMBER_AXIS))


def host_member_spec(ndim: int) -> P:
    """PartitionSpec sharding the leading (member) axis over BOTH mesh
    axes of a `multihost_member_mesh` — host-major blocks, ICI-contiguous
    within a host."""
    return P((HOST_AXIS, MEMBER_AXIS), *([None] * (ndim - 1)))


def _sharding_for(mesh: Mesh, ndim: int) -> NamedSharding:
    # observer axis sharded, every other axis replicated-dim; on a
    # multi-host [hosts, members] mesh the observer axis spans BOTH mesh
    # axes host-major (see multihost_member_mesh)
    if HOST_AXIS in mesh.axis_names:
        return NamedSharding(mesh, host_member_spec(ndim))
    spec = [MEMBER_AXIS] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def shard_member_state(state, mesh: Mesh):
    """Lay every per-member array of a state NamedTuple out row-sharded
    over the mesh (works for both `swim.SwimState` and
    `swim_pview.PViewState` — every array's leading axis is the member
    dimension). Scalars (the tick counter) stay replicated.

    The placement rule lives ONLY in `_state_shardings`; this just
    device_puts against it."""
    shardings = _state_shardings(state, mesh)
    return type(state)(
        **{
            name: jax.device_put(arr, getattr(shardings, name))
            for name, arr in state._asdict().items()
        }
    )


# back-compat alias (r1/r2 name)
shard_swim_state = shard_member_state


def _state_shardings(state, mesh: Mesh):
    out = {}
    for name, arr in state._asdict().items():
        if getattr(arr, "ndim", 0) == 0 or name in ("events", "ring"):
            # scalars AND the telemetry lanes replicate: the [N_EVENTS]
            # events vector and the [ring_ticks, N_FLIGHT_LANES] flight
            # ring are not per-member arrays (their leading axes are
            # table sizes, not member counts), and their integer
            # sums/maxes all-reduce bit-identically.  The r9 Lifeguard
            # lanes (lhm, susp_conf/susp_start, deg_loss/deg_lag) are
            # ordinary per-member arrays and take the member sharding
            # below — only these two stay replicated by name.
            out[name] = NamedSharding(mesh, P())
        else:
            out[name] = _sharding_for(mesh, arr.ndim)
    return type(state)(**out)


def sharded_tick(params: swim.SwimParams, mesh: Mesh, k: int = 1):
    """A jitted k-tick step whose outputs are constrained to the member
    sharding (inputs carry their shardings; XLA inserts the ICI
    collectives for the cross-shard gather/scatter in delivery and feed).
    With k>1 the ticks run as one lax.scan dispatch — the multi-chip
    convergence driver's shape (host syncs only between scans)."""

    example = jax.eval_shape(
        lambda: swim.init_state(params, jax.random.PRNGKey(0))
    )
    out_shardings = _state_shardings(example, mesh)

    def _tick(state: swim.SwimState, rng: jax.Array) -> swim.SwimState:
        if k == 1:
            return swim.tick_impl(state, rng, params)
        return swim._tick_n_impl(state, rng, params, k)

    return jax.jit(_tick, out_shardings=out_shardings)


def sharded_pview_tick(params: swim_pview.PViewParams, mesh: Mesh, k: int = 1):
    """Sharded k-tick step for the bounded partial-view kernel
    (`ops/swim_pview.py`): every state array is row-sharded over the
    member axis; the O(N·K) slot table is what carries the member count
    past the dense kernel's [N, N] memory wall (262k+ on a v5e-8)."""

    example = jax.eval_shape(
        lambda: swim_pview.init_state(params, jax.random.PRNGKey(0))
    )
    out_shardings = _state_shardings(example, mesh)

    def _tick(state, rng):
        if k == 1:
            return swim_pview.tick_impl(state, rng, params)
        return swim_pview._tick_n_impl(state, rng, params, k)

    return jax.jit(_tick, out_shardings=out_shardings)

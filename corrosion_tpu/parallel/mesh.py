"""Member-axis sharding over a jax.sharding.Mesh.

The reference scales clusters by spawning more processes; the TPU design
shards the *member dimension* across devices (SURVEY.md §2.6): every
per-member array — and the [N, N] view matrix's observer axis — is laid
out `P("members", ...)` so each device owns a contiguous block of
observers. Cross-shard message delivery (gossip scatter-max, feed-window
gathers of other shards' view rows) compiles to XLA collectives over ICI;
we annotate shardings and let the compiler insert them rather than
hand-writing NCCL-style exchanges.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.ops import swim

MEMBER_AXIS = "members"


def member_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(MEMBER_AXIS,))


def _sharding_for(mesh: Mesh, ndim: int) -> NamedSharding:
    # observer axis sharded, every other axis replicated-dim
    spec = [MEMBER_AXIS] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def shard_swim_state(state: swim.SwimState, mesh: Mesh) -> swim.SwimState:
    """Lay every per-member array out row-sharded over the mesh.

    Scalars (the tick counter) stay replicated.
    """
    out = {}
    for name, arr in state._asdict().items():
        if getattr(arr, "ndim", 0) == 0:
            out[name] = jax.device_put(arr, NamedSharding(mesh, P()))
        else:
            out[name] = jax.device_put(arr, _sharding_for(mesh, arr.ndim))
    return swim.SwimState(**out)


def sharded_tick(params: swim.SwimParams, mesh: Mesh, k: int = 1):
    """A jitted k-tick step whose outputs are constrained to the member
    sharding (inputs carry their shardings; XLA inserts the ICI
    collectives for the cross-shard gather/scatter in delivery and feed).
    With k>1 the ticks run as one lax.scan dispatch — the multi-chip
    convergence driver's shape (host syncs only between scans)."""

    out_shardings = swim.SwimState(
        t=NamedSharding(mesh, P()),
        alive=_sharding_for(mesh, 1),
        inc=_sharding_for(mesh, 1),
        view=_sharding_for(mesh, 2),
        buf_subj=_sharding_for(mesh, 2),
        buf_key=_sharding_for(mesh, 2),
        buf_sent=_sharding_for(mesh, 2),
        probe_phase=_sharding_for(mesh, 1),
        probe_subj=_sharding_for(mesh, 1),
        probe_deadline=_sharding_for(mesh, 1),
        probe_ok=_sharding_for(mesh, 1),
        susp_subj=_sharding_for(mesh, 2),
        susp_inc=_sharding_for(mesh, 2),
        susp_deadline=_sharding_for(mesh, 2),
    )

    def _tick(state: swim.SwimState, rng: jax.Array) -> swim.SwimState:
        if k == 1:
            return swim.tick_impl(state, rng, params)
        return swim._tick_n_impl(state, rng, params, k)

    return jax.jit(_tick, out_shardings=out_shardings)

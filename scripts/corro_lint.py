"""corro-analyze driver: run every static-analysis rule repo-wide.

The one CLI for the AST-based checker suite
(`corrosion_tpu/analysis/`): kernel-purity, lane-parity,
async-blocking, lock-discipline, codec-ext, capture-parity (r15: the
trigger DDL ↔ direct-capture lockstep), metrics-doc (the folded
r7 metric-name lint), timeout-discipline (r18: network awaits in
agent//api/ must carry wait_for deadlines — the zombie-node hang
class), actuator-discipline (r22: remediation actuators declare their
safety envelope) and profiler-safety (r23: the stack sampler's hot
path stays lock-free, asyncio-free and allocation-free).  Wired into
tier-1 via
tests/test_static_analysis.py, so a NEW finding — or a STALE baseline
entry — fails CI.

Usage:
    python scripts/corro_lint.py                 # exit 0 clean / 1 findings
    python scripts/corro_lint.py --rules a,b     # run a subset
    python scripts/corro_lint.py -v              # also list grandfathered
    python scripts/corro_lint.py --baseline      # re-bank ANALYSIS_BASELINE.json
                                                 # (keeps justifications of
                                                 # surviving entries; NEW
                                                 # entries get an UNREVIEWED
                                                 # placeholder you must edit)

Suppression: `# corro: noqa[rule]` on the flagged line.  Baseline: only
for proven-benign findings, one-line justification each — see
COMPONENTS.md "Static analysis".
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from corrosion_tpu.analysis import (  # noqa: E402
    AnalysisContext,
    all_checkers,
    load_baseline,
    run_analysis,
    save_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--baseline",
        action="store_true",
        help="re-bank current findings into ANALYSIS_BASELINE.json",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule subset (default: all)",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also list baselined and suppressed findings",
    )
    args = ap.parse_args(argv)

    ctx = AnalysisContext(REPO)
    checkers = all_checkers()
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")}
        known = {c.rule for c in checkers}
        unknown = wanted - known
        if unknown:
            print(
                f"corro_lint: unknown rule(s) {sorted(unknown)} — "
                f"available: {sorted(known)}"
            )
            return 2
        checkers = [c for c in checkers if c.rule in wanted]

    t0 = time.monotonic()
    baseline = load_baseline(ctx.root)
    result = run_analysis(ctx, checkers, baseline)
    elapsed = time.monotonic() - t0

    if args.baseline:
        fired = (
            result.new
            + [f for f, _ in result.baselined]
        )
        path = save_baseline(ctx.root, fired, baseline)
        print(
            f"corro_lint: banked {len(fired)} finding(s) to {path} — "
            "replace any UNREVIEWED justification before committing"
        )
        return 0

    for f in result.new:
        print(f"corro_lint: NEW {f.render()}")
    for key in result.stale_keys:
        print(
            f"corro_lint: STALE baseline entry no longer fires: {key} — "
            "run --baseline to shrink the grandfather list"
        )
    if args.verbose:
        for f, why in result.baselined:
            print(f"corro_lint: baselined {f.render()}  [{why}]")
        for f in result.suppressed:
            print(f"corro_lint: noqa'd {f.render()}")

    n_rules = len(checkers)
    if result.ok:
        print(
            f"corro_lint: OK — {n_rules} rule(s) clean in {elapsed:.2f}s "
            f"({len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed)"
        )
        return 0
    print(
        f"corro_lint: {len(result.new)} new finding(s), "
        f"{len(result.stale_keys)} stale baseline entr(ies) "
        f"in {elapsed:.2f}s"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())

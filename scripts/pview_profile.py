"""Per-phase profile of the partial-view SWIM tick + live-buffer accounting.

The dense kernel got a phase profiler in r3 (`scripts/profile_swim.py`,
TPU_PROFILE_10k.txt); the pview kernel — the designated scaling path past
512k members — never had one (VERDICT r5 weak #3).  This records:

1. **Phase table**: device wall for the tick's phases, sliced to match
   the r6 kernel structure (`ops/swim_pview.py`): partner/probe picks,
   gossip delivery (shift row-gather vs grouped sort), feed-window
   pulls, the merge scatter chain, buffer merge, stats — plus whole
   ticks in both tick modes ("fused" = the r6 restructure, "r5" = the
   round-5 formulation) so the restructure's end-to-end delta is one
   table row apart.  Every sample follows the tunnel measurement
   discipline of profile_swim.timeit (distinct inputs per dispatch,
   per-sample blocking).

2. **Live-buffer accounting** (the chipless AOT-compile loop): for a
   ladder of (n, K) shapes, `jit(...).lower(shapes).compile()` the
   donated scanned tick WITHOUT allocating, and report argument/alias/
   temp bytes plus the count of whole-table copy instructions in the
   optimized HLO, per tick mode.  Under JAX_PLATFORMS=cpu this measures
   the XLA:CPU lowering — a conservative UPPER bound (XLA:CPU's scatter
   expansion double-buffers even programs the TPU runs fully in place:
   the dense kernel shows 3 view-sized CPU copies at shapes whose TPU
   program has none, PROFILE.md r6) — so the meaningful chipless signal
   is the RELATIVE fused-vs-r5 structure, pinned by
   tests/test_pview_memguard.py.  On a live chip the same loop gives
   the real HBM verdict.

Writes the artifact `TPU_PROFILE_PVIEW_<n//1000>k.txt` (platform is
recorded inside — the CPU fallback writes the same file the way
BENCH_* artifacts do) and publishes the phase rows to the shared
metrics registry (`corro.kernel.phase.seconds{kernel="pview"}`).

Usage:  python scripts/pview_profile.py [n] [slots] [feeds]
Env:    PVIEW_PROFILE_OUT (artifact path override),
        PVIEW_PROFILE_AOT=0 (skip the AOT ladder),
        PVIEW_PROFILE_AOT_SHAPES="n1xk1,n2xk2,..." (override the ladder)
"""

from __future__ import annotations

import io
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.reexec_under_cpu(
    "PVIEW_PROFILE_CHILD", prefer_inherited_probe_s=20.0
)
jaxenv.enable_compilation_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from corrosion_tpu.ops import swim, swim_pview  # noqa: E402
from corrosion_tpu.runtime.metrics import record_phase_seconds  # noqa: E402
from profile_swim import timeit, vary_add, vary_key  # noqa: E402


def _code_sha() -> dict:
    import hashlib

    out = {}
    for rel in ("corrosion_tpu/ops/swim_pview.py", "corrosion_tpu/ops/swim.py"):
        with open(os.path.join(REPO, rel), "rb") as f:
            out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
    return out


def table_copy_count(hlo: str, n: int, k: int) -> int:
    """Whole-table copy instructions in an optimized HLO dump."""
    tbl = f"s32[{n},{k}]"
    return sum(
        1
        for line in hlo.splitlines()
        if re.search(r"\bcopy\(", line) and tbl in line
    )


def aot_compile_scanned_tick(params, chunk: int = 2):
    """Chipless AOT compile of the donated scanned tick: shapes only, no
    allocation (the r5 probing loop, PROFILE.md '1M on chip')."""
    state_shape = jax.eval_shape(
        lambda: swim_pview.init_state(
            params, jax.random.PRNGKey(0), seed_mode="fingers"
        )
    )
    rng_shape = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return (
        jax.jit(
            swim_pview._tick_n_impl,
            static_argnames=("params", "k"),
            donate_argnums=(0,),
        )
        .lower(state_shape, rng_shape, params, chunk)
        .compile()
    )


def live_buffer_report(n: int, k: int, feeds: int, tick_mode: str) -> dict:
    params = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=feeds,
        feed_entries=max(16, k // 16), tie_epoch=512, tick_mode=tick_mode,
    )
    # the accounting below reads memory_analysis()/as_text(): an
    # executable deserialized from the persistent cache reports zeroed
    # stats and no HLO, so the AOT introspection always compiles fresh
    # (tests/test_pview_memguard.py carries the same opt-out; the
    # reset matters — the cache singleton ignores config flips once
    # another compile has initialized it)
    from jax._src import compilation_cache as _cc

    old_enable = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()
    try:
        t0 = time.monotonic()
        compiled = aot_compile_scanned_tick(params)
        compile_s = time.monotonic() - t0
    finally:
        jax.config.update("jax_enable_compilation_cache", old_enable)
        _cc.reset_cache()
    ma = compiled.memory_analysis()
    copies = table_copy_count(compiled.as_text(), n, k)
    table_b = n * k * 4
    return {
        "n": n,
        "slots": k,
        "tick_mode": tick_mode,
        "compile_s": round(compile_s, 1),
        "table_gb": round(table_b / 2**30, 2),
        "argument_gb": round(ma.argument_size_in_bytes / 2**30, 3),
        "alias_gb": round(ma.alias_size_in_bytes / 2**30, 3),
        "temp_gb": round(ma.temp_size_in_bytes / 2**30, 3),
        "temp_over_table": round(ma.temp_size_in_bytes / table_b, 2),
        "table_copies": copies,
    }


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    feeds = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    fe = max(16, k // 16)
    plat = jax.devices()[0].platform
    out = io.StringIO()

    def emit(line: str = "") -> None:
        print(line, flush=True)
        out.write(line + "\n")

    emit(f"# pview kernel phase profile (r6 restructure)")
    emit(f"platform={plat} n={n} slots={k} feeds={feeds} fe={fe}")
    emit(f"code_sha={json.dumps(_code_sha())}")
    emit(f"measured_at={time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime())} UTC")
    emit()

    mk = lambda tm, gm: swim_pview.PViewParams(  # noqa: E731
        n=n, slots=k, feeds_per_tick=feeds, feed_entries=fe,
        tie_epoch=512, tick_mode=tm, gossip_mode=gm,
    )
    params = mk("fused", "shift")
    rng = jax.random.PRNGKey(0)
    state = swim_pview.init_state(params, rng, seed_mode="fingers")
    state = swim_pview.tick(state, jax.random.PRNGKey(1), params)  # populate
    jax.block_until_ready(state.slot_packed)
    idx = jnp.arange(n, dtype=jnp.int32)

    rows = []
    # sample count scales down with n: every phase-row dispatch at
    # n=100k moves hundreds of MB on a 1-core host
    it = 20 if n <= 25_000 else 6

    # whole ticks, all four structure combinations: the restructure's
    # end-to-end delta is the (fused,shift) vs (r5,pick) pair
    for tm, gm in (("fused", "shift"), ("fused", "pick"),
                   ("r5", "shift"), ("r5", "pick")):
        p_i = mk(tm, gm)
        rows.append((f"tick(1)[{tm},{gm}]", timeit(
            lambda s, kk, p_i=p_i: swim_pview.tick(s, kk, p_i), state, rng,
            iters=3, warmup=1, vary=vary_key(1))))
    chunk = 5
    t5 = timeit(
        lambda s, kk: swim_pview.tick_n(s, kk, params, chunk), state, rng,
        iters=2, warmup=1, vary=vary_key(1))
    rows.append((f"tick_n({chunk})/{chunk} [fused,shift]", t5 / chunk))

    # ---- phase slices (fused structure) ----------------------------------
    @jax.jit
    def ph_pick(packed, key):
        return swim_pview._pick_known_alive(params, packed, idx, key, 4, 0)

    rows.append(("pick x1", timeit(ph_pick, state.slot_packed, rng,
                                   iters=it, vary=vary_key(1))))

    @jax.jit
    def ph_lookup(packed, subjs):
        return swim_pview._lookup(params, packed, subjs, 0)

    subjs = jax.random.randint(rng, (n, 4), 0, n, dtype=jnp.int32)
    rows.append(("lookup [N,4]", timeit(ph_lookup, state.slot_packed, subjs,
                                        iters=it, vary=vary_add(1))))

    # gossip delivery: shift row-gather vs grouped sort, same widths
    f, m = params.fanout, params.piggyback + params.antientropy
    r2 = jax.random.PRNGKey(2)
    subj_gm = jax.random.randint(r2, (n, f, m), 0, n, dtype=jnp.int32)
    key_gm = jax.random.randint(jax.random.fold_in(r2, 1), (n, f, m), 1, 40,
                                dtype=jnp.int32)
    ok_gm = jax.random.uniform(jax.random.fold_in(r2, 2), (n, f, m)) < 0.8
    slots_in = params.incoming_slots

    @jax.jit
    def ph_inbox_shift(subj_gm, key_gm, ok_gm, off):
        src = (idx[:, None] - off[None, :]) % n
        sub_m = jnp.where(ok_gm, subj_gm, n)
        key_m = jnp.where(ok_gm, key_gm, 0)
        jj = jnp.arange(f, dtype=jnp.int32)[None, :]
        in_subj = sub_m[src, jj].reshape(n, f * m)
        in_key = key_m[src, jj].reshape(n, f * m)
        if f * m > slots_in:
            order = jnp.argsort(in_subj == n, axis=1, stable=True)
            take = order[:, :slots_in]
            in_subj = jnp.take_along_axis(in_subj, take, axis=1)
            in_key = jnp.take_along_axis(in_key, take, axis=1)
        return in_subj, in_key

    off = jnp.array([3, 1709], dtype=jnp.int32) % n
    rows.append((f"inbox[shift] f*m={f * m}", timeit(
        ph_inbox_shift, subj_gm, key_gm, ok_gm, off, iters=it,
        vary=vary_add(1))))

    gdst = jax.random.randint(jax.random.fold_in(r2, 3), (n * f,), 0, n,
                              dtype=jnp.int32)

    @jax.jit
    def ph_inbox_gsort(d, s, kk, o):
        return swim.dispatch_inbox("gsort", n, slots_in, d,
                                   s.reshape(-1, m), kk.reshape(-1, m),
                                   o.reshape(-1, m))

    rows.append((f"inbox[gsort] G={n * f}", timeit(
        ph_inbox_gsort, gdst, subj_gm, key_gm, ok_gm, iters=it,
        vary=vary_add(1))))

    # one feed-window pull (gather side only — the merge is its own row)
    @jax.jit
    def ph_feedpull(packed, key):
        partner = swim_pview._pick_known_alive(params, packed, idx, key, 2, 0)
        psafe = jnp.clip(partner, 0, n - 1)
        vw = jax.lax.dynamic_slice(packed, (jnp.int32(0), jnp.int32(0)),
                                   (n, fe))
        return jnp.take(vw, psafe, axis=0)

    rows.append(("feedpull x1", timeit(ph_feedpull, state.slot_packed, rng,
                                       iters=it, vary=vary_key(1))))

    # the merge scatter chain at the fused tick's full width
    wtot = (feeds + 1) * fe
    mvals = jax.random.randint(jax.random.fold_in(r2, 4), (n, wtot), 0,
                               2**30, dtype=jnp.int32)
    mcols = jax.random.randint(jax.random.fold_in(r2, 5), (n, wtot), 0, k,
                               dtype=jnp.int32)

    @jax.jit
    def ph_merge(packed, mvals, mcols):
        out = packed
        for w0 in range(0, wtot, fe):
            out = out.at[
                idx[:, None], jax.lax.slice_in_dim(mcols, w0, w0 + fe, axis=1)
            ].max(jax.lax.slice_in_dim(mvals, w0, w0 + fe, axis=1))
        return out

    rows.append((f"merge scatter [N,{wtot}]", timeit(
        ph_merge, state.slot_packed, mvals, mcols, iters=it,
        vary=vary_add(1))))

    bw = slots_in + 4
    bin_subj = jax.random.randint(r2, (n, bw), 0, n + 1, dtype=jnp.int32)
    bin_key = jax.random.randint(r2, (n, bw), 0, 40, dtype=jnp.int32)

    @jax.jit
    def ph_bufmrg(bs, bk, bt, isub, ikey):
        return swim._buffer_merge(
            params, bs, bk.astype(jnp.int32), bt.astype(jnp.int32), isub, ikey
        )

    rows.append(("bufmrg", timeit(
        ph_bufmrg, state.buf_subj, state.buf_key, state.buf_sent, bin_subj,
        bin_key, iters=it, vary=vary_add(4))))

    def vary_alive(i, args):
        (s,) = args
        return (s._replace(alive=s.alive.at[i % n].set(False)),)

    rows.append(("stats", timeit(
        lambda s: swim_pview.membership_stats(s, params), state, iters=3,
        vary=vary_alive)))

    emit(f"{'phase':<32} {'ms':>12}")
    for name, secs in rows:
        emit(f"{name:<32} {secs * 1e3:>12.3f}")
        record_phase_seconds("pview", name, secs)
    emit()

    # ---- live-buffer accounting (chipless AOT ladder) --------------------
    if os.environ.get("PVIEW_PROFILE_AOT", "1") != "0":
        shapes_env = os.environ.get("PVIEW_PROFILE_AOT_SHAPES")
        if shapes_env:
            shapes = [
                tuple(int(x) for x in s.split("x"))
                for s in shapes_env.split(",")
            ]
        else:
            shapes = [(n, k)]
        emit("# live-buffer accounting: donated scanned tick, AOT "
             "(no allocation)")
        emit("# CPU lowering OVERCOUNTS copies vs TPU (see module "
             "docstring); compare tick modes, not absolutes")
        hdr = (f"{'n':>9} {'K':>5} {'mode':<6} {'tbl_gb':>7} {'arg_gb':>7} "
               f"{'temp_gb':>8} {'t/tbl':>6} {'copies':>6} {'compile_s':>9}")
        emit(hdr)
        for (sn, sk) in shapes:
            for tm in ("fused", "r5"):
                r = live_buffer_report(sn, sk, feeds, tm)
                emit(
                    f"{r['n']:>9} {r['slots']:>5} {tm:<6} "
                    f"{r['table_gb']:>7.2f} {r['argument_gb']:>7.3f} "
                    f"{r['temp_gb']:>8.3f} {r['temp_over_table']:>6.2f} "
                    f"{r['table_copies']:>6} {r['compile_s']:>9.1f}"
                )

    path = os.environ.get(
        "PVIEW_PROFILE_OUT",
        os.path.join(REPO, f"TPU_PROFILE_PVIEW_{n // 1000}k.txt"),
    )
    with open(path, "w") as fh:
        fh.write(out.getvalue())
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()

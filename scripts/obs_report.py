"""Registry → per-PR observability report (the trend-tracking render).

`scripts/pview_profile.py` banks phase timings and BENCH_PR*.json banks
the wall-clock trajectory; this entry banks the EVENT trajectory — the
device telemetry lane (r7) rendered from the shared metrics registry in
the same table format as PROFILE.md's phase tables, so per-PR diffs of
"what the kernel did" (drops, overflows, suspicion churn, feed volume)
are one `git diff OBS_REPORT.md` away.

It boots a small `PViewClusterSim` to the convergence bar (the same
workload family as `bench_smoke.py`, tier-1-safe sizes), drains the lane
through the sim's stats readbacks, then renders every observability
family the status plane serves: kernel event totals, kernel phase
gauges, and a per-tick event-rate digest.  The CPU platform is FORCED
(plugin-stripped re-exec) for the same reason bench_smoke forces it —
points must share a platform to be comparable.

Usage:  python scripts/obs_report.py
Env:    OBS_REPORT_N (default 2048), OBS_REPORT_SLOTS (default 256),
        OBS_REPORT_MAX_TICKS (default 600), OBS_REPORT_E2E_WRITES
        (default 30 — the SLO section's write→event workload),
        OBS_REPORT_CLUSTER_WRITES (default 6 — the r12 cluster
        section's two-node partition replay), OBS_REPORT_OUT (path
        override, default OBS_REPORT.md)
"""

from __future__ import annotations

import io
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.reexec_under_cpu("OBS_REPORT_CHILD")
jaxenv.enable_compilation_cache()

import jax  # noqa: E402

from corrosion_tpu.models.cluster import PViewClusterSim  # noqa: E402
from corrosion_tpu.runtime.metrics import (  # noqa: E402
    EVENTS_BY_KERNEL,
    METRICS,
    kernel_event_totals,
)
from corrosion_tpu.runtime.records import FLIGHT  # noqa: E402


def _code_sha() -> dict:
    import hashlib

    out = {}
    for rel in (
        "corrosion_tpu/ops/swim_pview.py",
        "corrosion_tpu/ops/swim.py",
        "corrosion_tpu/runtime/metrics.py",
    ):
        with open(os.path.join(REPO, rel), "rb") as f:
            out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
    return out


def render_registry_tables(emit, ticks_run: int) -> None:
    """Render the observability families from the live registry in
    PROFILE.md's fixed-width table style (shared by the report CLI and
    its test)."""
    totals = kernel_event_totals(METRICS)
    emit("## kernel event totals (corro.kernel.events.total)")
    emit(f"{'kernel':<12} {'event':<20} {'total':>14} {'per_tick':>12}")
    for kernel in sorted(totals):
        order = {n: i for i, n in enumerate(EVENTS_BY_KERNEL.get(kernel, ()))}
        for event in sorted(totals[kernel], key=lambda e: order.get(e, 99)):
            v = totals[kernel][event]
            per_tick = v / ticks_run if ticks_run else 0.0
            emit(
                f"{kernel:<12} {event:<20} {v:>14.0f} {per_tick:>12.2f}"
            )
    emit()

    emit("## kernel phase gauges (corro.kernel.phase.seconds)")
    emit(f"{'kernel':<12} {'phase':<32} {'ms':>12}")
    for kind, name, labels, value in sorted(
        METRICS.snapshot(), key=lambda r: (r[1], sorted(r[2].items()))
    ):
        if kind == "gauge" and name == "corro.kernel.phase.seconds":
            emit(
                f"{labels.get('kernel', '?'):<12} "
                f"{labels.get('phase', '?'):<32} {value * 1e3:>12.3f}"
            )
    emit()


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values) -> str:
    """Unicode block sparkline of a numeric sequence (flat → all ▁)."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[int((v - lo) / span * (len(_SPARK) - 1))] for v in vals
    )


# the per-tick shapes an operator reads first: dissemination pressure,
# loss/overflow, and the suspicion → down → refute lifecycle (the
# per-protocol-period view SWIM pathologies are diagnosed by)
_FLIGHT_EVENT_LANES = (
    "gossip_emitted", "gossip_lost", "inbox_overflowed", "merge_won",
    "suspect_raised", "down_declared", "refuted",
)
_FLIGHT_CENSUS_LANES = (
    "census_alive", "census_suspect", "census_down",
    "inbox_highwater", "inc_max",
)


def render_flight_section(emit, kernel: str = "pview", window: int = 64):
    """Render the flight recorder's tick-resolved timeline: one
    sparkline + min/max/last per lane over the last `window` frames —
    the per-tick trend view the cumulative tables above cannot show."""
    frames = FLIGHT.window(window, kernel=kernel)
    emit(f"## flight recorder — last {len(frames)} ticks "
         f"(kernel={kernel}, corro.flight.*)")
    if not frames:
        emit("(no frames drained)")
        emit()
        return
    t0, t1 = frames[0]["tick"], frames[-1]["tick"]
    emit(f"ticks {t0}..{t1}; per-tick event deltas then census levels")
    emit(f"{'lane':<20} {'min':>8} {'max':>8} {'last':>8}  trend")
    for group, lanes in (
        ("events", _FLIGHT_EVENT_LANES),
        ("census", _FLIGHT_CENSUS_LANES),
    ):
        for lane in lanes:
            series = [f[group].get(lane, 0) for f in frames]
            emit(
                f"{lane:<20} {min(series):>8} {max(series):>8} "
                f"{series[-1]:>8}  {sparkline(series)}"
            )
    emit()


def _run_e2e_workload(writes: int) -> None:
    """Drive the write→event path so the SLO section has real samples:
    two in-process agents over a mem network, an HTTP subscription on B,
    `writes` cross-node writes on A, and the canary probe running on
    both nodes for a few cycles (its remote rows measure cross-node
    latency from the embedded origin wall stamp)."""
    import asyncio

    # a denser lottery than the production default (1/64 would keep ~0
    # of this tiny workload's traces): the report's slowest-traces table
    # should show real rows, and breach/error keeps are unaffected
    from corrosion_tpu.runtime import tracestore
    from corrosion_tpu.runtime.config import SloConfig

    tracestore.configure(targets=SloConfig().targets, lottery_n=4)

    async def workload() -> None:
        from corrosion_tpu.agent.run import (
            canary_loop,
            make_broadcastable_changes,
            run,
            setup,
            shutdown,
        )
        from corrosion_tpu.api.http import ApiServer
        from corrosion_tpu.client import CorrosionApiClient
        from corrosion_tpu.net.mem import MemNetwork
        from corrosion_tpu.runtime.config import Config
        from corrosion_tpu.runtime.tmpdb import fresh_db_path

        net = MemNetwork(seed=41)
        agents, apis, clients = [], [], []

        async def boot(name: str, bootstrap=()):
            cfg = Config()
            cfg.db.path = fresh_db_path(name)
            cfg.gossip.bind_addr = name
            cfg.gossip.bootstrap = list(bootstrap)
            cfg.perf.broadcast_interval_ms = 20
            cfg.perf.apply_queue_timeout_ms = 5
            cfg.api.bind_addr = ["127.0.0.1:0"]
            a = await setup(cfg, network=net)
            a.store.apply_schema_sql(
                "CREATE TABLE obs_e2e "
                "(id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
            )
            await run(a)
            api = ApiServer(a)
            await api.start()
            agents.append(a)
            apis.append(api)
            clients.append(CorrosionApiClient(api.addrs[0]))
            return a

        a = await boot("obs-a")
        b = await boot("obs-b", ["obs-a"])
        canaries = []
        try:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 15
            while (
                len(a.members) < 1 or len(b.members) < 1
            ) and loop.time() < deadline:
                await asyncio.sleep(0.02)
            stream = clients[1].subscribe("SELECT id, text FROM obs_e2e")
            it = stream.__aiter__()
            while True:
                ev = await asyncio.wait_for(it.__anext__(), 10)
                if "eoq" in ev:
                    break
            for ag in (a, b):
                ag.config.slo.canary = True
                ag.config.slo.canary_interval_secs = 0.25
                canaries.append(asyncio.ensure_future(canary_loop(ag)))
            got = 0
            for i in range(writes):
                await make_broadcastable_changes(
                    a,
                    lambda tx, i=i: [
                        tx.execute(
                            "INSERT OR REPLACE INTO obs_e2e (id, text) "
                            "VALUES (?, ?)",
                            [i, f"w{i}"],
                        )
                    ],
                )
                while got <= i:
                    ev = await asyncio.wait_for(it.__anext__(), 10)
                    if "change" in ev:
                        got += 1
            await asyncio.sleep(1.5)  # a few canary cycles on each node
        finally:
            for c in canaries:
                c.cancel()
            for c in canaries:
                try:
                    await c
                except (asyncio.CancelledError, Exception):
                    pass
            for cl in clients:
                await cl.close()
            for api in apis:
                await api.stop()
            for ag in agents:
                await shutdown(ag)

    asyncio.run(workload())


def render_slo_section(emit, writes: int = 30) -> None:
    """r11: the SLO latency plane — run a tiny e2e workload, then render
    the per-stage write→event percentile table (what GET /v1/slo serves)
    and the canary's measured round-trip sparkline."""
    from corrosion_tpu.runtime import latency as lat

    before = lat.snapshot_stages(METRICS)
    frames_before = len(FLIGHT.window(10_000, kernel="canary"))
    _run_e2e_workload(writes)
    rep = lat.stage_report(before=before, registry=METRICS)

    emit("## SLO latency plane (corro.e2e.*, write→event hop stamps)")
    emit(
        f"two-agent mem-net workload: {writes} cross-node writes + the "
        "canary probe on both nodes; per-stage percentiles in ms "
        "(~5 % log-bucket resolution, GET /v1/slo serves the same rows)"
    )

    def ms(v) -> str:
        return f"{v * 1e3:>9.3f}" if v is not None else f"{'—':>9}"

    emit(
        f"{'stage':<10} {'count':>6} {'p50':>9} {'p90':>9} {'p99':>9} "
        f"{'p999':>9} {'mean':>9}"
    )
    for stage in lat.E2E_STAGES:
        row = rep[stage]
        emit(
            f"{stage:<10} {row['count']:>6} "
            + " ".join(
                ms(row[k]) for k in ("p50", "p90", "p99", "p999", "mean")
            )
        )
    skew = sum(
        v
        for _k, name, labels, v in METRICS.snapshot()
        if name == "corro.e2e.skew.clamped.total"
    )
    emit(f"skew_clamped_total={skew:.0f}")
    emit()

    frames = FLIGHT.window(10_000, kernel="canary")[frames_before:]
    emit("## canary round trips (corro.e2e.canary.seconds)")
    if not frames:
        emit("(no canary frames recorded)")
        emit()
        return
    series = [f["events"].get("lat_us", 0) / 1e3 for f in frames]
    remote = sum(f["events"].get("remote", 0) for f in frames)
    emit(
        f"{len(series)} probes ({remote} cross-node); ms "
        f"min={min(series):.3f} max={max(series):.3f} "
        f"last={series[-1]:.3f}"
    )
    emit(f"trend {sparkline(series[-64:])}")
    emit()


def render_traces_section(emit, n: int = 8) -> None:
    """r19: the tail sampler's slowest kept traces — per-trace stage
    breakdown (the same rows GET /v1/traces serves), rendered after the
    SLO section's e2e workload so the kept ring holds that workload's
    lottery/breach winners."""
    from corrosion_tpu.runtime import tracestore

    st = tracestore.store()
    emit("## slowest kept traces (corro.trace.*, GET /v1/traces)")
    if st is None:
        emit("(trace plane not configured)")
        emit()
        return
    st.sweep(now=st._clock() + st.idle_close_secs + 1)  # close stragglers
    census = st.census()
    emit(
        f"kept={census['kept_total']} dropped={census['dropped_total']} "
        f"lottery=1/{census['lottery_n']} "
        f"idle_close={census['idle_close_secs']}s"
    )
    traces = st.kept(n=n)
    if not traces:
        emit("(no traces kept)")
        emit()
        return
    emit(
        f"{'trace_id':<16} {'ms':>9} {'reason':<12} {'spans':>5} "
        f"{'hops':>4}  stage breakdown (sum ms)"
    )
    for t in traces:
        breakdown = " ".join(
            f"{stage}={row['seconds'] * 1e3:.2f}"
            for stage, row in t["stages"].items()
        )
        emit(
            f"{t['trace_id'][:16]:<16} {t['duration_secs'] * 1e3:>9.3f} "
            f"{t['reason']:<12} {t['n_spans']:>5} {t['hops']:>4}  "
            f"{breakdown}"
        )
    emit()


def render_alerts_section(emit) -> None:
    """r20: the alerting plane — sample the now-populated registry into
    a short-ring TSDB, evaluate the default rule pack, and render the
    rule-state table (the same rows GET /v1/alerts serves).  A healthy
    report shows every rule ok with its current evaluation value; the
    point here is the end-to-end plumbing registry → TSDB → rules."""
    from corrosion_tpu.runtime.alerts import AlertEngine
    from corrosion_tpu.runtime.config import AlertsConfig
    from corrosion_tpu.runtime.tsdb import MetricsTSDB

    db = MetricsTSDB(
        registry=METRICS, sample_interval_secs=0.05, slots=64
    )
    eng = AlertEngine(
        tsdb=db, cfg=AlertsConfig(for_scale=0.01), registry=METRICS
    )
    for _ in range(4):  # a few ticks so counters get real rate points
        db.sample_once()
        time.sleep(0.06)
        eng.evaluate()
    rep = eng.report(history=True)
    c = db.census()

    emit("## alerting plane (corro.alerts.* / corro.tsdb.*, "
         "GET /v1/alerts)")
    emit(
        f"tsdb: {c['series']} series / {c['points']} points over "
        f"{c['samples']} samples; local health score "
        f"{rep['health_score']} (for-duration widening "
        f"×{1 + rep['health_score']:.2f})"
    )
    emit(
        f"{'rule':<20} {'sev':<5} {'kind':<10} {'state':<8} "
        f"{'value':>12}  series"
    )
    for r in rep["rules"]:
        v = "—" if r["value"] is None else f"{r['value']:.4g}"
        emit(
            f"{r['rule']:<20} {r['severity']:<5} {r['kind']:<10} "
            f"{r['state']:<8} {v:>12}  {r['series']}"
        )
    for h in rep.get("history", []):
        emit(
            f"  transition: {h['rule']} {h['event']}"
            + (f" [drill: {h['drill']}]" if h.get("drill") else "")
        )
    emit()


def render_cluster_section(emit, writes: int = 6) -> None:
    """r12: the cluster observatory — replay a two-node mem-net
    partition through the shared scenario harness and render what the
    gossiped digests saw: the any-node digest coverage table (what
    `GET /v1/cluster` serves per node) and the divergence detector's
    round-by-round timeline across fault and heal."""
    import asyncio

    from corrosion_tpu.models.cluster import cluster_observatory_scenario

    timeline: list = []
    rec = asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(
            cluster_observatory_scenario(
                "partition", seed=73, nodes=2, writes=writes,
                hold_secs=1.5, timeline=timeline,
            ),
            240,
        )
    )

    emit("## cluster observatory (corro.cluster.* / corro.digest.*, "
         "GET /v1/cluster)")
    emit(
        f"two-node mem-net partition replay: {writes} cross-node writes, "
        f"digest interval {rec['digest_interval_secs']}s; partition "
        f"detected in {rec['detect_rounds']} digest rounds "
        f"({rec['detect_secs']}s), cleared {rec['heal_rounds']} rounds "
        f"after heal; {rec['episodes_total']} episode(s), one incident "
        "dump each"
    )
    emit()
    emit("digest coverage at full aggregation (pre-fault):")
    emit(
        f"{'node':<26} {'fresh':>5} {'seq':>5} {'age_s':>7} "
        f"{'view':>7} {'samples':>8}  view_hash"
    )
    for name, row in sorted(rec["nodes_report"].items()):
        emit(
            f"{name:<26} {str(row['fresh']):>5} {row['seq']:>5} "
            f"{row['age_secs']:>7.3f} {row['view_size']:>7} "
            f"{sum(row['stage_counts'].values()):>8}  "
            f"{row['view_hash']}"
        )
    emit()
    emit("divergence timeline (one row per digest round, t from fault "
         "or heal):")
    emit(f"{'t':>6} {'groups':>7} {'silent':>7} {'episode':>8}")
    for row in timeline[-24:]:
        emit(
            f"{row['t']:>6.2f} {row['groups']:>7} {row['silent']:>7} "
            f"{'OPEN' if row['episode_open'] else '-':>8}"
        )
    emit(
        "episode trend "
        + sparkline([int(r["episode_open"]) for r in timeline])
    )
    emit()


def main() -> None:
    n = int(os.environ.get("OBS_REPORT_N", "2048"))
    slots = int(os.environ.get("OBS_REPORT_SLOTS", "256"))
    max_ticks = int(os.environ.get("OBS_REPORT_MAX_TICKS", "600"))

    out = io.StringIO()

    def emit(line: str = "") -> None:
        print(line, flush=True)
        out.write(line + "\n")

    emit("# observability report (device telemetry lane → registry render)")
    emit(
        f"platform={jax.devices()[0].platform} n={n} slots={slots} "
        f"max_ticks={max_ticks}"
    )
    emit(f"code_sha={json.dumps(_code_sha())}")
    emit(
        "measured_at="
        + time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
        + " UTC"
    )
    emit()

    t0 = time.monotonic()
    sim = PViewClusterSim(
        n, slots=slots, seed=0, seed_mode="fingers",
        feeds_per_tick=4, feed_entries=max(16, slots // 16), tie_epoch=512,
    )
    stable_tick = sim.run_until_converged(max_ticks=max_ticks, check_every=25)
    wall = time.monotonic() - t0
    stats = sim.stats()  # final drain of the lane

    emit(
        f"workload: pview boot to the four-term bar — stable_tick="
        f"{stable_tick} wall={wall:.2f}s "
        f"pv_coverage={stats['pv_coverage']:.4f} "
        f"fp={stats['false_positive']:.0f}"
    )
    emit()
    render_registry_tables(emit, sim.ticks)
    render_flight_section(emit, kernel="pview")
    render_slo_section(
        emit, writes=int(os.environ.get("OBS_REPORT_E2E_WRITES", "30"))
    )
    render_traces_section(emit)
    render_cluster_section(
        emit, writes=int(os.environ.get("OBS_REPORT_CLUSTER_WRITES", "6"))
    )
    render_alerts_section(emit)

    path = os.environ.get(
        "OBS_REPORT_OUT", os.path.join(REPO, "OBS_REPORT.md")
    )
    with open(path, "w") as fh:
        fh.write(out.getvalue())
    print(f"wrote {path}", flush=True)
    sys.exit(0 if stable_tick is not None else 1)


if __name__ == "__main__":
    main()

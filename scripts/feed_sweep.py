"""Find the true convergence tick of the bench config for a given
feeds_per_tick (the bench's 50-tick stats cadence can overshoot by up to
49 ticks). Stats are only checked inside the expected convergence window
so the probe itself stays cheap.

Usage: python scripts/feed_sweep.py <feeds> [n] [start] [step] [stop]
Appends one line to FEED_SWEEP.txt at the repo root.
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# JAX_PLATFORMS=cpu alone is NOT enough: with the TPU plugin still on
# PYTHONPATH a fresh `import jax` can hang in plugin discovery (see
# jaxenv). Re-exec under the known-good stripped CPU env.
jaxenv.reexec_under_cpu("FEED_SWEEP_CHILD")

from corrosion_tpu.models.cluster import ClusterSim  # noqa: E402


def main() -> None:
    import jax

    feeds = int(sys.argv[1])
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    start = int(sys.argv[3]) if len(sys.argv) > 3 else 70
    step = int(sys.argv[4]) if len(sys.argv) > 4 else 10
    stop = int(sys.argv[5]) if len(sys.argv) > 5 else 300
    fe = max(25, n // (4 * feeds))
    params = dict(
        feeds_per_tick=feeds, feed_entries=fe, piggyback=4,
        incoming_slots=8, buffer_slots=12, probe_candidates=2, antientropy=1,
    )
    # ONE scan length for every dispatch — each distinct chunk size is a
    # separate (slow) XLA compile on this host
    warm = ClusterSim(n, seed=1, **params)
    warm.step(step)
    warm.stats()
    del warm

    sim = ClusterSim(n, seed=0, **params)
    jax.block_until_ready(sim.state.view)
    t0 = time.monotonic()
    ticks = 0
    line = None
    while ticks < stop:
        sim.step(step)
        ticks += step
        if ticks < start:
            continue
        wall = time.monotonic() - t0
        s = sim.stats()
        if s["coverage"] >= 0.999:
            line = (
                f"n={n} feeds={feeds} fe={fe}: tick={ticks} "
                f"tick_wall={wall:.1f}s cov={s['coverage']:.5f} "
                f"fp={s['false_positive']}"
            )
            break
    if line is None:
        line = f"n={n} feeds={feeds} fe={fe}: NOT converged by {ticks}"
    print(line, flush=True)
    with open(os.path.join(REPO, "FEED_SWEEP.txt"), "a") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()

"""Per-phase wall-clock profile of the batched SWIM tick on the live chip.

VERDICT r2 "what's weak" #1: no profile of the flagship kernel was ever
recorded.  Times (a) the full tick / tick_n dispatch, and (b) phase-sliced
jits matching the r3 kernel structure (ops/swim.py):

  - pick:    _pick_known_alive target selection
  - inbox:   lax.sort by destination + rank scan + [N, R] compaction
  - viewupd: row-aligned gather + scatter-max of inbox into [N, N]
  - feed:    dynamic_slice window + row-take + update (one exchange)
  - bufmrg:  _buffer_merge lex sorts
  - stats:   fused row-reduction stats + device→host readback

Usage: python scripts/profile_swim.py [n] [feeds_per_tick]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from corrosion_tpu.runtime import jaxenv

jaxenv.enable_compilation_cache()

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import swim


def timeit(fn, *args, iters=20, warmup=2, vary=None):
    """Time fn(*args), making every dispatch DISTINCT via ``vary``.

    ``vary`` is ``(i, args) -> args`` producing a perturbed argument tuple
    per call.  This is load-bearing on the tunneled chip: the first r4
    on-chip table recorded tick_n(50) "completing" in 0.2 ms when re-run
    with identical input buffers — physically impossible (the [N,N] view
    update alone moves ~200 MB/tick at n=10k) — i.e. the remote platform
    appears to memoize identical dispatches.  Rows timed with varying
    inputs (the per-impl tick rows) were ~300x slower and mutually
    consistent, so those were real.  No two timed calls may share inputs.

    Every iteration blocks on its own result: end-of-loop-only blocking
    produced internally inconsistent tables on the tunneled chip (a row
    15x faster than an identical-workload row), so each sample is a
    self-contained dispatch+compute+sync — an upper bound including one
    tunnel round-trip, comparable across rows measured the same way.
    """
    if vary is None:
        vary = _vary_none
    for i in range(warmup):
        jax.block_until_ready(fn(*vary(-1 - i, args)))
    t0 = time.monotonic()
    for i in range(iters):
        jax.block_until_ready(fn(*vary(i, args)))
    return (time.monotonic() - t0) / iters


def _vary_none(i, args):
    return args


def vary_key(pos):
    """Fold the iteration index into the PRNG key at position ``pos``."""
    def v(i, args):
        a = list(args)
        a[pos] = jax.random.fold_in(a[pos], i + 1_000)
        return tuple(a)
    return v


def vary_add(pos):
    """Add a distinct small salt to the int array at position ``pos``
    (used on value planes that don't gate the amount of work done)."""
    def v(i, args):
        a = list(args)
        a[pos] = a[pos] + jnp.int32(i + 1)
        return tuple(a)
    return v


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    feeds = int(sys.argv[2]) if len(sys.argv) > 2 else max(4, n // (25 * 50))
    params = swim.SwimParams(n=n, feeds_per_tick=feeds)
    rng = jax.random.PRNGKey(0)
    state = swim.init_state(params, rng)
    state = swim.tick(state, jax.random.PRNGKey(1), params)  # populate
    jax.block_until_ready(state.view)
    print(f"platform={jax.devices()[0].platform} n={n} feeds={feeds}")

    rows = []
    rows.append(("tick(1)", timeit(
        lambda s, k: swim.tick(s, k, params), state, rng, iters=10,
        vary=vary_key(1))))
    t50 = timeit(lambda s, k: swim.tick_n(s, k, params, 50), state, rng,
                 iters=3, warmup=1, vary=vary_key(1))
    rows.append(("tick_n(50)/50", t50 / 50))

    idx = jnp.arange(n, dtype=jnp.int32)
    f, m = params.fanout, params.piggyback + params.antientropy
    mlen = n * f * m
    slots = params.incoming_slots

    @jax.jit
    def ph_pick(view, key):
        return swim._pick_known_alive(view, idx, key, params, 4)

    rows.append(("pick x1", timeit(ph_pick, state.view, rng,
                                   vary=vary_key(1))))

    r = jax.random.PRNGKey(2)
    dst = jax.random.randint(r, (mlen,), 0, n, dtype=jnp.int32)
    subj = jax.random.randint(jax.random.fold_in(r, 1), (mlen,), 0, n,
                              dtype=jnp.int32)
    key = jax.random.randint(jax.random.fold_in(r, 2), (mlen,), 0, 40,
                             dtype=jnp.int32)

    @jax.jit
    def ph_inbox(dst, subj, key):
        dst_s, subj_s, key_s = jax.lax.sort(
            (dst, subj, key), dimension=0, num_keys=1, is_stable=True
        )
        pos = jnp.arange(dst_s.shape[0], dtype=jnp.int32)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), dst_s[1:] != dst_s[:-1]]
        )
        first = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_start, pos, 0)
        )
        rank = pos - first
        ok = (dst_s < n) & (rank < slots)
        rows_ = jnp.where(ok, dst_s, 0)
        cols_ = jnp.where(ok, rank, 0)
        in_subj = jnp.full((n, slots), n, dtype=jnp.int32)
        in_key = jnp.zeros((n, slots), dtype=jnp.int32)
        in_subj = in_subj.at[rows_, cols_].min(jnp.where(ok, subj_s, n))
        in_key = in_key.at[rows_, cols_].max(jnp.where(ok, key_s, 0))
        return in_subj, in_key

    rows.append((f"inbox M={mlen}", timeit(ph_inbox, dst, subj, key,
                                           vary=vary_add(2))))
    in_subj, in_key = ph_inbox(dst, subj, key)

    # impl comparison: grouped [G, m] form, all three dispatch targets,
    # plus whole-tick deltas — the on-chip numbers VERDICT r3 item 3 asks
    # for land in the profile artifacts via these rows
    gG = n * f
    r3_ = jax.random.PRNGKey(3)
    gdst = jax.random.randint(r3_, (gG,), 0, n, dtype=jnp.int32)
    gsubj = jax.random.randint(
        jax.random.fold_in(r3_, 1), (gG, m), 0, n, dtype=jnp.int32)
    gkey = jax.random.randint(
        jax.random.fold_in(r3_, 2), (gG, m), 1, 40, dtype=jnp.int32)
    gok = jax.random.uniform(jax.random.fold_in(r3_, 3), (gG, m)) < 0.8
    on_tpu = jax.devices()[0].platform != "cpu"
    # pallas rows only on a real chip: off-TPU the kernel runs in
    # interpret mode, which is orders of magnitude slower than the real
    # thing and would both distort the table and blow the step timeout
    impls = ("sort", "gsort", "pallas") if on_tpu else ("sort", "gsort")
    for impl in impls:
        @jax.jit
        def ph_impl(d, s, k, o, impl=impl):
            return swim.dispatch_inbox(impl, n, slots, d, s, k, o)
        try:
            rows.append((f"inbox[{impl}] G={gG}",
                         timeit(ph_impl, gdst, gsubj, gkey, gok,
                                vary=vary_add(2))))
        except Exception as e:  # a kernel that won't compile is a result
            print(f"inbox[{impl}]: FAILED {type(e).__name__}: {e}")
    # gsort is params' default, making tick(1)[gsort] nominally the same
    # workload as the tick(1) row above — that duplication is deliberate:
    # the first on-chip table showed those two "identical" measurements
    # disagreeing 300x, so the pair acts as a measurement-consistency
    # check for the table itself.
    tick_impls = ("sort", "gsort", "pallas") if on_tpu else ("sort", "gsort")
    for impl in tick_impls:
        p_i = params._replace(inbox_impl=impl)
        try:
            rows.append((f"tick(1)[{impl}]", timeit(
                lambda s, k, p_i=p_i: swim.tick(s, k, p_i), state, rng,
                iters=10, vary=vary_key(1))))
        except Exception as e:
            print(f"tick[{impl}]: FAILED {type(e).__name__}: {e}")

    @jax.jit
    def ph_viewupd(view, in_subj, in_key):
        safe = jnp.clip(in_subj, 0, n - 1)
        eff = swim.to_view_key(jnp.where(in_subj < n, in_key, 0))
        prev = view[idx[:, None], safe]
        improved = eff > prev
        return view.at[idx[:, None], safe].max(eff), improved

    rows.append(("viewupd [N,R]", timeit(ph_viewupd, state.view, in_subj,
                                         in_key, vary=vary_add(2))))

    fe = min(params.feed_entries, n)

    @jax.jit
    def ph_feed(view, key):
        partner = swim._pick_known_alive(view, idx, key, params, 2)
        psafe = jnp.clip(partner, 0, n - 1)
        w = jnp.int32(0)
        vw = jax.lax.dynamic_slice(view, (jnp.int32(0), w), (n, fe))
        pulled = jnp.take(vw, psafe, axis=0)
        return jax.lax.dynamic_update_slice(
            view, jnp.maximum(vw, pulled), (jnp.int32(0), w)
        )

    t1 = timeit(ph_feed, state.view, rng, vary=vary_key(1))
    rows.append(("feed x1", t1))
    rows.append((f"feed x{feeds} (extrap)", t1 * feeds))

    bw = slots + 6
    bin_subj = jax.random.randint(r, (n, bw), 0, n + 1, dtype=jnp.int32)
    bin_key = jax.random.randint(r, (n, bw), 0, 40, dtype=jnp.int32)

    @jax.jit
    def ph_bufmrg(bs, bk, bt, isub, ikey):
        return swim._buffer_merge(params, bs, bk, bt, isub, ikey)

    rows.append(("bufmrg", timeit(
        ph_bufmrg, state.buf_subj, state.buf_key, state.buf_sent, bin_subj,
        bin_key, vary=vary_add(4))))

    def vary_alive(i, args):
        (s,) = args
        return (s._replace(alive=s.alive.at[i % n].set(False)),)

    rows.append(("stats", timeit(
        lambda s: swim.membership_stats(s), state, iters=5,
        vary=vary_alive)))

    print(f"{'phase':<24} {'ms':>10}")
    for name, secs in rows:
        print(f"{name:<24} {secs * 1e3:>10.3f}")


if __name__ == "__main__":
    main()

"""Run the BASELINE.json scale ladder and record measured numbers.

Rungs (BASELINE.json `configs`):
  0. 3-node devcluster, default SWIM params (PR1 CPU reference point)
  1. 128-member devcluster, 5% churn, infection broadcast only
  2. 1k-member mesh, fanout=3, suspect-timeout sweep
  3. 10k-member batched SWIM on a single device
  4. member-sharded kernel over an 8-device mesh at the largest
     host-feasible size, plus the 100k memory/extrapolation math
     (a real 100k run needs a v5e-8's HBM; the [N,N] int16 view is 19 GB
     sharded to 2.3 GB/chip — infeasible on a CPU host, validated here by
     running the identical sharded program at smaller N)

Usage:  python scripts/scale_ladder.py [rung ...]   (default: all)
Writes one JSON line per measurement to stdout and appends the collected
results to BASELINE_MEASURED.json at the repo root.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# Re-exec under the known-good CPU env when the inherited backend is
# unusable (same policy as bench.py). An 8-device count serves rung 4;
# single-device rungs ignore the extra devices.
jaxenv.reexec_under_cpu(
    "SCALE_LADDER_CHILD",
    n_devices=8,
    prefer_inherited_probe_s=float(os.environ.get("BENCH_PROBE_S", "60")),
)

jaxenv.enable_compilation_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from corrosion_tpu.models.cluster import ClusterSim  # noqa: E402
from corrosion_tpu.ops import swim  # noqa: E402

RESULTS: list[dict] = []


def emit(rung: int, name: str, **fields) -> None:
    rec = {"rung": rung, "name": name, **fields}
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


# -- rung 0: 3-node event-driven devcluster ---------------------------------


def rung0() -> None:
    from corrosion_tpu.agent.membership import SwimConfig
    from corrosion_tpu.devcluster import DevCluster, Topology
    from corrosion_tpu.net.mem import MemNetwork

    TEST_SCHEMA = (
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT NOT NULL DEFAULT '');"
    )

    async def main():
        cluster = DevCluster(
            Topology.parse("A -> C\nB -> C\n"),
            TEST_SCHEMA,
            network=MemNetwork(seed=1),
            swim_config=SwimConfig(),  # default params: the PR1 reference
        )
        await cluster.start()
        try:
            t = await cluster.wait_converged(timeout=60.0)
            lat = await cluster.measure_broadcast_latency(
                "A", "tests", 1, "ladder", timeout=60.0
            )
            # healthy soak: false positive = anyone losing a member
            await asyncio.sleep(5.0)
            sizes = list(cluster.membership_counts().values())
            emit(
                0,
                "devcluster_3node_default_swim",
                convergence_s=round(t, 3),
                broadcast_latency_s=round(max(lat.values()), 3),
                false_positive=0.0 if all(s == 3 for s in sizes) else 1.0,
                platform="host-asyncio",
            )
        finally:
            await cluster.stop()

    asyncio.run(main())


# -- batched-kernel helpers -------------------------------------------------


def _converge(sim: ClusterSim, target=0.999, max_ticks=3000, every=5):
    t0 = time.monotonic()
    tick = sim.run_until_stable(
        coverage_target=target, max_ticks=max_ticks, record_every=every
    )
    return tick, time.monotonic() - t0


def _churn(sim: ClusterSim, frac: int, seed: int, max_extra: int) -> dict:
    """Crash 1/frac of the members at once; measure full cluster-wide
    detection and post-churn FP. The single churn methodology shared by
    every churn rung (a fix here changes all recorded baselines alike)."""
    import numpy as np

    n = sim.params.n
    rng = np.random.default_rng(seed)
    crashed = rng.choice(n, size=max(1, n // frac), replace=False)
    for m in crashed:
        sim.crash(int(m))
    det_ticks = sim.run_until_detected(
        detect_target=1.0, max_extra_ticks=max_extra
    )
    s2 = sim.stats()
    return {
        "churn_crashed": len(crashed),
        "detect_all_ticks": det_ticks,
        "false_positive_after_churn": round(s2["false_positive"], 6),
    }


def rung1() -> None:
    n = 128
    sim = ClusterSim(n, seed=2)
    sim.step()  # compile
    tick, wall = _converge(sim)
    s = sim.stats()
    emit(
        1,
        "batched_128_churn5pct",
        n=n,
        convergence_ticks=tick,
        convergence_wall_s=round(wall, 3),
        false_positive_healthy=round(s["false_positive"], 6),
        platform=jax.devices()[0].platform,
        **_churn(sim, frac=20, seed=7, max_extra=300),
    )


def rung2() -> None:
    n = 1000
    for susp in (3, 6, 9):
        sim = ClusterSim(n, seed=3, fanout=3, suspicion_ticks=susp)
        sim.step()
        tick, wall = _converge(sim)
        s = sim.stats()
        sim.crash(n - 1)
        det = sim.run_until_detected(1.0, max_extra_ticks=200)
        emit(
            2,
            "batched_1k_fanout3_suspect_sweep",
            n=n,
            suspicion_ticks=susp,
            convergence_ticks=tick,
            convergence_wall_s=round(wall, 3),
            false_positive=round(s["false_positive"], 6),
            detect_one_ticks=det,
            platform=jax.devices()[0].platform,
        )


def rung3() -> None:
    n = int(os.environ.get("LADDER_R3_N", "10000"))
    # bench.py's boot-tuned configuration (W = n/4 feed bandwidth, few
    # large windows, trimmed gossip widths — PROFILE.md)
    sim = ClusterSim(
        n, seed=0, seed_mode="fingers",
        feeds_per_tick=4, feed_entries=max(25, n // 16),
        piggyback=4, incoming_slots=8, buffer_slots=12,
        probe_candidates=2, antientropy=1,
    )
    sim.step()
    sim.step(5)  # compile the 5-tick scan BEFORE timing it
    jax.block_until_ready(sim.state.view)
    # steady-state per-tick cost (the number that scales to TPU)
    t0 = time.monotonic()
    sim.step(5)
    jax.block_until_ready(sim.state.view)
    per_tick = (time.monotonic() - t0) / 5
    tick, wall = _converge(sim, every=50)
    s = sim.stats()
    emit(
        3,
        "batched_10k_single_device",
        n=n,
        seed_mode="fingers",
        per_tick_s=round(per_tick, 4),
        convergence_ticks=tick,
        convergence_wall_s=round(wall, 3),
        coverage=round(s["coverage"], 5),
        false_positive_healthy=round(s["false_positive"], 6),
        platform=jax.devices()[0].platform,
        # churn at bench scale (north star #2 evidence at 10k, not just
        # the 128/1k rungs): 1% crashed at once
        **_churn(sim, frac=100, seed=11, max_extra=400),
    )


def rung4() -> None:
    from corrosion_tpu.parallel import (
        member_mesh,
        shard_swim_state,
        sharded_tick,
    )

    n_dev = min(8, len(jax.devices()))
    n = int(os.environ.get("LADDER_R4_N", "16384"))
    params = swim.SwimParams(
        n=n, feeds_per_tick=max(4, n // (25 * 50))
    )
    mesh = member_mesh(jax.devices()[:n_dev])
    state = shard_swim_state(
        swim.init_state(params, jax.random.PRNGKey(0)), mesh
    )
    tick = sharded_tick(params, mesh)
    rng = jax.random.PRNGKey(1)
    rng, k = jax.random.split(rng)
    state = tick(state, k)  # compile
    jax.block_until_ready(state.view)
    t0 = time.monotonic()
    steps = 10
    for _ in range(steps):
        rng, k = jax.random.split(rng)
        state = tick(state, k)
    jax.block_until_ready(state.view)
    per_tick = (time.monotonic() - t0) / steps
    s = swim.membership_stats(state)
    itemsize = jnp.dtype(swim.VIEW_DTYPE).itemsize
    view_gb_100k = 100_000**2 * itemsize / 2**30
    emit(
        4,
        "sharded_8dev_largest_host_feasible",
        n=n,
        n_devices=n_dev,
        per_tick_s=round(per_tick, 4),
        coverage_after_10=round(s["coverage"], 5),
        view_bytes_per_chip_at_100k_gb=round(view_gb_100k / 8, 2),
        note=(
            "identical sharded program as the 100k v5e-8 target; "
            f"[N,N] {jnp.dtype(swim.VIEW_DTYPE).name} view at 100k = "
            f"{view_gb_100k:.0f} GiB total, {view_gb_100k / 8:.1f} GiB/chip "
            "on 8 chips — fits v5e-8 HBM (16 GiB/chip) with 2x headroom "
            "vs the int32 layout"
        ),
        platform=jax.devices()[0].platform,
    )


def main() -> None:
    rungs = [int(a) for a in sys.argv[1:]] or [0, 1, 2, 3, 4]
    t0 = time.monotonic()
    for r in rungs:
        {0: rung0, 1: rung1, 2: rung2, 3: rung3, 4: rung4}[r]()
    out = os.path.join(REPO, "BASELINE_MEASURED.json")
    existing = []
    if os.path.exists(out):
        try:
            with open(out) as f:
                existing = json.load(f)
        except ValueError:
            existing = []
    merged = {
        (r["rung"], r["name"], r.get("suspicion_ticks")): r
        for r in existing + RESULTS
    }
    with open(out, "w") as f:
        json.dump(list(merged.values()), f, indent=1)
    print(
        json.dumps(
            {"ladder_wall_s": round(time.monotonic() - t0, 1), "out": out}
        )
    )


if __name__ == "__main__":
    main()

"""Churn-detection parameter sweep for the partial-view kernel.

Measures ticks-to-cluster-wide-detection (detected == 1.0, FP 0) after
1% churn at a fixed n, across dissemination knobs: antientropy entries,
piggyback buffer width, and max_transmissions. The winner must earn its
keep in WALL time, not just tick count — wider message volume makes each
tick more expensive — so both are recorded.

Usage: python scripts/churn_sweep.py [n] [slots]   (defaults 8192 512)
Writes CHURN_SWEEP.json (merge_records).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# CPU-only parameter exploration: never touch the (possibly wedged)
# tunnel backend — JAX_PLATFORMS=cpu alone still loads the axon plugin
jaxenv.force_cpu_inprocess()
jaxenv.enable_compilation_cache()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from corrosion_tpu.ops import swim_pview  # noqa: E402
from corrosion_tpu.runtime.records import merge_records  # noqa: E402

CHUNK = 10
QUORUM = 8


def run_config(label: str, n: int, slots: int, **overrides) -> dict:
    params = swim_pview.PViewParams(
        n=n, slots=slots, feeds_per_tick=8,
        feed_entries=max(16, slots // 16), tie_epoch=512, **overrides
    )
    state = swim_pview.init_state(
        params, jax.random.PRNGKey(0), seed_mode="fingers"
    )
    rng = jax.random.PRNGKey(1)

    def advance(s, key):
        return swim_pview.tick_n_donated(s, key, params, CHUNK)

    # bootstrap to convergence
    ticks = 0
    converged = False
    while ticks < 1500:
        rng, key = jax.random.split(rng)
        state = advance(state, key)
        ticks += CHUNK
        st = swim_pview.membership_stats(state, params)
        converged = (
            st["pv_coverage"] >= 0.99
            and st["min_in_degree"] >= QUORUM
            and st["false_positive"] == 0.0
        )
        if converged:
            break
    if not converged:
        return {"label": label, "error": "no bootstrap convergence",
                "boot_ticks": ticks}

    # 1% churn -> detect-all
    kill = np.random.default_rng(7).choice(n, size=n // 100, replace=False)
    state = swim_pview.set_alive_many(state, kill, False)
    t0 = time.monotonic()
    det_ticks = 0
    detected = False
    while det_ticks < 3000:
        rng, key = jax.random.split(rng)
        state = advance(state, key)
        det_ticks += CHUNK
        st = swim_pview.membership_stats(state, params)
        if st["false_positive"] > 0:
            return {"label": label, "error": "false positive under churn",
                    "stats": {k: round(v, 5) for k, v in st.items()}}
        if st["detected"] >= 1.0:
            detected = True
            break
    wall = time.monotonic() - t0
    rec = {
        "rung": f"{label}-{n}",
        "label": label,
        "n": n, "slots": slots,
        "overrides": overrides,
        "boot_ticks": ticks,
        "detect_all_ticks": det_ticks if detected else None,
        "churn_wall_s": round(wall, 1),
        "s_per_tick": round(wall / max(1, det_ticks), 4),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(rec), flush=True)
    return rec


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    configs = [
        ("baseline", {}),
        ("ae8", {"antientropy": 8}),
        ("pb16", {"piggyback": 16}),
        ("mt20", {"max_transmissions": 20}),
        ("pb16-mt20", {"piggyback": 16, "max_transmissions": 20}),
        ("ae8-pb16-mt20", {"antientropy": 8, "piggyback": 16,
                           "max_transmissions": 20}),
        # r9: Lifeguard on — measures what the LHA-Suspicion ceiling
        # costs in detect-all ticks when the churned members are REALLY
        # dead (confirmations should shrink the window back toward the
        # floor; a large gap vs baseline means susp_k/susp_ceiling need
        # retuning at this scale)
        ("lifeguard", {"lhm_max": 8}),
    ]
    out = []
    for label, ov in configs:
        print(f"--- {label} ---", flush=True)
        out.append(run_config(label, n, slots, **ov))
    for r in out:
        r.setdefault("rung", f"{r['label']}-{n}")
    merge_records(os.path.join(REPO, "CHURN_SWEEP.json"), out)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

"""Partial-view convergence + churn-detection proof at scale.

VERDICT r3 item 2: the pview kernel's largest *convergence* evidence was
n=8,192 (coverage 0.970); 100k/262k rungs were execution proofs only, and
no churn/partition detection existed for the partial-view kernel at any
large n. This script runs the full bar at a given n:

  phase 1 (bootstrap):  tick until pv_coverage >= 0.99, min_in_degree >=
                        quorum floor, false_positive == 0
  phase 2 (churn):      kill 1% of members, tick until every dead member
                        is DETECTED (no live observer holds it alive —
                        membership_stats()["detected"] == 1.0) with
                        false_positive == 0 among survivors

Records a replace-by-rung entry in PVIEW_SCALE.json (merge_records).

Usage:  python scripts/pview_converge.py [n] [slots] [--devices N]
Env:    PVIEW_MAX_TICKS (default 2000), PVIEW_CHUNK (default 25 on CPU;
        on TPU auto-sized to keep one dispatch under the tunnel's
        ~45-60 s execution-time kill — PROFILE.md), PVIEW_CHECK_EVERY
        (stats cadence in ticks, default 10, min = chunk)

Single-device by default (the shape the one real v5e chip runs); pass
--devices 8 to run the sharded program on the virtual CPU mesh.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# --devices N (default 1); argv is NOT mutated — reexec_under_cpu forwards
# sys.argv[1:] verbatim, so the child must see the same flag
if "--devices" in sys.argv:
    _di = sys.argv.index("--devices")
    if _di + 1 >= len(sys.argv):
        sys.exit("usage: pview_converge.py [n] [slots] [--devices N]")
    DEVICES = int(sys.argv[_di + 1])
else:
    DEVICES = 1
# re-exec under a stripped CPU env unless already the child — or keep the
# inherited env when the real chip answers a quick probe (ladder policy)
jaxenv.reexec_under_cpu(
    "PVIEW_CHILD", n_devices=DEVICES, prefer_inherited_probe_s=20.0
)

jaxenv.enable_compilation_cache()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from corrosion_tpu.ops import swim_pview  # noqa: E402
from corrosion_tpu.runtime.metrics import KERNEL_EVENTS  # noqa: E402
from corrosion_tpu.runtime.records import (  # noqa: E402
    frames_from_ring,
    merge_records,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# suspicion-lifecycle lanes of the flight ring (r8): the tick-RESOLVED
# churn story — which tick suspicions spiked, when the down
# declarations landed, whether refutes trailed them — where the banked
# end-state stats can only say "detected eventually"
_TIMELINE_LANES = ("suspect_raised", "down_declared", "refuted")
_EV_IDX = {name: i for i, name in enumerate(KERNEL_EVENTS)}
_CEN_SUSPECT = len(KERNEL_EVENTS) + 1  # census_suspect lane offset


def flight_timeline(state, max_rows: int = 128):
    """Drain the device flight ring into [{tick, suspect_raised,
    down_declared, refuted, census_suspect}] rows (ACTIVE rows only —
    ticks where any lifecycle lane fired — capped at `max_rows`)."""
    import numpy as np2

    ring, t = jax.device_get((state.ring, state.t))
    ring = np2.asarray(ring)
    rows = []
    for tick, row in frames_from_ring(ring, int(t)):
        vals = {lane: int(row[_EV_IDX[lane]]) for lane in _TIMELINE_LANES}
        if any(vals.values()):
            vals["tick"] = tick
            vals["census_suspect"] = int(row[_CEN_SUSPECT])
            rows.append(vals)
    return rows[-max_rows:]


def print_timeline(label: str, rows) -> None:
    print(f"{label}: {len(rows)} active ticks", flush=True)
    for r in rows:
        print(
            f"  tick {r['tick']:>6}: suspect+{r['suspect_raised']} "
            f"down+{r['down_declared']} refute+{r['refuted']} "
            f"(open timers {r['census_suspect']})",
            flush=True,
        )


def main() -> None:
    argv = sys.argv[1:]
    if "--devices" in argv:
        di = argv.index("--devices")
        del argv[di : di + 2]
    args = [a for a in argv if not a.startswith("--")]
    n = int(args[0]) if args else 100_000
    slots = int(args[1]) if len(args) > 1 else 2048
    chunk_env = os.environ.get("PVIEW_CHUNK")
    chunk = int(chunk_env) if chunk_env else 25
    max_ticks = int(os.environ.get("PVIEW_MAX_TICKS", "2000"))
    quorum = 8
    plat = jax.devices()[0].platform
    if plat == "tpu" and not chunk_env:
        # the tunneled chip KILLS device programs that execute longer
        # than ~45-60 s (UNAVAILABLE "kernel fault"; PROFILE.md "the
        # tunnel's device-execution-time limit" — found when the >=262k
        # rungs faulted at the default 25-tick chunk while 10-tick
        # chunks ran clean).  Budget each dispatch at ~20 s using the
        # measured ~1.5 s/tick at n=100k, K=2048, scaled by the [n, K]
        # table the tick's cost is dominated by.
        chunk = max(1, min(25, int(1.3e6 / max(1, n * slots // 2048))))
    print(
        f"platform={plat} n={n} slots={slots} devices={DEVICES} "
        f"chunk={chunk}",
        flush=True,
    )

    # tuned on the load-49 ladder probe (n=25k, K=512): the tie-break
    # re-mask resets slot contests every epoch and winner re-installation
    # takes ~60 ticks of feed diffusion, so epochs must be long and feed
    # bandwidth high for instantaneous coverage to cross 0.99
    tie_epoch = int(os.environ.get("PVIEW_TIE_EPOCH", "512"))
    feeds = int(os.environ.get("PVIEW_FEEDS", "8"))
    # r6 restructure knobs: default to the kernel defaults (fused tick,
    # shift gossip); PVIEW_TICK_MODE=r5 / PVIEW_GOSSIP_MODE=pick re-run
    # the round-5 formulation for A/Bs against the banked rungs
    tick_mode = os.environ.get("PVIEW_TICK_MODE", "fused")
    gossip_mode = os.environ.get("PVIEW_GOSSIP_MODE", "shift")
    params = swim_pview.PViewParams(
        n=n, slots=slots, feeds_per_tick=feeds,
        feed_entries=max(16, slots // 16), tie_epoch=tie_epoch,
        tick_mode=tick_mode, gossip_mode=gossip_mode,
    )
    t0 = time.monotonic()
    state = swim_pview.init_state(
        params, jax.random.PRNGKey(0), seed_mode="fingers"
    )
    jax.block_until_ready(state.slot_packed)
    init_s = time.monotonic() - t0

    if DEVICES > 1:
        from corrosion_tpu.parallel import (
            member_mesh,
            shard_member_state,
            sharded_pview_tick,
        )

        mesh = member_mesh(jax.devices())
        state = shard_member_state(state, mesh)
        tick_n = sharded_pview_tick(params, mesh, chunk)

        def advance(s, key):
            return tick_n(s, key)
    else:
        def advance(s, key):
            return swim_pview.tick_n_donated(s, key, params, chunk)

    rng = jax.random.PRNGKey(1)
    t0 = time.monotonic()
    rng, key = jax.random.split(rng)
    state = advance(state, key)
    jax.block_until_ready(state.slot_packed)
    compile_s = time.monotonic() - t0
    print(f"init {init_s:.1f}s compile+first {compile_s:.1f}s", flush=True)

    # ---- phase 1: bootstrap convergence ----------------------------------
    ticks = chunk
    stats = {}
    converged = False
    # stats cadence decoupled from the dispatch chunk: the TPU-side
    # chunk shrinks to stay under the tunnel's execution-time limit
    # (1 tick at n=1M), and paying a stats pass + readback per chunk
    # would then dominate the run
    check_every = max(chunk, int(os.environ.get("PVIEW_CHECK_EVERY", "10")))

    def run_until(state, rng, done, target):
        """Advance in `chunk`-tick dispatches until `done` >= target."""
        while done < target:
            rng, key = jax.random.split(rng)
            state = advance(state, key)
            done += chunk
        return state, rng, done

    t0 = time.monotonic()
    while ticks < max_ticks:
        state, rng, ticks = run_until(
            state, rng, ticks, min(ticks + check_every, max_ticks)
        )
        stats = swim_pview.membership_stats(state, params)
        print(f"tick {ticks}: {json.dumps({k: round(v, 4) for k, v in stats.items()})}",
              flush=True)
        # pv_coverage is RELATIVE (in-degree >= half the current mean),
        # and a fingers bootstrap seeds ~log2(n) >= quorum in-degree at
        # tick 0 — so an early check (small adaptive chunks on TPU)
        # satisfied the old three-term bar at tick 8 with 0.9%-occupied
        # tables. Convergence additionally requires the table to have
        # actually FILLED: mean in-degree at >= 85% of its saturation
        # value (swim_pview.saturation_floor — the formula rationale
        # lives there, shared with the device-resident loop). Every
        # previously banked rung clears this — the weakest margins are
        # the 1M/2M CPU boot rungs at ~1847 mean in-degree vs the 1741
        # bar (the 512k TPU rung sits comfortably higher, 2026).
        saturated = swim_pview.saturation_floor(n, slots)
        converged = (
            stats["pv_coverage"] >= 0.99
            and stats["min_in_degree"] >= quorum
            and stats["mean_in_degree"] >= saturated
            and stats["false_positive"] == 0.0
        )
        if converged:
            break
    boot_wall = time.monotonic() - t0
    boot_ticks = ticks
    print(f"bootstrap: converged={converged} ticks={boot_ticks} "
          f"wall={boot_wall:.1f}s", flush=True)

    # ---- phase 2: 1% churn → cluster-wide detection ----------------------
    det_ticks = None
    churn_stats = {}
    churn_timeline = []
    n_kill = max(1, n // 100)
    skip_churn = os.environ.get("PVIEW_SKIP_CHURN") == "1"
    if skip_churn:
        n_kill = 0
    if converged and not skip_churn:
        kill = np.random.default_rng(7).choice(n, size=n_kill, replace=False)
        state = swim_pview.set_alive_many(state, kill, False)
        t0 = time.monotonic()
        extra = 0
        while extra < max_ticks:
            state, rng, extra = run_until(
                state, rng, extra, min(extra + check_every, max_ticks)
            )
            churn_stats = swim_pview.membership_stats(state, params)
            print(f"churn +{extra}: detected={churn_stats['detected']:.4f} "
                  f"fp={churn_stats['false_positive']:.6f}", flush=True)
            if (
                churn_stats["detected"] >= 1.0
                and churn_stats["false_positive"] == 0.0
            ):
                det_ticks = extra
                break
        churn_wall = time.monotonic() - t0
        # tick-resolved suspicion/refute timeline from the flight ring:
        # the per-protocol-period shape of the detection, not just its
        # end state (ring depth bounds how far back it reaches — the
        # tail of a long churn phase, which holds the detection story)
        churn_timeline = flight_timeline(state)
        print_timeline("churn timeline (flight ring)", churn_timeline)
    else:
        churn_wall = 0.0

    rec = {
        # churn-skipped runs record under their own rung key so they can
        # never overwrite a full run's detection evidence
        "rung": f"A-convergence-{n}" + ("-boot" if skip_churn else ""),
        "n": n,
        "slots": slots,
        "devices": DEVICES,
        "platform": plat,
        "quorum_floor": quorum,
        "seed_mode": "fingers",
        "tick_mode": tick_mode,
        "gossip_mode": gossip_mode,
        "init_s": round(init_s, 2),
        "compile_s": round(compile_s, 2),
        "ticks": boot_ticks,
        "wall_s": round(boot_wall, 2),
        "s_per_tick": round(boot_wall / max(1, boot_ticks - chunk), 4),
        "converged": converged,
        "stats": {k: round(v, 6) for k, v in stats.items()},
        "churn": {
            "killed": n_kill,
            "detect_all_ticks": det_ticks,
            "wall_s": round(churn_wall, 2),
            "stats": {k: round(v, 6) for k, v in churn_stats.items()},
            "timeline": churn_timeline,
        },
    }
    if skip_churn:
        rec["churn"] = {"skipped": True}
    merge_records(os.path.join(REPO, "PVIEW_SCALE.json"), [rec])
    print(json.dumps(rec), flush=True)
    ok = converged and (skip_churn or det_ticks is not None)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()

"""Pubsub engine throughput: sustained write → subscription-event rate,
scaled along BOTH serving-plane axes (table rows × live subscriptions).

Reference analog: the matcher's cmd_loop batches candidates for 600 ms /
1000 entries and diffs per-table rewritten queries
(`klukai-types/src/pubsub.rs:1062-1226`). This measures the end-to-end
event rate live NDJSON subscriptions sustain while a writer hammers
/v1/transactions on the same agent — change router, matcher diffs,
shared diff executor, per-sub sqlite dbs, HTTP streaming and the h2
front-end all in the path.

Writes INSERT ... ON CONFLICT upserts in batches; each subscriber is a
DISTINCT subscription (distinct SQL → its own matcher + sub db, the
expensive axis) that counts row-change events until every stream has
drained `n_rows` events. Records merge into PUBSUB_BENCH.json keyed by
rung; records are `code_sha`-stamped over the measured pubsub files
(bench.py replay-gate discipline) so before/after points in the shared
artifact stay auditable.

Usage:
  python scripts/bench_pubsub.py [n_rows] [batch]          one rung, 1 sub
  python scripts/bench_pubsub.py --subs N [n_rows] [batch] one rung, N subs
  python scripts/bench_pubsub.py --all [--tag T]           the full grid:
      rows axis  {5k, 20k, 80k} × 1 sub   (table-size scaling)
      subs axis  5k × {1, 16, 128} subs   (sub-count scaling)
  --tag suffixes every rung name (e.g. `-pre`/`-post` for an A/B banked
  into the same file).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.client import CorrosionApiClient  # noqa: E402
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.records import merge_records  # noqa: E402

from tests.test_http_api import boot_with_api  # noqa: E402

_MEASURED_FILES = (
    "corrosion_tpu/pubsub/matcher.py",
    "corrosion_tpu/pubsub/manager.py",
    "corrosion_tpu/pubsub/executor.py",
    "corrosion_tpu/api/pubsub_http.py",
    "scripts/bench_pubsub.py",
)


def _code_fingerprint() -> dict:
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


async def main(
    n_rows: int,
    batch: int,
    n_subs: int = 1,
    tag: str = "",
    distinct: bool = False,
) -> dict:
    net = MemNetwork(seed=9)
    agent, api, client = await boot_with_api(net, "agent-pubsub")
    sub_clients = [CorrosionApiClient(api.addrs[0]) for _ in range(n_subs)]
    done_counts = [0] * n_subs

    async def subscriber(k: int) -> None:
        # default: IDENTICAL SQL — the manager dedupes by hash so all
        # streams share ONE matcher's diff + once-encoded event bytes
        # (the reference serving architecture; the per-sub-rate bar is
        # judged here).  --distinct gives each stream its own predicate
        # → its own matcher + sub db: the matcher-count scaling axis.
        sql = (
            f"SELECT id, text FROM tests WHERE id >= -{k + 1}"
            if distinct
            else "SELECT id, text FROM tests"
        )
        # raw observer mode: count delivered change lines without a
        # json.loads per event — the bench measures the serving plane,
        # not the harness's decoder (uniform across every rung)
        async for line in sub_clients[k].subscribe(
            sql, skip_rows=True, raw=True
        ):
            if line.startswith('{"change":'):
                done_counts[k] += 1
                if done_counts[k] >= n_rows:
                    return
            elif line.startswith('{"error":'):
                raise RuntimeError(f"subscriber {k} got error frame: {line}")

    sub_tasks = [asyncio.ensure_future(subscriber(k)) for k in range(n_subs)]
    try:
        await asyncio.sleep(0.5 + 0.01 * n_subs)  # subscriptions established

        t0 = time.monotonic()
        for start in range(0, n_rows, batch):
            stmts = [
                [
                    "INSERT INTO tests (id, text) VALUES (?, ?) "
                    "ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                    [i, f"v{i}"],
                ]
                for i in range(start, min(start + batch, n_rows))
            ]
            await client.execute(stmts)
        write_wall = time.monotonic() - t0
        # wait on the subscriber TASKS, not just an event: a subscriber
        # crash must surface its real exception, not a bare TimeoutError
        await asyncio.wait_for(asyncio.gather(*sub_tasks), 600)
        total_wall = time.monotonic() - t0

        got = sum(done_counts)
        rung = f"pubsub-{n_rows}" + (
            f"x{n_subs}{'d' if distinct else ''}" if n_subs != 1 else ""
        )
        return {
            "rung": rung + (f"-{tag}" if tag else ""),
            "n_rows": n_rows,
            "n_subs": n_subs,
            "distinct_matchers": bool(distinct and n_subs != 1),
            "batch": batch,
            "write_wall_s": round(write_wall, 2),
            "events_delivered": got,
            "event_rate_per_s": round(got / total_wall, 1),
            "event_rate_per_sub_per_s": round(got / n_subs / total_wall, 1),
            "write_rate_per_s": round(n_rows / write_wall, 1),
            "total_wall_s": round(total_wall, 2),
            "code_sha": _code_fingerprint(),
            "measured_at": time.strftime(
                "%Y-%m-%d %H:%M:%S", time.gmtime()
            ),
        }
    finally:
        for t in sub_tasks:
            t.cancel()
        await client.close()
        for sc in sub_clients:
            await sc.close()
        await api.stop()
        from corrosion_tpu.agent.run import shutdown

        await shutdown(agent)


# the banked grid: rows axis at 1 sub, subs axis at 5k rows (shared
# matcher via dedupe), plus one distinct-matcher rung for the
# matcher-count scaling trajectory
ALL_RUNGS = (
    (5_000, 50, 1, False),
    (20_000, 50, 1, False),
    (80_000, 50, 1, False),
    (5_000, 50, 16, False),
    (5_000, 50, 128, False),
    (5_000, 50, 16, True),
)


def _run_and_merge(rungs, tag: str) -> None:
    recs = []
    for n_rows, batch, n_subs, distinct in rungs:
        rec = asyncio.run(main(n_rows, batch, n_subs, tag, distinct))
        print(json.dumps(rec), flush=True)
        recs.append(rec)
    merge_records(os.path.join(REPO, "PUBSUB_BENCH.json"), recs)


if __name__ == "__main__":
    args = sys.argv[1:]
    tag = ""
    if "--tag" in args:
        i = args.index("--tag")
        tag = args[i + 1]
        del args[i : i + 2]
    distinct = "--distinct" in args
    if distinct:
        args.remove("--distinct")
    if "--all" in args:
        _run_and_merge(ALL_RUNGS, tag)
        sys.exit(0)
    n_subs = 1
    if "--subs" in args:
        i = args.index("--subs")
        n_subs = int(args[i + 1])
        del args[i : i + 2]
    n_rows = int(args[0]) if args else 20_000
    batch = int(args[1]) if len(args) > 1 else 50
    _run_and_merge([(n_rows, batch, n_subs, distinct)], tag)

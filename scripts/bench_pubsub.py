"""Pubsub engine throughput: sustained write → subscription-event rate.

Reference analog: the matcher's cmd_loop batches candidates for 600 ms /
1000 entries and diffs per-table rewritten queries
(`klukai-types/src/pubsub.rs:1062-1226`). This measures the end-to-end
event rate a live NDJSON subscription sustains while a writer hammers
/v1/transactions on the same agent — matcher, per-sub sqlite db, HTTP
streaming and the h2 front-end all in the path.

Writes INSERT ... ON CONFLICT upserts in batches; the subscriber counts
row-change events until the writer stops and the stream drains. Records
into PUBSUB_BENCH.json.

Usage: python scripts/bench_pubsub.py [n_rows] [batch]   (default 20000 50)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.client import CorrosionApiClient  # noqa: E402
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.records import merge_records  # noqa: E402

from tests.test_http_api import boot_with_api  # noqa: E402


async def main(n_rows: int, batch: int) -> dict:
    net = MemNetwork(seed=9)
    agent, api, client = await boot_with_api(net, "agent-pubsub")
    sub_client = CorrosionApiClient(api.addrs[0])
    got = 0
    done = asyncio.Event()

    async def subscriber() -> None:
        nonlocal got
        async for ev in sub_client.subscribe(
            "SELECT id, text FROM tests", skip_rows=True
        ):
            if "change" in ev:
                got += 1
                if got >= n_rows:
                    done.set()
                    return

    sub_task = asyncio.ensure_future(subscriber())
    try:
        await asyncio.sleep(0.5)  # subscription established

        t0 = time.monotonic()
        for start in range(0, n_rows, batch):
            stmts = [
                [
                    "INSERT INTO tests (id, text) VALUES (?, ?) "
                    "ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                    [i, f"v{i}"],
                ]
                for i in range(start, min(start + batch, n_rows))
            ]
            await client.execute(stmts)
        write_wall = time.monotonic() - t0
        # wait on the subscriber TASK, not just the event: a subscriber
        # crash must surface its real exception, not a bare TimeoutError
        await asyncio.wait_for(sub_task, 300)
        total_wall = time.monotonic() - t0

        return {
            "rung": f"pubsub-{n_rows}",
            "n_rows": n_rows,
            "batch": batch,
            "write_wall_s": round(write_wall, 2),
            "events_delivered": got,
            "event_rate_per_s": round(got / total_wall, 1),
            "write_rate_per_s": round(n_rows / write_wall, 1),
            "total_wall_s": round(total_wall, 2),
        }
    finally:
        sub_task.cancel()
        await client.close()
        await sub_client.close()
        await api.stop()
        from corrosion_tpu.agent.run import shutdown

        await shutdown(agent)


if __name__ == "__main__":
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    rec = asyncio.run(main(n_rows, batch))
    merge_records(os.path.join(REPO, "PUBSUB_BENCH.json"), [rec])
    print(json.dumps(rec))

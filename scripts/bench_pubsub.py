"""Pubsub engine throughput: sustained write → subscription-event rate,
scaled along BOTH serving-plane axes (table rows × live subscriptions).

Reference analog: the matcher's cmd_loop batches candidates for 600 ms /
1000 entries and diffs per-table rewritten queries
(`klukai-types/src/pubsub.rs:1062-1226`). This measures the end-to-end
event rate live NDJSON subscriptions sustain while a writer hammers
/v1/transactions on the same agent — change router, matcher diffs,
shared diff executor, per-sub sqlite dbs, HTTP streaming and the h2
front-end all in the path.

Writes INSERT ... ON CONFLICT upserts in batches; each subscriber is a
DISTINCT subscription (distinct SQL → its own matcher + sub db, the
expensive axis) that counts row-change events until every stream has
drained `n_rows` events. Records merge into PUBSUB_BENCH.json keyed by
rung; records are `code_sha`-stamped over the measured pubsub files
(bench.py replay-gate discipline) so before/after points in the shared
artifact stay auditable.

Usage:
  python scripts/bench_pubsub.py [n_rows] [batch]          one rung, 1 sub
  python scripts/bench_pubsub.py --subs N [n_rows] [batch] one rung, N subs
  python scripts/bench_pubsub.py --all [--tag T]           the full grid:
      rows axis  {5k, 20k, 80k} × 1 sub   (table-size scaling)
      subs axis  5k × {1, 16, 128} subs   (sub-count scaling)
  --tag suffixes every rung name (e.g. `-pre`/`-post` for an A/B banked
  into the same file).

r16 stream-count ladder (banked into SUBS_SCALE.json):
  python scripts/bench_pubsub.py --streams N [--queries K] [--rows R]
      one rung: N concurrent NDJSON streams over K distinct queries
      (dedupe ratio N/K), events counted to completion on every stream,
      p99 deliver latency read from the server's corro.e2e.deliver
      histogram — the serving-plane headline.
  python scripts/bench_pubsub.py --scale [--tag T]
      the SUBS_SCALE ladder: 1k/10k/100k streams × shared(k=10) plus a
      1k distinct-queries rung (the matcher-count axis; capped at 1k —
      every distinct matcher is its own sqlite db + connection, and 10k
      of those would blow the container's fd budget: the cap is logged
      in the record, not silent).  The 100k rung runs under admission
      control and probes one over-limit subscribe for the typed 503.
  python scripts/bench_pubsub.py --scale --ab [--tag T]
      A/B: every rung ≤10k runs twice ADJACENT — fanout="queue" (the
      r10 per-stream drain loops, tag -pre) then fanout="writer" (the
      r16 coalesced writer, tag -post) — same-host noise discipline as
      bench_ingest; the 100k rung runs writer-only (100k drain-loop
      tasks is the pathology the round removes, not a baseline worth
      hours of wall).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.client import CorrosionApiClient  # noqa: E402
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.records import (  # noqa: E402
    cleanup_record_locks,
    merge_records,
)

from tests.test_http_api import boot_with_api  # noqa: E402

_MEASURED_FILES = (
    "corrosion_tpu/pubsub/matcher.py",
    "corrosion_tpu/pubsub/manager.py",
    "corrosion_tpu/pubsub/executor.py",
    "corrosion_tpu/pubsub/fanout.py",
    "corrosion_tpu/api/pubsub_http.py",
    "scripts/bench_pubsub.py",
)


def _code_fingerprint() -> dict:
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


async def main(
    n_rows: int,
    batch: int,
    n_subs: int = 1,
    tag: str = "",
    distinct: bool = False,
) -> dict:
    net = MemNetwork(seed=9)
    agent, api, client = await boot_with_api(net, "agent-pubsub")
    sub_clients = [CorrosionApiClient(api.addrs[0]) for _ in range(n_subs)]
    done_counts = [0] * n_subs

    async def subscriber(k: int) -> None:
        # default: IDENTICAL SQL — the manager dedupes by hash so all
        # streams share ONE matcher's diff + once-encoded event bytes
        # (the reference serving architecture; the per-sub-rate bar is
        # judged here).  --distinct gives each stream its own predicate
        # → its own matcher + sub db: the matcher-count scaling axis.
        sql = (
            f"SELECT id, text FROM tests WHERE id >= -{k + 1}"
            if distinct
            else "SELECT id, text FROM tests"
        )
        # raw observer mode: count delivered change lines without a
        # json.loads per event — the bench measures the serving plane,
        # not the harness's decoder (uniform across every rung)
        async for line in sub_clients[k].subscribe(
            sql, skip_rows=True, raw=True
        ):
            if line.startswith('{"change":'):
                done_counts[k] += 1
                if done_counts[k] >= n_rows:
                    return
            elif line.startswith('{"error":'):
                raise RuntimeError(f"subscriber {k} got error frame: {line}")

    sub_tasks = [asyncio.ensure_future(subscriber(k)) for k in range(n_subs)]
    try:
        await asyncio.sleep(0.5 + 0.01 * n_subs)  # subscriptions established

        t0 = time.monotonic()
        for start in range(0, n_rows, batch):
            stmts = [
                [
                    "INSERT INTO tests (id, text) VALUES (?, ?) "
                    "ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                    [i, f"v{i}"],
                ]
                for i in range(start, min(start + batch, n_rows))
            ]
            await client.execute(stmts)
        write_wall = time.monotonic() - t0
        # wait on the subscriber TASKS, not just an event: a subscriber
        # crash must surface its real exception, not a bare TimeoutError
        await asyncio.wait_for(asyncio.gather(*sub_tasks), 600)
        total_wall = time.monotonic() - t0

        got = sum(done_counts)
        rung = f"pubsub-{n_rows}" + (
            f"x{n_subs}{'d' if distinct else ''}" if n_subs != 1 else ""
        )
        return {
            "rung": rung + (f"-{tag}" if tag else ""),
            "n_rows": n_rows,
            "n_subs": n_subs,
            "distinct_matchers": bool(distinct and n_subs != 1),
            "batch": batch,
            "write_wall_s": round(write_wall, 2),
            "events_delivered": got,
            "event_rate_per_s": round(got / total_wall, 1),
            "event_rate_per_sub_per_s": round(got / n_subs / total_wall, 1),
            "write_rate_per_s": round(n_rows / write_wall, 1),
            "total_wall_s": round(total_wall, 2),
            "code_sha": _code_fingerprint(),
            "measured_at": time.strftime(
                "%Y-%m-%d %H:%M:%S", time.gmtime()
            ),
        }
    finally:
        for t in sub_tasks:
            t.cancel()
        await client.close()
        for sc in sub_clients:
            await sc.close()
        await api.stop()
        from corrosion_tpu.agent.run import shutdown

        await shutdown(agent)


# -- r16 stream-count ladder ------------------------------------------------


def _reg_peek(snap, name, labels=None):
    total = 0.0
    for _k, sname, slabels, value in snap:
        if sname == name and (labels is None or slabels == labels):
            total += value
    return total


async def streams_rung(
    n_streams: int,
    n_queries: int,
    n_rows: int,
    tag: str = "",
    distinct: bool = False,
    fanout: str = "writer",
) -> dict:
    """One SUBS_SCALE rung: N live NDJSON streams over K distinct
    queries on one node, all events delivered to every stream, raw
    h2 clients with widened receive windows so flow control measures
    the SERVER's fan-out path, not the harness's 64 KiB default."""
    import math

    from corrosion_tpu.net.h2 import H2Client
    from corrosion_tpu.runtime.latency import snapshot_stages
    from corrosion_tpu.runtime.metrics import METRICS

    if distinct:
        n_queries = n_streams
    net = MemNetwork(seed=9)
    agent, api, client = await boot_with_api(net, "agent-subs-scale")
    agent.config.subs.fanout = fanout
    agent.config.subs.max_streams = max(n_streams, 1)
    host, port = api.addrs[0].rsplit(":", 1)
    # ~250 streams per multiplexed conn (the server advertises h2
    # MAX_CONCURRENT_STREAMS=256); big windows so 100k streams aren't
    # throttled to 64 KiB per round trip
    n_conns = max(1, math.ceil(n_streams / 250))
    h2s = [
        H2Client(
            host, int(port),
            recv_window=1 << 20, conn_recv_window=64 << 20,
        )
        for _ in range(n_conns)
    ]
    queries = [
        f"SELECT id, text FROM tests WHERE id >= -{q + 1}"
        for q in range(n_queries)
    ]
    want = n_rows + 2  # columns + eoq + n_rows change lines
    counts = [0] * n_streams
    done_evt = asyncio.Event()
    remaining = [n_streams]

    async def consume(resp, k: int) -> None:
        async for chunk in resp.body():
            counts[k] += chunk.count(b"\n")
            if counts[k] >= want:
                break
        remaining[0] -= 1
        if remaining[0] == 0:
            done_evt.set()

    async def subscribe_one(k: int):
        body = json.dumps(queries[k % n_queries]).encode()
        resp = await h2s[k % n_conns].request(
            "POST", "/v1/subscriptions?skip_rows=true",
            headers={"content-type": "application/json"}, body=body,
        )
        assert resp.status == 200, (k, resp.status, await resp.read())
        return asyncio.ensure_future(consume(resp, k))

    t_setup = time.monotonic()
    tasks = []
    # bounded-concurrency establishment: 256 subscribes in flight
    for base in range(0, n_streams, 256):
        tasks.extend(
            await asyncio.gather(
                *(
                    subscribe_one(k)
                    for k in range(base, min(base + 256, n_streams))
                )
            )
        )
        if base % 10240 == 0 and base:
            print(f"  ... {base} streams attached", flush=True)
    setup_wall = time.monotonic() - t_setup
    matchers = len(api.subs.handles())

    # admission probe: one stream past the ceiling must get a typed 503
    probe = await h2s[0].request(
        "POST", "/v1/subscriptions?skip_rows=true",
        headers={"content-type": "application/json"},
        body=json.dumps(queries[0]).encode(),
    )
    probe_body = await probe.read()
    admission_rejected = (
        probe.status == 503 and b"subs_admission" in probe_body
    )

    pre = snapshot_stages()
    snap0 = METRICS.snapshot()
    t0 = time.monotonic()
    batch = 50
    for start in range(0, n_rows, batch):
        stmts = [
            [
                "INSERT INTO tests (id, text) VALUES (?, ?) "
                "ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                [i, f"v{i}"],
            ]
            for i in range(start, min(start + batch, n_rows))
        ]
        await client.execute(stmts)
    write_wall = time.monotonic() - t0
    try:
        await asyncio.wait_for(done_evt.wait(), 900)
    except asyncio.TimeoutError:
        pass  # recorded honestly below via events_delivered
    total_wall = time.monotonic() - t0

    deliver = snapshot_stages()["deliver"].diff(pre["deliver"])
    snap1 = METRICS.snapshot()

    def delta(name):
        return _reg_peek(snap1, name) - _reg_peek(snap0, name)

    delivered = sum(min(max(0, c - 2), n_rows) for c in counts)
    expected = n_streams * n_rows
    matcher_s = delta("corro.subs.process.time.seconds_sum")
    writer_s = delta("corro.subs.writer.round.seconds_sum")
    rec = {
        "rung": f"subs-{n_streams}x{n_queries}{'d' if distinct else ''}"
        + (f"-{tag}" if tag else ""),
        "fanout": fanout,
        "streams": n_streams,
        "queries": n_queries,
        "matchers": matchers,
        "dedupe_ratio": round(n_streams / max(1, matchers), 1),
        "distinct_cap_note": (
            "distinct axis capped at 1k streams: one sqlite db+conn per"
            " matcher; 10k+ would exhaust the 20k-fd container budget"
            if distinct
            else ""
        ),
        "n_rows": n_rows,
        "events_expected": expected,
        "events_delivered": delivered,
        "streams_complete": sum(1 for c in counts if c >= want),
        "admission": {
            "max_streams": agent.config.subs.max_streams,
            "over_limit_probe_rejected": admission_rejected,
        },
        "shed": delta("corro.subs.shed.total"),
        "deliver_p50_s": deliver.quantile(0.50),
        "deliver_p99_s": deliver.quantile(0.99),
        "deliver_observed": deliver.count,
        "matcher_seconds": round(matcher_s, 3),
        "writer_walk_seconds": round(writer_s, 3),
        "per_event_server_us": round(
            (matcher_s + writer_s) / max(1, delivered) * 1e6, 3
        ),
        "writer_writes": delta("corro.subs.writer.writes.total"),
        "writer_coalesced_batches": delta(
            "corro.subs.writer.coalesced.batches.total"
        ),
        "setup_wall_s": round(setup_wall, 2),
        "write_wall_s": round(write_wall, 2),
        "total_wall_s": round(total_wall, 2),
        "event_rate_per_s": round(delivered / max(1e-9, total_wall), 1),
        "code_sha": _code_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    for t in tasks:
        t.cancel()
    for h in h2s:
        try:
            await h.close()
        except Exception:
            pass
    await client.close()
    await api.stop()
    from corrosion_tpu.agent.run import shutdown

    await shutdown(agent)
    return rec


# (streams, queries, n_rows, distinct); the 100k rung keeps event
# volume small — 100k streams × 20 events = 2M deliveries on one core
SCALE_RUNGS = (
    (1_000, 10, 200, False),
    (1_000, 0, 50, True),  # distinct: queries = streams
    (10_000, 10, 100, False),
    (100_000, 10, 20, False),
)


def _run_scale(tag: str, ab: bool) -> None:
    recs = []
    for n_streams, n_queries, n_rows, distinct in SCALE_RUNGS:
        if ab and n_streams <= 10_000:
            # adjacent A/B per rung: the r10 drain-loop path first
            for mode, mtag in (("queue", "pre"), ("writer", "post")):
                t = f"{mtag}{('-' + tag) if tag else ''}"
                rec = asyncio.run(
                    streams_rung(
                        n_streams, n_queries, n_rows, t, distinct, mode
                    )
                )
                print(json.dumps(rec), flush=True)
                recs.append(rec)
        else:
            t = (
                f"post{('-' + tag) if tag else ''}"
                if ab
                else tag
            )
            rec = asyncio.run(
                streams_rung(
                    n_streams, n_queries, n_rows, t, distinct, "writer"
                )
            )
            print(json.dumps(rec), flush=True)
            recs.append(rec)
    merge_records(os.path.join(REPO, "SUBS_SCALE.json"), recs)


# the banked grid: rows axis at 1 sub, subs axis at 5k rows (shared
# matcher via dedupe), plus one distinct-matcher rung for the
# matcher-count scaling trajectory
ALL_RUNGS = (
    (5_000, 50, 1, False),
    (20_000, 50, 1, False),
    (80_000, 50, 1, False),
    (5_000, 50, 16, False),
    (5_000, 50, 128, False),
    (5_000, 50, 16, True),
)


def _run_and_merge(rungs, tag: str) -> None:
    recs = []
    for n_rows, batch, n_subs, distinct in rungs:
        rec = asyncio.run(main(n_rows, batch, n_subs, tag, distinct))
        print(json.dumps(rec), flush=True)
        recs.append(rec)
    merge_records(os.path.join(REPO, "PUBSUB_BENCH.json"), recs)


if __name__ == "__main__":
    args = sys.argv[1:]
    tag = ""
    if "--tag" in args:
        i = args.index("--tag")
        tag = args[i + 1]
        del args[i : i + 2]
    ab = "--ab" in args
    if ab:
        args.remove("--ab")
    distinct = "--distinct" in args
    if distinct:
        args.remove("--distinct")
    # whatever path runs (including a rung crashing mid-run or a
    # sys.exit), the merge flock sidecars must not strand in the tree
    try:
        if "--scale" in args:
            _run_scale(tag, ab)
            sys.exit(0)
        if "--streams" in args:
            i = args.index("--streams")
            n_streams = int(args[i + 1])
            del args[i : i + 2]
            n_queries = 10
            if "--queries" in args:
                i = args.index("--queries")
                n_queries = int(args[i + 1])
                del args[i : i + 2]
            n_rows = 100
            if "--rows" in args:
                i = args.index("--rows")
                n_rows = int(args[i + 1])
                del args[i : i + 2]
            rec = asyncio.run(
                streams_rung(n_streams, n_queries, n_rows, tag, distinct)
            )
            print(json.dumps(rec), flush=True)
            merge_records(os.path.join(REPO, "SUBS_SCALE.json"), [rec])
            sys.exit(0)
        if "--all" in args:
            _run_and_merge(ALL_RUNGS, tag)
            sys.exit(0)
        n_subs = 1
        if "--subs" in args:
            i = args.index("--subs")
            n_subs = int(args[i + 1])
            del args[i : i + 2]
        n_rows = int(args[0]) if args else 20_000
        batch = int(args[1]) if len(args) > 1 else 50
        _run_and_merge([(n_rows, batch, n_subs, distinct)], tag)
    finally:
        cleanup_record_locks(
            os.path.join(REPO, "SUBS_SCALE.json"),
            os.path.join(REPO, "PUBSUB_BENCH.json"),
        )

"""Per-PR bench smoke: a miniature pview convergence, banked per round.

The repo's bench trajectory between chip windows had no CPU-comparable
per-PR points (BENCH_r0*.json are driver-owned; the scale rungs are too
heavy to re-run every PR).  This entry is tier-1-safe — CPU only, small
n, seconds — and replays the SAME workload every PR: boot an
n=2048 × K=256 partial-view cluster with finger bootstrap to the full
four-term convergence bar via the device-resident loop
(`swim_pview.run_to_converged`), then 1% churn to full detection.

Each run writes `BENCH_PR<tag>.json` (tag = argv[1], else the next free
integer), `code_sha`-stamped over the measured kernel + driver files at
run START, so the series stays comparable and auditable the way the
TPU bench records are (bench.py's replay-gate discipline).  The CPU
platform is FORCED (plugin-stripped re-exec): a point that silently
measured a live chip would not be comparable with its neighbors.

Usage:  python scripts/bench_smoke.py [tag]
Env:    BENCH_SMOKE_N (default 2048), BENCH_SMOKE_SLOTS (default 256),
        BENCH_SMOKE_MAX_TICKS (default 600), BENCH_SMOKE_OUT (path
        override), BENCH_SMOKE_SKIP_CHURN=1
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# ALWAYS the stripped-CPU child (no prefer_inherited probe): per-PR
# points must share a platform to be comparable
jaxenv.reexec_under_cpu("BENCH_SMOKE_CHILD")
jaxenv.enable_compilation_cache()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from corrosion_tpu.models.cluster import PViewClusterSim  # noqa: E402
from corrosion_tpu.ops import swim_pview  # noqa: E402

_MEASURED_FILES = (
    "corrosion_tpu/ops/swim_pview.py",
    "corrosion_tpu/ops/swim.py",
    "corrosion_tpu/models/cluster.py",
)


def _code_fingerprint() -> dict:
    import hashlib

    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


def _next_tag() -> str:
    taken = set()
    for p in glob.glob(os.path.join(REPO, "BENCH_PR*.json")):
        m = re.match(r"BENCH_PR(\d+)\.json$", os.path.basename(p))
        if m:
            taken.add(int(m.group(1)))
    return f"{(max(taken) + 1) if taken else 1:02d}"


def main() -> None:
    tag = sys.argv[1] if len(sys.argv) > 1 else _next_tag()
    n = int(os.environ.get("BENCH_SMOKE_N", "2048"))
    slots = int(os.environ.get("BENCH_SMOKE_SLOTS", "256"))
    max_ticks = int(os.environ.get("BENCH_SMOKE_MAX_TICKS", "600"))
    code_sha = _code_fingerprint()  # at run START (bench.py discipline)

    t0 = time.monotonic()
    sim = PViewClusterSim(
        n, slots=slots, seed=0, seed_mode="fingers",
        feeds_per_tick=4, feed_entries=max(16, slots // 16), tie_epoch=512,
    )
    jax.block_until_ready(sim.state.slot_packed)
    init_s = time.monotonic() - t0

    # compile warm-up on a throwaway sim (same shapes/static args) so the
    # measured run starts cold at tick 0 with a warm executable cache
    t0 = time.monotonic()
    warm = PViewClusterSim(
        n, slots=slots, seed=1, seed_mode="fingers",
        feeds_per_tick=4, feed_entries=max(16, slots // 16), tie_epoch=512,
    )
    warm.state = warm.state._replace(t=np.int32(max_ticks))  # cond-only pass
    warm.ticks = max_ticks
    warm.run_until_converged_device(max_ticks=0, check_every=25)
    del warm
    compile_s = time.monotonic() - t0

    t0 = time.monotonic()
    stable_tick = sim.run_until_converged_device(
        max_ticks=max_ticks, check_every=25
    )
    boot_wall = time.monotonic() - t0
    stats = sim.stats()

    det_ticks = None
    churn_wall = 0.0
    n_kill = max(1, n // 100)
    if os.environ.get("BENCH_SMOKE_SKIP_CHURN") == "1":
        n_kill = 0
    elif stable_tick is not None:
        kill = np.random.default_rng(7).choice(n, size=n_kill, replace=False)
        sim.crash_many(kill)
        t0 = time.monotonic()
        base = sim.ticks
        while sim.ticks - base < max_ticks:
            sim.step(25)
            cs = sim.stats()
            if cs["detected"] >= 1.0 and cs["false_positive"] == 0.0:
                det_ticks = sim.ticks - base
                break
        churn_wall = time.monotonic() - t0

    rec = {
        "metric": f"pview_smoke_n{n}_k{slots}",
        "value": round(boot_wall, 3),
        "unit": "s",
        "detail": {
            "n": n,
            "slots": slots,
            "seed_mode": "fingers",
            "tick_mode": sim.params.tick_mode,
            "gossip_mode": sim.params.gossip_mode,
            "init_s": round(init_s, 2),
            "compile_s": round(compile_s, 2),
            "stable_tick": stable_tick,
            "boot_wall_s": round(boot_wall, 3),
            "churn_killed": n_kill,
            "churn_detect_all_ticks": det_ticks,
            "churn_wall_s": round(churn_wall, 3),
            "stats": {m: round(float(v), 6) for m, v in stats.items()},
            "platform": jax.devices()[0].platform,
            "code_sha": code_sha,
            "measured_at": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        },
    }
    path = os.environ.get(
        "BENCH_SMOKE_OUT", os.path.join(REPO, f"BENCH_PR{tag}.json")
    )
    with open(path, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")
    print(json.dumps(rec))
    ok = stable_tick is not None and (n_kill == 0 or det_ticks is not None)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()

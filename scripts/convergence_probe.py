"""Measure ticks-to-coverage and s/tick for the SWIM kernel on this host.

Usage: python scripts/convergence_probe.py [n] [feeds] [chunk]
Prints one line per chunk: tick, coverage, fp, cumulative wall seconds.
Used to compare kernel variants (ticks-to-converge must not regress when
the tick gets cheaper).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from corrosion_tpu.models.cluster import ClusterSim


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    feeds = int(sys.argv[2]) if len(sys.argv) > 2 else max(4, n // (25 * 50))
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 10
    target = float(os.environ.get("PROBE_COVERAGE", "0.999"))
    max_ticks = int(os.environ.get("PROBE_MAX_TICKS", "2000"))

    sim = ClusterSim(n, seed=0, feeds_per_tick=feeds)
    sim.step()  # compile warm-up
    import jax

    jax.block_until_ready(sim.state.view)
    print(f"platform={jax.devices()[0].platform} n={n} feeds={feeds}")
    t0 = time.monotonic()
    done = 0
    while done < max_ticks:
        sim.step(chunk)
        done += chunk
        s = sim.stats()
        el = time.monotonic() - t0
        print(
            f"tick={sim.ticks:5d} cov={s['coverage']:.5f} "
            f"fp={s['false_positive']:.6f} wall={el:8.2f}s "
            f"({el / done * 1000:7.1f} ms/tick)"
        )
        if s["coverage"] >= target:
            print(f"CONVERGED tick={sim.ticks} wall={el:.2f}s")
            return
    print("DID NOT CONVERGE")


if __name__ == "__main__":
    main()

"""Beyond-100k member scale demonstration for the partial-view kernel.

VERDICT r2 missing #5 / next-round #3: the dense [N, N] view caps the
simulation at ~100k members on a v5e-8; `ops/swim_pview.py` replaces it
with an O(N·K) bounded hash-slot table. This script demonstrates:

  rung A — convergence: n=8192, K=512 partial view runs to stable
           in-degree coverage (pv_coverage >= 0.999, FP = 0)
  rung B — scale: n=262144, K=1024 sharded over the 8-device virtual
           CPU mesh executes real ticks (the identical program a v5e-8
           would run), with measured s/tick
  rung C — memory math for 262k and 1M printed against chip HBM

Usage: python scripts/pview_scale.py [rungA_n] [rungB_n]
Appends one JSON line per rung to stdout and PVIEW_SCALE.json at repo
root. Runs under the known-good CPU env (re-exec like bench.py) so a
wedged TPU tunnel cannot hang it.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.reexec_under_cpu(
    "PVIEW_SCALE_CHILD",
    n_devices=8,
    timeout=float(os.environ.get("PVIEW_SCALE_BUDGET_S", "3000")),
)

jaxenv.enable_compilation_cache()

import jax  # noqa: E402

from corrosion_tpu.ops import swim_pview  # noqa: E402
from corrosion_tpu.parallel import (  # noqa: E402
    member_mesh,
    shard_member_state,
    sharded_pview_tick,
)

results = []


def emit(rec):
    results.append(rec)
    print(json.dumps(rec), flush=True)


def rung_a(n: int):
    """Stability for a bounded-view membership service is an ABSOLUTE
    in-degree quorum — every live member known-alive by >= q live
    observers (SWIM detection latency scales with 1/in-degree; q = 8
    gives robust probing) — plus zero false positives. A mean-relative
    coverage threshold is reported but not gated: bounded-offer gossip
    has an inherently wide stationary in-degree spread."""
    k = max(64, n // 16)
    q = 8
    params = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=4, feed_entries=max(16, k // 16)
    )
    # fingers bootstrap: the same topology the TPU hunter's pview run
    # uses, so CPU and TPU convergence records stay like-for-like
    state = swim_pview.init_state(
        params, jax.random.PRNGKey(0), seed_mode="fingers"
    )
    rng = jax.random.PRNGKey(1)
    t0 = time.monotonic()
    stats = {}
    ticks = 0
    converged = False
    while ticks < 600:
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n_donated(state, key, params, 25)
        ticks += 25
        stats = swim_pview.membership_stats(state, params)
        converged = (
            stats["min_in_degree"] >= q
            and stats["false_positive"] == 0.0
            and stats["pv_coverage"] >= 0.95
        )
        if converged:
            break
    emit(
        {
            "rung": "A-convergence",
            "n": n,
            "slots": k,
            "quorum_floor": q,
            "ticks": ticks,
            "wall_s": round(time.monotonic() - t0, 2),
            "stats": {m: round(v, 6) for m, v in stats.items()},
            "converged": converged,
        }
    )


def rung_b(n: int):
    k = 1024
    ndev = 8
    devices = jax.devices()[:ndev]
    assert len(devices) == ndev, f"need {ndev} devices, have {len(jax.devices())}"
    mesh = member_mesh(devices)
    params = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=4, feed_entries=64
    )
    t0 = time.monotonic()
    state = swim_pview.init_state(params, jax.random.PRNGKey(0))
    state = shard_member_state(state, mesh)
    jax.block_until_ready(state.slot_packed)
    init_s = time.monotonic() - t0
    tick5 = sharded_pview_tick(params, mesh, k=5)
    rng = jax.random.PRNGKey(1)
    # compile + first dispatch
    t0 = time.monotonic()
    state = tick5(state, rng)
    jax.block_until_ready(state.slot_packed)
    compile_s = time.monotonic() - t0
    # measured dispatches
    t0 = time.monotonic()
    ticks = 0
    for i in range(3):
        rng, key = jax.random.split(rng)
        state = tick5(state, key)
        ticks += 5
    jax.block_until_ready(state.slot_packed)
    per_tick = (time.monotonic() - t0) / ticks
    stats = swim_pview.membership_stats(state, params)
    emit(
        {
            "rung": "B-scale-sharded",
            "n": n,
            "slots": k,
            "devices": ndev,
            "init_s": round(init_s, 2),
            "compile_s": round(compile_s, 2),
            "s_per_tick_cpu_1core": round(per_tick, 3),
            "ticks_run": ticks + 5,
            "stats": {m: round(v, 6) for m, v in stats.items()},
            "note": (
                "virtual 8-device CPU mesh on one core; identical sharded "
                "program a v5e-8 runs with ~100x the arithmetic throughput"
            ),
        }
    )


def rung_c():
    import jax.numpy as jnp

    from corrosion_tpu.ops import swim

    dense_item = jnp.dtype(swim.VIEW_DTYPE).itemsize

    def math_for(n, k):
        rec = {"n": n, "slots": k}
        rec.update(swim_pview.memory_gb(n, k))
        rec["dense_view_gb_for_comparison"] = round(
            n * n * dense_item / 2**30, 1
        )
        return rec

    emit(
        {
            "rung": "C-memory-math",
            "v5e_hbm_gb_per_chip": 16,
            "configs": [
                math_for(262_144, 1024),
                math_for(1_048_576, 1024),
                math_for(1_048_576, 4096),
            ],
        }
    )


def main():
    rung_a(int(sys.argv[1]) if len(sys.argv) > 1 else 8192)
    rung_b(int(sys.argv[2]) if len(sys.argv) > 2 else 262_144)
    rung_c()
    # merge-write: other scripts (pview_1m.py) record their own rungs in
    # the same file — replace only the rungs this run re-measured
    from corrosion_tpu.runtime.records import merge_records

    merge_records(os.path.join(REPO, "PVIEW_SCALE.json"), results)


if __name__ == "__main__":
    main()

"""A/B the CRDT batch-merge engines at sync-flood batch sizes.

VERDICT r4 #5 / SURVEY §7 step 1: the on-TPU merge placement was argued,
never measured.  This harness measures it: identical synthetic batches
(change mix shaped like a sync flood: mostly equal-cl column updates
over a hot row population, some transitions/deletes) through the three
engines via the SAME store path (CORRO_CRDT_ENGINE), end to end —
including phase A snapshot reads and phase C SQLite flushes — plus the
isolated phase-B decision time per engine.  Output: CRDT_MERGE_AB.json.

Run on CPU by default (forced in-process — the axon plugin can hang);
pass --tpu to let jax pick up the chip for the array engine's kernel
(host marshaling then crosses the tunnel and is timed honestly).

Usage: python scripts/bench_crdt_merge.py [--tpu] [--sizes 512,4096,...]
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402


def synth_batch(rng: random.Random, n: int, hot_rows: int) -> list:
    """Sync-flood-shaped batch: column updates dominate, occasional
    delete/re-create chains, several sites racing."""
    from corrosion_tpu.types.actor import ActorId
    from corrosion_tpu.types.base import Timestamp
    from corrosion_tpu.types.change import SENTINEL, Change
    from corrosion_tpu.types.pack import pack_columns

    sites = [ActorId(bytes([i]) * 16) for i in (1, 2, 3, 4, 5)]
    out = []
    dbv = {s.bytes16: 0 for s in sites}
    for _ in range(n):
        site = rng.choice(sites).bytes16
        row = rng.randint(1, hot_rows)
        pk = pack_columns([row])
        r = rng.random()
        if r < 0.05:
            cl, cid, val, cv = rng.choice([2, 4]), SENTINEL, None, 1
        elif r < 0.10:
            cl, cid, val, cv = rng.choice([1, 3, 5]), SENTINEL, None, 1
        else:
            cl = rng.choice([1, 1, 1, 1, 3])
            cid = rng.choice(["a", "b"])
            cv = rng.randint(1, 6)
            val = (
                rng.randint(0, 10**6)
                if cid == "b"
                else rng.choice(["x", "yy", "zzz", "abcdef", ""])
            )
        dbv[site] += rng.choice([0, 1])
        out.append(
            Change(
                table="kv", pk=pk, cid=cid, val=val, col_version=cv,
                db_version=max(1, dbv[site]), seq=rng.randint(0, 3),
                site_id=site, cl=cl,
                ts=Timestamp.from_unix(rng.randint(1, 100)),
            )
        )
    return out


def mk_store():
    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.actor import ActorId

    st = CrdtStore(":memory:", site_id=ActorId(bytes([9]) * 16))
    st.apply_schema_sql(
        "CREATE TABLE kv (id INTEGER NOT NULL PRIMARY KEY,"
        " a TEXT NOT NULL DEFAULT '', b INTEGER NOT NULL DEFAULT 0);"
    )
    return st


def bench_engine(engine: str, batches, warm_batch) -> dict:
    os.environ["CORRO_CRDT_ENGINE"] = engine
    st = mk_store()
    # warm: jit compile (array), lib load (native), code paths hot
    st.apply_changes(copy.deepcopy(warm_batch))
    t0 = time.monotonic()
    total = 0
    for batch in batches:
        st.apply_changes(copy.deepcopy(batch))
        total += len(batch)
    wall = time.monotonic() - t0
    st.close()
    return {
        "engine": engine,
        "changes": total,
        "wall_s": round(wall, 4),
        "changes_per_s": round(total / wall) if wall > 0 else None,
    }


def bench_decision_only(engine: str, batch) -> dict:
    """Phase B in isolation on a fresh-store snapshot (empty locals)."""
    st = mk_store()
    os.environ["CORRO_CRDT_ENGINE"] = engine
    pks = {c.pk for c in batch}
    base = {
        pk: {"cl": 0, "clock": {}, "vals": {}, "disk": {}} for pk in pks
    }

    def run_once():
        stx = copy.deepcopy(base)
        plans = ({}, set(), {}, {}, set(), set())
        if engine == "array":
            from corrosion_tpu.ops.crdt_merge import merge_table_array

            return merge_table_array(st, "kv", batch, stx, *plans)
        if engine == "native":
            lib = st._merge_lib
            return st._merge_table_native(lib, "kv", batch, stx, *plans)
        return st._merge_table_python("kv", batch, stx, *plans)

    run_once()  # warm
    reps = 5
    t0 = time.monotonic()
    for _ in range(reps):
        out = run_once()
    wall = (time.monotonic() - t0) / reps
    st.close()
    return {
        "engine": engine,
        "declined": out is None,
        "decision_wall_s": round(wall, 5),
        "decisions_per_s": round(len(batch) / wall) if wall > 0 else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu", action="store_true")
    ap.add_argument("--sizes", default="512,4096,16384,65536")
    ap.add_argument("--hot-rows", type=int, default=2048)
    # a chip run must not clobber the banked CPU record (both are
    # decision evidence — COMPONENTS.md "CRDT engine placement")
    ap.add_argument("--out", default="CRDT_MERGE_AB.json")
    args = ap.parse_args()

    if not args.tpu:
        jaxenv.force_cpu_inprocess()
    import jax

    platform = jax.devices()[0].platform
    sizes = [int(s) for s in args.sizes.split(",")]
    rng = random.Random(1234)

    results = {"platform": platform, "hot_rows": args.hot_rows, "rungs": []}
    for size in sizes:
        warm = synth_batch(rng, min(size, 2048), args.hot_rows)
        batches = [synth_batch(rng, size, args.hot_rows) for _ in range(3)]
        rung = {"batch_size": size, "end_to_end": [], "decision_only": []}
        for engine in ("python", "native", "array"):
            rung["end_to_end"].append(
                bench_engine(engine, batches, warm)
            )
            rung["decision_only"].append(
                bench_decision_only(engine, batches[0])
            )
            print(
                f"[{size}] {engine}: e2e {rung['end_to_end'][-1]}"
                f" decision {rung['decision_only'][-1]}",
                flush=True,
            )
        results["rungs"].append(rung)

    results["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({"metric": "crdt_merge_ab", "platform": platform,
                      "rungs": len(results["rungs"])}))


if __name__ == "__main__":
    main()

"""Execute the partial-view SWIM kernel at ONE MILLION members.

SURVEY §2.6 targets 10^4–10^6 simulated members; `PVIEW_SCALE.json`
records the 262k sharded run and the 1M memory math. This script closes
the last octave by EXECUTING n=2^20 × K=1024 sharded over the 8-device
virtual CPU mesh — the identical program a v5e-8 runs (0.53 GB/chip) —
and recording init/compile/s-per-tick plus membership stats.

On one CPU core this is slow (~3 min/tick); the point is an executed
proof, not a converged run: real ticks, real collectives, stats sane.

Usage: python scripts/pview_1m.py [n] [ticks_per_dispatch] [dispatches]
Merges the record into PVIEW_SCALE.json as rung "D-{n}-executed"
(e.g. "D-1048576-executed" for the default 1M run).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.reexec_under_cpu(
    "PVIEW_1M_CHILD",
    n_devices=8,
    timeout=float(os.environ.get("PVIEW_1M_BUDGET_S", "5400")),
)

jaxenv.enable_compilation_cache()

import jax  # noqa: E402

from corrosion_tpu.ops import swim_pview  # noqa: E402
from corrosion_tpu.runtime.records import merge_records  # noqa: E402
from corrosion_tpu.parallel import (  # noqa: E402
    member_mesh,
    shard_member_state,
    sharded_pview_tick,
)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_048_576
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    dispatches = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    k = 1024
    ndev = 8
    devices = jax.devices()[:ndev]
    assert len(devices) == ndev, f"need {ndev} devices, have {len(jax.devices())}"
    mesh = member_mesh(devices)
    params = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=2, feed_entries=64
    )
    t0 = time.monotonic()
    state = swim_pview.init_state(params, jax.random.PRNGKey(0))
    state = shard_member_state(state, mesh)
    jax.block_until_ready(state.slot_packed)
    init_s = time.monotonic() - t0
    print(f"init {init_s:.1f}s", flush=True)

    tick_k = sharded_pview_tick(params, mesh, k=chunk)
    rng = jax.random.PRNGKey(1)
    t0 = time.monotonic()
    state = tick_k(state, rng)
    jax.block_until_ready(state.slot_packed)
    compile_s = time.monotonic() - t0
    print(f"compile+first {compile_s:.1f}s", flush=True)

    t0 = time.monotonic()
    ticks = 0
    for _ in range(dispatches):
        rng, key = jax.random.split(rng)
        state = tick_k(state, key)
        ticks += chunk
    jax.block_until_ready(state.slot_packed)
    per_tick = (time.monotonic() - t0) / max(1, ticks)
    stats = swim_pview.membership_stats(state, params)
    # label + per-chip math derived from the actual n/k (the script takes
    # n as an argument; the record must describe the run that happened)
    mem = swim_pview.memory_gb(n, k)
    rec = {
        "rung": f"D-{n}-executed",
        "n": n,
        "slots": k,
        "devices": ndev,
        "init_s": round(init_s, 1),
        "compile_s": round(compile_s, 1),
        "s_per_tick_cpu_1core": round(per_tick, 2),
        "ticks_run": ticks + chunk,
        "stats": {m: round(float(v), 6) for m, v in stats.items()},
        "note": (
            "executed on the 8-device virtual CPU mesh backed by one core; "
            "identical sharded program at "
            f"{mem['per_chip_gb_v5e8']} GB/chip on a v5e-8"
        ),
    }
    print(json.dumps(rec), flush=True)
    merge_records(os.path.join(REPO, "PVIEW_SCALE.json"), [rec])


if __name__ == "__main__":
    main()

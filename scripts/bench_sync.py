"""Cold-node catch-up bench: SYNC_SCALE.json (r17).

The sync plane's scale story, measured: a cold node joins a cluster
whose origin holds a 100k- or 1M-row table, under {quiet,
concurrent-write-fire}, with the snapshot bootstrap ON vs OFF (pure
delta — the A/B axis `[sync] snapshot=false` provides), plus the chaos
loop: partition → heal → catch-up → converge with the cluster
observatory's divergence detector as the convergence oracle.

Convergence bar per rung: the cold node's row count equals the
origin's, its bookie reports no needed gaps, and the CRDT clock-row
count matches (nothing lost, nothing left buffered).  Under fire the
writer stops first, then the bar must close.

Margin discipline (r15 memory): this 1-core host drifts ±30% between
runs — the banked record carries wall-clock numbers as EVIDENCE, but
the tier-1 guard (tests/test_sync_bank.py) pins deterministic facts
(full convergence, snapshot-vs-delta speedup > 1 on the large rung,
zero divergence after heal), never wall-clock absolutes.

Usage: python scripts/bench_sync.py [--quick]   (--quick: 100k only)
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.agent.run import (  # noqa: E402
    make_broadcastable_changes,
    setup,
    shutdown,
    run as run_agent,
)
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.metrics import METRICS  # noqa: E402
from corrosion_tpu.sync import held_total  # noqa: E402

from tests.test_agent import (  # noqa: E402
    FAST_SWIM,
    TEST_SCHEMA,
    fast_config,
    wait_until,
)

ROWS_PER_TX = 2000  # one version per tx: 1M rows = 500 versions
FIRE_ROWS = 10  # concurrent writer: rows per tx
FIRE_PERIOD = 0.05  # seconds between fire txs

_MEASURED_FILES = (
    "corrosion_tpu/store/snapshot.py",
    "corrosion_tpu/agent/catchup.py",
    "corrosion_tpu/agent/syncer.py",
    "corrosion_tpu/sync.py",
    "corrosion_tpu/store/restore.py",
)


def _code_fingerprint() -> dict:
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


def peek(name: str, **labels) -> float:
    for _kind, sname, slabels, value in METRICS.snapshot():
        if sname == name and slabels == labels:
            return value
    return 0.0


def count_rows(agent) -> int:
    conn = agent.store.read_conn()
    try:
        return conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
    finally:
        conn.close()


def clock_count(agent) -> int:
    conn = agent.store.read_conn()
    try:
        return conn.execute(
            "SELECT COUNT(*) FROM tests__crdt_clock"
        ).fetchone()[0]
    finally:
        conn.close()


async def boot(net, name, bootstrap=(), tune=None, swim=None):
    cfg = fast_config(name, bootstrap)
    cfg.perf.sync_interval_min_secs = 0.2
    cfg.perf.sync_interval_max_secs = 1.0
    cfg.cluster.digest_interval_secs = 0.5
    if tune:
        tune(cfg)
    agent = await setup(cfg, network=net)
    agent.membership.config = swim or FAST_SWIM
    agent.store.apply_schema_sql(TEST_SCHEMA)
    await run_agent(agent)
    return agent


async def load_rows(agent, n_rows: int, base: int = 0) -> int:
    """`n_rows` rows in ROWS_PER_TX-row transactions (one version
    each); returns versions written."""
    versions = 0
    for start in range(base, base + n_rows, ROWS_PER_TX):
        count = min(ROWS_PER_TX, base + n_rows - start)
        await make_broadcastable_changes(
            agent,
            lambda tx, s=start, c=count: [
                tx.execute(
                    "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                    (s + k, f"row-{s + k}"),
                )
                for k in range(c)
            ],
        )
        versions += 1
    return versions


async def run_rung(n_rows: int, fire: bool, mode: str, seed: int) -> dict:
    """One cold-join measurement; mode is "snapshot" or "delta"."""
    assert mode in ("snapshot", "delta")
    net = MemNetwork(seed=seed)
    a = await boot(net, "origin")
    t_load = time.monotonic()
    await load_rows(a, n_rows)
    load_s = time.monotonic() - t_load
    await asyncio.sleep(1.0)  # retire the broadcast backlog

    installs0 = peek("corro.snapshot.install.total")
    delta0 = peek("corro.sync.client.changes.received")
    waves0 = peek("corro.sync.resume.waves.total")

    def tune(cfg):
        cfg.sync.snapshot = mode == "snapshot"
        # the load writes ROWS_PER_TX-row versions: 100k rows = 50
        # versions, so the heuristic threshold sits below that
        cfg.sync.snapshot_min_gap_versions = 20

    fire_task = None
    fire_state = {"rows": 0, "stop": False}

    async def fire_writer():
        base = 10_000_000
        while not fire_state["stop"]:
            await make_broadcastable_changes(
                a,
                lambda tx: [
                    tx.execute(
                        "INSERT OR REPLACE INTO tests (id, text)"
                        " VALUES (?, ?)",
                        (base + fire_state["rows"] + k, "fire"),
                    )
                    for k in range(FIRE_ROWS)
                ],
            )
            fire_state["rows"] += FIRE_ROWS
            await asyncio.sleep(FIRE_PERIOD)

    t0 = time.monotonic()
    c = await boot(net, "cold", bootstrap=("origin",), tune=tune)
    if fire:
        fire_task = asyncio.ensure_future(fire_writer())
    try:
        def caught_up() -> bool:
            if not fire:
                return count_rows(c) >= n_rows
            # under fire the target MOVES: a caught-up node rides the
            # live stream a few in-flight transactions behind, and
            # instantaneous row equality may never be sampled while the
            # writer runs — "caught" = within a handful of fire txs;
            # the writer then stops and the EXACT bar below must close
            return count_rows(a) - count_rows(c) <= 5 * FIRE_ROWS

        # generous cap: the 1M delta rung streams every change
        assert await wait_until(caught_up, timeout=3600, step=0.25), (
            f"cold node stalled at {count_rows(c)}"
        )
        if fire:
            fire_state["stop"] = True
            await fire_task
            fire_task = None
        # final bar: rows equal, no gaps, clock rows equal
        def fully_converged() -> bool:
            if count_rows(c) != count_rows(a):
                return False
            if held_total(c.bookie) != held_total(a.bookie):
                return False
            return clock_count(c) == clock_count(a)

        assert await wait_until(fully_converged, timeout=600, step=0.25), (
            f"final convergence stalled: rows {count_rows(c)}/"
            f"{count_rows(a)} held {held_total(c.bookie)}/"
            f"{held_total(a.bookie)}"
        )
        wall = time.monotonic() - t0
        rec = {
            "rung": f"sync-{n_rows // 1000}k-"
            f"{'fire' if fire else 'quiet'}-{mode}",
            "rows": n_rows,
            "fire": fire,
            "fire_rows_written": fire_state["rows"],
            "mode": mode,
            "versions": (n_rows + ROWS_PER_TX - 1) // ROWS_PER_TX,
            "load_wall_s": round(load_s, 2),
            "wall_to_converged_s": round(wall, 2),
            "converged": True,
            "rows_final": count_rows(c),
            "clock_rows_final": clock_count(c),
            "snapshot_installed": int(
                peek("corro.snapshot.install.total") - installs0
            ),
            "delta_changes_received": int(
                peek("corro.sync.client.changes.received") - delta0
            ),
            "resume_waves": int(
                peek("corro.sync.resume.waves.total") - waves0
            ),
        }
        if mode == "snapshot":
            rec["snapshot_raw_bytes"] = c.catchup_census.get("raw_bytes", 0)
            rec["snapshot_install_s"] = c.catchup_census.get("seconds")
        return rec
    finally:
        if fire_task is not None:
            fire_state["stop"] = True
            fire_task.cancel()
        await shutdown(c)
        await shutdown(a)


async def chaos_phase(seed: int = 29) -> dict:
    """partition → heal → catch-up → converge, with the observatory's
    divergence detector as the oracle: the partition must OPEN a
    divergence episode, and after heal + catch-up the detector must
    report one view group, no silent nodes, episode closed — zero
    divergence — while every replica's tables match the origin's."""
    from corrosion_tpu.agent.membership import SwimConfig

    net = MemNetwork(seed=seed)

    def tune(cfg):
        # circuits open DURING the partition (the breaker working); a
        # short reset keeps the post-heal catch-up prompt — the knob an
        # operator running frequent-partition topologies would set
        cfg.sync.circuit_reset_secs = 3.0

    # suspicion window longer than the partition: members stay (at
    # worst SUSPECT, refuted on heal) so the measured catch-up is the
    # SYNC plane's, not a full SWIM eviction/rejoin cycle; divergence
    # detection rides the digest-silence signal
    gentle = SwimConfig(probe_period=0.25, probe_rtt=0.1, suspicion_mult=4)
    a = await boot(net, "chaos-a", tune=tune, swim=gentle)
    b = await boot(net, "chaos-b", bootstrap=("chaos-a",), tune=tune,
                   swim=gentle)
    c = await boot(net, "chaos-c", bootstrap=("chaos-a",), tune=tune,
                   swim=gentle)
    try:
        await load_rows(a, 10_000)
        assert await wait_until(
            lambda: count_rows(b) == 10_000 and count_rows(c) == 10_000,
            timeout=300, step=0.25,
        ), "pre-chaos convergence stalled"

        # partition C away and keep writing on the majority side
        t0 = time.monotonic()
        for name in ("chaos-a", "chaos-b"):
            net.partition(name, "chaos-c")
        await load_rows(a, 4_000, base=10_000)

        def detected() -> bool:
            return a.observatory.check_divergence()["episode_open"]

        assert await wait_until(detected, timeout=60, step=0.25), (
            "divergence never detected during partition"
        )
        detect_s = time.monotonic() - t0

        for name in ("chaos-a", "chaos-b"):
            net.heal(name, "chaos-c")
        t_heal = time.monotonic()

        def converged() -> bool:
            return (
                count_rows(c) == count_rows(a) == 14_000
                and count_rows(b) == 14_000
                and held_total(c.bookie) == held_total(a.bookie)
            )

        assert await wait_until(converged, timeout=600, step=0.25), (
            f"post-heal convergence stalled: {count_rows(c)}"
        )
        catchup_s = time.monotonic() - t_heal

        def divergence_zero() -> bool:
            v = a.observatory.check_divergence()
            return (
                not v["divergent"]
                and not v["episode_open"]
                and v["groups"] == 1
                and not v["silent"]
            )

        assert await wait_until(divergence_zero, timeout=120, step=0.5), (
            f"divergence never closed: {a.observatory.check_divergence()}"
        )
        verdict = a.observatory.check_divergence()
        return {
            "rows": 14_000,
            "partition_writes": 4_000,
            "detect_s": round(detect_s, 2),
            "catchup_s": round(catchup_s, 2),
            "divergence_zero": True,
            "episodes": verdict["episodes"],
            "final_groups": verdict["groups"],
        }
    finally:
        await shutdown(c)
        await shutdown(b)
        await shutdown(a)


def main() -> None:
    quick = "--quick" in sys.argv
    sizes = [100_000] if quick else [100_000, 1_000_000]
    rungs = []
    for n_rows in sizes:
        plan = [
            (n_rows, False, "delta"),
            (n_rows, False, "snapshot"),
            (n_rows, True, "snapshot"),
        ]
        if n_rows == 100_000:
            plan.insert(2, (n_rows, True, "delta"))
        for i, (rows, fire, mode) in enumerate(plan):
            t0 = time.monotonic()
            rec = asyncio.new_event_loop().run_until_complete(
                run_rung(rows, fire, mode, seed=17 + i)
            )
            rec["rung_wall_s"] = round(time.monotonic() - t0, 1)
            rungs.append(rec)
            print(json.dumps(rec), flush=True)
    # in-band speedup on the largest rung measured (quiet A/B)
    big = max(sizes)
    d = next(r for r in rungs if r["rung"] == f"sync-{big // 1000}k-quiet-delta")
    s = next(
        r for r in rungs if r["rung"] == f"sync-{big // 1000}k-quiet-snapshot"
    )
    speedup = d["wall_to_converged_s"] / max(1e-9, s["wall_to_converged_s"])
    chaos = asyncio.new_event_loop().run_until_complete(chaos_phase())
    print(json.dumps({"chaos": chaos}), flush=True)
    record = {
        "rungs": rungs,
        "chaos": chaos,
        "large_rung_rows": big,
        "snapshot_vs_delta_speedup": round(speedup, 2),
        "code_sha": _code_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    path = os.path.join(REPO, "SYNC_SCALE.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {path}: speedup {record['snapshot_vs_delta_speedup']}x")


if __name__ == "__main__":
    main()

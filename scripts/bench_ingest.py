"""Measure CRDT ingestion throughput (changes/s): batched vs per-row.

The reference logs changes/s per sync round (`agent/handlers.rs:884-895`);
this bench produces the comparable number for our store's remote-apply
path, before/after the round-2 batching of `apply_changes`.

Usage: python scripts/bench_ingest.py [n_changes] [batch_size]
"""

from __future__ import annotations

import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.store.crdt import CrdtStore  # noqa: E402
from corrosion_tpu.types.actor import ActorId  # noqa: E402
from corrosion_tpu.types.base import Timestamp  # noqa: E402
from corrosion_tpu.types.change import Change  # noqa: E402
from corrosion_tpu.types.pack import pack_columns  # noqa: E402

SCHEMA = (
    "CREATE TABLE kv (id INTEGER NOT NULL PRIMARY KEY,"
    " a TEXT NOT NULL DEFAULT '', b INTEGER NOT NULL DEFAULT 0,"
    " c TEXT NOT NULL DEFAULT '');"
)


def gen(n: int, n_pks: int, seed=0) -> list:
    rng = random.Random(seed)
    site = ActorId(bytes([1]) * 16).bytes16
    ts = Timestamp.from_unix(1)
    out = []
    for i in range(n):
        pk = pack_columns([rng.randint(1, n_pks)])
        cid = rng.choice(["a", "b", "c"])
        val = rng.randint(0, 10**6) if cid == "b" else f"v{i}"
        out.append(
            Change(
                table="kv", pk=pk, cid=cid, val=val,
                col_version=i // n_pks + 1, db_version=i + 1, seq=0,
                site_id=site, cl=1, ts=ts,
            )
        )
    return out


def run(mode: str, changes, batch: int, tmp: str) -> float:
    path = os.path.join(tmp, f"bench-{mode}.db")
    if os.path.exists(path):
        os.unlink(path)
    st = CrdtStore(path)
    st.apply_schema_sql(SCHEMA)
    t0 = time.monotonic()
    if mode in ("batched", "native"):
        for i in range(0, len(changes), batch):
            st.apply_changes(changes[i : i + batch])
    else:
        from tests.test_crdt_batch import apply_reference

        for i in range(0, len(changes), batch):
            apply_reference(st, changes[i : i + batch])
    dt = time.monotonic() - t0
    st.close()
    return len(changes) / dt


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 500
    sys.path.insert(0, REPO)
    import tempfile

    changes = gen(n, n_pks=max(100, n // 50))
    with tempfile.TemporaryDirectory() as tmp:
        per_row = run("per_row", changes, batch, tmp)
        os.environ["CORRO_NATIVE_BATCH"] = "0"
        batched = run("batched", changes, batch, tmp)
        os.environ["CORRO_NATIVE_BATCH"] = "1"
        from corrosion_tpu import native as native_mod

        native = (
            run("native", changes, batch, tmp)
            if native_mod.merge_batch_lib() is not None
            else 0.0
        )
    print(
        f"ingest throughput n={n} batch={batch}: "
        f"per_row={per_row:,.0f} changes/s  "
        f"batched={batched:,.0f} changes/s  "
        f"native={native:,.0f} changes/s  "
        f"speedup={(native or batched) / per_row:.2f}x"
    )


if __name__ == "__main__":
    main()

"""Ingest/write-path throughput trajectory → INGEST_BENCH.json (r14).

The reference logs changes/s per sync round (`agent/handlers.rs:884-895`);
this bench banks the comparable numbers for BOTH sides of our write
plane, before/after the r14 write-path round (group-commit local
transactions + vectorized `_finalize_pending` + encode-once broadcast):

  ingest-local-wN   rows/s through the REAL public write path
                    (`make_broadcastable_changes` on a booted agent) at
                    N ∈ {1, 4, 16} concurrent writers, plus per-commit
                    p50/p99 latency (the solo-p50-unchanged guard).
  ingest-remote     remote-apply rows/s (`CrdtStore.apply_changes`,
                    uniform low-conflict stream).
  ingest-conflict   merge-heavy remote apply: 3 sites racing
                    overlapping pks through delete/re-create/value-tie
                    transitions.
  ingest-e2e        write→event latency through a live HTTP
                    subscription, snapshot-diffed from the r11
                    `corro.e2e.total` histograms and cross-checked
                    against `GET /v1/slo`.

`--ab` measures pre AND post in one run; nothing leaks into
`os.environ` afterwards (scoped_env).  The A/B axis is TAG-AWARE:
- `--tag r15` (and the untagged r14 rungs): the CHANGE-CAPTURE engine —
  pre = `CORRO_CAPTURE=trigger` (the AFTER-trigger → `__crdt_pending`
  round-trip) vs post = direct in-memory capture (store/capture.py),
  with group commit / vectorized finalize / encode-once identical.
- `--tag r21*`: the write-path round-3 pair — pre =
  `CORRO_FINALIZE=vector` (the r14/r15 per-cell emit loop, kept
  bit-for-bit) + `CORRO_GROUP_FANOUT=0` (per-tx post-commit
  hooks/chunk/send) vs post = columnar finalize phase B + per-group
  fanout, with capture / group commit / encode-once identical.
Tagged rungs land NEXT TO the banked earlier records
(`ingest-local-*-{pre,post}[-tag]`) instead of overwriting them —
tests/test_ingest_bench.py compares each round's post both against its
own pre and against the banked prior-round post.  Records merge by
rung into INGEST_BENCH.json, `code_sha`-stamped over the measured
write-path files (bench.py replay-gate discipline).

`--profile` (r23) banks WRITE_PROFILE.json beside INGEST_BENCH.json
instead: the solo-writer rung runs with the continuous profiler ON and
the banked record attributes submit→resolve commit wall across the five
`corro.write.profile.seconds` buckets (asyncio dispatch / write gate /
to_thread hop / finalize / sqlite flush — the write-path round-4 work
list), plus the sqlite COMMIT-flush wall and the top statement shapes;
the w16 rung then measures what always-on sampling costs: the
sampler's duty cycle read live under load (primary — it resolves
fractions of a percent), corroborated by a position-balanced
steady-state throughput A/B banked with its noise floor — the ≤2%
acceptance bar `tests/test_write_profile.py` guards.

Usage:
  python scripts/bench_ingest.py [--mode pre|post|ab] [--tag T]
  python scripts/bench_ingest.py --profile
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.records import (  # noqa: E402
    cleanup_record_locks,
    merge_records,
)
from corrosion_tpu.store.crdt import CrdtStore  # noqa: E402
from corrosion_tpu.types.actor import ActorId  # noqa: E402
from corrosion_tpu.types.base import Timestamp  # noqa: E402
from corrosion_tpu.types.change import SENTINEL, Change  # noqa: E402
from corrosion_tpu.types.pack import pack_columns  # noqa: E402

_MEASURED_FILES = (
    "corrosion_tpu/store/crdt.py",
    "corrosion_tpu/store/capture.py",
    "corrosion_tpu/agent/run.py",
    "corrosion_tpu/agent/handle.py",
    "corrosion_tpu/agent/broadcast.py",
    "corrosion_tpu/runtime/channels.py",
    "corrosion_tpu/types/codec.py",
    "corrosion_tpu/runtime/profiler.py",
    "corrosion_tpu/native.py",
    "native/crdt_batch.cpp",
    "scripts/bench_ingest.py",
)

# local-write workload: every writer commits TXS_TOTAL/N transactions of
# ROWS_PER_TX rows each — the per-commit overhead (BEGIN/COMMIT, lock,
# bookkeeping, fsync batching) is exactly what group commit amortizes.
# r15 tripled the run length: the 192-tx rungs finished in ~0.15 s and
# the banked rows/s swung ±20% with host noise on the 1-core bench box
TXS_TOTAL = 576
ROWS_PER_TX = 10


def _code_fingerprint() -> dict:
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


@contextlib.contextmanager
def scoped_env(**kv):
    """Set env vars for the block and RESTORE them after — the r13 bench
    leaked CORRO_NATIVE_BATCH into os.environ permanently; nothing in
    this bench may outlive its rung."""
    old = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _pre_env(mode: str, tag: str = "") -> dict:
    if tag.startswith("r24"):
        # r24 A/B: pre restores the r15–r23 per-batch to_thread hop and
        # the (default) columnar Python finalize; post runs the
        # dedicated committer thread AND the native C++ phase B, so the
        # delta isolates exactly this round's two changes.  Both sides
        # share capture, group commit, columnar flush and fanout.
        if mode == "pre":
            return {"CORRO_COMMITTER": "to_thread",
                    "CORRO_FINALIZE": "columnar"}
        return {"CORRO_FINALIZE": "native"}
    if mode != "pre":
        return {}
    if tag.startswith("r21"):
        # r21 A/B: pre restores the per-cell emit-loop finalize (the
        # r14/r15 "vector" engine, kept bit-for-bit) AND the per-tx
        # post-commit hooks/chunk/send path, so the delta isolates
        # columnar phase B + per-group fanout; capture, group commit
        # and encode-once are identical on both sides
        return {"CORRO_FINALIZE": "vector", "CORRO_GROUP_FANOUT": "0"}
    # r15 A/B: pre restores the trigger/__crdt_pending capture path
    # (everything else — group commit, vectorized finalize, encode-once
    # — identical), so the delta isolates direct capture itself
    return {"CORRO_CAPTURE": "trigger"}


def _record(rung: str, mode: str, tag: str, **fields) -> dict:
    rec = {
        "rung": f"{rung}-{mode}" + (f"-{tag}" if tag else ""),
        "mode": mode,
        **fields,
        "code_sha": _code_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    return rec


# -- local write path (the tentpole rung) ----------------------------------


async def _local_write(
    n_writers: int, mode: str, tag: str, durable: bool = False,
    profile: bool = None,
) -> dict:
    from tests.test_agent import boot, fast_config

    from corrosion_tpu.agent.run import make_broadcastable_changes, shutdown

    name = f"bench-ingest-w{n_writers}{'d' if durable else ''}"
    net = MemNetwork(seed=11)
    cfg = fast_config(name)
    if profile is not None:
        # the --profile overhead A/B drives this explicitly; the normal
        # rungs keep the config default (the sampler IS production load)
        cfg.profile.enabled = profile
    agent = await boot(net, name, cfg=cfg)
    if durable:
        # the fsync-per-commit regime (PRAGMA synchronous=FULL on the
        # write conn): every COMMIT syncs the WAL — the regime where
        # group commit's one-fsync-per-batch amortization is the story.
        # The default rungs keep the store's shipped NORMAL setting
        # (WAL syncs at checkpoint, commits are cheap).
        agent.store._conn.execute("PRAGMA synchronous = FULL")
    txs_per_writer = TXS_TOTAL // n_writers
    lat_ms: list = []

    sql = "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)"

    def mk_fn(base: int):
        # both modes drive the r14 bulk API — the r15 A/B isolates the
        # capture engine, not the statement style
        rows = [(base + j, f"v{base + j}") for j in range(ROWS_PER_TX)]

        def fn(tx):
            return [tx.executemany(sql, rows)]

        return fn

    async def writer(w: int) -> None:
        for t in range(txs_per_writer):
            base = (w * txs_per_writer + t) * ROWS_PER_TX
            t0 = time.monotonic()
            await make_broadcastable_changes(agent, mk_fn(base))
            lat_ms.append((time.monotonic() - t0) * 1e3)

    try:
        # warm the path (jit-free, but first commit pays schema caches)
        await make_broadcastable_changes(agent, mk_fn(10_000_000))
        t0 = time.monotonic()
        await asyncio.gather(*(writer(w) for w in range(n_writers)))
        wall = time.monotonic() - t0
    finally:
        await shutdown(agent)
    rows = txs_per_writer * n_writers * ROWS_PER_TX
    lat_ms.sort()
    return _record(
        f"ingest-local-w{n_writers}{'-durable' if durable else ''}",
        mode, tag,
        writers=n_writers,
        durable=durable,
        txs=txs_per_writer * n_writers,
        rows_per_tx=ROWS_PER_TX,
        rows=rows,
        wall_s=round(wall, 3),
        rows_per_s=round(rows / wall, 1),
        commit_p50_ms=round(lat_ms[len(lat_ms) // 2], 2),
        commit_p99_ms=round(lat_ms[int(len(lat_ms) * 0.99) - 1], 2),
    )


# -- remote apply ----------------------------------------------------------

_SCHEMA = (
    "CREATE TABLE kv (id INTEGER NOT NULL PRIMARY KEY,"
    " a TEXT NOT NULL DEFAULT '', b INTEGER NOT NULL DEFAULT 0,"
    " c TEXT NOT NULL DEFAULT '');"
)


def _gen_uniform(n: int, n_pks: int, seed=0) -> list:
    rng = random.Random(seed)
    site = ActorId(bytes([1]) * 16).bytes16
    ts = Timestamp.from_unix(1)
    out = []
    for i in range(n):
        pk = pack_columns([rng.randint(1, n_pks)])
        cid = rng.choice(["a", "b", "c"])
        val = rng.randint(0, 10**6) if cid == "b" else f"v{i}"
        out.append(
            Change(
                table="kv", pk=pk, cid=cid, val=val,
                col_version=i // n_pks + 1, db_version=i + 1, seq=0,
                site_id=site, cl=1, ts=ts,
            )
        )
    return out


def _gen_conflict(n: int, seed=3) -> list:
    """Merge-heavy mix: 3 sites race 200 pks through causal transitions
    (delete/re-create sentinels) and equal-clock value ties."""
    rng = random.Random(seed)
    sites = [ActorId(bytes([i]) * 16).bytes16 for i in (1, 2, 3)]
    ts = Timestamp.from_unix(2)
    out = []
    versions = {s: 0 for s in sites}
    for i in range(n):
        site = rng.choice(sites)
        pk = pack_columns([rng.randint(1, 200)])
        cl = rng.choice([1, 1, 1, 2, 3, 3, 4, 5])
        if cl % 2 == 0 or rng.random() < 0.1:
            cid, val = SENTINEL, None
        else:
            cid = rng.choice(["a", "b", "c"])
            # small value space → frequent equal-(cl, cv) ties
            val = rng.randint(0, 4) if cid == "b" else rng.choice(["x", "y"])
        versions[site] += rng.choice([0, 1])
        out.append(
            Change(
                table="kv", pk=pk, cid=cid, val=val,
                col_version=rng.randint(1, 3),
                db_version=max(1, versions[site]),
                seq=rng.randint(0, 3), site_id=site, cl=cl, ts=ts,
            )
        )
    return out


def _apply_rung(rung: str, changes: list, batch: int, mode: str, tag: str,
                tmp: str) -> dict:
    path = os.path.join(tmp, f"bench-{rung}-{mode}.db")
    if os.path.exists(path):
        os.unlink(path)
    st = CrdtStore(path)
    st.apply_schema_sql(_SCHEMA)
    t0 = time.monotonic()
    for i in range(0, len(changes), batch):
        st.apply_changes(changes[i : i + batch])
    wall = time.monotonic() - t0
    st.close()
    return _record(
        rung, mode, tag,
        rows=len(changes), batch=batch, wall_s=round(wall, 3),
        rows_per_s=round(len(changes) / wall, 1),
    )


# -- end-to-end write→event (the r11 SLO plane, snapshot-diffed) -----------


async def _e2e(mode: str, tag: str) -> dict:
    import aiohttp

    from corrosion_tpu.agent.run import shutdown
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import CorrosionApiClient
    from corrosion_tpu.runtime import latency as lat
    from tests.test_agent import boot, fast_config

    net = MemNetwork(seed=13)
    cfg = fast_config("bench-ingest-e2e")
    agent = await boot(net, "bench-ingest-e2e", cfg=cfg)
    api = ApiServer(agent)
    agent.config.api.bind_addr = ["127.0.0.1:0"]
    await api.start()
    client = CorrosionApiClient(api.addrs[0])
    n_writes = 30
    got = asyncio.Event()
    seen = [0]

    async def subscriber() -> None:
        async for line in client.subscribe(
            "SELECT id, text FROM tests", skip_rows=True, raw=True
        ):
            if line.startswith('{"change":'):
                seen[0] += 1
                if seen[0] >= n_writes:
                    got.set()
                    return

    sub_task = asyncio.ensure_future(subscriber())
    try:
        await asyncio.sleep(0.5)
        before = lat.stage_hists(window_secs=None)["total"]
        t0 = time.monotonic()
        for i in range(n_writes):
            await client.execute(
                [["INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                  [i, f"e{i}"]]]
            )
            await asyncio.sleep(0.02)
        await asyncio.wait_for(got.wait(), 120)
        wall = time.monotonic() - t0
        d = lat.stage_hists(window_secs=None)["total"].diff(before)
        # cross-check: the live plane serves the same distribution
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://{api.addrs[0]}/v1/slo") as resp:
                slo_body = await resp.json()
    finally:
        sub_task.cancel()
        await client.close()
        await api.stop()
        await shutdown(agent)
    return _record(
        "ingest-e2e", mode, tag,
        writes=n_writes,
        events=seen[0],
        wall_s=round(wall, 2),
        total_p50_s=round(d.quantile(0.5), 4),
        total_p99_s=round(d.quantile(0.99), 4),
        candidate_batch_wait=cfg.pubsub.candidate_batch_wait,
        slo_plane_total=slo_body["stages"].get("total", {}),
    )


# -- driver ----------------------------------------------------------------


def _mode_env(mode: str, tag: str = ""):
    env = _pre_env(mode, tag)
    return scoped_env(**env) if env else contextlib.nullcontext()


def run_mode(mode: str, tag: str) -> list:
    import tempfile

    recs = []
    with _mode_env(mode, tag):
        for n in (1, 4, 16):
            recs.append(asyncio.run(_local_write(n, mode, tag)))
        for n in (1, 4, 16):
            recs.append(asyncio.run(_local_write(n, mode, tag, durable=True)))
        with tempfile.TemporaryDirectory() as tmp:
            recs.append(_apply_rung(
                "ingest-remote", _gen_uniform(20_000, 400), 500, mode, tag,
                tmp,
            ))
            recs.append(_apply_rung(
                "ingest-conflict", _gen_conflict(20_000), 500, mode, tag,
                tmp,
            ))
        recs.append(asyncio.run(_e2e(mode, tag)))
    return recs


AB_REPS = 3


def run_ab(tag: str) -> list:
    """A/B with pre and post INTERLEAVED per rung, banking the median
    of `AB_REPS` repetitions per mode: the 1-core bench host's
    throughput drifts ±30% over a multi-minute run, so a single
    adjacent pre/post pair still hands whichever side lands on a slow
    minute a phantom (de)regression — r21's re-bank showed the same
    build measuring 0.78x and 1.31x at w16 minutes apart.  Repetitions
    alternate pre,post,pre,post so both modes sample the same drift,
    and the banked record is the median by throughput (by write→event
    p50 for the e2e rung), a real measured run — never an average of
    runs that never happened."""
    import tempfile

    recs = []

    def _score(rec: dict) -> float:
        if "rows_per_s" in rec:
            return rec["rows_per_s"]
        return -rec["total_p50_s"]

    def ab(run_one) -> None:
        per_mode = {"pre": [], "post": []}
        for _ in range(AB_REPS):
            for mode in ("pre", "post"):
                with _mode_env(mode, tag):
                    per_mode[mode].append(run_one(mode))
        for mode in ("pre", "post"):
            ranked = sorted(per_mode[mode], key=_score)
            recs.append(ranked[len(ranked) // 2])

    for durable in (False, True):
        for n in (1, 4, 16):
            ab(lambda mode, n=n, durable=durable: asyncio.run(
                _local_write(n, mode, tag, durable=durable)
            ))
    with tempfile.TemporaryDirectory() as tmp:
        uniform = _gen_uniform(20_000, 400)
        conflict = _gen_conflict(20_000)
        ab(lambda mode: _apply_rung(
            "ingest-remote", uniform, 500, mode, tag, tmp,
        ))
        ab(lambda mode: _apply_rung(
            "ingest-conflict", conflict, 500, mode, tag, tmp,
        ))
    ab(lambda mode: asyncio.run(_e2e(mode, tag)))
    return recs


# -- continuous-profiler attribution + overhead (--profile, r23) -----------


async def _overhead_phases(
    n_writers: int = 16, pairs: int = 6, txs_per_phase: int = 576
) -> dict:
    """Measure what always-on sampling costs the w16 write plane.

    The PRIMARY number is the sampler's own duty cycle — busy/wall per
    32-sample block, the same accounting the adaptive governor sheds
    on — read under the live w16 load from ONE long-lived profiler
    whose governor has settled (warmup phases run sampler-ON, so the
    shed ladder reaches its steady state before anything is banked).
    That instrument resolves fractions of a percent exactly.

    A throughput A/B rides along as corroboration, built as carefully
    as the host allows: one booted agent (fresh boots swing ±20-30%
    rows/s), steady-state `stop()`/`start()` toggles (shed state and
    warm intern caches persist, so an on-phase is the production
    sampler, not a cold restart), every phase REPLACEs the same id
    range (constant btree size), and pair order cycles the ABBA square
    — (off,on),(on,off),(on,off),(off,on) — so each side lands on
    every position mod 4 and periodic host drift cannot alias into the
    off/on split the way simple mirroring lets it.  Even so, on this
    1-core host individual phases swing ±20-30%, far above a ≤1% duty
    — the banked A/B carries its per-pair spread so a reader sees the
    noise floor instead of mistaking the median for a measurement of
    the sampler."""
    from corrosion_tpu.runtime import profiler as prof_mod
    from tests.test_agent import boot, fast_config

    from corrosion_tpu.agent.run import make_broadcastable_changes, shutdown

    prof_mod.configure()  # drop any prior install; this run owns it
    net = MemNetwork(seed=17)
    cfg = fast_config("bench-ingest-prof-ab")
    cfg.profile.enabled = False  # the phases drive install explicitly
    agent = await boot(net, "bench-ingest-prof-ab", cfg=cfg)
    sql = "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)"
    txs_per_writer = txs_per_phase // n_writers

    async def writer(w: int) -> None:
        for t in range(txs_per_writer):
            base = (w * txs_per_writer + t) * ROWS_PER_TX
            rows = [(base + j, f"p{base + j}") for j in range(ROWS_PER_TX)]
            await make_broadcastable_changes(
                agent, lambda tx, rows=rows: [tx.executemany(sql, rows)]
            )

    async def phase() -> float:
        t0 = time.monotonic()
        await asyncio.gather(*(writer(w) for w in range(n_writers)))
        return txs_per_writer * n_writers * ROWS_PER_TX / (
            time.monotonic() - t0
        )

    import gc
    import statistics

    prof = prof_mod.configure(
        hz=cfg.profile.hz,
        shed_hz=cfg.profile.shed_hz,
        max_overhead_pct=cfg.profile.max_overhead_pct,
        window_secs=cfg.profile.window_secs,
        slots=cfg.profile.slots,
        max_stacks=cfg.profile.max_stacks,
    )
    prof.register_loop_coldpath()

    abba = ((False, True), (True, False), (True, False), (False, True))
    deltas = []
    rates = {False: [], True: []}
    phase_rows = txs_per_writer * n_writers * ROWS_PER_TX
    on_busy = 0.0
    on_wall = 0.0
    duty_phase_max = 0.0
    try:
        # warmup with the sampler ON: schema caches, first-commit
        # costs, and — the point — the governor settling under load
        await phase()
        await phase()
        # prove the shed ladder live under the real w16 load before
        # anything is banked: the r23 bank happened to shed on a
        # warmup spike, but the r24 write path holds steady duty well
        # under budget, so a run that merely HOPES for a shed banks
        # sheds_total=0 and says nothing about the governor.  Drop
        # the budget to the floor until an adapt block trips the
        # production shed path, then restore the budget and return to
        # full rate — the recovery hysteresis (projected < 0.5×
        # budget) is deliberately not waited on, because near-budget
        # duty would pin the whole banked measurement at shed_hz and
        # underreport the full-rate cost the acceptance bar is about
        # (exactly what the r23 bank did: it measured at 11 Hz).
        budget = prof.max_overhead_pct
        base_sheds = prof.sheds_total
        prof.max_overhead_pct = 1e-4
        for _ in range(4):
            await phase()
            if prof.sheds_total > base_sheds:
                break
        prof.max_overhead_pct = budget
        shed_fired = prof.sheds_total > base_sheds
        prof.shed = False
        prof._interval = 1.0 / prof.hz
        for i in range(pairs):
            pair_rate = {}
            for on in abba[i % 4]:
                if on:
                    prof.start()
                else:
                    prof.stop()
                gc.collect()  # phases start from the same gc state
                busy0 = prof.busy_secs_total
                pair_rate[on] = await phase()
                if on:
                    busy = prof.busy_secs_total - busy0
                    wall = phase_rows / pair_rate[on]
                    on_busy += busy
                    on_wall += wall
                    duty_phase_max = max(
                        duty_phase_max, 100.0 * busy / wall
                    )
            for on in (False, True):
                rates[on].append(pair_rate[on])
            deltas.append(
                100.0 * (1.0 - pair_rate[True] / pair_rate[False])
            )
        census = prof.census()
    finally:
        prof_mod.configure()
        await shutdown(agent)
    return {
        "rung": "ingest-local-w16-steady",
        "overhead_pct": round(100.0 * on_busy / max(1e-9, on_wall), 3),
        "method": (
            "sampler duty: monotone busy accumulator differenced "
            "across every sampler-on phase, aggregated over the full "
            "on wall — exact accounting of sample-path time under the "
            "live w16 load (an overestimate if anything: a sample "
            "preempted mid-walk charges the preemption too); the "
            "throughput A/B below is corroboration with its noise "
            "floor attached"
        ),
        "duty_phase_max_pct": round(duty_phase_max, 3),
        "hz_effective": (
            census["shed_hz"] if census["shed"] else census["hz"]
        ),
        "shed": census["shed"],
        "sheds_total": census["sheds_total"],
        "governor_probe": {
            "forced_budget_pct": 1e-4,
            "shed_fired": shed_fired,
        },
        "ab": {
            "reps": pairs,
            "ordering": "ABBA, steady-state sampler stop/start",
            "rows_per_s_off": round(statistics.median(rates[False]), 1),
            "rows_per_s_on": round(statistics.median(rates[True]), 1),
            "median_paired_delta_pct": round(
                statistics.median(deltas), 2
            ),
            "pair_delta_spread_pct": [
                round(min(deltas), 2), round(max(deltas), 2)
            ],
            "note": (
                "1-core host: per-phase throughput noise exceeds the "
                "sampler duty by an order of magnitude; the duty "
                "accounting above is the load-bearing measurement"
            ),
        },
    }


def _hist_deltas(name: str, before=None):
    """(sum, count) per label value for one histogram family — diffed
    against `before` when given, so a rung's contribution is isolated
    from whatever the process accumulated earlier."""
    from corrosion_tpu.runtime.metrics import METRICS

    sums: dict = {}
    counts: dict = {}
    for kind, nm, labels, val in METRICS.snapshot():
        if kind != "histogram":
            continue
        key = labels.get("bucket") or labels.get("shape") or "-"
        if nm == name + "_sum":
            sums[key] = sums.get(key, 0.0) + val
        elif nm == name + "_count":
            counts[key] = counts.get(key, 0) + val
    if before is not None:
        b_sums, b_counts = before
        sums = {
            k: v - b_sums.get(k, 0.0) for k, v in sums.items()
            if v - b_sums.get(k, 0.0) > 0
        }
        counts = {
            k: v - b_counts.get(k, 0) for k, v in counts.items()
            if v - b_counts.get(k, 0) > 0
        }
    return sums, counts


# a multiple of 4 keeps the ABBA square balanced: each side of the
# overhead A/B lands on every position mod 4 equally often
PROFILE_OVERHEAD_REPS = 8


def run_profile() -> dict:
    """Bank WRITE_PROFILE.json: solo-writer bucket attribution with the
    sampler ON, then the w16 steady-state sampler-overhead measurement
    (duty accounting primary, position-balanced A/B corroborating)."""
    from corrosion_tpu.runtime import profiler as prof_mod

    # -- 1) w1 solo: where does one commit's wall actually go? -------------
    prof_mod.configure()  # fresh install at boot (first agent wins)
    wb_before = _hist_deltas("corro.write.profile.seconds")
    fl_before = _hist_deltas("corro.store.commit.flush.seconds")
    st_before = _hist_deltas("corro.store.stmt.seconds")
    rec_w1 = asyncio.run(_local_write(1, "post", "profile", profile=True))
    wb_sums, wb_counts = _hist_deltas(
        "corro.write.profile.seconds", wb_before
    )
    fl_sums, fl_counts = _hist_deltas(
        "corro.store.commit.flush.seconds", fl_before
    )
    st_sums, _ = _hist_deltas("corro.store.stmt.seconds", st_before)
    prof = prof_mod.get()
    sampler_census = prof.census() if prof is not None else {}
    stmt_rows = prof.ring.stmt_rows()[:10] if prof is not None else []

    from corrosion_tpu.runtime.profiler import WRITE_BUCKETS

    buckets = {
        b: round(wb_sums.get(b, 0.0), 6) for b in WRITE_BUCKETS
    }
    wall = wb_sums.get("wall", 0.0)

    # -- 2) w16: what does always-on sampling cost the write plane? --------
    overhead = asyncio.run(
        _overhead_phases(pairs=PROFILE_OVERHEAD_REPS)
    )

    doc = {
        "rung": "write-profile",
        "buckets_secs": buckets,
        "bucket_commits": wb_counts.get("wall", 0),
        "wall_secs": round(wall, 6),
        "coverage_pct": round(
            100.0 * sum(buckets.values()) / wall, 2
        ) if wall else 0.0,
        "detail": {
            "commit_fsync_secs": round(fl_sums.get("-", 0.0), 6),
            "commit_fsync_count": fl_counts.get("-", 0),
            "stmt_secs": {
                k: round(v, 6)
                for k, v in sorted(st_sums.items(), key=lambda kv: -kv[1])[:10]
            },
            "stmt_rows": stmt_rows,
            "sampler": sampler_census,
            "w1_rows_per_s": rec_w1["rows_per_s"],
        },
        "overhead": overhead,
        "code_sha": _code_fingerprint(),
        "measured_at": time.strftime(
            "%Y-%m-%d %H:%M:%S", time.gmtime()
        ),
    }
    path = os.path.join(REPO, "WRITE_PROFILE.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def main() -> None:
    args = sys.argv[1:]
    mode = "post"
    tag = ""
    if "--tag" in args:
        i = args.index("--tag")
        tag = args[i + 1]
        del args[i : i + 2]
    if "--mode" in args:
        i = args.index("--mode")
        mode = args[i + 1]
        del args[i : i + 2]
    if "--ab" in args:
        mode = "ab"
    if "--profile" in args:
        doc = run_profile()
        ov = doc["overhead"]
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(
            f"write profile: {doc['coverage_pct']}% of "
            f"{doc['wall_secs']:.2f}s wall attributed across "
            f"{len(doc['buckets_secs'])} buckets; sampler duty "
            f"{ov['overhead_pct']}% at w16 (shed={ov['shed']}, "
            f"A/B median {ov['ab']['median_paired_delta_pct']}%)"
        )
        return
    bank = os.path.join(REPO, "INGEST_BENCH.json")
    try:
        if mode == "ab":
            all_recs = run_ab(tag)
            for r in all_recs:
                print(json.dumps(r), flush=True)
        else:
            all_recs = run_mode(mode, tag)
            for r in all_recs:
                print(json.dumps(r), flush=True)
        merge_records(bank, all_recs)
    finally:
        # the merge's flock sidecar must not strand in the working
        # tree — on ANY exit, including a rung crashing mid-run
        cleanup_record_locks(bank)
    # headline: the banked acceptance ratios when both halves exist
    with open(bank) as f:
        banked = {r["rung"]: r for r in json.load(f)}

    def ratio(rung: str) -> str:
        sfx = f"-{tag}" if tag else ""
        pre = banked.get(f"{rung}-pre{sfx}")
        post = banked.get(f"{rung}-post{sfx}")
        if not pre or not post:
            return "n/a"
        return f"{post['rows_per_s'] / pre['rows_per_s']:.2f}x"

    print(
        "speedup post/pre: "
        f"w1={ratio('ingest-local-w1')} w4={ratio('ingest-local-w4')} "
        f"w16={ratio('ingest-local-w16')} remote={ratio('ingest-remote')} "
        f"conflict={ratio('ingest-conflict')}"
    )


if __name__ == "__main__":
    main()

"""Converge the NORTH-STAR scale: 100k members, dense kernel, 8-way mesh.

BASELINE.md's target is <60 s to stable membership at 100k simulated
members on a v5e-8. This script EXECUTES that exact sharded program —
[hosts-less] 8-device member mesh, int16 view (2.33 GiB/chip), finger
bootstrap — on the virtual CPU mesh and runs it TO CONVERGENCE
(coverage >= 0.999, FP = 0), recording ticks, s/tick, and wall. On the
single backing CPU core this takes minutes, not seconds; the per-tick
arithmetic is what a v5e-8 runs with ~100x the throughput, so the
recorded tick count x chip-speed is the projection the bench validates
at 10k on real hardware.

Usage: python scripts/dense_100k.py [n] [chunk]
Merges rung 5 into BASELINE_MEASURED.json.

KNOWN LIMIT of the VIRTUAL mesh (not the program): at n=100k the run
dies in XLA's CPU-collective stuck-rendezvous terminator (hard 40 s,
rendezvous.cc) — with 8 device threads time-slicing ONE physical core,
the threads busy with their 2.3 GB shard segments cannot all reach an
all-gather inside 40 s. The recorded rung therefore uses the largest
reliably-schedulable size on this host (n=32768, converged, FP 0); a
real v5e-8 runs each device on its own chip and rendezvouses in
microseconds.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.reexec_under_cpu(
    "DENSE_100K_CHILD",
    n_devices=8,
    timeout=float(os.environ.get("DENSE_100K_BUDGET_S", "7000")),
)

jaxenv.enable_compilation_cache()

import jax  # noqa: E402

from corrosion_tpu.ops import swim  # noqa: E402
from corrosion_tpu.parallel import (  # noqa: E402
    member_mesh,
    shard_member_state,
    sharded_tick,
)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    ndev = 8
    devices = jax.devices()[:ndev]
    assert len(devices) == ndev
    mesh = member_mesh(devices)
    # more, smaller feed windows at the same total bandwidth (W = n/4):
    # each window's cross-shard gather is a single collective, and on the
    # single-core virtual mesh a multi-GB collective can trip XLA's
    # stuck-rendezvous terminator — smaller windows keep every collective
    # well under it (convergence ticks are cadence-independent, measured)
    feeds = int(os.environ.get("DENSE_100K_FEEDS", "16"))
    fe = max(25, n // (4 * feeds))
    params = swim.SwimParams(
        n=n, feeds_per_tick=feeds, feed_entries=fe, piggyback=4,
        incoming_slots=8, buffer_slots=12, probe_candidates=2, antientropy=1,
    )
    t0 = time.monotonic()
    state = shard_member_state(
        swim.init_state(params, jax.random.PRNGKey(0), seed_mode="fingers"),
        mesh,
    )
    jax.block_until_ready(state.view)
    init_s = time.monotonic() - t0
    print(f"init {init_s:.1f}s", flush=True)

    tick_k = sharded_tick(params, mesh, k=chunk)
    rng = jax.random.PRNGKey(1)
    t0 = time.monotonic()
    rng, key = jax.random.split(rng)
    state = tick_k(state, key)
    jax.block_until_ready(state.view)
    compile_s = time.monotonic() - t0
    print(f"compile+first-dispatch {compile_s:.1f}s", flush=True)

    ticks = chunk
    t0 = time.monotonic()
    stats = {"coverage": 0.0, "false_positive": 1.0}
    converged = False
    while ticks < 400:
        rng, key = jax.random.split(rng)
        state = tick_k(state, key)
        ticks += chunk
        stats = swim.membership_stats(state)
        print(
            f"tick {ticks}: coverage {stats['coverage']:.6f} "
            f"fp {stats['false_positive']}",
            flush=True,
        )
        if stats["coverage"] >= 0.999 and stats["false_positive"] == 0.0:
            converged = True
            break
    wall = time.monotonic() - t0 + compile_s
    measured = ticks - chunk  # ticks after the compile dispatch
    per_tick = (time.monotonic() - t0) / max(1, measured)
    rec = {
        "rung": 5,
        # n in the name: it is part of the merge key, so a smoke run at
        # a toy size can never overwrite the canonical measured record
        "name": f"dense_sharded_convergence_n{n}",
        "n": n,
        "n_devices": ndev,
        "seed_mode": "fingers",
        "view_dtype": "int16",
        "init_s": round(init_s, 1),
        "compile_s": round(compile_s, 1),
        "s_per_tick_cpu_1core": round(per_tick, 2),
        "convergence_ticks": ticks,
        "convergence_wall_s": round(wall, 1),
        "coverage": round(stats["coverage"], 6),
        "false_positive": round(stats["false_positive"], 6),
        "converged": converged,
        "platform": jax.devices()[0].platform,
        "note": (
            "the identical sharded program a v5e-8 runs; per-tick cost on "
            "one CPU core — chip throughput is the bench-validated "
            "projection (BENCH at 10k)"
        ),
    }
    print(json.dumps(rec), flush=True)
    out = os.path.join(REPO, "BASELINE_MEASURED.json")
    try:
        with open(out) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = []
    merged = {
        (r.get("rung"), r.get("name"), r.get("suspicion_ticks")): r
        for r in existing + [rec]
    }
    with open(out, "w") as f:
        json.dump(list(merged.values()), f, indent=1)
    sys.exit(0 if converged else 1)


if __name__ == "__main__":
    main()

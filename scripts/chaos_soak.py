"""Standalone chaos soak: strict invariants, seeded faults, banked record.

Runs tests/test_chaos_soak.run_soak twice (different seeds — the
flake-free-repeat requirement of VERDICT r4 #8) under
CORRO_INVARIANTS=strict and writes CHAOS_SOAK.json.  Any
always-invariant violation raises; the sometimes coverage contract is
asserted inside the soak.

Usage: python scripts/chaos_soak.py [seed1 seed2 ...]
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()
os.environ["CORRO_INVARIANTS"] = "strict"

from tests.test_chaos_soak import run_soak  # noqa: E402


def _soak_fingerprint() -> dict:
    """Tie the banked record to a code version (the r4 provenance rule
    the bench path enforces): git HEAD + dirty flag + a digest over the
    agent/runtime source the soak exercises."""
    import hashlib
    import subprocess

    out: dict = {}
    try:
        out["git_head"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        out["git_dirty"] = bool(subprocess.run(
            ["git", "status", "--porcelain", "corrosion_tpu", "tests"],
            cwd=REPO, capture_output=True, text=True, timeout=10,
        ).stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    h = hashlib.sha256()
    for root, _dirs, files in sorted(os.walk(os.path.join(REPO, "corrosion_tpu"))):
        for name in sorted(files):
            if name.endswith(".py"):
                with open(os.path.join(root, name), "rb") as f:
                    h.update(name.encode() + b"\0" + f.read())
    out["source_sha"] = h.hexdigest()[:16]
    return out


def main() -> None:
    seeds = [int(s) for s in sys.argv[1:]] or [1337, 4242]
    runs = []
    for seed in seeds:
        t0 = time.monotonic()
        # outer bound > the inner wait_progress livelock cap (900 s):
        # a stall must surface as the phase's diagnostic assertion
        summary = asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run_soak(seed), 1200)
        )
        summary["wall_s"] = round(time.monotonic() - t0, 1)
        runs.append(summary)
        print(f"seed {seed}: {len(summary['phases'])} phases, "
              f"{summary['wall_s']}s, sometimes={summary['sometimes']}",
              flush=True)
    record = {
        "mode": "strict",
        "runs": runs,
        "code": _soak_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    with open(os.path.join(REPO, "CHAOS_SOAK.json"), "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({"metric": "chaos_soak", "runs": len(runs),
                      "all_phases": all(len(r["phases"]) == 5 for r in runs)}))


if __name__ == "__main__":
    main()

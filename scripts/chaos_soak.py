"""Standalone chaos soak: strict invariants, seeded faults, banked record.

Runs tests/test_chaos_soak.run_soak twice (different seeds — the
flake-free-repeat requirement of VERDICT r4 #8) under
CORRO_INVARIANTS=strict and writes CHAOS_SOAK.json.  Any
always-invariant violation raises; the sometimes coverage contract is
asserted inside the soak.

Usage: python scripts/chaos_soak.py [seed1 seed2 ...]
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()
os.environ["CORRO_INVARIANTS"] = "strict"

from tests.test_chaos_soak import run_soak  # noqa: E402


def _soak_fingerprint() -> dict:
    """Tie the banked record to a code version (the r4 provenance rule
    the bench path enforces): git HEAD + dirty flag + a digest over the
    agent/runtime source the soak exercises."""
    import hashlib
    import subprocess

    out: dict = {}
    try:
        out["git_head"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        out["git_dirty"] = bool(subprocess.run(
            ["git", "status", "--porcelain", "corrosion_tpu", "tests"],
            cwd=REPO, capture_output=True, text=True, timeout=10,
        ).stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    h = hashlib.sha256()
    for root, _dirs, files in sorted(os.walk(os.path.join(REPO, "corrosion_tpu"))):
        for name in sorted(files):
            if name.endswith(".py"):
                with open(os.path.join(root, name), "rb") as f:
                    h.update(name.encode() + b"\0" + f.read())
    out["source_sha"] = h.hexdigest()[:16]
    return out


def kernel_flight_phase(seed: int = 7) -> dict:
    """Kernel-level churn episode with a tick-RESOLVED verdict: boot a
    small pview cluster, kill 3%, run to full detection, and report the
    suspicion/down/refute timeline from the device flight ring (r8) —
    the per-protocol-period shape of the detection, where the agent
    phases above only bank end-state aggregates."""
    import numpy as np

    from corrosion_tpu.models.cluster import PViewClusterSim
    from corrosion_tpu.runtime.records import FLIGHT

    n = 256
    sim = PViewClusterSim(
        n, slots=64, seed=seed, seed_mode="fingers",
        feeds_per_tick=2, feed_entries=16, suspicion_ticks=4,
    )
    sim.run_until_converged(max_ticks=400, check_every=25)
    kill = np.random.default_rng(seed).choice(
        n, size=max(1, n * 3 // 100), replace=False
    )
    base = sim.ticks
    sim.crash_many(kill)
    det = None
    while sim.ticks - base < 200:
        sim.step(10)
        cs = sim.stats()  # drains the ring into FLIGHT as it goes
        if cs["detected"] >= 1.0 and cs["false_positive"] == 0.0:
            det = sim.ticks - base
            break
    timeline = [
        {
            "tick": f["tick"] - base,
            "suspect_raised": f["events"]["suspect_raised"],
            "down_declared": f["events"]["down_declared"],
            "refuted": f["events"]["refuted"],
            "open_timers": f["census"]["census_suspect"],
        }
        for f in FLIGHT.window(4096, kernel="pview")
        if f["tick"] >= base
        and (
            f["events"]["suspect_raised"]
            or f["events"]["down_declared"]
            or f["events"]["refuted"]
        )
    ][-128:]
    assert det is not None, "kernel flight phase: churn never detected"
    assert any(r["down_declared"] for r in timeline), (
        "flight ring shows no down_declared tick for a detected churn"
    )
    return {
        "n": n,
        "killed": int(len(kill)),
        "detect_ticks": det,
        "timeline": timeline,
    }


def flaky_node_phase(seeds=(3, 11)) -> dict:
    """r9 Lifeguard A/B: one degraded member (processing lag — the
    flaky-accuser pathology), vanilla vs lifeguard at the SAME seeds,
    asserting the acceptance inequalities before banking:
    >= 5x fewer ground-truth false-positive suspicions of healthy
    members, wrongful downs likewise, and a truly-crashed member still
    detected within 2x the vanilla tick count.  Tick-resolved suspicion
    timelines ride along from the flight recorder (r8)."""
    from corrosion_tpu.models.cluster import flaky_node_ab

    runs = []
    for seed in seeds:
        r = flaky_node_ab(
            kernel="dense", seed=seed, n=96, boot_ticks=40, window=240,
            lag=2, chunk=20, detect_chunk=5, drain_flight=True,
        )
        v, lf = r["vanilla"], r["lifeguard"]
        assert v["suspect_fp"] >= 5 * max(1, lf["suspect_fp"]), (
            f"seed {seed}: FP suspicions did not collapse 5x: {r}"
        )
        assert v["down_fp"] >= 5 * max(1, lf["down_fp"]), (
            f"seed {seed}: wrongful downs did not collapse 5x: {r}"
        )
        assert v["detect_ticks"] is not None and lf["detect_ticks"], (
            f"seed {seed}: crash never detected: {r}"
        )
        assert lf["detect_ticks"] <= 2 * v["detect_ticks"], (
            f"seed {seed}: lifeguard detection too slow: {r}"
        )
        assert lf["timeline"], f"seed {seed}: no flight timeline: {r}"
        runs.append(r)
        print(
            f"flaky-node seed {seed}: suspect_fp {v['suspect_fp']} -> "
            f"{lf['suspect_fp']}, down_fp {v['down_fp']} -> "
            f"{lf['down_fp']}, detect {v['detect_ticks']} -> "
            f"{lf['detect_ticks']}", flush=True,
        )
    return {"scenario": "one member lag=2 ticks, alive throughout",
            "runs": runs}


def _bank(update: dict) -> None:
    """Merge keys into CHAOS_SOAK.json, preserving phases not re-run."""
    path = os.path.join(REPO, "CHAOS_SOAK.json")
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    record.update(update)
    record["code"] = _soak_fingerprint()
    record["measured_at"] = time.strftime(
        "%Y-%m-%d %H:%M:%S", time.gmtime()
    )
    with open(path, "w") as f:
        json.dump(record, f, indent=1)


def main() -> None:
    args = sys.argv[1:]
    phase_only = None
    if "--phase" in args:
        i = args.index("--phase")
        phase_only = args[i + 1]
        args = args[:i] + args[i + 2:]
    if phase_only == "flaky-node":
        t0 = time.monotonic()
        fl = flaky_node_phase()
        fl["wall_s"] = round(time.monotonic() - t0, 1)
        _bank({"flaky_node": fl})
        print(json.dumps({"metric": "chaos_soak", "phase": "flaky-node",
                          "runs": len(fl["runs"])}))
        return
    if phase_only is not None:
        raise SystemExit(f"unknown --phase {phase_only!r}")
    seeds = [int(s) for s in args] or [1337, 4242]
    runs = []
    for seed in seeds:
        t0 = time.monotonic()
        # outer bound > the inner wait_progress livelock cap (900 s):
        # a stall must surface as the phase's diagnostic assertion
        summary = asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run_soak(seed), 1200)
        )
        summary["wall_s"] = round(time.monotonic() - t0, 1)
        runs.append(summary)
        print(f"seed {seed}: {len(summary['phases'])} phases, "
              f"{summary['wall_s']}s, sometimes={summary['sometimes']}",
              flush=True)
    t0 = time.monotonic()
    flight = kernel_flight_phase()
    flight["wall_s"] = round(time.monotonic() - t0, 1)
    print(f"kernel flight: detect_ticks={flight['detect_ticks']} "
          f"({len(flight['timeline'])} active ticks)", flush=True)
    t0 = time.monotonic()
    flaky = flaky_node_phase()
    flaky["wall_s"] = round(time.monotonic() - t0, 1)
    _bank({
        "mode": "strict",
        "runs": runs,
        "kernel_flight": flight,
        "flaky_node": flaky,
    })
    print(json.dumps({"metric": "chaos_soak", "runs": len(runs),
                      "all_phases": all(len(r["phases"]) == 5 for r in runs)}))


if __name__ == "__main__":
    main()

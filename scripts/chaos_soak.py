"""Standalone chaos soak: strict invariants, seeded faults, banked record.

Runs tests/test_chaos_soak.run_soak twice (different seeds — the
flake-free-repeat requirement of VERDICT r4 #8) under
CORRO_INVARIANTS=strict and writes CHAOS_SOAK.json.  Any
always-invariant violation raises; the sometimes coverage contract is
asserted inside the soak.  r11 adds the SLO baseline phase: per-stage
write→event percentiles (quiet / churn / degraded-writer scenarios on a
3-node devcluster with the canary probe live) banked to
SLO_BASELINE.json.

Usage: python scripts/chaos_soak.py [seed1 seed2 ...]
       python scripts/chaos_soak.py --phase slo      (SLO baseline only)
       python scripts/chaos_soak.py --phase cluster  (r12 cluster
           observatory: CLUSTER_OBS.json — 3-node devcluster x {quiet,
           partition→heal, churn}, divergence detection-round latency +
           one incident dump per episode)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()
os.environ["CORRO_INVARIANTS"] = "strict"

from tests.test_chaos_soak import run_soak  # noqa: E402


def _soak_fingerprint() -> dict:
    """Tie the banked record to a code version (the r4 provenance rule
    the bench path enforces): git HEAD + dirty flag + a digest over the
    agent/runtime source the soak exercises."""
    import hashlib
    import subprocess

    out: dict = {}
    try:
        out["git_head"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        out["git_dirty"] = bool(subprocess.run(
            ["git", "status", "--porcelain", "corrosion_tpu", "tests"],
            cwd=REPO, capture_output=True, text=True, timeout=10,
        ).stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    h = hashlib.sha256()
    for root, _dirs, files in sorted(os.walk(os.path.join(REPO, "corrosion_tpu"))):
        for name in sorted(files):
            if name.endswith(".py"):
                with open(os.path.join(root, name), "rb") as f:
                    h.update(name.encode() + b"\0" + f.read())
    out["source_sha"] = h.hexdigest()[:16]
    return out


def kernel_flight_phase(seed: int = 7) -> dict:
    """Kernel-level churn episode with a tick-RESOLVED verdict: boot a
    small pview cluster, kill 3%, run to full detection, and report the
    suspicion/down/refute timeline from the device flight ring (r8) —
    the per-protocol-period shape of the detection, where the agent
    phases above only bank end-state aggregates."""
    import numpy as np

    from corrosion_tpu.models.cluster import PViewClusterSim
    from corrosion_tpu.runtime.records import FLIGHT

    n = 256
    sim = PViewClusterSim(
        n, slots=64, seed=seed, seed_mode="fingers",
        feeds_per_tick=2, feed_entries=16, suspicion_ticks=4,
    )
    sim.run_until_converged(max_ticks=400, check_every=25)
    kill = np.random.default_rng(seed).choice(
        n, size=max(1, n * 3 // 100), replace=False
    )
    base = sim.ticks
    sim.crash_many(kill)
    det = None
    while sim.ticks - base < 200:
        sim.step(10)
        cs = sim.stats()  # drains the ring into FLIGHT as it goes
        if cs["detected"] >= 1.0 and cs["false_positive"] == 0.0:
            det = sim.ticks - base
            break
    timeline = [
        {
            "tick": f["tick"] - base,
            "suspect_raised": f["events"]["suspect_raised"],
            "down_declared": f["events"]["down_declared"],
            "refuted": f["events"]["refuted"],
            "open_timers": f["census"]["census_suspect"],
        }
        for f in FLIGHT.window(4096, kernel="pview")
        if f["tick"] >= base
        and (
            f["events"]["suspect_raised"]
            or f["events"]["down_declared"]
            or f["events"]["refuted"]
        )
    ][-128:]
    assert det is not None, "kernel flight phase: churn never detected"
    assert any(r["down_declared"] for r in timeline), (
        "flight ring shows no down_declared tick for a detected churn"
    )
    return {
        "n": n,
        "killed": int(len(kill)),
        "detect_ticks": det,
        "timeline": timeline,
    }


def flaky_node_phase(seeds=(3, 11)) -> dict:
    """r9 Lifeguard A/B: one degraded member (processing lag — the
    flaky-accuser pathology), vanilla vs lifeguard at the SAME seeds,
    asserting the acceptance inequalities before banking:
    >= 5x fewer ground-truth false-positive suspicions of healthy
    members, wrongful downs likewise, and a truly-crashed member still
    detected within 2x the vanilla tick count.  Tick-resolved suspicion
    timelines ride along from the flight recorder (r8)."""
    from corrosion_tpu.models.cluster import flaky_node_ab

    runs = []
    for seed in seeds:
        r = flaky_node_ab(
            kernel="dense", seed=seed, n=96, boot_ticks=40, window=240,
            lag=2, chunk=20, detect_chunk=5, drain_flight=True,
        )
        v, lf = r["vanilla"], r["lifeguard"]
        assert v["suspect_fp"] >= 5 * max(1, lf["suspect_fp"]), (
            f"seed {seed}: FP suspicions did not collapse 5x: {r}"
        )
        assert v["down_fp"] >= 5 * max(1, lf["down_fp"]), (
            f"seed {seed}: wrongful downs did not collapse 5x: {r}"
        )
        assert v["detect_ticks"] is not None and lf["detect_ticks"], (
            f"seed {seed}: crash never detected: {r}"
        )
        assert lf["detect_ticks"] <= 2 * v["detect_ticks"], (
            f"seed {seed}: lifeguard detection too slow: {r}"
        )
        assert lf["timeline"], f"seed {seed}: no flight timeline: {r}"
        runs.append(r)
        print(
            f"flaky-node seed {seed}: suspect_fp {v['suspect_fp']} -> "
            f"{lf['suspect_fp']}, down_fp {v['down_fp']} -> "
            f"{lf['down_fp']}, detect {v['detect_ticks']} -> "
            f"{lf['detect_ticks']}", flush=True,
        )
    return {"scenario": "one member lag=2 ticks, alive throughout",
            "runs": runs}


def slo_baseline_phase(writes: int = 40) -> dict:
    """r11: bank the first write→event SLO baseline — per-stage
    percentiles (`corro.e2e.*`) from a 3-node devcluster under three
    scenarios: quiet (steady writes), churn (a node bounced mid-run:
    sync catch-up + regossip while writes flow), degraded (the writer's
    traffic delayed 50 ms one-way through the mem-net fault knobs).
    Every scenario runs the canary probe on all nodes and must produce
    a non-empty percentile table for all five stages; the snapshot-diff
    isolation (`latency.stage_report(before=...)`) keeps scenarios
    exact despite the shared process registry."""
    from corrosion_tpu.agent.membership import SwimConfig
    from corrosion_tpu.devcluster import DevCluster, Topology
    from corrosion_tpu.net.mem import MemNetwork
    from corrosion_tpu.runtime import latency as lat

    schema = (
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
    )

    async def run_scenario(name: str, seed: int) -> dict:
        from corrosion_tpu.agent.run import (
            canary_loop,
            make_broadcastable_changes,
        )
        from corrosion_tpu.api.http import ApiServer
        from corrosion_tpu.client import CorrosionApiClient

        net = MemNetwork(seed=seed)
        cluster = DevCluster(
            Topology.parse("A -> C\nB -> C\n"),
            schema,
            network=net,
            swim_config=SwimConfig(
                probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0
            ),
        )
        await cluster.start()
        api = client = None
        canaries = []
        try:
            await cluster.wait_converged(timeout=30.0)
            writer = cluster.agents["A"]
            subber = cluster.agents["C"]
            subber.config.api.bind_addr = ["127.0.0.1:0"]
            api = ApiServer(subber)
            await api.start()
            client = CorrosionApiClient(api.addrs[0])
            stream = client.subscribe("SELECT id, text FROM tests")
            it = stream.__aiter__()
            while True:
                ev = await asyncio.wait_for(it.__anext__(), 10)
                if "eoq" in ev:
                    break
            for ag in cluster.agents.values():
                ag.config.slo.canary = True
                ag.config.slo.canary_interval_secs = 0.25
                canaries.append(asyncio.ensure_future(canary_loop(ag)))
            before = lat.snapshot_stages()
            if name == "degraded":
                net.degrade("A", latency=0.05)
            got = 0
            for i in range(writes):
                if name == "churn" and i in (writes // 3, 2 * writes // 3):
                    net.take_down("B")
                    await asyncio.sleep(0.2)
                    net.bring_up("B")
                await make_broadcastable_changes(
                    writer,
                    lambda tx, i=i: [
                        tx.execute(
                            "INSERT OR REPLACE INTO tests (id, text) "
                            "VALUES (?, ?)",
                            [i, f"{name}-{i}"],
                        )
                    ],
                )
                while got <= i:
                    ev = await asyncio.wait_for(it.__anext__(), 30)
                    if "change" in ev:
                        got += 1
            await asyncio.sleep(1.2)  # canary cycles + sync stragglers
            rep = lat.stage_report(before=before)
            for stage in lat.E2E_STAGES:
                assert rep[stage]["count"] > 0, (
                    f"slo baseline {name}: stage {stage} observed nothing"
                )
            # the live plane serves the same stages over HTTP
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{api.addrs[0]}/v1/slo"
                ) as resp:
                    assert resp.status == 200
                    slo_body = await resp.json()
            canary_n = sum(
                w.snapshot_cumulative().count
                for _n, _l, w in lat._registry().latency_family(
                    "corro.e2e.canary.seconds"
                )
            )
            return {
                "writes": writes,
                "stages": rep,
                "canary_probes_cumulative": canary_n,
                "slo_breached_now": {
                    s: slo_body["stages"][s]["breached"]
                    for s in slo_body["stages"]
                },
            }
        finally:
            for c in canaries:
                c.cancel()
            for c in canaries:
                try:
                    await c
                except (asyncio.CancelledError, Exception):
                    pass
            if client is not None:
                await client.close()
            if api is not None:
                await api.stop()
            await cluster.stop()

    out: dict = {"scenarios": {}}
    for i, name in enumerate(("quiet", "churn", "degraded")):
        t0 = time.monotonic()
        rec = asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run_scenario(name, seed=97 + i), 600)
        )
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        out["scenarios"][name] = rec
        p99 = rec["stages"]["total"]["p99"]
        print(
            f"slo baseline {name}: total p99="
            f"{p99 * 1e3 if p99 else float('nan'):.1f}ms "
            f"counts={{"
            + ", ".join(
                f"{s}: {rec['stages'][s]['count']}"
                for s in rec["stages"]
            )
            + "}}",
            flush=True,
        )
    return out


def cluster_obs_phase() -> dict:
    """r12: bank the cluster-observatory baseline — a 3-node devcluster
    under {quiet, partition→heal, churn}, all through the shared
    scenario harness (`models/cluster.py::cluster_observatory_scenario`)
    whose internal pins already assert the exactness contract (cluster-
    merged stage percentiles == merge of the per-node local histograms,
    over HTTP on one node).  This phase adds the black-box accounting on
    top: each scenario runs with a FRESH $CORRO_FLIGHT_DIR and the
    number of `cluster_divergence` incident dumps must equal the number
    of divergence episodes the agents recorded — exactly one dump per
    episode, zero in quiet.  Headline number: detection latency in
    digest rounds."""
    import glob
    import tempfile

    from corrosion_tpu.models.cluster import cluster_observatory_scenario

    out: dict = {"scenarios": {}}
    for i, name in enumerate(("quiet", "partition", "churn")):
        with tempfile.TemporaryDirectory() as flight_dir:
            old = os.environ.get("CORRO_FLIGHT_DIR")
            os.environ["CORRO_FLIGHT_DIR"] = flight_dir
            try:
                t0 = time.monotonic()
                timeline: list = []
                rec = asyncio.new_event_loop().run_until_complete(
                    asyncio.wait_for(
                        cluster_observatory_scenario(
                            name, seed=211 + i, timeline=timeline
                        ),
                        300,
                    )
                )
                rec["wall_s"] = round(time.monotonic() - t0, 1)
                dumps = len(
                    glob.glob(
                        os.path.join(flight_dir, "*cluster_divergence*")
                    )
                )
            finally:
                if old is None:
                    os.environ.pop("CORRO_FLIGHT_DIR", None)
                else:
                    os.environ["CORRO_FLIGHT_DIR"] = old
        expected_dumps = rec.get("episodes_total", 0)
        assert dumps == expected_dumps, (
            f"cluster obs {name}: {dumps} incident dumps for "
            f"{expected_dumps} divergence episodes"
        )
        rec["incident_dumps"] = dumps
        rec["timeline"] = timeline[-64:]
        out["scenarios"][name] = rec
        msg = f"cluster obs {name}: coverage_rounds={rec['coverage_rounds']}"
        if "detect_rounds" in rec:
            msg += (
                f" detect_rounds={rec['detect_rounds']}"
                f" ({rec['detect_secs']}s)"
                f" heal_rounds={rec['heal_rounds']}"
                f" episodes={rec['episodes_total']} dumps={dumps}"
            )
        print(msg, flush=True)
    return out


def _bank_cluster_obs(rec: dict) -> None:
    """CLUSTER_OBS.json: the cluster-observatory detection baseline —
    its own artifact because topology/convergence rounds re-bank it."""
    path = os.path.join(REPO, "CLUSTER_OBS.json")
    rec["code"] = _soak_fingerprint()
    rec["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"wrote {path}", flush=True)


def _bank(update: dict) -> None:
    """Merge keys into CHAOS_SOAK.json, preserving phases not re-run."""
    path = os.path.join(REPO, "CHAOS_SOAK.json")
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    record.update(update)
    record["code"] = _soak_fingerprint()
    record["measured_at"] = time.strftime(
        "%Y-%m-%d %H:%M:%S", time.gmtime()
    )
    with open(path, "w") as f:
        json.dump(record, f, indent=1)


def _bank_slo_baseline(slo: dict) -> None:
    """SLO_BASELINE.json: the write→event percentile baseline the next
    perf rounds (ingest, sync catch-up) are judged against — its own
    artifact (not CHAOS_SOAK.json) because those rounds re-bank it."""
    path = os.path.join(REPO, "SLO_BASELINE.json")
    slo["code"] = _soak_fingerprint()
    slo["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
    with open(path, "w") as f:
        json.dump(slo, f, indent=1)
    print(f"wrote {path}", flush=True)


def main() -> None:
    args = sys.argv[1:]
    phase_only = None
    if "--phase" in args:
        i = args.index("--phase")
        phase_only = args[i + 1]
        args = args[:i] + args[i + 2:]
    if phase_only == "slo":
        t0 = time.monotonic()
        slo = slo_baseline_phase()
        slo["wall_s"] = round(time.monotonic() - t0, 1)
        _bank_slo_baseline(slo)
        print(json.dumps({"metric": "chaos_soak", "phase": "slo",
                          "scenarios": sorted(slo["scenarios"])}))
        return
    if phase_only == "cluster":
        t0 = time.monotonic()
        cl = cluster_obs_phase()
        cl["wall_s"] = round(time.monotonic() - t0, 1)
        _bank_cluster_obs(cl)
        print(json.dumps({
            "metric": "chaos_soak", "phase": "cluster",
            "detect_rounds": {
                n: s.get("detect_rounds")
                for n, s in cl["scenarios"].items()
            },
        }))
        return
    if phase_only == "flaky-node":
        t0 = time.monotonic()
        fl = flaky_node_phase()
        fl["wall_s"] = round(time.monotonic() - t0, 1)
        _bank({"flaky_node": fl})
        print(json.dumps({"metric": "chaos_soak", "phase": "flaky-node",
                          "runs": len(fl["runs"])}))
        return
    if phase_only is not None:
        raise SystemExit(f"unknown --phase {phase_only!r}")
    seeds = [int(s) for s in args] or [1337, 4242]
    runs = []
    for seed in seeds:
        t0 = time.monotonic()
        # outer bound > the inner wait_progress livelock cap (900 s):
        # a stall must surface as the phase's diagnostic assertion
        summary = asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run_soak(seed), 1200)
        )
        summary["wall_s"] = round(time.monotonic() - t0, 1)
        runs.append(summary)
        print(f"seed {seed}: {len(summary['phases'])} phases, "
              f"{summary['wall_s']}s, sometimes={summary['sometimes']}",
              flush=True)
    t0 = time.monotonic()
    flight = kernel_flight_phase()
    flight["wall_s"] = round(time.monotonic() - t0, 1)
    print(f"kernel flight: detect_ticks={flight['detect_ticks']} "
          f"({len(flight['timeline'])} active ticks)", flush=True)
    t0 = time.monotonic()
    flaky = flaky_node_phase()
    flaky["wall_s"] = round(time.monotonic() - t0, 1)
    t0 = time.monotonic()
    slo = slo_baseline_phase()
    slo["wall_s"] = round(time.monotonic() - t0, 1)
    _bank_slo_baseline(slo)
    t0 = time.monotonic()
    cl = cluster_obs_phase()
    cl["wall_s"] = round(time.monotonic() - t0, 1)
    _bank_cluster_obs(cl)
    _bank({
        "mode": "strict",
        "runs": runs,
        "kernel_flight": flight,
        "flaky_node": flaky,
    })
    print(json.dumps({"metric": "chaos_soak", "runs": len(runs),
                      "all_phases": all(len(r["phases"]) == 6 for r in runs)}))


if __name__ == "__main__":
    main()

"""Bridge scale rung: ONE real agent tracking a kernel-simulated
population via normal SWIM channels (models/bridge.py).

The devcluster lineage (`klukai-devcluster/src/main.rs:107-232`) tops
out at a handful of real processes; the kernel-peer bridge replaces the
population with array state, so a single real agent exercises its
production membership pipeline against thousands of peers. Records
absorption time (announce → full member table) and silent-crash
detection latency at the configured scale into BRIDGE_SCALE.json.

Usage: python scripts/bridge_scale.py [n_sim] [n_crash] [mode]
       (default 10000 20 silent)

Detection modes:
  silent — crashed virtual members just go quiet; the ONE real agent's
           own probe/suspicion pipeline must find them. Detection is
           probe-sweep-bound (~n * probe_period), which is the honest
           single-prober physics: this mode pins the production
           pipeline and is the default through the 10k rung.
  gossip — the bridge gossips the kernel's ground-truth DOWNs (the
           bridge default in production use): detection reaches the
           agent epidemically, the way a real n-member cluster
           collectively detects (whoever probes the dead gossips it).
           The 100k rung uses this mode — a lone prober sweeping 100k
           members would need ~84 min per cycle by construction, not
           by defect.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()
jaxenv.enable_compilation_cache()

from corrosion_tpu.models.bridge import KernelPeerBridge, sim_actor_id  # noqa: E402
from corrosion_tpu.models.cluster import ClusterSim  # noqa: E402
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.records import merge_records  # noqa: E402

from tests.test_agent import boot, wait_progress, wait_until  # noqa: E402


async def main(n_sim: int, n_crash: int, mode: str = "silent") -> dict:
    net = MemNetwork(seed=11)
    sim = ClusterSim(n_sim, seed=3)
    bridge = KernelPeerBridge(net, sim, seed=5, gossip_down=(mode == "gossip"))
    bridge.start()
    agent = await boot(net, "agent-real")
    ms = agent.membership
    try:
        t0 = time.monotonic()
        await ms.announce(bridge.addr(0))
        # progress-based (r4 weak #6 pattern): absorption may take
        # minutes at 100k — only a genuine STALL fails the rung
        absorbed = await wait_progress(
            lambda: ms.cluster_size >= n_sim + 1,
            lambda: ms.cluster_size,
            stall=60.0, cap=3600.0, step=0.25,
        )
        absorb_s = time.monotonic() - t0
        print(f"absorbed={absorbed} size={ms.cluster_size} "
              f"in {absorb_s:.1f}s", flush=True)

        dead = (
            list(range(0, n_sim, max(1, n_sim // n_crash)))[:n_crash]
            if n_crash > 0
            else []  # pure-absorption rung
        )
        dead_ids = {sim_actor_id(j) for j in dead}
        for j in dead:
            bridge.crash(j)
        # one real prober sweeps the ring at ~probe_period per member:
        # worst-case detection of the LAST crash ≈ a full cycle + the
        # suspicion window — give it two cycles of headroom
        detect_budget = max(600.0, n_sim * 0.05 * 2 + 120.0)
        t0 = time.monotonic()
        detected = await wait_until(
            lambda: dead_ids <= set(ms.downed), timeout=detect_budget,
            step=0.25,
        )
        detect_s = time.monotonic() - t0
        fp = sorted(str(i) for i in set(ms.downed) - dead_ids)
        print(f"detected={detected} in {detect_s:.1f}s fp={len(fp)}",
              flush=True)
        return {
            "rung": f"bridge-{n_sim}" + ("" if mode == "silent" else f"-{mode}"),
            "n_sim": n_sim,
            "n_crash": len(dead),
            "mode": mode,
            "absorbed": absorbed,
            "absorb_s": round(absorb_s, 1),
            "detected": detected,
            "detect_all_s": round(detect_s, 1),
            "false_positives": len(fp),
            "cluster_size": ms.cluster_size,
        }
    finally:
        from corrosion_tpu.agent.run import shutdown

        await shutdown(agent)
        await bridge.stop()


if __name__ == "__main__":
    n_sim = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    n_crash = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    mode = sys.argv[3] if len(sys.argv) > 3 else "silent"
    rec = asyncio.run(main(n_sim, n_crash, mode))
    merge_records(os.path.join(REPO, "BRIDGE_SCALE.json"), [rec])
    print(json.dumps(rec))

"""Opportunistic TPU measurement battery: wait for the tunnel, then measure.

The driver image's TPU tunnel (axon platform) is intermittently available:
it can be up for minutes and then wedge so hard that even ``jax.devices()``
hangs (see corrosion_tpu/runtime/jaxenv.py).  Round-2/3 history: the tunnel
was up at the start of each round and wedged minutes later, so every missed
window costs a round's worth of real-chip evidence.

This script turns that around: it probes the tunnel on a cadence (bounded
subprocess — a wedged backend can never hang the watcher), and the moment a
probe succeeds it runs the measurement battery **serially, one jax client
at a time** (two concurrent clients are suspected to wedge the tunnel):

  smoke, then headline benches first (bench10k/40k + shift A/Bs), the
  pview convergence rungs (100k/262k), phase profiles (10k/40k), and the
  long gambles (bench80k) last — see battery_steps() for the live list.

Steps that completed successfully are never re-run; a step that fails or
times out sends the watcher back to probing (the tunnel likely died
mid-battery) and is retried on the next window.  State in TPU_HUNT.json.

Usage:  python scripts/tpu_hunter.py            # run until battery done
Env:    TPU_HUNT_BUDGET_S (default 21600), TPU_HUNT_PROBE_S (default 90),
        TPU_HUNT_COOLDOWN_S (wait between probes, default 150)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

STATE_PATH = os.path.join(REPO, "TPU_HUNT.json")


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": [], "attempts": {}, "windows": []}


def save_state(state: dict) -> None:
    with open(STATE_PATH, "w") as f:
        json.dump(state, f, indent=1)


def run_step(name: str, argv: list[str], env_extra: dict, timeout: float,
             outfile: str) -> bool:
    """Run one battery step as a bounded subprocess; tee output to a file.

    Success = exit 0 within the timeout.  Only a SUCCESSFUL run replaces
    ``outfile`` (atomically) — a redo step that dies mid-run must not
    clobber a good record the round-end replay depends on.  Failures
    leave their evidence in ``<outfile>.failed`` instead.
    """
    env = os.environ.copy()
    env.update(env_extra)
    t0 = time.monotonic()
    log(f"step {name}: {' '.join(argv)} (timeout {timeout:.0f}s)")
    try:
        proc = subprocess.run(
            argv, env=env, timeout=timeout, capture_output=True, text=True,
            cwd=REPO,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        err += f"\n[tpu_hunter] TIMEOUT after {timeout:.0f}s"
    wall = time.monotonic() - t0
    # every battery step is a TPU measurement: a child that silently
    # landed on a CPU fallback (half-dead tunnel) must not count as
    # success nor replace a good on-chip record
    on_tpu = 'platform=tpu' in out or '"platform": "tpu"' in out
    ok = rc == 0 and on_tpu
    dest = outfile if ok else outfile + ".failed"
    path = os.path.join(REPO, dest)
    with open(path + ".part", "w") as f:
        f.write(out)
        if err.strip():
            f.write("\n--- stderr tail ---\n" + err[-4000:])
    os.replace(path + ".part", path)
    log(f"step {name}: rc={rc} on_tpu={on_tpu} wall={wall:.0f}s -> {dest}")
    return ok




def battery_steps() -> list[tuple[str, list[str], dict, float, str]]:
    py = sys.executable
    # no BENCH_RECORD_EVERY override: the TPU runs must use bench.py's
    # default cadence so records stay comparable with the CPU baselines
    bench_env = {"CORRO_BENCH_CHILD": "1"}
    return [
        ("smoke",
         [py, "-u", "scripts/profile_swim.py", "1024", "4"],
         {}, 900.0, "TPU_PROFILE_1k.txt"),
        # HEADLINE BENCHES FIRST (r4 lesson: profile10k burned a 30-min
        # timeout on a window that wedged 15 s in; the benches are what
        # BENCH_r{N} replays, so they bank before anything else)
        ("bench10k",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "10000"}, 1500.0, "BENCH_TPU_10k.json"),
        ("bench40k",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "40000"}, 2400.0, "BENCH_TPU_40k.json"),
        # shift is the default since the r5 flip (COMPONENTS.md); the
        # A/B direction reverses — these measure the OLD pick mode so a
        # chip window can still overturn the CPU-evidence decision
        ("bench10k_pick",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "10000", "BENCH_GOSSIP_MODE": "pick"},
         1500.0, "BENCH_TPU_10k_pick.json"),
        ("bench40k_pick",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "40000", "BENCH_GOSSIP_MODE": "pick"},
         2400.0, "BENCH_TPU_40k_pick.json"),
        # phase tables with the fixed pallas kernel and per-iteration
        # input variation; 40k shows where its per-tick time goes
        ("profile10k",
         [py, "-u", "scripts/profile_swim.py", "10000"],
         {}, 1200.0, "TPU_PROFILE_10k.txt"),
        ("profile40k",
         [py, "-u", "scripts/profile_swim.py", "40000", "4"],
         {}, 1800.0, "TPU_PROFILE_40k.txt"),
        # the sort-impl A/B the r3 phase table motivated
        ("bench10k_sort",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "10000", "BENCH_INBOX_IMPL": "sort"},
         1500.0, "BENCH_TPU_10k_sort.json"),
        # the long gambles last: a mid-step tunnel death costs the
        # whole remaining window
        # int16 view: [80k,80k] = 12.8 GB, fits one 16 GB v5e chip donated
        ("bench80k",
         [py, "-u", "bench.py"],
         {**bench_env, "BENCH_N": "80000"}, 3000.0, "BENCH_TPU_80k.json"),
        # on-chip boot-convergence ladder above 100k (r4 verdict item 6's
        # on-chip option), matching the CPU ladder's boot-only shape —
        # the churn tail is detection-protocol-bound (~1625+ ticks),
        # affordable only at 100k on a shared window.  512k = 4.3 GB
        # table, 1M = 8.6 GB — both fit the 16 GB chip with the donated
        # tick; 2M (16.8 GB table) does not
        ("pview262k_boot",
         [py, "-u", "scripts/pview_converge.py", "262144", "2048"],
         {"PVIEW_SKIP_CHURN": "1"}, 2400.0, "TPU_PVIEW_CONV_262k.txt"),
        ("pview512k_boot",
         [py, "-u", "scripts/pview_converge.py", "524288", "2048"],
         {"PVIEW_SKIP_CHURN": "1"}, 3600.0, "TPU_PVIEW_CONV_512k.txt"),
        # VERDICT r4 item 5's chip half: the array-merge A/B was
        # CPU-measured (native wins 3-4x); this measures whether the
        # chip overturns it at sync-flood batch sizes.  Own artifact
        # file — must not clobber the banked CPU record.
        ("crdt_ab_tpu",
         [py, "-u", "scripts/bench_crdt_merge.py", "--tpu",
          "--out", "CRDT_MERGE_AB_TPU.json"],
         {}, 1800.0, "TPU_CRDT_AB.txt"),
        # the true long gambles: the 100k full-churn bar (VERDICT r3
        # item 2 on chip — the churn tail is protocol-bound at ~1625
        # ticks, the CPU record's count exactly; cap sized from the
        # measured ~1.7 s/tick) and the 1M boot rung.  These run LAST:
        # the first 100k attempt cost a whole window to a hung init
        # (since replaced), and a 5400 s step must never gate the
        # cheap banks.
        ("pview100k_conv",
         [py, "-u", "scripts/pview_converge.py", "100000", "2048"],
         {}, 5400.0, "TPU_PVIEW_CONV_100k.txt"),
        # (pview1m_boot was dropped: 1M x 2048 is blocked by a
        # compiler-inserted whole-table copy — 2 x 8 GiB > HBM — and
        # K=1024 under-provisions connectivity; both documented with
        # evidence in PROFILE.md "1M on chip". Re-add the step when the
        # tick's in-place story changes.)
        # (the legacy pview100k inline-code step was dropped: its 0.95
        # coverage bar is strictly weaker than pview100k_conv's 0.99 +
        # churn phase — a live window must not pay for the same rung twice)
    ]


def main() -> None:
    budget = float(os.environ.get("TPU_HUNT_BUDGET_S", "21600"))
    probe_s = float(os.environ.get("TPU_HUNT_PROBE_S", "90"))
    cooldown = float(os.environ.get("TPU_HUNT_COOLDOWN_S", "150"))
    t_start = time.monotonic()
    state = load_state()
    steps = battery_steps()

    # Completed steps record the measured-code fingerprint so a later
    # session can tell whether an artifact matches the tree (bench.py
    # replay independently REJECTS records whose embedded code_sha does
    # not match HEAD — r4 verdict: a chip number must be tied to a code
    # version).  Fingerprints are recomputed every loop turn so a hunter
    # that outlives a code edit re-runs the affected steps instead of
    # leaving a stale "done" mark shadowing the new code.  Each step's
    # fingerprint covers the kernel files bench.py declares measured
    # PLUS the step's own entry script (profile/pview edits must stale
    # their steps too) plus the pview kernel for pview steps.
    done_sha = state.setdefault("done_sha", {})

    def step_fingerprint(name: str, argv: list[str]) -> dict:
        import hashlib

        from bench import _code_fingerprint

        out = _code_fingerprint()
        extras = [a for a in argv[1:] if a.endswith(".py")]
        if "pview" in name:
            extras.append("corrosion_tpu/ops/swim_pview.py")
        for rel in extras:
            try:
                with open(os.path.join(REPO, rel), "rb") as f:
                    out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
            except OSError:
                out[rel] = "missing"
        return out

    by_name = {s[0]: s for s in steps}
    while time.monotonic() - t_start < budget:
        stale = [
            name for name in state["done"]
            if name in by_name
            and done_sha.get(name) != step_fingerprint(name, by_name[name][1])
        ]
        if stale:
            log(f"measured code changed; re-queueing stale steps: {stale}")
            state["done"] = [n for n in state["done"] if n not in stale]
            save_state(state)
        pending = [s for s in steps if s[0] not in state["done"]]
        # a step that keeps failing (e.g. deterministically outruns its
        # timeout) must not starve the queue — but ONE failure proves
        # nothing (the common case is the tunnel dying under the step,
        # and a single wedge must not demote a headline bench behind the
        # long gambles).  Demote only from the second FAILURE on —
        # counted separately from attempts, which also tally successful
        # runs (a stale-fingerprint re-queue must not demote a bench for
        # having succeeded before).  Stable sort keeps battery order.
        failures = state.setdefault("failures", {})
        pending.sort(key=lambda s: max(0, failures.get(s[0], 0) - 1))
        if not pending:
            log("battery complete")
            return
        platform = jaxenv.probe(None, probe_s)
        if platform in (None, "cpu"):
            log(f"tunnel down (probe -> {platform}); sleeping {cooldown:.0f}s; "
                f"pending: {[s[0] for s in pending]}")
            time.sleep(cooldown)
            continue
        log(f"tunnel UP (platform={platform}); starting battery window")
        state["windows"].append(time.strftime("%Y-%m-%d %H:%M:%S"))
        save_state(state)
        for name, argv, env_extra, timeout, outfile in pending:
            remaining = budget - (time.monotonic() - t_start)
            if remaining < 120:
                break
            # fingerprint per step, not per window: a battery window can
            # span hours, and a mid-window code edit must tag only the
            # steps that actually measured the old code
            step_sha = step_fingerprint(name, argv)
            ok = run_step(name, argv, env_extra, min(timeout, remaining),
                          outfile)
            # attempts = run-count telemetry for the round logs; the
            # demotion sort reads ONLY the failures dict
            state["attempts"][name] = state["attempts"].get(name, 0) + 1
            if ok:
                state["done"].append(name)
                done_sha[name] = step_sha
                # a success clears the failure history: a later stale
                # re-queue must treat this step as healthy, not demoted
                state.setdefault("failures", {}).pop(name, None)
                save_state(state)
                # brief pause so the tunnel's client slot is fully released
                time.sleep(10)
            else:
                fails = state.setdefault("failures", {})
                fails[name] = fails.get(name, 0) + 1
                save_state(state)
                log("step failed; returning to probe loop")
                time.sleep(cooldown)
                break
    log(f"budget exhausted; done={state['done']}")


if __name__ == "__main__":
    main()

"""Metric-name drift lint: call sites ↔ COMPONENTS.md observability table.

Every series the code can emit must be documented in the COMPONENTS.md
"Observability" table, and every documented series must still have an
emitting call site — otherwise dashboards rot silently (the reference's
`metrics.rs` principle: the inventory IS the contract).  Wired as a
tier-1 test (`tests/test_metrics_lint.py`) so drift fails CI.

What counts as a call site: any
`<registry>.counter(/gauge(/histogram(/latency(`
whose first argument is a string literal (possibly on the next line),
scanned over `corrosion_tpu/` and `scripts/`.  f-string names (one site:
the write-gate lane gauges) are matched as wildcards — every table entry
the pattern covers is considered emitted, and the pattern must cover at
least one entry.

Usage:  python scripts/lint_metrics.py   (exit 0 clean / 1 drift)
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CALL_RE = re.compile(
    r"\.(counter|gauge|histogram|latency)\(\s*(f?)\"([^\"\n]+)\"", re.S
)
_TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")

TABLE_BEGIN = "<!-- metrics-table:begin -->"
TABLE_END = "<!-- metrics-table:end -->"

SCAN_DIRS = ("corrosion_tpu", "scripts")


def scan_call_sites() -> Tuple[Dict[str, Set[str]], List[str]]:
    """(literal series name → emitting files, f-string wildcard regexes)."""
    literals: Dict[str, Set[str]] = {}
    wildcards: List[str] = []
    for top in SCAN_DIRS:
        for dirpath, _dirs, files in os.walk(os.path.join(REPO, top)):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, REPO)
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                for m in _CALL_RE.finditer(text):
                    is_f, name = m.group(2), m.group(3)
                    if is_f:
                        # {expr} holes become wildcards over one label
                        # segment; the pattern must cover ≥1 table row
                        pat = "^" + re.sub(
                            r"\\\{[^}]*\\\}", "[^.]+",
                            re.escape(name)
                        ) + "$"
                        wildcards.append(pat)
                    else:
                        literals.setdefault(name, set()).add(rel)
    return literals, wildcards


def parse_components_table() -> List[str]:
    """Backticked series names from column 1 of the fenced table."""
    path = os.path.join(REPO, "COMPONENTS.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if TABLE_BEGIN not in text or TABLE_END not in text:
        raise SystemExit(
            f"COMPONENTS.md is missing the {TABLE_BEGIN}/{TABLE_END} "
            "markers around the observability table"
        )
    section = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    names = []
    for line in section.splitlines():
        m = _TABLE_ROW_RE.match(line.strip())
        if m:
            names.append(m.group(1))
    return names


def lint() -> List[str]:
    """Return a list of drift complaints (empty = clean)."""
    literals, wildcards = scan_call_sites()
    table = parse_components_table()
    table_set = set(table)
    problems: List[str] = []

    dupes = {n for n in table_set if table.count(n) > 1}
    for n in sorted(dupes):
        problems.append(f"duplicate table row: {n}")

    for name in sorted(literals):
        if name not in table_set:
            where = ", ".join(sorted(literals[name]))
            problems.append(
                f"emitted but undocumented: {name} ({where}) — add a row "
                "to the COMPONENTS.md observability table"
            )

    covered_by_wildcard: Set[str] = set()
    for pat in wildcards:
        hits = {n for n in table_set if re.match(pat, n)}
        if not hits:
            problems.append(
                f"f-string call site matches no table row: /{pat}/"
            )
        covered_by_wildcard |= hits

    for name in sorted(table_set):
        if name not in literals and name not in covered_by_wildcard:
            problems.append(
                f"documented but never emitted: {name} — remove the row "
                "or restore the call site"
            )
    return problems


def main() -> None:
    problems = lint()
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}")
        print(f"lint_metrics: {len(problems)} problem(s)")
        sys.exit(1)
    literals, wildcards = scan_call_sites()
    print(
        f"lint_metrics: OK — {len(literals)} literal series + "
        f"{len(wildcards)} wildcard site(s) match the COMPONENTS.md table"
    )
    sys.exit(0)


if __name__ == "__main__":
    main()

"""Back-compat shim: the metric-name drift lint moved into the
corro-analyze framework (`corrosion_tpu/analysis/metricsdoc.py`, rule
`metrics-doc`) so ONE driver — `scripts/corro_lint.py` — runs every
static-analysis rule.  This shim keeps the r7 CLI and the module API
(`scan_call_sites` / `parse_components_table` / `lint`) stable for
existing callers and tests/test_metrics_lint.py.

Usage:  python scripts/lint_metrics.py   (exit 0 clean / 1 drift)
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from corrosion_tpu.analysis import metricsdoc  # noqa: E402


def scan_call_sites() -> Tuple[Dict[str, Set[str]], List[str]]:
    return metricsdoc.scan_call_sites(REPO)


def parse_components_table() -> List[str]:
    return metricsdoc.parse_components_table(REPO)


def lint() -> List[str]:
    return metricsdoc.lint(REPO)


def main() -> None:
    problems = lint()
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}")
        print(f"lint_metrics: {len(problems)} problem(s)")
        sys.exit(1)
    literals, wildcards = scan_call_sites()
    print(
        f"lint_metrics: OK — {len(literals)} literal series + "
        f"{len(wildcards)} wildcard site(s) match the COMPONENTS.md table"
    )
    sys.exit(0)


if __name__ == "__main__":
    main()

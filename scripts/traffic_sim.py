"""Production traffic simulator + chaos scenario matrix → TRAFFIC_SIM.json
(r18).

The standing "does the whole system still serve under X" gate: an
N-agent devcluster (gossip over MemNetwork, REAL HTTP APIs per node)
runs the mixed workload (`chaos/workload.py`: writes, point queries,
live subscriptions, template renders) while the `ChaosEngine`
(`chaos/scenarios.py`) lands one scenario at a time across the three
fault layers — then restores and measures recovery.

Per scenario the record banks:
- per-stage client-observed p50/p99 + the four-way op accounting
  (ok / typed refusals / fast errors / TIMEOUTS — the hang witness),
- availability = (ok + refusals) / attempts,
- the cluster's OWN scorecard scraped from /v1/slo (windowed
  write→event stage percentiles) and /v1/cluster (digest coverage +
  divergence verdict),
- recovery: seconds from restore() until a fresh probe write converges
  on every node, row counts agree everywhere, and the divergence
  detector reports one view group — the closing zero-divergence
  verdict.

Bars asserted BEFORE banking (the same ones tests/test_traffic_sim.py
guards against the banked artifact): zero op timeouts in EVERY
scenario (faults may shrink `ok`, never convert requests into stalls —
Prime CCL, arXiv:2505.14065), availability floors, recovery under the
cap, zero divergence at close.  Scenario shapes follow Potato
(arXiv:2308.12698): geo-latency matrices, slow/heterogeneous nodes.

r22 adds the remediation A/B axis: `--remediation` runs the full
matrix TWICE at steady (production-shaped) sync cadence — once
observe-only (the `[remediation]` kill-switch default) and once armed
— and banks the per-scenario recovery walls side by side under the
`remediation_ab` key (`r22`-tagged, BESIDE the preserved r18 top-level
records, not over them).  The bar: the armed side strictly improves
recovery-to-zero-divergence on ≥3 faulted scenarios, with zero
regressions (timeouts==0 everywhere, availability floors held) and
every fired action a typed cooldown-stamped flight-recorded event
served by `GET /v1/remediation`.

Usage:
    python scripts/traffic_sim.py            # full matrix → TRAFFIC_SIM.json
    python scripts/traffic_sim.py --tier1    # tiny-shape subset, no banking
                                             # (what tests/test_traffic_sim.py
                                             # runs in-suite, ≤10 s)
    python scripts/traffic_sim.py --remediation   # A/B → remediation_ab key
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sys
import time
import zlib
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess()

from corrosion_tpu.agent.run import (  # noqa: E402
    make_broadcastable_changes,
    run as run_agent,
    setup,
    shutdown,
)
from corrosion_tpu.agent import syncer  # noqa: E402
from corrosion_tpu.agent.membership import SwimConfig  # noqa: E402
from corrosion_tpu.api.http import ApiServer  # noqa: E402
from corrosion_tpu.chaos.scenarios import (  # noqa: E402
    ChaosEngine,
    Injection,
    Scenario,
    asymmetric_partition,
    churn_storm,
    flap_storm,
    geo_latency,
    sick_disk,
    slow_disk,
    zombie_node,
)
from corrosion_tpu.chaos.workload import (  # noqa: E402
    MixedWorkload,
    WorkloadNode,
)
from corrosion_tpu.client import CorrosionApiClient  # noqa: E402
from corrosion_tpu.net.mem import MemNetwork  # noqa: E402
from corrosion_tpu.runtime.config import Config  # noqa: E402
from corrosion_tpu.runtime.tmpdb import fresh_db_path  # noqa: E402

TEST_SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
)

_MEASURED_FILES = (
    "corrosion_tpu/chaos/faults.py",
    "corrosion_tpu/chaos/scenarios.py",
    "corrosion_tpu/chaos/workload.py",
    "corrosion_tpu/net/mem.py",
    "corrosion_tpu/agent/syncer.py",
    "corrosion_tpu/agent/remediation.py",
    "scripts/traffic_sim.py",
)


def _code_fingerprint() -> dict:
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


class SimNode:
    """One node's full lifecycle: agent + HTTP API + client, restartable
    in place (same db file, same gossip addr) so churn rides the real
    boot/rejoin path."""

    def __init__(self, name: str, net: MemNetwork, bootstrap: Tuple[str, ...],
                 tune, swim: SwimConfig):
        self.name = name
        self.net = net
        self.bootstrap = bootstrap
        self.tune = tune
        self.swim = swim
        self.db_path = fresh_db_path(f"tsim-{name}")
        self.agent = None
        self.api: Optional[ApiServer] = None
        self.client: Optional[CorrosionApiClient] = None

    async def start(self) -> None:
        cfg = Config()
        cfg.db.path = self.db_path
        cfg.gossip.bind_addr = self.name
        cfg.gossip.bootstrap = list(self.bootstrap)
        cfg.perf.broadcast_interval_ms = 20
        cfg.perf.apply_queue_timeout_ms = 5
        cfg.perf.sync_interval_min_secs = 0.1
        cfg.perf.sync_interval_max_secs = 0.5
        cfg.cluster.digest_interval_secs = 0.3
        cfg.api.bind_addr = ["127.0.0.1:0"]
        if self.tune:
            self.tune(cfg)
        agent = await setup(cfg, network=self.net)
        agent.membership.config = self.swim
        agent.store.apply_schema_sql(TEST_SCHEMA)
        await run_agent(agent)
        self.agent = agent
        self.api = ApiServer(agent)
        await self.api.start()
        self.client = CorrosionApiClient(self.api.addrs[0])

    async def stop(self) -> None:
        if self.client is not None:
            await self.client.close()
            self.client = None
        if self.api is not None:
            await self.api.stop()
            self.api = None
        if self.agent is not None:
            await shutdown(self.agent)
            self.agent = None

    @property
    def workload_node(self) -> Optional[WorkloadNode]:
        if self.agent is None or self.client is None or self.api is None:
            return None
        return WorkloadNode(
            name=self.name,
            agent=self.agent,
            client=self.client,
            api_addr=self.api.addrs[0],
        )


class TrafficSim:
    """The harness: cluster lifecycle + one scenario run at a time."""

    def __init__(self, tiny: bool = False, seed: int = 31,
                 remediation: bool = False, steady_sync: bool = False):
        self.tiny = tiny
        self.remediation = remediation
        self.steady_sync = steady_sync
        self.net = MemNetwork(seed=seed)
        self.engine = ChaosEngine()
        n = 3 if tiny else 4
        # suspicion window longer than any fault window (the bench_sync
        # chaos-phase discipline): members stay at worst SUSPECT through
        # a scenario and refute on restore, so recovery measures the
        # SYNC/serving planes, not a full SWIM eviction/rejoin cycle
        self.swim = SwimConfig(
            probe_period=0.12 if tiny else 0.25,
            probe_rtt=0.05 if tiny else 0.1,
            suspicion_mult=8,
            # prompt re-announce after an eviction window: the knob an
            # operator running frequent-fault topologies would set (the
            # announce_wake fix makes isolation START this ramp at
            # once; the ramp bounds how fast it then lands)
            announce_backoff_start=0.3,
            announce_backoff_max=2.0,
        )
        self.duration = 0.8 if tiny else 6.0
        self.recovery_cap = 8.0 if tiny else 45.0
        # alert settle caps: how long after the workload window (faults
        # still injected) the expected typed alert may take to reach
        # FIRING, and how long after recovery it may take to resolve.
        # The tiny caps are sized for FULL-SUITE load, not a quiet
        # host: the 0.08 s TSDB/eval cadence is an asyncio task that a
        # loaded 1-core runner starves, so the rate rule can need
        # several extra seconds to see the error samples (r21 — the
        # poll exits early on success, so the nominal wall is
        # unchanged; only a genuinely late alert spends the headroom).
        self.alert_fire_cap = 8.0 if tiny else 10.0
        self.alert_resolve_cap = 8.0 if tiny else 12.0
        self.nodes: Dict[str, SimNode] = {}

        def tune(cfg):
            cfg.sync.circuit_reset_secs = 1.0 if tiny else 3.0
            # r20 alerting plane at scenario-window timescales: fast
            # TSDB sampling/eval and for-durations scaled so a typed
            # alert can complete pending→firing inside the fault window
            # and resolve inside the recovery window (the health score
            # may still widen them Lifeguard-style — the caps above
            # leave room for the worst-case ×4)
            cfg.tsdb.sample_interval_secs = 0.08 if tiny else 0.25
            cfg.alerts.eval_interval_secs = 0.08 if tiny else 0.2
            cfg.alerts.for_scale = 0.04 if tiny else 0.15
            if self.steady_sync:
                # the A/B axis runs at production-shaped anti-entropy
                # cadence on BOTH sides (same config, only the arming
                # bit differs): recovery-off is then dominated by the
                # sync backoff — exactly the gap the view-divergence
                # actuator exists to close
                cfg.perf.sync_interval_min_secs = 1.0
                cfg.perf.sync_interval_max_secs = 4.0
            # r23 continuous profiler at scenario timescales: short
            # fold windows so a capture's lookback is dominated by the
            # scenario that triggered it, and a loosened overhead
            # budget — the loaded 1-core tiny replica would otherwise
            # shed to 11 Hz instantly and starve the fault window of
            # samples (the production ≤2% budget is proven where it
            # belongs, on the quiet ingest-bench rung)
            cfg.profile.window_secs = 1.0 if tiny else 5.0
            cfg.profile.max_overhead_pct = 4.0 if tiny else 1.0
            # the supervisor TICK is scaled for observe-only runs too
            # (the same timescale discipline the tsdb/alert cadences
            # above get): a tiny-shape firing window is ~0.5 s, so the
            # 2 s production tick would make the would_act audit trail
            # a phase race instead of a recorded fact
            cfg.remediation.tick_secs = 0.1 if tiny else 0.25
            if self.remediation:
                # r22: arm the plane, cooldowns/sustain scaled to the
                # scenario-window timescale (the same scaling the
                # alerting plane above gets)
                cfg.remediation.enabled = True
                cfg.remediation.act_timeout_secs = 0.8 if tiny else 1.5
                cfg.remediation.sync_cooldown_secs = 0.4 if tiny else 0.75
                cfg.remediation.drain_cooldown_secs = 1.0 if tiny else 2.0
                cfg.remediation.shed_cooldown_secs = 0.5 if tiny else 1.0
                cfg.remediation.slo_sustain_secs = 0.3 if tiny else 1.0
                cfg.remediation.refuse_bulk_secs = 1.5 if tiny else 3.0

        names = [f"n{i}" for i in range(n)]
        for name in names:
            bootstrap = () if name == "n0" else ("n0",)
            self.nodes[name] = SimNode(
                name, self.net, bootstrap, tune, self.swim
            )
        self._probe_id = 50_000_000
        self._id_base = 0

    def live_nodes(self) -> Dict[str, WorkloadNode]:
        out = {}
        for name, node in self.nodes.items():
            wn = node.workload_node
            if wn is not None:
                out[name] = wn
        return out

    async def start_cluster(self) -> None:
        for node in self.nodes.values():
            await node.start()
        # full membership before any scenario lands
        deadline = time.monotonic() + 30
        n = len(self.nodes)
        while time.monotonic() < deadline:
            if all(
                node.agent.membership.cluster_size == n
                for node in self.nodes.values()
            ):
                return
            await asyncio.sleep(0.05)
        raise RuntimeError("cluster never converged at boot")

    async def stop_cluster(self) -> None:
        for node in self.nodes.values():
            await node.stop()

    # -- measurement helpers ------------------------------------------------

    def _row_counts(self) -> Dict[str, int]:
        out = {}
        for name, node in self.nodes.items():
            if node.agent is None:
                continue
            conn = node.agent.store.read_conn()
            try:
                out[name] = conn.execute(
                    "SELECT COUNT(*) FROM tests"
                ).fetchone()[0]
            finally:
                conn.close()
        return out

    def _divergence_zero(self) -> bool:
        obs = self.nodes["n0"].agent.observatory
        v = obs.check_divergence()
        return not v["divergent"] and v["groups"] == 1 and not v["silent"]

    async def measure_recovery(self) -> dict:
        """Seconds from restore() until a fresh probe write converges on
        every node, row counts agree, and the divergence detector reports
        one view group."""
        t0 = time.monotonic()
        deadline = t0 + self.recovery_cap
        self._probe_id += 1
        probe = self._probe_id
        wrote = False
        recovered_at = None
        while time.monotonic() < deadline:
            if not wrote:
                try:
                    await make_broadcastable_changes(
                        self.nodes["n0"].agent,
                        lambda tx: [tx.execute(
                            "INSERT OR REPLACE INTO tests (id, text)"
                            " VALUES (?, ?)", [probe, "probe"],
                        )],
                    )
                    wrote = True
                except Exception:
                    await asyncio.sleep(0.1)
                    continue
            counts = self._row_counts()
            same_rows = len(set(counts.values())) == 1
            if same_rows and self._divergence_zero():
                recovered_at = time.monotonic()
                break
            await asyncio.sleep(0.1)
        counts = self._row_counts()
        return {
            "secs": (
                round(recovered_at - t0, 3)
                if recovered_at is not None else None
            ),
            "converged": len(set(counts.values())) == 1,
            "rows": max(counts.values()) if counts else 0,
            "divergence_zero": self._divergence_zero(),
        }

    # -- alert observation --------------------------------------------------

    # the drill-vs-outage proof (r20): each fault scenario that has a
    # typed alert in the default pack must RAISE it while injected
    # (with the drill mark, since the chaos census is populated) and
    # RESOLVE it after restore()
    EXPECTED_ALERTS = {
        "sick-disk": "store-faults",
        "slow-disk": "commit-stall",
        "zombie-node": "view-divergence",
    }

    async def _scrape_alerts(self) -> Optional[dict]:
        wn = self.nodes["n0"].workload_node
        if wn is None:
            return None
        from corrosion_tpu.chaos.workload import MixedWorkload

        return await MixedWorkload(self.live_nodes).scrape(
            wn, "/v1/alerts?history=0"
        )

    @staticmethod
    def _alert_row(report: Optional[dict], rule: str) -> Optional[dict]:
        for r in (report or {}).get("rules", []):
            if r["rule"] == rule:
                return r
        return None

    async def _await_alert_state(
        self, rule: str, want_firing: bool, cap: float
    ) -> Optional[dict]:
        """Poll n0's /v1/alerts until `rule` reaches (or leaves) the
        FIRING state; returns the final report (never raises — the
        bars judge the banked outcome)."""
        deadline = time.monotonic() + cap
        report = None
        while time.monotonic() < deadline:
            report = await self._scrape_alerts()
            row = self._alert_row(report, rule)
            if row is not None and (row["state"] == "firing") == want_firing:
                break
            await asyncio.sleep(0.1)
        return report

    # -- one scenario -------------------------------------------------------

    async def run_scenario(
        self, scenario_id: str, injections: List[Injection]
    ) -> dict:
        self._id_base += 1_000_000  # fresh pk range per scenario
        scenario_wall0 = time.time()  # to window remediation events
        workload = MixedWorkload(
            self.live_nodes,
            op_timeout_secs=3.0 if self.tiny else 5.0,
            write_period_secs=0.04 if self.tiny else 0.03,
            query_period_secs=0.05 if self.tiny else 0.04,
            render_period_secs=0.3 if self.tiny else 0.25,
            seed=zlib.crc32(scenario_id.encode()) & 0xFFFF,
            id_base=self._id_base,
        )
        await self.engine.apply(Scenario(scenario_id, injections))
        await workload.start()
        await asyncio.sleep(self.duration)
        await workload.stop()
        # scrape the cluster's own scorecard from a node no scenario
        # injects faults into (n0 is the sim's designated control node)
        summary = await workload.summary(
            scrape_node=self.nodes["n0"].workload_node
        )
        # r20 alert proof, injection half: faults are STILL live here —
        # the scenario's typed alert must be firing (drill-marked) on
        # the alerting plane before restore() is allowed to clear it
        expected_alert = self.EXPECTED_ALERTS.get(scenario_id)
        alerts_during = None
        if expected_alert is not None:
            alerts_during = await self._await_alert_state(
                expected_alert, want_firing=True, cap=self.alert_fire_cap
            )
        await self.engine.restore()
        recovery = await self.measure_recovery()
        alerts_after = None
        if expected_alert is not None:
            alerts_after = await self._await_alert_state(
                expected_alert, want_firing=False,
                cap=self.alert_resolve_cap,
            )
        rec = {
            "scenario": scenario_id,
            "injections": [
                f"[{i.layer}] {i.summary}" for i in injections
            ],
            "duration_secs": self.duration,
            "recovery": recovery,
            **summary,
        }
        # r22: scrape every live node's GET /v1/remediation and bank
        # THIS scenario's decision trail (the supervisor history is
        # process-lived, so window it by the scenario's wall start) —
        # armed runs bank acted/reverted events, observe-only runs
        # bank the kill-switch's would_act audit trail
        rem_events: List[dict] = []
        rem_counts: Dict[str, dict] = {}
        for name in self.nodes:
            wn = self.nodes[name].workload_node
            if wn is None:
                continue
            rep = await workload.scrape(wn, "/v1/remediation")
            if not rep:
                continue
            rem_counts[name] = rep.get("counts", {})
            for ev in rep.get("history", []):
                if ev.get("wall", 0.0) >= scenario_wall0:
                    rem_events.append({"node": name, **ev})
        rec["remediation"] = {
            "armed": self.remediation,
            "events": rem_events,
            "counts": rem_counts,
        }
        if expected_alert is not None:
            during_row = self._alert_row(alerts_during, expected_alert)
            after_row = self._alert_row(alerts_after, expected_alert)
            rec["alerts"] = {
                "expected": expected_alert,
                "during": during_row,
                "after": after_row,
                "raised": bool(during_row)
                and during_row["state"] == "firing",
                "drill": (during_row or {}).get("drill"),
                "resolved": bool(after_row)
                and after_row["state"] != "firing",
            }
        if scenario_id == "churn-storm":
            # r19 (closes the r18 ROADMAP sub-item): the churned node
            # restarted through the real boot path and recovered over
            # the r17 catch-up plane — bank ITS /v1/status catch-up
            # census so the record says HOW it caught up (bootstrap
            # state, held versions, resume waves, open circuits), not
            # just that row counts converged
            churned = self.nodes[list(self.nodes)[-1]].workload_node
            status = (
                await workload.scrape(churned, "/v1/status")
                if churned is not None
                else None
            )
            rec["catchup"] = (status or {}).get("sync", {}).get("catchup")
        return rec

    def scenario_matrix(self) -> List[Tuple[str, List[Injection]]]:
        names = list(self.nodes)
        n = len(names)
        store = lambda i: self.nodes[names[i % n]].agent.store  # noqa: E731

        async def stop_node(name: str) -> None:
            await self.nodes[name].stop()

        async def start_node(name: str) -> None:
            await self.nodes[name].start()

        regions = {
            name: ("us" if i < (n + 1) // 2 else "eu")
            for i, name in enumerate(names)
        }
        lat = 0.04 if self.tiny else 0.08
        flap = 0.3 if self.tiny else 0.5
        churn = 0.6 if self.tiny else 1.0
        matrix: List[Tuple[str, List[Injection]]] = [
            ("baseline", []),
            (
                "geo-latency",
                [geo_latency(self.net, regions, {("us", "eu"): lat})],
            ),
            (
                "asym-partition",
                [asymmetric_partition(
                    self.net, names[1], [m for m in names if m != names[1]]
                )],
            ),
            (
                "flap-storm",
                [flap_storm(self.net, names[0], names[-1], flap)],
            ),
            (
                "churn-storm",
                [churn_storm([names[-1]], stop_node, start_node, churn)],
            ),
            ("zombie-node", [zombie_node(self.net, names[-1])]),
            (
                "slow-disk",
                [slow_disk(store(1), 0.03 if self.tiny else 0.05)],
            ),
            (
                "sick-disk",
                # tiny mode fails EVERY statement on the sick node: the
                # ~0.8 s window sees only a handful of writes there, and
                # a transient rate would make the refusals>0 bar a coin
                # flip (deterministic pins only — the r15 noise lesson).
                # The full matrix keeps transient rates but pins seed=4,
                # whose first draw (0.236 < 0.25) fires on the sick
                # node's FIRST statement — this 1-core host runs few
                # enough ops per window that an unlucky seed (0: no
                # draw under 0.25 in its first 16) banked zero refusals
                [sick_disk(store(2), busy_rate=1.0, io_error_rate=0.0)
                 if self.tiny else
                 sick_disk(store(2), busy_rate=0.25, io_error_rate=0.1,
                           seed=4)],
            ),
        ]
        if self.tiny:
            # slow-disk rides the tier-1 replica since r23: it is the
            # scenario that proves the commit-stall page alert AND the
            # alert-triggered profile capture pinning store/ frames
            keep = {"baseline", "zombie-node", "slow-disk", "sick-disk"}
            matrix = [m for m in matrix if m[0] in keep]
        return matrix


def _assert_bars(rec: dict, tiny: bool) -> None:
    """The serving bars every scenario must clear before banking — and
    the tier-1 replica asserts live."""
    sid = rec["scenario"]
    stages = rec["stages"]
    for stage, st in stages.items():
        assert st["timeouts"] == 0, (
            f"{sid}/{stage}: {st['timeouts']} op(s) hit the deadline — "
            "a fault converted requests into stalls"
        )
    for stage in ("write", "query"):
        st = stages[stage]
        assert st["attempts"] > 0, f"{sid}/{stage}: no traffic ran"
        floor = 0.98 if sid == "baseline" else 0.5
        assert st["availability"] >= floor, (
            f"{sid}/{stage}: availability {st['availability']} < {floor}"
        )
    assert rec["events_delivered"] > 0, f"{sid}: no subscription events"
    r = rec["recovery"]
    assert r["secs"] is not None, f"{sid}: never recovered"
    assert r["converged"], f"{sid}: row counts never converged"
    assert r["divergence_zero"], f"{sid}: divergence open at close"
    if sid == "sick-disk":
        assert stages["write"]["refusals"] > 0, (
            "sick-disk: injected store faults never surfaced as typed "
            "refusals"
        )
    # r20 alert bars: the scenario's typed alert raised while injected
    # (drill-marked — the chaos census was live) and resolved after
    # restore().  Tier-1 replica asserts the sick-disk store-fault and
    # slow-disk commit-stall alerts; the full matrix additionally holds
    # zombie-node's view-divergence alert to the same bar.
    if sid in ("sick-disk", "slow-disk") or (
        sid == "zombie-node" and not tiny
    ):
        al = rec.get("alerts")
        assert al, f"{sid}: no alert observation in the record"
        assert al["raised"], (
            f"{sid}: typed alert {al['expected']!r} never reached "
            f"FIRING while the fault was injected: {al['during']}"
        )
        assert al["drill"], (
            f"{sid}: alert fired without the drill mark while the "
            f"chaos census was active: {al['during']}"
        )
        assert al["resolved"], (
            f"{sid}: alert {al['expected']!r} still firing after "
            f"restore + recovery: {al['after']}"
        )
    # r23 profile-attachment bars: a disk-pathology page alert must
    # arrive with the continuous profiler's hot-window capture pinned
    # to it, and that capture's dominant store-worker stack must name
    # the store commit path — the incident record says WHERE the wall
    # went, not just that a threshold tripped
    if sid in ("sick-disk", "slow-disk"):
        prof = (rec["alerts"]["during"] or {}).get("profile")
        assert prof, (
            f"{sid}: page alert fired without an attached profile "
            "capture"
        )
        assert prof["reason"] == f"alert_{rec['alerts']['expected']}"
        assert prof["samples"] > 0, prof
        store_stacks = {
            k: v for k, v in prof["folded"].items()
            if k.startswith("store;")
        }
        assert store_stacks, (
            f"{sid}: attached profile holds no store-worker stacks: "
            f"{sorted(prof['folded'])[:8]}"
        )
        top = max(store_stacks, key=store_stacks.get)
        assert "store/crdt.py" in top, (
            f"{sid}: top store-worker stack does not name the commit "
            f"path: {top}"
        )
    if sid == "churn-storm":
        cc = rec.get("catchup")
        assert cc, (
            "churn-storm: the restarted node's /v1/status catch-up "
            "census was not scraped into the record"
        )
        assert "held_versions" in cc and "bootstrap" in cc, cc
    # r22: every remediation event the scenario banked is fully typed
    # — action, rule, outcome mode, wall stamp, cooldown stamp, drill
    # mark (the flight-record contract GET /v1/remediation serves)
    rem = rec.get("remediation")
    if rem is not None:
        for ev in rem["events"]:
            missing = {
                "node", "action", "rule", "mode", "wall",
                "cooldown_secs", "drill", "detail",
            } - set(ev)
            assert not missing, f"{sid}: untyped remediation event {ev}"
            assert ev["mode"] in (
                "acted", "would_act", "deferred", "refused",
                "failed", "reverted",
            ), ev
            assert ev["cooldown_secs"] > 0, ev


async def run_matrix(
    tiny: bool,
    remediation: bool = False,
    steady_sync: bool = False,
    seed: int = 31,
    only: Optional[Tuple[str, ...]] = None,
) -> dict:
    from corrosion_tpu.runtime import profiler as _prof
    from corrosion_tpu.runtime import tsdb as _tsdb

    saved = (syncer.RECV_TIMEOUT, syncer.OPEN_TIMEOUT)
    # fresh global TSDB at the sim's sampling cadence: an in-suite
    # replica must not inherit (or leave behind) another test's
    # sampler config or ring history — agent setup's ensure() then
    # adopts this instance for every sim node
    _tsdb.configure(
        sample_interval_secs=0.08 if tiny else 0.25,
        slots=600,
        max_series=4096,
    )
    # same discipline for the r23 continuous profiler (the knobs tune()
    # writes into each node's cfg.profile — configured up front so the
    # first node's ensure() adopts THIS instance, not a leftover): the
    # page-alert captures the slow/sick-disk bars assert ride on it
    _prof.configure(
        window_secs=1.0 if tiny else 5.0,
        max_overhead_pct=4.0 if tiny else 1.0,
    )
    if tiny:
        # tiny-shape deadlines: the zombie window is ~1 s, so the sync
        # plane's deadlines must be proportionally tight for recovery
        # to fit the replica budget (module globals, read per call —
        # restored in the finally so an in-suite replica run leaves the
        # production constants untouched for later tests)
        syncer.RECV_TIMEOUT = 2.0
        syncer.OPEN_TIMEOUT = 1.0
    sim = TrafficSim(tiny=tiny, seed=seed, remediation=remediation,
                     steady_sync=steady_sync)
    records: List[dict] = []
    t0 = time.monotonic()
    await sim.start_cluster()
    try:
        for scenario_id, injections in sim.scenario_matrix():
            if only is not None and scenario_id not in only:
                continue
            rec = await sim.run_scenario(scenario_id, injections)
            _assert_bars(rec, tiny)
            records.append(rec)
            print(json.dumps({
                "scenario": scenario_id,
                "remediation": remediation,
                "write_avail": rec["stages"]["write"]["availability"],
                "events": rec["events_delivered"],
                "recovery_s": rec["recovery"]["secs"],
            }), flush=True)
    finally:
        await sim.stop_cluster()
        syncer.RECV_TIMEOUT, syncer.OPEN_TIMEOUT = saved
        _tsdb.configure()  # uninstall: later tests ensure() their own
        _prof.configure()  # ditto — the sampler thread must not leak
    out = {
        "metric": "traffic_sim",
        "mode": "tier1" if tiny else "full",
        "nodes": len(sim.nodes),
        "remediation": remediation,
        "duration_per_scenario_secs": sim.duration,
        "wall_secs": round(time.monotonic() - t0, 2),
        "scenarios": records,
    }
    if tiny and not remediation and only is None:
        # the r22 tier-1 replica addendum: one remediation-ARMED
        # zombie-node scenario on a fresh tiny cluster — the plane
        # boots, ticks, serves GET /v1/remediation, and every bar
        # (timeouts==0, recovery, zero divergence) holds with the
        # actuators live
        armed = await run_matrix(
            True, remediation=True, seed=37, only=("zombie-node",)
        )
        rec = armed["scenarios"][0]
        assert rec["remediation"]["armed"] is True
        rec["scenario"] = "zombie-node-remediated"
        out["scenarios"].append(rec)
    return out


def _stage_timeouts(rec: dict) -> int:
    return sum(st["timeouts"] for st in rec["stages"].values())


async def run_remediation_ab() -> dict:
    """The r22 proof harness: the full matrix twice at steady sync
    cadence — observe-only, then armed — returning the banked A/B
    record.  Bars asserted here (the same ones
    tests/test_traffic_sim.py guards against the banked artifact):
    the armed side strictly improves recovery on ≥3 faulted scenarios,
    zero regressions, every fired action typed."""
    off = await run_matrix(False, remediation=False, steady_sync=True)
    on = await run_matrix(False, remediation=True, steady_sync=True,
                          seed=32)
    by_off = {r["scenario"]: r for r in off["scenarios"]}
    by_on = {r["scenario"]: r for r in on["scenarios"]}
    scenarios: Dict[str, dict] = {}
    improved: List[str] = []
    for sid in by_off:
        a, b = by_off[sid], by_on[sid]
        row = {
            "recovery_off_secs": a["recovery"]["secs"],
            "recovery_on_secs": b["recovery"]["secs"],
            "improved": b["recovery"]["secs"] < a["recovery"]["secs"],
            "timeouts_off": _stage_timeouts(a),
            "timeouts_on": _stage_timeouts(b),
            "write_availability_off":
                a["stages"]["write"]["availability"],
            "write_availability_on":
                b["stages"]["write"]["availability"],
        }
        scenarios[sid] = row
        if row["improved"] and sid != "baseline":
            improved.append(sid)
    actions = [
        ev
        for rec in on["scenarios"]
        for ev in rec["remediation"]["events"]
    ]
    would_act = sum(
        1
        for rec in off["scenarios"]
        for ev in rec["remediation"]["events"]
        if ev["mode"] == "would_act"
    )
    # the acceptance bars, asserted BEFORE banking
    assert len(improved) >= 3, (
        f"remediation improved recovery on only {improved} — "
        "the A/B must show ≥3 faulted scenarios strictly better"
    )
    for sid, row in scenarios.items():
        assert row["timeouts_on"] == 0 and row["timeouts_off"] == 0, (
            f"{sid}: timeouts in the A/B run"
        )
    fired = [ev for ev in actions if ev["mode"] == "acted"]
    assert fired, "armed run fired no actions at all"
    assert would_act > 0, (
        "observe-only run recorded no would_act events — the "
        "kill-switch audit trail is empty"
    )
    return {
        "tag": "r22",
        "sync_profile": {
            "sync_interval_min_secs": 1.0,
            "sync_interval_max_secs": 4.0,
        },
        "scenarios": scenarios,
        "improved_faulted": sorted(improved),
        "actions": actions,
        "observe_only_would_act": would_act,
        "code_sha": _code_fingerprint(),
        "measured_at": time.strftime(
            "%Y-%m-%d %H:%M:%S", time.gmtime()
        ),
    }


def main() -> None:
    from corrosion_tpu.runtime.records import cleanup_record_locks

    tiny = "--tier1" in sys.argv
    ab = "--remediation" in sys.argv
    out = os.path.join(REPO, "TRAFFIC_SIM.json")
    try:
        if ab and not tiny:
            # A/B axis: bank BESIDE the r18 top-level records — load,
            # set the one key, write back
            ab_rec = asyncio.run(run_remediation_ab())
            try:
                with open(out) as f:
                    banked = json.load(f)
            except (OSError, ValueError):
                banked = {}
            banked["remediation_ab"] = ab_rec
            with open(out, "w") as f:
                json.dump(banked, f, indent=1)
                f.write("\n")
            print(
                f"banked {out} remediation_ab: improved="
                f"{ab_rec['improved_faulted']}, "
                f"{len(ab_rec['actions'])} action events"
            )
            return
        record = asyncio.run(run_matrix(tiny))
        record["code_sha"] = _code_fingerprint()
        record["measured_at"] = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.gmtime()
        )
        if tiny:
            print(json.dumps(record, indent=1))
            return
        try:
            with open(out) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            prior = {}
        if "remediation_ab" in prior:
            # a full re-measure preserves the banked A/B axis
            record["remediation_ab"] = prior["remediation_ab"]
        with open(out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        print(f"banked {out}: {len(record['scenarios'])} scenarios, "
              f"wall {record['wall_secs']}s")
    finally:
        # this script writes TRAFFIC_SIM.json directly (no merge), but
        # shares the working tree with merge_records users — never
        # leave a stranded flock sidecar behind on any exit
        cleanup_record_locks(out)


if __name__ == "__main__":
    main()

"""Chaos contract on the QUIC lane: loss + partition/heal at the UDP layer.

The mem-transport chaos soak (test_chaos_soak.py) proves the agent stack
under seeded faults, but its faults are injected in MemNetwork — the QUIC
lane never sees them.  This test injects the same fault classes at the
real UDP receive path (`QuicEndpoint._on_udp`): seeded 10% datagram loss
throughout, then a full partition with divergent writes, then heal.  The
product claims under test mirror the reference's quinn behavior
(`transport.rs:81-230`, sync over bi streams per SURVEY §2.6):

  - SWIM + broadcast + sync all survive sustained datagram loss (PTO
    retransmission carries streams; SWIM datagrams are loss-tolerant by
    protocol),
  - a partition produces divergence (the non-cut side still replicates),
  - after heal, anti-entropy repairs both sides to identical stores.

Receive-side injection is deliberate: with GSO on the send path a single
sendmsg can carry many datagrams, but the kernel re-segments so the
receiver still sees (and drops) individual datagrams.  Source-agent
attribution uses the local port of every socket an agent binds (listener
+ 8 dial-only spread sockets) — ephemeral dial ports make address-based
filtering reliable only with that full map.
"""

from __future__ import annotations

import asyncio
import random

from corrosion_tpu.agent.run import run, setup, shutdown
from tests.test_agent import (
    FAST_SWIM,
    TEST_SCHEMA,
    count_rows,
    fast_config,
    free_port,
    insert,
    wait_until,
)


class UdpChaos:
    """Seeded receive-side fault injector over a set of QUIC agents."""

    def __init__(self, seed: int) -> None:
        self.rng = random.Random(seed)
        self.loss = 0.0
        self.groups: dict[str, int] = {}  # agent name -> partition group
        self.port_owner: dict[int, str] = {}
        self.dropped = 0

    def endpoints(self, agent):
        t = agent.transport
        return [t._endpoint, *t._client_eps]

    def install(self, name: str, agent) -> None:
        for ep in self.endpoints(agent):
            self.port_owner[int(ep.addr.rsplit(":", 1)[1])] = name
        for ep in self.endpoints(agent):
            inner = ep._on_udp

            def filtered(data, addr, _inner=inner, _me=name):
                src = self.port_owner.get(addr[1])
                if src is not None and src != _me:
                    if self.groups and self.groups.get(src) != self.groups.get(_me):
                        self.dropped += 1
                        return
                    if self.loss and self.rng.random() < self.loss:
                        self.dropped += 1
                        return
                _inner(data, addr)

            ep._on_udp = filtered

    def partition(self, groups: dict[str, int]) -> None:
        self.groups = dict(groups)

    def heal(self) -> None:
        self.groups = {}


def test_quic_lane_survives_loss_partition_heal():
    async def main():
        chaos = UdpChaos(seed=7)
        agents: dict[str, object] = {}
        addrs = {n: f"127.0.0.1:{free_port(dgram=True)}" for n in ("a", "b", "c")}
        # loss is armed BEFORE boot: join/bootstrap itself runs lossy
        chaos.loss = 0.10
        for name, addr in addrs.items():
            cfg = fast_config(addr, bootstrap=[v for k, v in addrs.items() if k != name])
            cfg.gossip.transport = "quic"
            agent = await setup(cfg, network=None)
            agent.membership.config = FAST_SWIM
            agent.store.apply_schema_sql(TEST_SCHEMA)
            chaos.install(name, agent)
            await run(agent)
            agents[name] = agent

        a, b, c = agents["a"], agents["b"], agents["c"]

        # phase 1: boot + replicate under sustained 10% datagram loss
        assert await wait_until(
            lambda: all(len(ag.members.states) >= 2 for ag in agents.values()),
            timeout=30,
        ), "QUIC agents did not form a full mesh under loss"
        await insert(a, 1, "boot-row")
        assert await wait_until(
            lambda: count_rows(b) == 1 and count_rows(c) == 1, timeout=30
        ), "row did not replicate over lossy QUIC"

        # phase 2: partition {a} | {b,c}; divergent writes on both sides
        chaos.partition({"a": 0, "b": 1, "c": 1})
        await insert(a, 2, "island-row")
        await insert(b, 3, "mainland-row")
        # the non-cut side must still replicate; the cut row must NOT cross
        assert await wait_until(lambda: count_rows(c) == 2, timeout=30), (
            "mainland replication died during partition"
        )
        assert count_rows(c, "id = 2") == 0, "partition leaked a datagram"
        assert count_rows(a) == 2

        # phase 3: heal; anti-entropy must repair both sides fully
        chaos.heal()
        assert await wait_until(
            lambda: all(count_rows(ag) == 3 for ag in agents.values()),
            timeout=60,
        ), (
            "stores did not converge after heal: "
            f"{[(n, count_rows(ag)) for n, ag in agents.items()]}"
        )
        assert chaos.dropped > 0, "injector never dropped anything"

        for agent in agents.values():
            await shutdown(agent)

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 180))

"""DB maintenance: WAL truncate ladder + incremental vacuum
(VERDICT r2 missing #3 — `perf.wal_threshold_gb` must be live).
Reference: `klukai-agent/src/agent/handlers.rs:379-547`.
"""

import asyncio
import os

import pytest

from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.store import maintenance

SCHEMA = "CREATE TABLE t (id INTEGER PRIMARY KEY, blob TEXT);"


@pytest.fixture
def store(tmp_path):
    s = CrdtStore(str(tmp_path / "m.db"))
    s.apply_schema_sql(SCHEMA)
    yield s
    s.close()


def _grow_wal(store, rows=200):
    with store.write_tx(Timestamp.now()) as tx:
        for i in range(rows):
            tx.execute(
                "INSERT OR REPLACE INTO t (id, blob) VALUES (?, ?)",
                (i, "x" * 2048),
            )


def test_busy_timeout_ladder():
    assert maintenance.calc_busy_timeout_s(0) == 30.0
    assert maintenance.calc_busy_timeout_s(1) == 60.0
    assert maintenance.calc_busy_timeout_s(2) == 120.0
    # 16-minute cap (handlers.rs:529)
    assert maintenance.calc_busy_timeout_s(10) == 960.0


def test_wal_truncates_past_threshold(store):
    _grow_wal(store)
    size = maintenance.wal_size_bytes(store)
    assert size > 4096, "writes should have grown the WAL"
    # tiny threshold: the knob is live and truncation observable
    result = maintenance.truncate_wal_if_needed(store, threshold_bytes=4096)
    assert result is True
    assert maintenance.wal_size_bytes(store) == 0


def test_wal_below_threshold_untouched(store):
    _grow_wal(store, rows=5)
    size = maintenance.wal_size_bytes(store)
    assert maintenance.truncate_wal_if_needed(store, 2**30) is None
    assert maintenance.wal_size_bytes(store) == size


def test_wal_truncate_busy_with_open_reader(store):
    """A read transaction pins the WAL: TRUNCATE cannot complete and the
    caller escalates the ladder instead of spinning."""
    _grow_wal(store)
    reader = store.read_conn()
    reader.execute("BEGIN")
    reader.execute("SELECT COUNT(*) FROM t").fetchone()
    try:
        # zero patience for the test (ladder base is 30s in production)
        old = maintenance.BUSY_TIMEOUT_BASE_S
        maintenance.BUSY_TIMEOUT_BASE_S = 0.05
        try:
            result = maintenance.truncate_wal_if_needed(store, 4096)
        finally:
            maintenance.BUSY_TIMEOUT_BASE_S = old
        assert result is False  # busy → escalate, not crash
        assert maintenance.wal_size_bytes(store) > 0
    finally:
        reader.close()
    # reader gone → next attempt succeeds
    assert maintenance.truncate_wal_if_needed(store, 4096) is True


def test_incremental_vacuum_reclaims_freelist(store):
    _grow_wal(store, rows=500)
    with store.write_tx(Timestamp.now()) as tx:
        tx.execute("DELETE FROM t")
    maintenance.truncate_wal_if_needed(store, 0)
    free = maintenance.freelist_pages(store)
    assert free > 10, "bulk delete should leave freelist pages"
    reclaimed = maintenance.incremental_vacuum_if_needed(
        store, min_freelist_pages=5
    )
    assert reclaimed > 0
    assert maintenance.freelist_pages(store) < 5


def test_maintenance_loops_run_in_agent(tmp_path):
    """The loops actually spawn with the agent and consume the config knobs:
    a tiny threshold + fast cadence truncates a grown WAL within a second."""
    from corrosion_tpu.agent.run import run, setup, shutdown
    from corrosion_tpu.runtime.config import Config

    async def main():
        cfg = Config()
        cfg.db.path = str(tmp_path / "agent.db")
        cfg.gossip.bind_addr = "127.0.0.1:0"
        cfg.perf.wal_threshold_gb = 4096 / 2**30  # 4 KiB
        cfg.perf.wal_check_interval_secs = 0.1
        cfg.perf.vacuum_interval_secs = 0.1
        cfg.perf.vacuum_min_freelist_pages = 5
        agent = await setup(cfg)
        agent.store.apply_schema_sql(SCHEMA)
        await run(agent)
        _grow_wal(agent.store)
        assert maintenance.wal_size_bytes(agent.store) > 4096
        for _ in range(100):
            await asyncio.sleep(0.05)
            if maintenance.wal_size_bytes(agent.store) == 0:
                break
        size = maintenance.wal_size_bytes(agent.store)
        await shutdown(agent)
        assert size == 0, f"maintenance loop never truncated (size={size})"

    asyncio.run(main())
